"""Shared eval-task dispatch: one place that knows how to turn
(task name, manifest, video root) into metrics — used by BOTH the eval
CLI and the in-training evaluator so the two can't drift (the reference
duplicated its eval loop into each eval_*.py script AND the trainers,
where the trainer copy rotted into dead code, SURVEY §2.4 #35).
"""

from __future__ import annotations

from milnce_tpu.config import DataConfig
from milnce_tpu.data.datasets import HMDBSource, MSRVTTSource, YouCookSource

EVAL_TASKS = ("hmdb", "youcook", "msrvtt")


def evaluate_task(task: str, model, variables, mesh, *, data_cfg: DataConfig,
                  csv_path: str, video_root: str, tokenizer=None,
                  num_clip: int = 4, batch_size: int = 16,
                  decoder=None, max_words: int = 30) -> dict:
    """Run one downstream eval task; returns its metrics dict
    (R@k/MedR for retrieval, per-split accuracy for the probe).

    ``tokenizer`` is required for the retrieval tasks; ``decoder=None``
    uses ffmpeg (pass a FakeDecoder for hermetic runs)."""
    if task not in EVAL_TASKS:
        raise ValueError(f"unknown eval task {task!r}; expected one of "
                         f"{'|'.join(EVAL_TASKS)}")
    if task == "hmdb":
        from milnce_tpu.eval.linear_probe import evaluate_linear_probe

        source = HMDBSource(csv_path, video_root, data_cfg,
                            num_clip=num_clip, decoder=decoder)
        return evaluate_linear_probe(model, variables, source, mesh)

    from milnce_tpu.eval.retrieval import evaluate_retrieval

    assert tokenizer is not None, "retrieval tasks need a tokenizer"
    cls = YouCookSource if task == "youcook" else MSRVTTSource
    source = cls(csv_path, video_root, data_cfg, tokenizer,
                 num_clip=num_clip, decoder=decoder, max_words=max_words)
    return evaluate_retrieval(model, variables, source, mesh,
                              batch_size=batch_size)
