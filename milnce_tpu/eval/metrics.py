"""Retrieval metrics: R@1 / R@5 / R@10 / MedianRank from a similarity
matrix (behavior spec: reference metrics.py:9-29).

Given sim[i, j] = score of query i against candidate j with the ground
truth on the diagonal, the rank of each diagonal entry within its row
(0 = best) yields the recall@k rates and the median rank (1-indexed).
"""

from __future__ import annotations

import numpy as np


def compute_retrieval_metrics(sim: np.ndarray) -> dict:
    sim = np.asarray(sim)
    order = np.argsort(-sim, axis=1)
    gt = np.arange(sim.shape[0])[:, None]
    ranks = np.argmax(order == gt, axis=1)
    return {
        "R1": float(np.mean(ranks == 0)),
        "R5": float(np.mean(ranks < 5)),
        "R10": float(np.mean(ranks < 10)),
        "MR": float(np.median(ranks) + 1),
    }


def format_metrics(metrics: dict) -> str:
    return ("R@1: {R1:.4f} - R@5: {R5:.4f} - R@10: {R10:.4f} - "
            "Median R: {MR}".format(**metrics))
