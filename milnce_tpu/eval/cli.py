"""Evaluation entry points:

    python -m milnce_tpu.eval.cli youcook --ckpt <dir|.pth> --csv ... --video_root ...
    python -m milnce_tpu.eval.cli msrvtt  ...
    python -m milnce_tpu.eval.cli hmdb    ...

One CLI replaces the three reference scripts (eval_youcook.py,
eval_msrvtt.py, eval_hmdb.py), including their dual checkpoint-format
sniffing (eval_msrvtt.py:21-32): a directory is treated as an Orbax run
checkpoint; a ``.pth``/``.pth.tar`` file as a torch checkpoint converted
through ``milnce_tpu.utils.torch_convert`` (both the DDP 'state_dict'
wrapper and the upstream flat S3D_HowTo100M format, the latter implying
``space_to_depth=True``).
"""

from __future__ import annotations

import argparse
import os

import jax

from milnce_tpu.config import DataConfig, ModelConfig
from milnce_tpu.data.datasets import build_tokenizer
from milnce_tpu.eval.metrics import format_metrics
from milnce_tpu.models.build import build_model
from milnce_tpu.parallel.mesh import build_mesh
from milnce_tpu.config import ParallelConfig


def load_variables(ckpt: str, model, model_cfg: ModelConfig,
                   sample_shapes) -> dict:
    if not os.path.exists(ckpt):
        raise FileNotFoundError(
            f"checkpoint not found: {ckpt!r} (expected an Orbax run "
            "directory or a torch .pth/.pth.tar file)")
    if os.path.isdir(ckpt):
        from milnce_tpu.train.checkpoint import CheckpointManager

        # read-only: a mistyped path must raise, not mkdir itself and
        # silently evaluate freshly-initialized weights.  restore_raw
        # takes shapes from the checkpoint's own metadata and reads only
        # params/batch_stats — eval neither needs the optimizer state
        # nor should break when its structure evolves (e.g. the masked
        # frozen-embedding moments)
        mgr = CheckpointManager(ckpt, create=False)
        epoch, tree = mgr.restore_raw(subtrees={"params", "batch_stats"})
        if not isinstance(tree, dict):   # a TrainState restored as object
            tree = {"params": tree.params, "batch_stats": tree.batch_stats}
        print(f"loaded Orbax checkpoint (epoch {epoch}) from {ckpt}")
        return {"params": tree["params"], "batch_stats": tree["batch_stats"]}
    # torch formats
    from milnce_tpu.utils.torch_convert import load_torch_checkpoint_as_flax

    variables = load_torch_checkpoint_as_flax(ckpt)
    print(f"loaded torch checkpoint from {ckpt}")
    return variables


def main(argv=None):
    p = argparse.ArgumentParser(description="milnce-tpu eval")
    p.add_argument("task", choices=["youcook", "msrvtt", "hmdb"])
    p.add_argument("--ckpt", required=True)
    p.add_argument("--csv", required=True)
    p.add_argument("--video_root", required=True)
    p.add_argument("--token_dict", default="")
    p.add_argument("--word2vec", default="")
    p.add_argument("--num_windows", type=int, default=4)
    p.add_argument("--batch_size", type=int, default=16)
    p.add_argument("--num_frames", type=int, default=16)
    p.add_argument("--video_size", type=int, default=224)
    p.add_argument("--fps", type=int, default=10)
    p.add_argument("--space_to_depth", action="store_true",
                   help="upstream flat checkpoints need this")
    p.add_argument("--max_words", type=int, default=30)
    # model-shape overrides (hermetic smoke runs / ablations)
    p.add_argument("--embedding_dim", type=int, default=None)
    p.add_argument("--inception_blocks", type=int, default=None)
    p.add_argument("--word_embedding_dim", type=int, default=None)
    p.add_argument("--text_hidden_dim", type=int, default=None)
    p.add_argument("--vocab_size", type=int, default=None)
    p.add_argument("--fake_decoder", action="store_true",
                   help="deterministic in-memory decoder (no ffmpeg/videos); "
                        "hermetic CLI smoke only")
    p.add_argument("--platform", default="",
                   help="force a jax backend (e.g. 'cpu' for hermetic runs "
                        "on accelerator hosts)")
    args = p.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    data_cfg = DataConfig(fps=args.fps, num_frames=args.num_frames,
                          video_size=args.video_size, max_words=args.max_words)
    model_cfg = ModelConfig(space_to_depth=args.space_to_depth,
                            token_dict_path=args.token_dict,
                            word2vec_path=args.word2vec)
    for fld in ("embedding_dim", "inception_blocks", "word_embedding_dim",
                "text_hidden_dim", "vocab_size"):
        if getattr(args, fld) is not None:
            setattr(model_cfg, fld, getattr(args, fld))
    decoder = None
    if args.fake_decoder:
        from milnce_tpu.data.video import FakeDecoder

        decoder = FakeDecoder()
    model = build_model(model_cfg)
    mesh = build_mesh(ParallelConfig())

    import jax.numpy as jnp
    sample = (jnp.zeros((1, args.num_frames, args.video_size,
                         args.video_size, 3), jnp.float32),
              jnp.zeros((1, args.max_words), jnp.int32))
    variables = load_variables(args.ckpt, model, model_cfg, sample)
    # Orbax-restored arrays are committed to one device; replicate over the
    # mesh so they compose with the shard_map'ed embed fns (same fix as the
    # train-resume path, train/loop.py; multihost-safe assembly).
    from milnce_tpu.parallel.mesh import replicate_to_mesh

    variables = replicate_to_mesh(variables, mesh)

    from milnce_tpu.eval.runner import evaluate_task

    tokenizer = (None if args.task == "hmdb"
                 else build_tokenizer(model_cfg, args.max_words))
    metrics = evaluate_task(
        args.task, model, variables, mesh, data_cfg=data_cfg,
        csv_path=args.csv, video_root=args.video_root, tokenizer=tokenizer,
        num_clip=args.num_windows, batch_size=args.batch_size,
        decoder=decoder, max_words=args.max_words)
    if args.task == "hmdb":
        for k, v in metrics.items():
            print(f"HMDB top-1 {k}: {v:.4f}")
    else:
        print(format_metrics(metrics))
    return metrics


if __name__ == "__main__":
    main()
