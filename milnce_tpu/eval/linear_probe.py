"""HMDB-51 linear probe: frozen 1024-d mixed_5c features + LinearSVC.

Behavior of the reference probe (eval_hmdb.py:60-104 and its in-trainer
duplicate main_distributed.py:243-287): extract per-window features with
``mixed5c=True``, per official split fit ``LinearSVC(C=100)`` on training
videos (each window a sample, labels repeated), sum the decision scores
over a test video's windows, argmax -> top-1 accuracy.

sklearn runs on host; feature extraction is the jitted sharded forward.
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh

from milnce_tpu.train.step import make_video_embed_fn


def extract_probe_features(model, variables, source, mesh: Mesh,
                           batch_videos: int = 8, data_axis: str = "data"):
    """Returns (features (N, num_clip, 1024), labels (N,), splits (N, 3))."""
    video_fn = make_video_embed_fn(model, mesh, data_axis, mixed5c=True)
    n_dev = int(np.prod(list(mesh.shape.values())))

    feats, labels, splits = [], [], []
    buf, buf_meta = [], []

    def flush():
        if not buf:
            return
        pad = (-len(buf)) % n_dev
        videos = np.stack(buf + [buf[-1]] * pad)        # (B, C, T, H, W, 3)
        b, c = videos.shape[:2]
        out = np.asarray(video_fn(
            variables, videos.reshape((-1,) + videos.shape[2:])))
        out = out.reshape(b, c, -1)
        keep = b - pad if pad else b
        feats.append(out[:keep])
        for label, spl in buf_meta[:keep]:
            labels.append(label)
            splits.append(spl)
        buf.clear()
        buf_meta.clear()

    for i in range(len(source)):
        s = source.sample(i)
        buf.append(s["video"])
        buf_meta.append((s["label"], s["splits"]))
        if len(buf) == batch_videos:
            flush()
    flush()
    return (np.concatenate(feats), np.asarray(labels), np.stack(splits))


def linear_probe_accuracy(features: np.ndarray, labels: np.ndarray,
                          splits: np.ndarray, C: float = 100.0,
                          splits_to_run=(0, 1, 2)) -> dict:
    """Fit/eval the SVM per split (eval_hmdb.py:86-104).

    features: (N, W, D) per-window; splits: (N, 3) with 1=train, 2=test.
    """
    from sklearn import preprocessing
    from sklearn.svm import LinearSVC

    le = preprocessing.LabelEncoder()
    y = le.fit_transform(labels)
    n, w, d = features.shape
    accs = {}
    for s in splits_to_run:
        tr = np.where(splits[:, s] == 1)[0]
        te = np.where(splits[:, s] == 2)[0]
        x_train = features[tr].reshape(-1, d)
        y_train = np.repeat(y[tr], w)
        x_test = features[te].reshape(-1, d)
        clf = LinearSVC(C=C)
        clf.fit(x_train, y_train)
        scores = clf.decision_function(x_test)
        if scores.ndim == 1:          # binary: sklearn returns one margin
            scores = np.stack([-scores, scores], axis=1)
        scores = scores.reshape(len(te), w, -1)
        pred = scores.sum(axis=1).argmax(axis=1)
        accs[f"split{s + 1}"] = float(np.mean(pred == y[te]))
    accs["mean"] = float(np.mean(list(accs.values())))
    return accs


def evaluate_linear_probe(model, variables, source, mesh: Mesh,
                          C: float = 100.0) -> dict:
    feats, labels, splits = extract_probe_features(model, variables, source,
                                                   mesh)
    return linear_probe_accuracy(feats, labels, splits, C)
