"""Zero-shot text->video retrieval evaluation.

Shape of the reference eval scripts (eval_msrvtt.py:57-76,
eval_youcook.py identical): batched no-grad forward of both towers,
mean-pool the ``num_windows_test`` clip embeddings per video (window
ensembling, eval_msrvtt.py:68-69), then the full T x V dot-product
matrix -> R@k / MedR.

The forward runs as a jitted shard_map over the mesh (uint8 in, /255 on
device); embedding accumulation happens on host exactly like the
reference (:70-72).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

from milnce_tpu.eval.metrics import compute_retrieval_metrics
from milnce_tpu.train.step import make_text_embed_fn, make_video_embed_fn


def extract_retrieval_embeddings(model, variables, source, mesh: Mesh,
                                 batch_size: int = 16,
                                 data_axis: str = "data"):
    """Iterate an eval source ({'video': (C,T,H,W,3) u8, 'text': (1,W)}),
    return (text_embds (N,D), video_embds (N,D)) with window-mean pooling."""
    video_fn = make_video_embed_fn(model, mesh, data_axis)
    text_fn = make_text_embed_fn(model, mesh, data_axis)
    n_dev = int(np.prod(list(mesh.shape.values())))
    batch_size = max(n_dev, (batch_size // n_dev) * n_dev)

    v_out, t_out = [], []
    buf_v, buf_t = [], []

    def flush():
        if not buf_v:
            return
        pad = (-len(buf_v)) % n_dev            # pad to divisibility, drop after
        videos = np.stack(buf_v + [buf_v[-1]] * pad)     # (B, C, T, H, W, 3)
        texts = np.stack(buf_t + [buf_t[-1]] * pad)      # (B, 1, W)
        b, c = videos.shape[:2]
        clip_embd = video_fn(variables, videos.reshape((-1,) + videos.shape[2:]))
        clip_embd = np.asarray(clip_embd).reshape(b, c, -1)
        v_out.append(clip_embd.mean(axis=1)[:b - pad if pad else b])
        t_embd = np.asarray(text_fn(variables, texts.reshape(-1, texts.shape[-1])))
        t_out.append(t_embd.reshape(b, -1)[:b - pad if pad else b])
        buf_v.clear()
        buf_t.clear()

    for i in range(len(source)):
        s = source.sample(i)
        buf_v.append(s["video"])
        buf_t.append(s["text"])
        if len(buf_v) == batch_size:
            flush()
    flush()
    return np.concatenate(t_out), np.concatenate(v_out)


def evaluate_retrieval(model, variables, source, mesh: Mesh,
                       batch_size: int = 16) -> dict:
    t, v = extract_retrieval_embeddings(model, variables, source, mesh,
                                        batch_size)
    return compute_retrieval_metrics(t @ v.T)
