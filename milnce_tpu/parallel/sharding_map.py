"""FSDP-style per-parameter sharding map for the ``(data, model)`` mesh.

The training runtime is natively 2-D (ROADMAP item 2, SNIPPETS.md
[1]-[3]): the batch shards over the ``data`` axis as always, and LARGE
parameter tensors additionally shard over the ``model`` axis so every
chip stores only ``1/model_parallel_size`` of each big kernel and of its
Adam moments.  This module owns the *map* — which tensor shards, on
which dimension — and the placement helpers; the train step
(train/step.py) owns the collectives that the map implies (per-leaf
all_gather of sharded params before the forward, slice +
reduce-scatter-style grad reduction after the backward).

Map construction mirrors ``ModelConfig.conv_impl_map``: an automatic
size-threshold rule covers everything, and an optional inline spec or
JSON artifact (``ParallelConfig.sharding_map``) overrides per-parameter
decisions by path glob.  The chosen map is summarized and hashed so
bench records (``milnce.obs/v1``) can tell two runs' layouts apart.

Default rule (the FSDP size threshold):

- a parameter with ``>= min_size`` elements shards on its
  largest-extent dimension divisible by the model-axis size (ties break
  toward the LAST dim — channels-out for conv kernels, which keeps the
  gathered layout contiguous);
- everything smaller — BN scales, biases, the text tower's small
  denses — replicates: gathering a 64-float vector costs more latency
  than its storage ever saves;
- a large parameter with NO divisible dimension replicates too, and is
  *counted*: callers (bench.py) warn when the map shards nothing, so a
  silently-replicated-everything 2-D run cannot masquerade as FSDP.
"""

from __future__ import annotations

import fnmatch
import hashlib
import json
import math
from typing import Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Elements, not bytes: 65536 f32 elements = 256 KiB per replica — below
# this, the per-step all_gather latency outweighs the storage win.
DEFAULT_FSDP_MIN_SIZE = 65536


def is_spec(x) -> bool:
    """PartitionSpec subclasses tuple on older jax, so plain tree_map
    would recurse INTO a spec; every tree walk over spec trees must pass
    this as ``is_leaf``."""
    return isinstance(x, P)


def spec_leaves(spec_tree) -> list:
    return jax.tree_util.tree_leaves(spec_tree, is_leaf=is_spec)


def map_with_specs(f, tree, spec_tree):
    """``tree_map(f, tree, spec_tree)`` that treats PartitionSpec leaves
    as atoms (see :func:`is_spec`)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    specs = spec_leaves(spec_tree)
    assert len(leaves) == len(specs), (len(leaves), len(specs))
    return treedef.unflatten([f(l, s) for l, s in zip(leaves, specs)])


def sharded_dim(spec: P, axis_name: str) -> Optional[int]:
    """Index of the dim ``spec`` shards over ``axis_name``; None if
    replicated on that axis."""
    for d, names in enumerate(spec):
        if names is None:
            continue
        names = names if isinstance(names, tuple) else (names,)
        if axis_name in names:
            return d
    return None


def _dim_spec(dim: int, axis_name: str) -> P:
    """``P`` sharding ``dim`` over ``axis_name``, NORMALIZED: no trailing
    ``None`` entries.  jax normalizes away trailing Nones on the arrays a
    ``shard_map`` returns, so an un-normalized spec here would make the
    step's INPUT layout compare unequal to its own OUTPUT layout and
    retrace the program on the second step (one jit-cache entry per
    optimizer step — the recompile class the 0-recompile acceptance gate
    exists to catch)."""
    return P(*([None] * dim + [axis_name]))


def _auto_dim(shape: tuple, axis_size: int, min_size: int) -> Optional[int]:
    """The dimension the automatic rule shards, or None (replicate)."""
    if math.prod(shape) < max(1, min_size):
        return None
    best = None
    for d, extent in enumerate(shape):
        if extent % axis_size == 0 and extent >= axis_size:
            if best is None or extent >= shape[best]:
                best = d
    return best


def parse_sharding_spec(spec: str) -> dict:
    """``ParallelConfig.sharding_map`` -> ``{path_glob: dim | None}``.

    Accepts '' (empty — pure automatic rule), an inline
    ``glob=dim[,glob=dim...]`` spec (``dim`` an integer, or ``-`` to
    force-replicate), or a path to a JSON file — either a raw map or an
    artifact whose map lives under the ``sharding_map`` key.  Mirrors
    ``config.parse_conv_impl_map``: malformed items fail at config time,
    not as silently-ignored keys."""
    if not spec:
        return {}
    if "=" in spec:
        items = [item for item in spec.split(",") if item]
        bad = [item for item in items if "=" not in item]
        if bad:
            raise ValueError(f"sharding map items missing '=': {bad} "
                             "(inline form is 'glob=dim[,glob=dim...]')")
        mapping = dict(item.split("=", 1) for item in items)
    else:
        with open(spec) as fh:
            payload = json.load(fh)
        mapping = payload.get("sharding_map", payload)
    out: dict = {}
    for pattern, val in mapping.items():
        if val in ("-", None):
            out[pattern] = None
            continue
        try:
            out[pattern] = int(val)
        except (TypeError, ValueError):
            raise ValueError(
                f"sharding map entry {pattern!r} has dim {val!r} — "
                "expected an integer dim index or '-' (replicate)")
    return out


def _path_str(path) -> str:
    parts = []
    for p in path:
        for attr in ("key", "name", "idx"):
            if hasattr(p, attr):
                parts.append(str(getattr(p, attr)))
                break
        else:
            parts.append(str(p))
    return "/".join(parts)


def build_param_specs(params, mesh: Mesh, model_axis: str,
                      min_size: int = DEFAULT_FSDP_MIN_SIZE,
                      spec: str = ""):
    """Per-parameter PartitionSpec tree for ``params``.

    Raises when ``model_axis`` is absent from ``mesh`` (a map naming a
    phantom axis would trace fine and silently replicate everything —
    the exact failure GL009 exists to catch in source) and when an
    override pattern matches no parameter or names an unshardable dim."""
    if model_axis not in mesh.axis_names:
        raise ValueError(
            f"sharding map targets axis {model_axis!r} but the mesh has "
            f"axes {mesh.axis_names} — build the mesh with "
            "ParallelConfig.model_axis/model_parallel_size first")
    axis_size = mesh.shape[model_axis]
    overrides = parse_sharding_spec(spec)
    matched: set = set()

    def spec_for(path, leaf):
        name = _path_str(path)
        shape = tuple(leaf.shape)
        dim = _auto_dim(shape, axis_size, min_size)
        for pattern, odim in overrides.items():
            if fnmatch.fnmatchcase(name, pattern):
                matched.add(pattern)
                dim = odim
                if dim is not None:
                    if not (0 <= dim < len(shape)):
                        raise ValueError(
                            f"sharding map override {pattern!r}: dim {dim} "
                            f"out of range for {name} {shape}")
                    if shape[dim] % axis_size != 0:
                        raise ValueError(
                            f"sharding map override {pattern!r}: {name} dim "
                            f"{dim} (extent {shape[dim]}) does not divide "
                            f"the {model_axis} axis size {axis_size}")
        if dim is None:
            return P()
        return _dim_spec(dim, model_axis)

    specs = jax.tree_util.tree_map_with_path(spec_for, params)
    unmatched = set(overrides) - matched
    if unmatched:
        raise ValueError(
            f"sharding map patterns matched no parameter: "
            f"{sorted(unmatched)} (typo'd glob — params are addressed by "
            "their '/'-joined tree path)")
    return specs


def describe_map(params, specs, model_axis: str) -> dict:
    """``{path: 'model@dim (shape)' | 'replicated (shape)'}`` — the
    human/machine summary the hash and bench warnings are built from."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    out = {}
    for (path, leaf), spec in zip(flat, spec_leaves(specs)):
        dim = sharded_dim(spec, model_axis)
        shape = "x".join(str(s) for s in leaf.shape)
        out[_path_str(path)] = (f"{model_axis}@{dim} ({shape})"
                                if dim is not None
                                else f"replicated ({shape})")
    return out


def map_hash(summary: dict) -> str:
    """Stable 12-hex digest of a :func:`describe_map` summary — emitted
    into ``milnce.obs/v1`` bench records so 1-D and 2-D runs (and two
    different maps) are distinguishable in ``obs_report``."""
    blob = json.dumps(summary, sort_keys=True).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def sharded_count(specs, model_axis: str) -> int:
    return sum(1 for s in spec_leaves(specs)
               if sharded_dim(s, model_axis) is not None)


def state_partition_specs(state, mesh: Mesh, model_axis: str,
                          min_size: int = DEFAULT_FSDP_MIN_SIZE,
                          spec: str = ""):
    """TrainState-of-PartitionSpec for the whole train state.

    - ``params``: :func:`build_param_specs` (automatic rule + overrides);
    - ``opt_state``: each leaf inherits the spec of the param whose tree
      path it mirrors (Adam's mu/nu repeat the param tree under a
      prefix; longest path-suffix match, shapes verified), falling back
      to the automatic rule — so an override on a kernel moves its
      moments with it even when a same-shape sibling exists, and scalars
      (step counts, injected hyperparams) replicate;
    - ``batch_stats``: ALWAYS replicated — BatchNorm applies and
      pmean-merges full per-channel vectors every step, so a sharded
      stats leaf would buy a few KB and cost a gather in the forward
      (and under an aggressively low test threshold it would silently
      change the program);
    - ``step``: replicated scalar.
    """
    axis_size = mesh.shape[model_axis]
    param_specs = build_param_specs(state.params, mesh, model_axis,
                                    min_size=min_size, spec=spec)
    # Moments follow their parameter by TREE-PATH SUFFIX, not by shape:
    # Adam's mu/nu mirror the param tree under a prefix (.mu/conv/kernel
    # <- conv/kernel), and a shape-keyed lookup would hand every
    # same-shape sibling the FIRST sibling's spec — an override on one
    # of two identical kernels would silently mis-spec the other's
    # moments and fail at trace time with a local-vs-global shape error.
    flat_params, _ = jax.tree_util.tree_flatten_with_path(state.params)
    by_path = {_path_str(path): (tuple(leaf.shape), sp)
               for (path, leaf), sp in zip(flat_params,
                                           spec_leaves(param_specs))}

    def follow(path, leaf):
        name = _path_str(path)
        shape = tuple(leaf.shape)
        best = None
        for ppath, (pshape, sp) in by_path.items():
            if shape == pshape and (name == ppath
                                    or name.endswith("/" + ppath)):
                if best is None or len(ppath) > len(best[0]):
                    best = (ppath, sp)
        if best is not None:
            return best[1]
        # scalars (step counts), injected hyperparams, anything not
        # mirroring a param: the automatic rule
        dim = _auto_dim(shape, axis_size, min_size)
        if dim is None:
            return P()
        return _dim_spec(dim, model_axis)

    return state.replace(
        step=P(),
        params=param_specs,
        batch_stats=jax.tree_util.tree_map(lambda _: P(),
                                           state.batch_stats),
        opt_state=jax.tree_util.tree_map_with_path(follow, state.opt_state))


def tree_shardings(spec_tree, mesh: Mesh):
    """Spec tree -> NamedSharding tree (placement form of the map)."""
    return jax.tree_util.tree_map(lambda s: NamedSharding(mesh, s),
                                  spec_tree, is_leaf=is_spec)


def _already_placed(x, sh) -> bool:
    if not isinstance(x, jax.Array) or not hasattr(x, "sharding"):
        return False
    try:
        return x.sharding.is_equivalent_to(sh, x.ndim)
    except (AttributeError, TypeError):
        return x.sharding == sh


def place_tree(tree, spec_tree, mesh: Mesh):
    """Place ``tree`` on ``mesh`` per the spec tree — THE reshard path.

    Handles every arrival sharding the runtime produces: a fresh init or
    an Orbax restore committed to one device, a 1-D-mesh checkpoint
    restoring onto a 2-D mesh, and the reverse (a 2-D FSDP checkpoint
    opening on a plain data mesh).  A leaf ALREADY in the target
    sharding passes through untouched — the rollback path restores into
    the live state's shardings, so its re-place is an identity and must
    not round-trip bytes (multi-process it CANNOT: a model-axis shard's
    siblings live on other hosts).  Single-process uses the plain
    ``device_put`` fast path; multi-process assembles each global array
    from process-local host data via ``make_array_from_callback``
    (mirroring ``mesh.replicate_to_mesh``'s reasoning) — which requires
    the arrival value to be fully addressable (host numpy from a
    restore, or a replicated array); a cross-LAYOUT reshard of an
    already-partitioned global array would need a cross-host gather, so
    it fails loudly with the supported route instead of crashing inside
    ``np.asarray``."""
    import numpy as np

    shardings = tree_shardings(spec_tree, mesh)
    if jax.process_count() == 1:
        return jax.tree_util.tree_map(
            lambda x, sh: x if _already_placed(x, sh)
            else jax.device_put(x, sh),
            tree, shardings)

    def place(x, sh):
        if _already_placed(x, sh):
            return x
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            raise ValueError(
                f"cannot reshard a non-fully-addressable array from "
                f"{x.sharding} to {sh} in process — restore it from a "
                "checkpoint onto the target mesh instead (restores read "
                "host data and place straight into the target layout)")
        host = np.asarray(x)
        return jax.make_array_from_callback(
            host.shape, sh, lambda idx: host[idx])

    return jax.tree_util.tree_map(place, tree, shardings)


class ShardedPlacement:
    """``shard_and_place_state`` result: the placed state plus the map
    identity every caller reports (summary/hash/sharded count)."""

    def __init__(self, state, specs, summary, digest, n_sharded):
        self.state = state
        self.specs = specs
        self.summary = summary
        self.hash = digest
        self.n_sharded = n_sharded


def shard_and_place_state(state, mesh: Mesh, model_axis: str,
                          min_size: int = DEFAULT_FSDP_MIN_SIZE,
                          spec: str = "") -> ShardedPlacement:
    """Build the state spec tree, summarize it, and place the state —
    the one sequence every 2-D entry point (train loop, bench,
    trace-invariant setup) runs.  Callers differ only in how they react
    to ``n_sharded == 0`` (warn / refuse / assert), so that stays with
    them."""
    specs = state_partition_specs(state, mesh, model_axis,
                                  min_size=min_size, spec=spec)
    summary = describe_map(state.params, specs.params, model_axis)
    return ShardedPlacement(place_tree(state, specs, mesh), specs, summary,
                            map_hash(summary),
                            sharded_count(specs.params, model_axis))
