"""Version shims for the moving jax API surface.

The framework targets current jax (``jax.shard_map``, ``jax.set_mesh``,
``check_vma``); deployment images sometimes pin an older release where
those names live under ``jax.experimental.shard_map`` (kwarg
``check_rep``) and the active-mesh context manager is the ``Mesh`` object
itself.  Every call site imports from here so the version split lives in
exactly one file — and the graftlint trace-invariant pass (which must
trace the train step on whatever jax the image ships) stays runnable
everywhere.
"""

from __future__ import annotations

import jax


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across the ``check_vma``/``check_rep`` rename.

    The default mirrors new jax's (True), so converted call sites that
    omit the kwarg keep the replication/VMA checking they had — only
    sites that explicitly opt out lose it.

    ``check_rep=False`` (not True) on old jax: True additionally swaps
    in a replication-checking rewrite that rejects ``ppermute`` bodies
    outright ("must be applied to a device-varying replication type" —
    the sequence-parallel soft-DTW wavefront hits this).  The one
    grad-semantics divergence that remains under False — old jax
    transposes an in-body ``psum`` to ``psum``, overcounting replicated
    cotangents by the axis size — is neutralized at its single use site
    (losses/milnce.py's stop_gradient identity) rather than here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def psum_with_identity_grad(x, axis_name: str):
    """``lax.psum`` whose reverse-mode gradient is identity to the LOCAL
    term, on both jax generations.

    New jax: plain psum already transposes to identity, and MUST be used
    plain — it is what keeps the result replication-typed (vma-unvarying)
    so ``out_specs=P()`` callers under ``check_vma=True`` still trace.
    Old jax transposes psum to psum, overcounting the replicated
    cotangent by the axis size when grad is taken inside the shard_map
    body; there the stop_gradient identity (value = global sum, gradient
    = local only) restores the correct gradient, and old jax has no vma
    typing to upset."""
    from jax import lax

    if hasattr(jax, "shard_map"):
        return lax.psum(x, axis_name)
    sg = lax.stop_gradient
    return lax.psum(sg(x), axis_name) - sg(x) + x


def donation_argnums_for_backend(backend: str, *argnums: int) -> tuple:
    """The backend-gating rule of :func:`donation_argnums` as a pure
    function of the backend name — what the graftlint Pass 4 donation
    audit (analysis/memplan.py GL014) interrogates: the audit runs ON
    the CPU mesh, where donation is legitimately dropped, but must still
    verify the TPU path would REQUEST it."""
    return argnums if backend != "cpu" else ()


def donation_argnums(*argnums: int) -> tuple:
    """``donate_argnums`` value, gated by backend.

    Donation is an HBM-reuse optimization on accelerators.  On the CPU
    backend it buys nothing — and on old jax it is actively unsafe with
    the hermetic virtual-device mesh: donating a state whose replicated
    shards alias one host buffer (an orbax-restored tree re-replicated
    over 8 virtual CPU devices) double-frees on the second training leg
    (glibc "corrupted double-linked list"; found by the resume tests the
    moment the shard_map compat made them runnable on jax 0.4.x).  TPU
    and GPU keep full donation."""
    return donation_argnums_for_backend(jax.default_backend(), *argnums)


def axis_size(axis_name: str):
    """Static size of a named mesh axis from inside a shard_map/pmap
    body.  Older jax has no ``lax.axis_size``; there ``psum(1, axis)``
    constant-folds to the same static int."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh.  Older
    jax has no ``jax.set_mesh``; there the ``Mesh`` object itself is the
    context manager (legacy pjit idiom)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    return mesh
