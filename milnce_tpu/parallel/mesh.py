"""Device mesh + multi-host bootstrap.

TPU-native replacement for the reference's distributed runtime
(main_distributed.py:35-75, train.py:37-66): no UDP self-IP discovery, no
hardcoded node IP lists, no per-GPU ``mp.spawn`` — one process per host
calls :func:`initialize_distributed` (a thin wrapper over
``jax.distributed.initialize``) and every chip joins a named
``jax.sharding.Mesh``.  Collectives ride ICI within a slice and DCN
across slices; the GSPMD partitioner places them — there is no backend
flag to pick (the reference's ``--dist-backend nccl``, args.py:46).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from milnce_tpu.config import ParallelConfig


def _multihost_tpu_env() -> bool:
    """True on a multi-host Cloud TPU slice: more than one worker in the
    TPU runtime's worker list means this process must join a
    jax.distributed cluster before touching devices.

    The list comes from the env when the TPU env file was sourced, else
    from the instance metadata — the same two sources JAX's own cluster
    detection consults (clusters/cloud_tpu_cluster.py), so a process
    launched from a bare shell on a pod VM is still detected."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES")
    if hosts is None:
        try:
            # Private jax API (mirrors its GcpTpuCluster): guarded — if it
            # moves, autodetect degrades to env-only, never crashes.  The
            # running_in_cloud_tpu_vm gate (libtpu presence) keeps the
            # metadata HTTP lookup — retried with long timeouts inside
            # jax — off every non-TPU startup path.
            from jax._src.cloud_tpu_init import running_in_cloud_tpu_vm
            from jax._src.clusters.cloud_tpu_cluster import get_tpu_env_value

            if running_in_cloud_tpu_vm:
                hosts = get_tpu_env_value("WORKER_HOSTNAMES") or ""
            else:
                hosts = ""
        except Exception:  # graftlint: disable=GL007(private-jax-API probe: if it moves, autodetect deliberately degrades to env-only — documented in the try block above)
            hosts = ""
    return "," in hosts


def initialize_distributed(cfg: ParallelConfig) -> None:
    """Multi-host process bootstrap.

    - ``platform`` set: pin the jax backend first (``jax.config`` wins
      where a bare env var loses to accelerator plugins) — hermetic CPU
      runs on accelerator hosts;
    - explicit ``coordinator_address``: classic bring-up (any platform);
    - no address but a multi-host TPU slice detected: bare
      ``jax.distributed.initialize()`` — coordinator, process count and
      id all come from the TPU metadata, zero flags (contrast the
      reference's hand-maintained 10-IP list, train.py:48);
    - single host: no-op, ``jax.devices()`` already sees every chip.
    """
    if cfg.platform:
        jax.config.update("jax_platforms", cfg.platform)
    if cfg.coordinator_address:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
    elif cfg.platform and cfg.platform != "tpu":
        # pinned off the TPU: a hermetic single-process run on an
        # accelerator host must NOT auto-join the pod's jax.distributed
        # cluster (it would block at the coordinator barrier waiting for
        # workers that were never launched)
        pass
    elif _multihost_tpu_env():
        jax.distributed.initialize()


def build_mesh(cfg: ParallelConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D data mesh by default; optional trailing model axis when
    ``model_parallel_size > 1`` (S3D is small — DP is the workhorse, as in
    the reference, SURVEY.md §2.3 — but the mesh is ready for TP)."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    if cfg.model_axis and cfg.model_parallel_size > 1:
        assert devs.size % cfg.model_parallel_size == 0
        grid = devs.reshape(-1, cfg.model_parallel_size)
        return Mesh(grid, (cfg.data_axis, cfg.model_axis))
    return Mesh(devs, (cfg.data_axis,))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def make_flag_reducer(mesh: Mesh, overlap: bool = False):
    """Cluster-wide OR of per-process boolean flags (e.g. "I received
    SIGTERM"): each process contributes one element per local device of
    a mesh-sharded vector; the jitted sum is a collective every worker
    executes identically, so all of them see the same answer at the same
    step — the primitive behind cooperative preemption (one worker
    exiting unilaterally would wedge the rest inside their next
    collective).

    The reduction program is AOT-compiled here (compilation is pure XLA,
    no communicator setup), so callers that need to align processes
    before the first collective executes (Gloo CPU transports have a
    hard 30 s setup timeout) can barrier between building and first use.

    ``overlap=False`` (default): each call blocks the host on
    ``float(reduce(f))`` — the verdict reflects the flags passed to THIS
    call, at the cost of stalling the async-dispatch pipeline at every
    sync boundary (ADVICE r4).  ``overlap=True`` pipelines instead: each
    call enqueues this boundary's reduction and returns the PREVIOUS
    boundary's verdict (False on the first call), so the host never
    waits on an unfinished collective — detection latency grows by one
    boundary (worst case 2 x preempt_sync_steps steps; budget the grace
    window accordingly).  Both modes are cluster-uniform: every process
    runs the same sequence, so all see the same verdict at the same
    boundary."""
    import jax.numpy as jnp

    sharding = NamedSharding(mesh, P(mesh.axis_names))
    reduce = jax.jit(lambda f: f.sum()).lower(
        jax.ShapeDtypeStruct((jax.device_count(),), jnp.float32,
                             sharding=sharding)).compile()
    pending = []                        # overlap mode: last enqueued result

    def any_flagged(local_flag: bool) -> bool:
        per_dev = np.full((jax.local_device_count(),), float(local_flag),
                          np.float32)
        f = jax.make_array_from_process_local_data(sharding, per_dev)
        if not overlap:
            return float(reduce(f)) > 0.0
        out = reduce(f)                 # enqueue; don't materialize yet
        verdict = float(pending.pop()) > 0.0 if pending else False
        pending.append(out)
        return verdict

    return any_flagged


def broadcast_str(value: str, max_len: int = 64) -> str:
    """Every process returns PROCESS 0's ``value`` (utf-8, truncated to
    ``max_len`` bytes).  The cluster-uniform run-id primitive: the obs
    run context must carry ONE id across a pod (aggregation refuses a
    mixed-run merge), and per-process clocks/pids can't produce that.
    One-time init cost, before the steady-state transfer guard arms;
    single-process is a pass-through."""
    if jax.process_count() == 1:
        return value
    from jax.experimental import multihost_utils

    buf = np.zeros((max_len,), np.uint8)
    raw = value.encode("utf-8")[:max_len]
    buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf))
    return bytes(out[out != 0]).decode("utf-8")


def replicate_to_mesh(tree, mesh: Mesh):
    """Re-replicate host-local arrays (e.g. an Orbax restore committed to
    one device) over a possibly MULTI-HOST mesh.

    ``jax.device_put(x, NamedSharding(mesh, P()))`` raises on multi-host
    CPU/TPU backends without DCN transfer flags ("does not support
    cross-host device transfers") — but a replicated target needs no
    transfer at all: every process already holds the full value, so the
    global array is assembled from process-local data.  Single-process
    keeps the plain device_put fast path.  (Found by the 4-process
    cluster test resuming a checkpoint — tests/test_multihost.py.)"""
    sh = NamedSharding(mesh, P())
    if jax.process_count() == 1:
        return jax.device_put(tree, sh)
    return jax.tree_util.tree_map(
        lambda x: jax.make_array_from_process_local_data(
            sh, np.asarray(x)), tree)


def shard_batch(mesh: Mesh, batch, axis: str = "data"):
    """Device-put a host batch (pytree of arrays) sharded on dim 0."""
    sh = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)
