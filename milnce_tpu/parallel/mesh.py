"""Device mesh + multi-host bootstrap.

TPU-native replacement for the reference's distributed runtime
(main_distributed.py:35-75, train.py:37-66): no UDP self-IP discovery, no
hardcoded node IP lists, no per-GPU ``mp.spawn`` — one process per host
calls :func:`initialize_distributed` (a thin wrapper over
``jax.distributed.initialize``) and every chip joins a named
``jax.sharding.Mesh``.  Collectives ride ICI within a slice and DCN
across slices; the GSPMD partitioner places them — there is no backend
flag to pick (the reference's ``--dist-backend nccl``, args.py:46).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from milnce_tpu.config import ParallelConfig


def initialize_distributed(cfg: ParallelConfig) -> None:
    """Multi-host process bootstrap.  Single-host (coordinator unset) is a
    no-op — ``jax.devices()`` already sees every local chip."""
    if cfg.coordinator_address:
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator_address,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )


def build_mesh(cfg: ParallelConfig,
               devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D data mesh by default; optional trailing model axis when
    ``model_parallel_size > 1`` (S3D is small — DP is the workhorse, as in
    the reference, SURVEY.md §2.3 — but the mesh is ready for TP)."""
    devs = np.asarray(devices if devices is not None else jax.devices())
    if cfg.model_axis and cfg.model_parallel_size > 1:
        assert devs.size % cfg.model_parallel_size == 0
        grid = devs.reshape(-1, cfg.model_parallel_size)
        return Mesh(grid, (cfg.data_axis, cfg.model_axis))
    return Mesh(devs, (cfg.data_axis,))


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Shard the leading (batch) dim over the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, batch, axis: str = "data"):
    """Device-put a host batch (pytree of arrays) sharded on dim 0."""
    sh = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), batch)
