"""Epoch driver: the orchestration layer.

Mirrors the reference's main_worker + train loop responsibilities
(main_distributed.py:65-224) minus everything XLA/the mesh already does:
no DDP wrapper, no per-GPU batch arithmetic, no CUDA device pinning.

Logging format parity: every ``n_display`` steps emit epoch, elapsed
time, epoch progress, windowed mean loss, and current LR
(main_distributed.py:211-222), to stdout and a logfile under
``log_root`` (:304-306).
"""

from __future__ import annotations

import json
import os
import signal
import time
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from milnce_tpu import elastic
from milnce_tpu.config import Config
from milnce_tpu.data.pipeline import (ShardedLoader, device_prefetch,
                                      flatten_text, shard_placer)
from milnce_tpu.data.synthetic import SyntheticVideoTextSource
from milnce_tpu.models.build import build_model
from milnce_tpu.obs import export as obs_export
from milnce_tpu.obs import goodput as obs_goodput
from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.obs import runctx as obs_runctx
from milnce_tpu.obs import spans as obs_spans
from milnce_tpu.obs.anomaly import EwmaSpikeDetector
from milnce_tpu.obs.capture import ProfilerCapture
from milnce_tpu.parallel.mesh import (broadcast_str, build_mesh,
                                      initialize_distributed,
                                      replicate_to_mesh)
from milnce_tpu.resilience import faults
from milnce_tpu.train import curriculum
from milnce_tpu.train.checkpoint import CheckpointManager
from milnce_tpu.train.schedule import (build_host_schedule_total,
                                       build_schedule_total)
from milnce_tpu.train.state import TrainState, build_optimizer, create_train_state
from milnce_tpu.train.step import make_train_step
from milnce_tpu.utils.logging import RunLogger
from milnce_tpu.utils.profiling import StepTimer, maybe_trace
from milnce_tpu.utils.roofline import (device_peak_flops as roofline_peak,
                                       mfu as roofline_mfu,
                                       train_step_flops as
                                       roofline_step_flops)


def build_source(cfg: Config, log_fn=None):
    if cfg.data.synthetic:
        return SyntheticVideoTextSource(cfg.data, vocab_size=cfg.model.vocab_size)
    from milnce_tpu.data.datasets import HowTo100MSource

    return HowTo100MSource(cfg.data, cfg.model, log_fn=log_fn)


def resume_batch_offset(restored_step: int, steps_per_epoch: int) -> int:
    """Mid-epoch resume position: how many global batches of the current
    epoch the restored step counter has already consumed (an end-of-epoch
    save lands on the boundary -> 0).  Only valid while steps_per_epoch
    matches the run being resumed.

    Flat-run reference semantics only: run_training itself derives the
    offset from the curriculum plan's ``locate`` (train/curriculum.py),
    which reduces to exactly this modulo for a single-stage plan — the
    equivalence is pinned by tests/test_curriculum.py."""
    return int(restored_step) % steps_per_epoch


def stop_save_label(epoch: int, opt_step: int,
                    steps_per_epoch: int) -> tuple:
    """(checkpoint label, force) for a mid-epoch stop at ``opt_step``.

    A stop landing ON the epoch's last batch labels epoch+1 (a
    current-epoch label with offset 0 would retrain the whole epoch on
    resume); any other stop labels the CURRENT epoch and must FORCE the
    save — the previous epoch's boundary save holds the same label and
    Orbax would otherwise silently skip it, dropping the partial epoch."""
    done = opt_step % steps_per_epoch == 0
    return (epoch + 1 if done else epoch), (not done)


def stop_save_label_planned(epoch: int, opt_step: int, plan) -> tuple:
    """Plan-aware twin of :func:`stop_save_label`: per-stage batch sizes
    make the epoch boundary a plan lookup, not a modulo.  Identical to
    the flat helper for single-stage plans (tests/test_curriculum.py)."""
    done = opt_step == plan.epoch_end_step(epoch)
    return (epoch + 1 if done else epoch), (not done)


# Finite-guard window accumulators: pure device-side jnp (jitted), so the
# per-step bookkeeping adds one tiny async dispatch and ZERO host syncs.
# Skipped (non-finite) steps are excluded from the windowed loss mean —
# their loss is the NaN the guard just refused to apply — and drive a
# consecutive-skip counter for the loop's circuit breaker.
def _guard_restart(loss, skipped, consec, total):
    keep = skipped == 0
    running = jnp.where(keep, loss, jnp.zeros_like(loss))
    valid = keep.astype(jnp.int32)
    consec = jnp.where(keep, jnp.zeros_like(consec), consec + 1)
    return running, valid, consec, total + skipped


def _guard_acc(running, valid, consec, total, loss, skipped):
    keep = skipped == 0
    return (jnp.where(keep, running + loss, running),
            valid + keep.astype(valid.dtype),
            jnp.where(keep, jnp.zeros_like(consec), consec + 1),
            total + skipped)


_guard_restart_j = jax.jit(_guard_restart)
_guard_acc_j = jax.jit(_guard_acc)


def _fetch_guard_window(running, valid, consec, total):
    """Display-cadence fetch of the guarded window: ONE host transfer for
    the mean-over-valid-steps loss plus both skip counters."""
    r, v, c, t = jax.device_get((running, valid, consec, total))
    mean = float(r) / int(v) if int(v) else float("nan")
    return mean, int(c), int(t)


@dataclass
class TrainResult:
    state: TrainState
    steps: int
    last_loss: float
    skipped_steps: int = 0      # finite-guard: updates skipped on
                                # non-finite gradients (0 when disabled)
    rollbacks: int = 0          # circuit-breaker checkpoint restores
    stage: int = 0              # curriculum stage at exit (flat runs: 0)
    drained: bool = False       # exited on a preemption drain (SIGTERM /
                                # signal file / host.preempt) with a
                                # forced checkpoint + ELASTIC_STAMP —
                                # the CLI maps this to DRAINED_EXIT_CODE


def _finalize_goodput_ledger(rec, rec_path, run_id, process_index,
                             registry, obs_dir, log_fn,
                             extra: Optional[dict] = None) -> None:
    """End-of-run goodput ledger (obs/goodput.py): read back this run's
    event stream (the JSONL file when one exists — the ring is bounded
    — selecting THIS run out of a shared append-only file by run_id),
    export the attribution as ``milnce.obs/v1`` gauges, and write the
    per-run summary snapshot next to the stream.  Best-effort by
    design: the ledger must never turn a finished (or already-failing)
    run into an error."""
    try:
        if rec_path and os.path.exists(rec_path):
            with open(rec_path) as fh:
                records = [json.loads(line) for line in fh if line.strip()]
        else:
            records = rec.tail()
        ledger = obs_goodput.compute_ledger(records, run_id=run_id)
        obs_goodput.ledger_to_registry(ledger, registry)
        if rec_path:
            name = ("GOODPUT.json" if not process_index
                    else f"GOODPUT.p{process_index}.json")
            payload = ledger.to_extra()
            payload.update(extra or {})     # e.g. the live mfu gauge's
            #                                 last value, gate-able at
            #                                 top level like clips/s
            obs_export.write_snapshot(
                os.path.join(obs_dir, name), registry, kind="goodput",
                extra=payload)
        log_fn(ledger.summary_line())
    except Exception as exc:
        log_fn(f"goodput ledger failed ({type(exc).__name__}: {exc}) — "
               "telemetry only, run result unaffected")


def _in_training_eval(cfg: Config, model, state: TrainState, mesh,
                      logger) -> None:
    """Periodic downstream eval during training.  The reference intended
    an HMDB probe here but shipped it dead (main_distributed.py:243-287,
    NameError'd test_loader — SURVEY §2.4); ours runs, and also covers
    the retrieval tasks (train.eval_task: hmdb | youcook | msrvtt).
    Dispatch is shared with the eval CLI (eval/runner.py)."""
    from milnce_tpu.data.datasets import build_tokenizer
    from milnce_tpu.eval.runner import evaluate_task

    decoder = None
    if cfg.data.synthetic:      # hermetic runs eval on the fake decoder too
        from milnce_tpu.data.video import FakeDecoder

        decoder = FakeDecoder()
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    task = cfg.train.eval_task
    tokenizer = (None if task == "hmdb" else
                 build_tokenizer(cfg.model, cfg.data.eval_max_words))
    metrics = evaluate_task(
        task, model, variables, mesh, data_cfg=cfg.data,
        csv_path=cfg.data.eval_csv, video_root=cfg.data.eval_video_root,
        tokenizer=tokenizer, num_clip=cfg.train.num_windows_test,
        batch_size=cfg.train.batch_size_val, decoder=decoder,
        max_words=cfg.data.eval_max_words)
    if task == "hmdb":
        logger.log(f"HMDB linear probe: {metrics}")
    else:
        from milnce_tpu.eval.metrics import format_metrics

        logger.log(f"{task} retrieval: {format_metrics(metrics)}")


def run_training(cfg: Config, max_steps: Optional[int] = None) -> TrainResult:
    if max_steps is None:
        max_steps = cfg.train.max_steps
    if cfg.train.evaluate:
        from milnce_tpu.eval.runner import EVAL_TASKS

        if cfg.train.eval_task not in EVAL_TASKS:   # fail before any init
            raise ValueError(
                f"unknown train.eval_task {cfg.train.eval_task!r}; "
                f"expected one of {'|'.join(EVAL_TASKS)}")
    if cfg.train.faults:
        # deterministic fault injection (chaos tests / failure drills):
        # armed before any decode or step build so every site sees it
        faults.arm(cfg.train.faults)
    initialize_distributed(cfg.parallel)
    # Elastic capacity (milnce_tpu/elastic/): parallel.num_devices builds
    # the mesh over a PREFIX of the local devices — how a drained run
    # resumes onto a smaller mesh on the same host (8-way -> 4-way) and
    # how the chaos tests change topology within one process.
    mesh_devices = None
    if cfg.parallel.num_devices:
        avail = jax.devices()
        if cfg.parallel.num_devices > len(avail):
            raise ValueError(
                f"parallel.num_devices={cfg.parallel.num_devices} exceeds "
                f"the {len(avail)} visible devices")
        mesh_devices = avail[:cfg.parallel.num_devices]
    mesh = build_mesh(cfg.parallel, devices=mesh_devices)
    axis = cfg.parallel.data_axis
    # 2-D (data, model) mesh: the batch shards over BOTH axes (every
    # chip is a data shard — global-batch semantics identical to a 1-D
    # mesh of the same size) and the train state shards per the FSDP
    # sharding map (parallel/sharding_map.py, PERF.md).
    model_axis = cfg.parallel.model_axis
    if model_axis and model_axis not in mesh.axis_names:
        # refuse-loudly, like every other silent-replication path in the
        # 2-D stack (GL009, build_param_specs, bench's shards-NOTHING):
        # a model_axis that never made it into the mesh would quietly
        # train 1-D while the config claims FSDP
        raise ValueError(
            f"parallel.model_axis={model_axis!r} is set but the mesh has "
            f"axes {mesh.axis_names} — set parallel.model_parallel_size "
            f"> 1 (it is {cfg.parallel.model_parallel_size})")
    batch_axes = (axis, model_axis) if model_axis else axis

    logger = RunLogger(cfg.train.log_root, cfg.train.checkpoint_dir,
                       enabled=jax.process_index() == 0 and cfg.train.verbose)
    logger.log(f"mesh: {mesh.shape} | devices: {len(jax.devices())} "
               f"| global batch: {cfg.train.batch_size}")

    # Observability (obs/, OBSERVABILITY.md): an append-only span/event
    # stream (RUN_EVENTS.jsonl) plus display-cadence metrics on the
    # process-wide registry.  Recording is HOST-side only — the gauges
    # are fed exclusively from values the display fetch already
    # materialized, and the per-step span times host dispatch, never the
    # device (pinned by the train_step_milnce_instrumented trace
    # invariant: identical collectives, survives the transfer guard).
    #
    # Run identity: ONE run_id across the whole pod (process 0's value,
    # broadcast), stamped on every event line and snapshot so streams
    # sharing an obs_dir split cleanly and pod aggregation can verify
    # same-run before merging (obs/runctx.py, obs/aggregate.py).
    process_index = jax.process_index()
    run_id = cfg.train.run_id or broadcast_str(obs_runctx.auto_run_id())
    prev_runctx = obs_runctx.set_run_context(run_id, process_index)
    obs_dir = cfg.train.obs_dir or cfg.train.log_root
    rec_path = None
    if cfg.train.verbose and obs_dir:
        # EVERY process writes its own stream (process 0 keeps the
        # unsuffixed name) — the per-host streams are what obs_report
        # --merge turns into the pod view with straggler skew
        os.makedirs(obs_dir, exist_ok=True)
        name = ("RUN_EVENTS.jsonl" if process_index == 0
                else f"RUN_EVENTS.p{process_index}.jsonl")
        rec_path = os.path.join(obs_dir, name)
    rec = obs_spans.SpanRecorder(
        path=rec_path, profiler_bridge=cfg.train.obs_profiler_bridge)
    rec.event("run.start", seed=cfg.train.seed,
              batch_size=cfg.train.batch_size,
              processes=jax.process_count())
    reg = obs_metrics.registry()
    m_steps = reg.counter("milnce_train_steps_total",
                          "optimizer steps dispatched (display-cadence fed)")
    g_loss = reg.gauge("milnce_train_loss",
                       "windowed mean training loss at the last display")
    g_lr = reg.gauge("milnce_train_learning_rate",
                     "current LR (numpy host-schedule twin)")
    g_tput = reg.gauge("milnce_train_clips_per_sec",
                       "windowed throughput at the last display")
    g_skipped = reg.gauge("milnce_train_skipped_steps",
                          "finite-guard skipped updates (run total)")
    m_rollbacks = reg.counter("milnce_train_rollbacks_total",
                              "circuit-breaker checkpoint restores")
    g_mfu = reg.gauge("milnce_train_mfu",
                      "live MFU at the last display (roofline step FLOPs "
                      "over device peak; only set when both are known)")
    g_goodput = reg.gauge("milnce_train_goodput_fraction",
                          "windowed goodput at the last display: elapsed "
                          "minus data-wait, times the applied-update "
                          "fraction, over elapsed")
    g_stage = reg.gauge("milnce_train_stage",
                        "live curriculum stage index (0-based; flat runs "
                        "stay 0)")
    # the data-wait accumulator device_prefetch feeds (create-or-get:
    # same child) — window deltas drive the live goodput gauge
    m_data_wait = reg.counter(
        "milnce_data_wait_seconds_total",
        "host seconds the training loop blocked waiting for batch data")

    # Live MFU denominator/numerator (utils/roofline.py — the SAME
    # table + formula bench.py uses, pinned within 2% by
    # tests/test_goodput.py).  FLOPs only for configs the analytic
    # model covers (bench.py applies the identical guard: DTW losses
    # and the two-pass grad-accum step would make the number fiction).
    n_chips = int(mesh.devices.size)    # the mesh's chips, not the
    #                                     host's — an elastic 4-way resume
    #                                     on an 8-device host must not
    #                                     halve its MFU by fiction
    dev0 = jax.devices()[0]
    peak = roofline_peak(str(getattr(dev0, "device_kind", dev0.platform)))

    def _stage_step_flops(st) -> Optional[float]:
        # per-stage: the curriculum changes batch/frames/resolution, and
        # a stale FLOPs count would make the live MFU gauge fiction
        if not (peak and cfg.loss.name == "milnce"
                and cfg.train.grad_accum == 1):
            return None
        return roofline_step_flops(
            st.batch_size, st.num_frames, st.resolution,
            cfg.data.num_candidates, cfg.data.max_words,
            space_to_depth=cfg.model.space_to_depth,
            inception_blocks=cfg.model.inception_blocks,
            embedding_dim=cfg.model.embedding_dim,
            word_dim=cfg.model.word_embedding_dim,
            hidden=cfg.model.text_hidden_dim)

    # Anomaly-triggered profiler capture (obs/anomaly.py + obs/
    # capture.py): the EWMA detector watches the window step time the
    # display already computes (host-side, no new syncs); a spike emits
    # an 'anomaly' event and — when a capture dir is configured — arms
    # ONE bounded jax.profiler capture.  SIGUSR1 arms it manually.
    profiler_capture = None
    if cfg.train.capture_dir:
        profiler_capture = ProfilerCapture(
            cfg.train.capture_dir,
            duration_s=cfg.train.capture_ms / 1e3,
            cooldown_s=cfg.train.anomaly_cooldown_s,
            max_captures=cfg.train.capture_max, recorder=rec)
    spike_detector = None
    if cfg.train.anomaly_detect:
        spike_detector = EwmaSpikeDetector(
            "train.step_ms", ratio=cfg.train.anomaly_ratio,
            warmup=cfg.train.anomaly_warmup,
            cooldown_s=cfg.train.anomaly_cooldown_s, recorder=rec,
            on_anomaly=((lambda v, e: profiler_capture.arm(
                reason="step_time_spike"))
                if profiler_capture is not None else None))
    capture_requested = {"flag": False}     # SIGUSR1, acted on at display
    prev_usr1 = None
    if profiler_capture is not None:
        def _on_sigusr1(signum, frame):
            capture_requested["flag"] = True

        try:
            prev_usr1 = signal.signal(signal.SIGUSR1, _on_sigusr1)
        except ValueError:       # non-main thread (tests)
            prev_usr1 = None

    # ----- curriculum plan (train/curriculum.py) -----
    # Flat runs are a single open-ended stage through the SAME plan
    # machinery, so resume offsets / epoch progress / schedule totals
    # have exactly one derivation (pinned equal to the historical
    # modulo helpers by tests/test_curriculum.py).
    stages = curriculum.parse_curriculum(
        cfg.train.curriculum, default_batch_size=cfg.train.batch_size)
    curriculum_on = bool(stages)
    if not curriculum_on:
        stages = curriculum.flat_stages(cfg.data, cfg.train.batch_size)
    stage_cfgs = [curriculum.stage_config(cfg, st) for st in stages]
    source0 = build_source(stage_cfgs[0], log_fn=logger.log)
    plan = curriculum.plan_curriculum(stages, len(source0),
                                      cfg.optim.epochs)
    if curriculum_on:
        rec.event("curriculum.plan", total_steps=plan.total_steps,
                  stages=[{"num_frames": s.num_frames,
                           "resolution": s.resolution,
                           "batch_size": s.batch_size}
                          for s in plan.stages])
        logger.log("curriculum: "
                   + " -> ".join(s.label() for s in plan.stages)
                   + f" ({plan.total_steps} steps planned)")

    def _stage_pipeline(idx: int):
        """(source, loader, zero_start, step_flops) for one stage —
        rebuilt at every boundary (the decode shapes and the hoisted
        start fallback are per-stage; the model/optimizer are not)."""
        st = plan.stages[idx]
        src = (source0 if idx == 0
               else build_source(stage_cfgs[idx], log_fn=logger.log))
        ldr = ShardedLoader(src, st.batch_size, seed=cfg.train.seed,
                            num_threads=cfg.data.num_reader_threads,
                            lookahead_batches=cfg.data.decode_lookahead,
                            sample_timeout=cfg.data.sample_timeout,
                            timeout_retries=cfg.data.sample_timeout_retries,
                            log_fn=logger.log)
        zstart = shard_placer(mesh, batch_axes)(
            np.zeros((st.batch_size // jax.process_count(), ),
                     np.float32))
        return src, ldr, zstart, _stage_step_flops(st)

    model = build_model(cfg.model, bn_axis_name=batch_axes)
    rng = jax.random.PRNGKey(cfg.train.seed)
    # init at stage-0 shapes: the TrainState tree is shape-invariant
    # across stages (conv/BN params don't depend on frames/resolution),
    # so transitions and checkpoints ride place_state untouched
    st0 = plan.stages[0]
    sample_video = np.zeros((2, st0.num_frames, st0.resolution,
                             st0.resolution, 3), np.float32)
    sample_text = np.zeros((2 * cfg.data.num_candidates, cfg.data.max_words),
                           np.int32)
    variables = model.init(rng, sample_video, sample_text)
    if cfg.train.pretrain_ckpt:
        # converted reference weights (main_distributed.py:81-83)
        from milnce_tpu.utils.torch_convert import load_torch_checkpoint_as_flax

        variables = load_torch_checkpoint_as_flax(cfg.train.pretrain_ckpt)
        logger.log(f"loaded pretrained weights from {cfg.train.pretrain_ckpt}")

    # Schedule over the PLAN's total (satellite: per-stage batch sizes
    # make steps_per_epoch * epochs wrong for warmup/cosine totals) — a
    # pure function of the global step, so opt-state structure and
    # checkpoints are identical to a flat run's.
    schedule = build_schedule_total(cfg.optim, plan.total_steps)
    optimizer = build_optimizer(cfg.optim, schedule)
    state = create_train_state(variables, optimizer)

    # State placement: the ONE path every arrival sharding goes through
    # (fresh init, Orbax restore, rollback restore) — on the 2-D mesh it
    # also RESHARDS, so a 1-D-mesh checkpoint opens on a (data, model)
    # grid and vice versa (MIGRATING.md).
    if model_axis:
        from milnce_tpu.parallel.sharding_map import (place_tree,
                                                      shard_and_place_state)

        placement = shard_and_place_state(
            state, mesh, model_axis, min_size=cfg.parallel.fsdp_min_size,
            spec=cfg.parallel.sharding_map)
        state_specs = placement.specs
        logger.log(f"sharding map: {placement.n_sharded}/"
                   f"{len(placement.summary)} params "
                   f"sharded on '{model_axis}' "
                   f"(threshold {cfg.parallel.fsdp_min_size} elements, "
                   f"hash {placement.hash})")
        if placement.n_sharded == 0:
            logger.log("sharding map WARNING: no parameter shards — the "
                       "2-D mesh is paying model-axis collectives for "
                       "pure replication (lower parallel.fsdp_min_size "
                       "or fix parallel.sharding_map)")
        # the fresh state is already placed; a restore below then uses
        # the PLACED state as its template, so Orbax reads any
        # checkpoint (1-D or 2-D origin) straight into the FSDP layout
        # and the explicit place_state after it is an identity
        state = placement.state
        place_state = lambda s: place_tree(s, state_specs, mesh)  # noqa: E731
    else:
        state_specs = None
        place_state = lambda s: replicate_to_mesh(s, mesh)  # noqa: E731

    ckpt_dir = os.path.join(cfg.train.checkpoint_root,
                            cfg.train.checkpoint_dir or "run")
    manager = CheckpointManager(ckpt_dir, keep=cfg.train.checkpoint_keep,
                                save_retries=cfg.train.checkpoint_save_retries)
    start_epoch = 0
    resume_step = 0
    if cfg.train.resume:
        # Resume-compatibility guard BEFORE any Orbax I/O: a curriculum
        # checkpoint resumed with train.curriculum removed would
        # otherwise silently continue at the flat config's full shape
        # (the state tree is shape-invariant, so nothing else fails).
        curriculum.check_resume_compatible(
            curriculum.read_stage_stamp(ckpt_dir),
            curriculum_spec=cfg.train.curriculum,
            flat_frames=cfg.data.num_frames,
            flat_resolution=cfg.data.video_size,
            flat_batch=cfg.train.batch_size)
        # Topology guard (elastic/stamp.py), also before any Orbax I/O:
        # indivisible per-stage batches and a stale sidecar pair refuse
        # loudly; a mesh-shape change is logged and the restore runs
        # under the elastic.resume span so the reshard cost lands in the
        # ledger's reshard bucket instead of hiding in checkpoint.
        estamp = elastic.read_elastic_stamp(ckpt_dir)
        topo_note = elastic.check_topology_resume(
            estamp, mesh_shape=dict(mesh.shape),
            batch_sizes=[st.batch_size for st in plan.stages],
            curriculum_stamp=curriculum.read_stage_stamp(ckpt_dir))
        if topo_note:
            logger.log(topo_note)
        if estamp is not None:
            with rec.span("elastic.resume", label="latest",
                          from_mesh=str(dict(estamp.get("mesh") or {})),
                          to_mesh=str(dict(mesh.shape))):
                start_epoch, state = manager.restore_latest(state)
        else:
            with rec.span("ckpt.restore", label="latest"):
                start_epoch, state = manager.restore_latest(state)
        # Mid-epoch checkpoints (preemption / max_steps) are labeled
        # with the CURRENT epoch; the restored step counter places us
        # inside it via the plan's locate() — the containing stage
        # segment plus its batch offset — so the loader skips the
        # consumed batches at the index level and no sample is trained
        # twice (an end-of-epoch save lands on a boundary -> offset 0).
        resume_step = int(state.step)
        resume_seg, resume_off = plan.locate(resume_step)
        logger.log(
            f"resumed from epoch {start_epoch}"
            + (f" at batch {resume_seg.skip_batches + resume_off}"
               if resume_step else "")
            + (f" (curriculum stage {resume_seg.stage}, "
               f"{plan.stages[resume_seg.stage].label()})"
               if curriculum_on else ""))

    # Explicitly place the state (freshly initialized OR restored — both
    # land committed to one device) over the mesh NOW: leaving it
    # single-device made the first step_fn call perform the re-placement
    # as an IMPLICIT device-to-device transfer — invisible until the
    # steady-state transfer guard flagged it.  Multihost-safe: assembles
    # from process-local data instead of a cross-host device_put, so it
    # composes with the batch-sharded step inputs.
    state = place_state(state)
    if model_axis:
        # the FSDP storage win, made visible: per-chip bytes of the
        # placed state (host-side shard inspection, no transfer)
        from milnce_tpu.train.state import per_device_state_bytes

        per_dev = per_device_state_bytes(state)
        if per_dev:
            logger.log(f"state bytes/chip: "
                       f"{max(per_dev.values()) / 2 ** 20:.2f} MiB "
                       f"(params + moments + stats, post-sharding)")

    guard_on = cfg.train.finite_guard
    if cfg.train.grad_accum > 1:
        from milnce_tpu.train.step import make_grad_cache_step

        step_fn = make_grad_cache_step(
            model, optimizer, mesh, cfg.train.grad_accum, data_axis=axis,
            loss_cfg=cfg.loss, finite_guard=guard_on,
            state_specs=state_specs, model_axis=model_axis,
            overlap_grad_reduce=cfg.parallel.overlap_grad_reduce)
    else:
        step_fn = make_train_step(
            model, optimizer, mesh, data_axis=axis, loss_cfg=cfg.loss,
            finite_guard=guard_on, state_specs=state_specs,
            model_axis=model_axis,
            overlap_grad_reduce=cfg.parallel.overlap_grad_reduce)

    # Curriculum mem_plan pre-flight (train/curriculum.py, reusing the
    # PR 8 autotune planner): every stage's step is statically planned
    # against the per-chip HBM budget HERE — an over-budget stage is
    # refused with its top-3 contributors named before anything traces
    # or compiles, never an OOM at a mid-run boundary.
    if curriculum_on:
        budget = curriculum.hbm_budget_bytes()
        if budget:
            for note in curriculum.preflight_stages(
                    step_fn, state, plan.stages,
                    num_candidates=cfg.data.num_candidates,
                    max_words=cfg.data.max_words, budget_bytes=budget):
                logger.log(f"curriculum pre-flight: {note}")
        else:
            logger.log("curriculum pre-flight skipped: no per-chip HBM "
                       "budget known (set MILNCE_HBM_GIB to arm the "
                       "refusal gate)")

    # Preemption-safe shutdown: TPU-VM maintenance events deliver SIGTERM;
    # save a checkpoint and exit cleanly instead of losing the epoch (the
    # reference has no preemption handling — SURVEY.md §5 failure-detection
    # note; recovery there is manual restart from the last epoch file).
    # The controller (elastic/drain.py) latches SIGTERM, the
    # train.drain_signal_file path, and the host.preempt fault site into
    # one per-step poll; a drained exit forces a checkpoint + writes
    # ELASTIC_STAMP.json and returns TrainResult(drained=True).
    drain = elastic.DrainController(
        signal_file=cfg.train.drain_signal_file, recorder=rec)
    drain.install()

    # Straggler policy (elastic/straggler.py): the display cadence feeds
    # this host's window step-time p50 into the live twin of obs_report
    # --merge's skew rule; demotions ride the goodput snapshot.
    straggler_policy = elastic.StragglerPolicy(
        ratio=cfg.train.straggler_ratio,
        window=cfg.train.straggler_window,
        recommend_resize=cfg.train.straggler_resize, recorder=rec)

    # Multi-process: a maintenance event may signal only SOME workers; a
    # worker acting on its local flag alone would leave the rest wedged
    # in their next collective.  All-reduce the flag every
    # preempt_sync_steps so the whole cluster agrees to checkpoint at
    # the same step boundary (tests/test_multihost.py drives this with a
    # real one-worker SIGTERM).
    multi = jax.process_count() > 1
    if multi:
        from milnce_tpu.parallel.mesh import make_flag_reducer

        any_preempted = make_flag_reducer(mesh)
        sync_every = max(1, cfg.train.preempt_sync_steps)

    # In-training eval cadence: every total_batch//512 epochs, like the
    # reference's gate (main_distributed.py:188-189) — which is dead code
    # there (undefined test_loader, SURVEY.md §2.4); here it works.
    eval_every = max(1, cfg.train.batch_size // 512)

    # The loss stays ON DEVICE in the hot loop: a per-step ``float(loss)``
    # would block the host on every step's completion and defeat the async
    # dispatch that device_prefetch exists to enable (the reference has the
    # same flaw implicitly — loss.item() per batch, main_distributed.py:212).
    # Host transfer happens only every ``n_display`` steps and at exit, and
    # the steady state runs under ``jax.transfer_guard("disallow")`` so a
    # smuggled implicit sync RAISES instead of silently stalling the
    # pipeline (tests/test_transfer_guard.py); the display/checkpoint
    # branches re-enter "allow" — the audited escape hatch.
    total_steps = 0
    last_loss_dev = None
    running_dev = None
    valid_dev = None            # finite guard: non-skipped steps in window
    consec_dev = None           # finite guard: consecutive skipped updates
    skips_total_dev = None      # finite guard: run-total skipped updates
    rollbacks = 0
    last_rollback = None        # (total_steps, total_skips) at the last
                                # breaker trip — bounds the rollback loop
    window = 0
    # Wall clock feeds the human-facing elapsed display only; bench numbers
    # come from utils/timing.py's differenced protocol.
    # graftlint: disable=GL005(elapsed-display only; the windowed loss fetch at the same cadence is the device sync)
    tick = time.time()

    # Step counter tracked ON HOST: state.step is a device scalar, and
    # reading it back (int(state.step)) at display/stop cadence was a
    # hidden sync — graftlint GL001.  The restored value is read ONCE
    # here; afterwards host arithmetic stays exact.
    opt_step0 = int(state.step)

    # Live-goodput window baselines (host counters, reset per display):
    # data-wait delta off the prefetcher's accumulator, skip delta off
    # the guard fetch — everything the gauge needs already exists.
    window_wait0 = m_data_wait.value
    prev_k_total = 0
    last_mfu = None

    # LR display comes from the numpy twin of the device schedule:
    # float(schedule(step)) of the jnp form was a per-display device
    # round-trip (the original graftlint finding this PR fixes).
    host_schedule = build_host_schedule_total(cfg.optim, plan.total_steps)

    # Initial stage pipeline (a resume may land past stage 0 — the plan
    # says where).  The hoisted zero_start fallback: building np.zeros
    # INSIDE the loop fed the jitted step an implicit H2D transfer every
    # step; placed once per STAGE, explicitly, mesh-sharded via the same
    # placement helper the prefetcher uses.
    stage_idx = plan.stage_at(resume_step)
    source, loader, zero_start, step_flops = _stage_pipeline(stage_idx)
    timer = StepTimer(clips_per_step=plan.stages[stage_idx].batch_size)
    g_stage.set(stage_idx)

    def fetch(dev_val) -> float:
        # the ONE audited transfer of the display path (off-cadence by
        # design; see the n_display branch)
        return (float(jax.device_get(dev_val))
                if dev_val is not None else float("nan"))

    def exit_metrics():
        # one transfer covers both the final loss and the skip counter
        if skips_total_dev is None:
            return fetch(last_loss_dev), 0
        last, k = jax.device_get((last_loss_dev, skips_total_dev))
        return float(last), int(k)

    def check_finite(mean_loss: float, step_label: int) -> None:
        """Divergence guard, evaluated only at display fetches (no extra
        host syncs): a non-finite windowed loss snapshots the run state
        for post-mortem and halts instead of burning the rest of the
        epoch budget on NaNs.

        The snapshot goes to a SEPARATE ``nan_postmortem/`` directory,
        step-labeled: the rotation manager would both silently refuse the
        save (Orbax rejects a label <= the last saved one) and — worse —
        hand the NaN-poisoned params straight back to the next
        ``--resume``, which restores from the rotation only."""
        if np.isfinite(mean_loss) or not cfg.train.halt_on_nan:
            return
        pm = CheckpointManager(os.path.join(ckpt_dir, "nan_postmortem"),
                               keep=1)
        pm.save(step_label, state)
        pm.wait()
        logger.log(f"non-finite training loss ({mean_loss}) — post-mortem "
                   f"state saved under nan_postmortem/{step_label}; halting")
        raise FloatingPointError(
            f"training loss became non-finite ({mean_loss}) at step "
            f"{step_label}")

    prev_rec = obs_spans.install(rec)   # pipeline watchdog events land
                                        # in this run's stream
    try:
      with maybe_trace(cfg.train.trace_dir or None):
        # Steady state: IMPLICIT device transfers are a bug (a hidden
        # host sync or a per-step H2D upload) and raise immediately.
        # Explicit device_put/device_get stay legal; the display /
        # preemption-sync / checkpoint branches re-enter "allow" — every
        # escape hatch is a deliberate, cadenced one.
        with jax.transfer_guard("disallow"):
          for epoch in range(start_epoch, cfg.optim.epochs):
            if (cfg.train.evaluate and cfg.data.eval_video_root
                    and epoch % eval_every == 0):
                with jax.transfer_guard("allow"):   # epoch-cadence eval
                    _in_training_eval(cfg, model, state, mesh, logger)
            for seg in plan.segments_for_epoch(epoch):
              # Resume offsets come from the plan's locate() semantics:
              # segments fully consumed by the restored step are skipped
              # whole; the containing one starts at its batch offset.
              seg_done = 0
              if resume_step:
                  if resume_step >= seg.end_step:
                      continue
                  seg_done = max(0, resume_step - seg.start_step)
                  resume_step = 0       # applies once
              if seg.stage != stage_idx:
                  # Curriculum boundary: the previous stage's prefetcher
                  # is already drained (closed below); rebuild the
                  # pipeline at the new shapes.  The stage.switch span
                  # feeds the goodput ledger's stage_switch bucket; the
                  # NEXT step dispatch blocks on the new stage's
                  # trace+compile (one fresh jit entry per stage) and
                  # the ledger attributes that step there too.
                  st = plan.stages[seg.stage]
                  with jax.transfer_guard("allow"):   # boundary cadence
                    with rec.span("stage.switch", stage=seg.stage,
                                  prev_stage=stage_idx,
                                  step=opt_step0 + total_steps,
                                  num_frames=st.num_frames,
                                  resolution=st.resolution,
                                  batch_size=st.batch_size):
                        (source, loader, zero_start,
                         step_flops) = _stage_pipeline(seg.stage)
                  stage_idx = seg.stage
                  g_stage.set(stage_idx)
                  logger.log(f"curriculum: entering stage {stage_idx} "
                             f"({st.label()}) at step "
                             f"{opt_step0 + total_steps}")
                  # fresh stage, fresh display window — the windowed
                  # loss/throughput must not mix shapes across the
                  # boundary (the loss-continuity acceptance compares
                  # post-switch windows against a flat run at the new
                  # shape)
                  running_dev = None
                  valid_dev = None
                  window = 0
                  timer = StepTimer(clips_per_step=st.batch_size)
                  window_wait0 = m_data_wait.value
                  tick = time.time()
              prefetch = device_prefetch(
                  loader.epoch(epoch,
                               skip_batches=seg.skip_batches + seg_done),
                  mesh, batch_axes, depth=cfg.data.prefetch_depth)
              for batch in prefetch:
                video, text = flatten_text(batch)
                start = batch.get("start", zero_start)
                # span times HOST dispatch of the async step (device
                # truth needs the profiler bridge / trace_dir) — no
                # sync, no transfer, file write is line-buffered host IO
                with rec.span("step", step=total_steps + 1):
                    # host.slow chaos site: inflate THIS process's step
                    # wall time (a persistently slow host for the
                    # straggler policy); the sleep lands inside the step
                    # span so the recorded skew is the injected one
                    faults.maybe_hang("host.slow", default_sleep=0.05)
                    if guard_on:
                        state, loss, skipped = step_fn(state, video, text,
                                                       start)
                        skipped = skipped.addressable_data(0)
                    else:
                        state, loss = step_fn(state, video, text, start)
                # Accumulate on the PROCESS-LOCAL replica of the (P()-
                # replicated) loss: a zero-copy shard view.  Eager/jit
                # arithmetic on the multi-process global array itself is
                # a cross-process XLA computation — unsupported on the
                # CPU backend and pure waste on TPU (every process holds
                # the full value; SPMD determinism keeps the per-process
                # accumulators identical, so display/breaker verdicts
                # stay cluster-uniform).
                loss = loss.addressable_data(0)
                total_steps += 1
                seg_done += 1
                window += 1
                timer.tick()
                # async device-side accumulation — no host sync here (the
                # guard trackers are jitted jnp updates on device scalars)
                if guard_on:
                    if consec_dev is None:
                        consec_dev = skipped - skipped      # local-shard 0
                    if skips_total_dev is None:
                        skips_total_dev = skipped - skipped
                    if running_dev is None:
                        (running_dev, valid_dev, consec_dev,
                         skips_total_dev) = _guard_restart_j(
                            loss, skipped, consec_dev, skips_total_dev)
                    else:
                        (running_dev, valid_dev, consec_dev,
                         skips_total_dev) = _guard_acc_j(
                            running_dev, valid_dev, consec_dev,
                            skips_total_dev, loss, skipped)
                else:
                    running_dev = (loss if running_dev is None
                                   else running_dev + loss)
                last_loss_dev = loss
                if window % cfg.train.n_display == 0:
                  # LR + progress from the host step counter (seeded by
                  # the RESTORED device counter once, before the loop),
                  # so they stay correct across resumes with no sync.
                  opt_step = opt_step0 + total_steps
                  lr = host_schedule(opt_step)
                  # epoch progress from the plan (per-stage batch sizes
                  # make a run-constant steps_per_epoch meaningless);
                  # the modulo keeps the flat-run display byte-identical
                  ep_len = max(1, plan.epoch_steps(epoch))
                  progress = ((opt_step - plan.epoch_start_step(epoch))
                              % ep_len) / ep_len
                  with jax.transfer_guard("allow"):  # display-cadence fetch
                    consec = 0
                    k_total = 0
                    extra = ""
                    # the sync span is where the async pipeline's device
                    # work surfaces on the host — the goodput ledger's
                    # compute category reads step-dispatch + sync spans
                    with rec.span("sync", cause="display", step=opt_step):
                        if guard_on:
                            (mean_loss, consec,
                             k_total) = _fetch_guard_window(
                                running_dev, valid_dev, consec_dev,
                                skips_total_dev)
                        else:
                            mean_loss = fetch(running_dev) / window
                    if curriculum_on:
                        extra += f", Stage: {stage_idx}"
                    if guard_on:
                        extra += f", Skipped steps: {k_total}"
                    fails = getattr(source, "decode_failures", 0)
                    extra += f", Decode failures: {fails}"
                    if loader.decode_timeouts:
                        extra += (f", Decode timeouts: "
                                  f"{loader.decode_timeouts}")
                    # ONE timer read feeds throughput, MFU and the
                    # detector, so the three can never disagree on the
                    # window they describe
                    sps = timer.steps_per_sec
                    elapsed = timer.elapsed_s
                    clips_per_sec = sps * plan.stages[stage_idx].batch_size
                    if step_flops is not None and sps > 0:
                        last_mfu = roofline_mfu(step_flops, sps, peak,
                                                n_chips)
                        g_mfu.set(last_mfu)
                        extra += f", MFU: {last_mfu:.3f}"
                    # windowed goodput: elapsed minus host data-wait,
                    # scaled by the applied-update fraction (a skipped
                    # step burnt chip time for no kept progress)
                    wait_now = m_data_wait.value
                    wait_delta = max(0.0, wait_now - window_wait0)
                    window_wait0 = wait_now
                    applied_frac = 1.0
                    if guard_on and window > 0:
                        skip_delta = max(0, k_total - prev_k_total)
                        prev_k_total = k_total
                        applied_frac = max(0.0, 1.0 - skip_delta / window)
                    goodput_frac = 0.0
                    if elapsed > 0:
                        goodput_frac = (max(0.0, elapsed - wait_delta)
                                        / elapsed) * applied_frac
                    g_goodput.set(goodput_frac)
                    logger.log(
                        f"Epoch {epoch + 1}, Elapsed Time: "
                        f"{time.time() - tick:.3f}, Epoch status: "
                        f"{progress:.4f}, Training loss: "
                        f"{mean_loss:.4f}, "
                        f"Learning rate: {lr:.6f}, Throughput: "
                        f"{clips_per_sec:.1f} clips/s{extra}")
                    # registry feed: ONLY host values the fetch above
                    # already materialized (the tentpole invariant —
                    # no extra device_get, no per-step recording)
                    m_steps.inc(window)
                    g_loss.set(mean_loss)
                    g_lr.set(lr)
                    g_tput.set(clips_per_sec)
                    if guard_on:
                        g_skipped.set(k_total)
                    rec.event("display", step=opt_step, epoch=epoch + 1,
                              loss=float(mean_loss), lr=float(lr),
                              clips_per_sec=clips_per_sec,
                              goodput_fraction=round(goodput_frac, 5),
                              stage=stage_idx,
                              skipped_total=k_total,
                              **({"mfu": round(last_mfu, 5)}
                                 if last_mfu is not None else {}))
                    # anomaly path (host-side): feed the window's mean
                    # step wall time; a spike arms the bounded capture.
                    # The window containing the run's FIRST step is
                    # excluded — its compile time would set the EWMA
                    # baseline several times too high and mask every
                    # real spike for the rest of the run (the ledger
                    # excludes it from compute for the same reason).
                    if (spike_detector is not None and window > 0
                            and (opt_step - window) != opt_step0):
                        spike_detector.observe(elapsed * 1e3 / window,
                                               step=opt_step)
                    # straggler feed: THIS host's window mean step wall
                    # time, same first-window exclusion as the spike
                    # detector (compile time is not skew).  Single-host
                    # runs accumulate but never flag — skew needs a
                    # second host to compare against.
                    if window > 0 and (opt_step - window) != opt_step0:
                        straggler_policy.observe(
                            process_index, elapsed * 1e3 / window,
                            step=opt_step)
                    if (profiler_capture is not None
                            and capture_requested["flag"]):
                        capture_requested["flag"] = False
                        verdict = profiler_capture.arm(reason="sigusr1")
                        logger.log(f"SIGUSR1 profiler capture: {verdict}")
                    # a guarded window with ZERO applied updates displays
                    # nan by construction — that is the breaker's case to
                    # handle, not the halt-on-nan divergence guard's
                    if not (guard_on and np.isnan(mean_loss)):
                        check_finite(mean_loss, opt_step)
                    if (guard_on and cfg.train.skip_rollback_after
                            and consec >= cfg.train.skip_rollback_after):
                        # Circuit breaker: K consecutive non-finite
                        # updates means the guard alone isn't enough (a
                        # poisoned data window, diverged state).  Roll the
                        # WEIGHTS back to the last rotation checkpoint but
                        # keep the CURRENT step counter — it tracks
                        # batches consumed, so the run resumes PAST the
                        # poisoned window instead of replaying it (or
                        # halting, as the pre-breaker NaN guard did).
                        latest = manager.latest_epoch()
                        if latest is None:
                            raise FloatingPointError(
                                f"{consec} consecutive non-finite updates "
                                "and no rotation checkpoint to roll back "
                                "to — halting")
                        # Termination bound: a rollback is only worth
                        # repeating if SOME update applied since the last
                        # one.  Zero applied updates between trips means
                        # the failure is persistent (LR bug, corrupted
                        # hardware, every-step injection), and looping
                        # rollback-skip-rollback would burn the pod
                        # forever — halt like the pre-breaker NaN guard.
                        if last_rollback is not None:
                            applied = ((total_steps - last_rollback[0])
                                       - (k_total - last_rollback[1]))
                            if applied <= 0:
                                raise FloatingPointError(
                                    f"circuit breaker: {consec} consecutive "
                                    "non-finite updates with ZERO applied "
                                    "updates since the previous rollback — "
                                    "the failure is persistent, halting "
                                    "instead of rolling back in a loop")
                        last_rollback = (total_steps, k_total)
                        manager.wait()
                        with rec.span("ckpt.restore", label=int(latest)):
                            restored = manager.restore(latest, state)
                        state = restored.replace(
                            step=jnp.asarray(opt_step, jnp.int32))
                        state = place_state(state)
                        rollbacks += 1
                        m_rollbacks.inc()
                        # rollback-lost attribution (goodput ledger):
                        # applied updates since the restored boundary
                        # save are now discarded — the skipped streak
                        # is already badput, so it doesn't count twice
                        # checkpoint labeled L holds state at epoch L's
                        # start — the plan maps that to a global step
                        # even when stages change the per-epoch count
                        lost = max(0, (opt_step
                                       - plan.epoch_start_step(int(latest))
                                       - consec))
                        rec.event("rollback", step=opt_step,
                                  restored_epoch=int(latest),
                                  consecutive_skips=consec,
                                  lost_updates=lost)
                        consec_dev = None       # fresh weights: reset streak
                        logger.log(
                            f"circuit breaker: {consec} consecutive "
                            f"non-finite updates — restored rotation "
                            f"checkpoint {latest}, resuming at step "
                            f"{opt_step} past the poisoned data window")
                  running_dev = None
                  valid_dev = None
                  window = 0
                  timer.reset()
                  tick = time.time()
                # one drain poll per optimizer step (host-side: a dict
                # read + disarmed-fault check — the host.preempt
                # occurrence count is therefore the step number)
                local_drain = drain.poll(total_steps)
                if multi:
                    # every process evaluates the collective at the SAME
                    # steps (total_steps advances in lockstep), so they
                    # all see the same verdict.  The guard escape opens
                    # only on the cadence hit — the 1-in-sync_every step
                    # where the reducer materializes its verdict on host.
                    stopping = False
                    if total_steps % sync_every == 0:
                        with jax.transfer_guard("allow"):
                            stopping = any_preempted(local_drain)
                else:
                    stopping = local_drain
                if stopping or (max_steps is not None
                                and total_steps >= max_steps):
                  with jax.transfer_guard("allow"):  # checkpoint + exit
                    drained = bool(stopping)
                    if drained:
                        logger.log(
                            f"drain ({drain.source or 'cluster peer'}) — "
                            "checkpointing and exiting"
                            + (" (cluster-coordinated)" if multi else ""))
                    # label/force semantics: stop_save_label (module
                    # top); the planned twin handles per-stage epoch
                    # lengths.  Edge cases pinned in
                    # tests/test_resilience.py + test_train.py
                    label, force = stop_save_label_planned(
                        epoch, opt_step0 + total_steps, plan)
                    # a drain's forced save is badput the preemption
                    # caused: it lands in the ledger's drain bucket
                    # (span INSTEAD of ckpt.save — overlapping both
                    # would double-count against the sum-to-wall pin)
                    with rec.span(
                            "elastic.drain" if drained else "ckpt.save",
                            label=label, forced=force, stage=stage_idx,
                            **({"source": drain.source} if drained
                               else {})):
                        manager.save(label, state, force=force)
                        manager.wait()
                    if process_index == 0:
                        opt_step = opt_step0 + total_steps
                        curriculum.write_stage_stamp(
                            ckpt_dir, spec=cfg.train.curriculum,
                            stage_index=stage_idx,
                            stage=plan.stages[stage_idx],
                            step=opt_step)
                        seg_c, off_c = plan.locate(opt_step)
                        elastic.write_elastic_stamp(
                            ckpt_dir, mesh_shape=dict(mesh.shape),
                            sharding_hash=(placement.hash if model_axis
                                           else ""),
                            step=opt_step, stage_index=stage_idx,
                            batch_offset=seg_c.skip_batches + off_c,
                            drained=drained)
                    last, skips = exit_metrics()
                    return TrainResult(state, total_steps, last,
                                       skips, rollbacks, stage_idx,
                                       drained)
                if seg_done >= seg.n_steps:
                    break       # segment complete (stage boundary or
                                # epoch tail) — drain + re-arm below
              # Deterministic drain at the segment edge: close the
              # prefetch generator so its in-flight decode futures and
              # device puts retire via the loader's finally blocks NOW,
              # not at GC — the old stage's readers must not race the
              # new stage's (and the stage.switch span must not start
              # while they run).
              prefetch.close()
            with jax.transfer_guard("allow"):       # epoch-boundary save
                # the span times the async SUBMIT (Orbax writes in the
                # background); the stop-save span above times a full
                # submit+wait
                with rec.span("ckpt.save", label=epoch + 1, forced=False,
                              stage=stage_idx):
                    manager.save(epoch + 1, state)
                if process_index == 0:
                    opt_step = opt_step0 + total_steps
                    curriculum.write_stage_stamp(
                        ckpt_dir, spec=cfg.train.curriculum,
                        stage_index=stage_idx,
                        stage=plan.stages[stage_idx],
                        step=opt_step)
                    # the topology sidecar rides EVERY save (the pair
                    # must stay in lockstep — check_topology_resume
                    # cross-checks their plan cursors on resume)
                    seg_c, off_c = plan.locate(opt_step)
                    elastic.write_elastic_stamp(
                        ckpt_dir, mesh_shape=dict(mesh.shape),
                        sharding_hash=(placement.hash if model_axis
                                       else ""),
                        step=opt_step, stage_index=stage_idx,
                        batch_offset=seg_c.skip_batches + off_c,
                        drained=False)
    finally:
        manager.wait()
        if cfg.train.faults:
            faults.disarm()     # a config-armed registry dies with the run
        drain.uninstall()
        if prev_usr1 is not None:
            signal.signal(signal.SIGUSR1, prev_usr1)
        if profiler_capture is not None:
            profiler_capture.close()    # flush a mid-capture trace
        rec.event("run.end", steps=total_steps)
        # per-run attribution (obs/goodput.py): partition this run's
        # wall time, export gauges + the GOODPUT snapshot — best-effort,
        # AFTER run.end so the ledger's wall covers the whole run
        ledger_extra = dict(straggler_policy.ledger_extra())
        if last_mfu is not None:
            ledger_extra["mfu"] = round(last_mfu, 5)
        _finalize_goodput_ledger(
            rec, rec_path, run_id, process_index, reg, obs_dir,
            logger.log, extra=ledger_extra or None)
        obs_spans.install(prev_rec)     # this run's stream detaches
        rec.close()
        obs_runctx.set_run_context(*prev_runctx)
        logger.close()
    last, skips = exit_metrics()
    return TrainResult(state, total_steps, last, skips, rollbacks,
                       stage_idx)
