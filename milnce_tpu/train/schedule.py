"""LR schedules.

Cosine-with-warmup matching the reference (utils.py:26-38): linear warmup
over ``num_warmup_steps`` then ``max(0, 0.5*(1 + cos(pi * num_cycles * 2 *
progress)))``, stepped PER BATCH (main_distributed.py:240).  Expressed as
an optax schedule (pure fn of the step) instead of a stateful LambdaLR.

The schedule exists in two evaluation modes sharing one formula:

- ``xp=jnp`` (default): traced into the optimizer via
  ``optax.inject_hyperparams`` — lives on device with the step;
- ``xp=np`` (via :func:`build_host_schedule`): evaluated with numpy on
  the HOST for log-cadence LR display.  ``float(schedule(step))`` of the
  device form blocks the host on the device stream (graftlint GL001 —
  the finding that motivated this split); the numpy twin costs
  nanoseconds and keeps the steady-state ``transfer_guard`` airtight.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from milnce_tpu.config import OptimConfig


def cosine_with_warmup(base_lr: float, num_warmup_steps: int,
                       num_training_steps: int, num_cycles: float = 0.5,
                       xp=jnp):
    def schedule(step):
        step = xp.asarray(step, xp.float32)
        warmup = step / xp.maximum(1.0, num_warmup_steps)
        progress = (step - num_warmup_steps) / xp.maximum(
            1.0, num_training_steps - num_warmup_steps)
        cosine = xp.maximum(
            0.0, 0.5 * (1.0 + xp.cos(xp.pi * num_cycles * 2.0 * progress)))
        return base_lr * xp.where(step < num_warmup_steps, warmup, cosine)

    return schedule


def build_schedule_total(cfg: OptimConfig, total_steps: int, xp=jnp):
    """Schedule over an explicit run-total step count.  The curriculum
    path (train/curriculum.py) computes the total from its step-level
    plan — per-stage batch sizes make ``steps_per_epoch * epochs`` wrong
    there, which would silently stretch/compress warmup and the cosine
    tail.  The schedule stays a pure function of the GLOBAL step, so the
    optimizer state keeps one structure across stages and checkpoints
    stay compatible."""
    return cosine_with_warmup(cfg.lr, cfg.warmup_steps, total_steps,
                              cfg.num_cycles, xp=xp)


def build_schedule(cfg: OptimConfig, steps_per_epoch: int, xp=jnp):
    return build_schedule_total(cfg, steps_per_epoch * cfg.epochs, xp=xp)


def build_host_schedule_total(cfg: OptimConfig, total_steps: int):
    """``step -> float`` twin of :func:`build_schedule_total` computed
    entirely with numpy — no device values touched, so the hot loop's LR
    display never blocks (and never trips the steady-state transfer
    guard)."""
    sched = build_schedule_total(cfg, total_steps, xp=np)

    def host_schedule(step: int) -> float:
        return float(sched(step))

    return host_schedule


def build_host_schedule(cfg: OptimConfig, steps_per_epoch: int):
    """Flat-run convenience over :func:`build_host_schedule_total`."""
    return build_host_schedule_total(cfg, steps_per_epoch * cfg.epochs)
