"""LR schedules.

Cosine-with-warmup matching the reference (utils.py:26-38): linear warmup
over ``num_warmup_steps`` then ``max(0, 0.5*(1 + cos(pi * num_cycles * 2 *
progress)))``, stepped PER BATCH (main_distributed.py:240).  Expressed as
an optax schedule (pure fn of the step) instead of a stateful LambdaLR.
"""

from __future__ import annotations

import jax.numpy as jnp

from milnce_tpu.config import OptimConfig


def cosine_with_warmup(base_lr: float, num_warmup_steps: int,
                       num_training_steps: int, num_cycles: float = 0.5):
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warmup = step / jnp.maximum(1.0, num_warmup_steps)
        progress = (step - num_warmup_steps) / jnp.maximum(
            1.0, num_training_steps - num_warmup_steps)
        cosine = jnp.maximum(
            0.0, 0.5 * (1.0 + jnp.cos(jnp.pi * num_cycles * 2.0 * progress)))
        return base_lr * jnp.where(step < num_warmup_steps, warmup, cosine)

    return schedule


def build_schedule(cfg: OptimConfig, steps_per_epoch: int):
    total = steps_per_epoch * cfg.epochs
    return cosine_with_warmup(cfg.lr, cfg.warmup_steps, total, cfg.num_cycles)
