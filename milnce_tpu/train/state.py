"""Train state + optimizer factory.

Replaces the reference's torch Adam/SGD setup (main_distributed.py:154-157)
with optax; the schedule is folded into the optimizer via
``optax.inject_hyperparams`` so the current LR is observable for logging
(the reference reads ``optimizer.param_groups[0]['lr']``,
main_distributed.py:220).
"""

from __future__ import annotations

from typing import Any

import jax
import optax
from flax import struct

from milnce_tpu.config import OptimConfig


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any


def build_optimizer(cfg: OptimConfig, schedule) -> optax.GradientTransformation:
    if cfg.name == "adam":
        opt = optax.inject_hyperparams(optax.adam)(learning_rate=schedule)
    elif cfg.name == "sgd":
        opt = optax.inject_hyperparams(optax.sgd)(
            learning_rate=schedule, momentum=cfg.momentum)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    return opt


def create_train_state(variables, optimizer) -> TrainState:
    import jax.numpy as jnp

    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=optimizer.init(variables["params"]),
    )


def current_lr(state: TrainState) -> float:
    """Read the LR that the last/next step uses (for n_display logging)."""
    return float(state.opt_state.hyperparams["learning_rate"])
