"""Train state + optimizer factory.

Replaces the reference's torch Adam/SGD setup (main_distributed.py:154-157)
with optax; the schedule is folded into the optimizer via
``optax.inject_hyperparams`` so the current LR is observable for logging
(the reference reads ``optimizer.param_groups[0]['lr']``,
main_distributed.py:220).
"""

from __future__ import annotations

from typing import Any

import jax
import optax
from flax import struct

from milnce_tpu.config import OptimConfig


@struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    batch_stats: Any
    opt_state: Any


def _trainable_mask(params):
    """False for the frozen word2vec table: its lookup is under
    ``stop_gradient`` (reference parity, s3dg.py:199-200), so its grads
    are structural zeros — optimizer moments for the ~20M-entry table
    (~160 MB of HBM at the full vocab, 2x for Adam) would be waste the
    reference never pays (torch's lazy per-param state never
    materializes for no-grad params)."""
    def trainable(path, _):
        return not any(getattr(p, "key", None) == "word_embd" for p in path)

    return jax.tree_util.tree_map_with_path(trainable, params)


def build_optimizer(cfg: OptimConfig, schedule) -> optax.GradientTransformation:
    if cfg.name == "adam":
        def make_adam(learning_rate):
            return optax.masked(optax.adam(learning_rate), _trainable_mask)

        opt = optax.inject_hyperparams(make_adam)(learning_rate=schedule)
    elif cfg.name == "sgd":
        def make_sgd(learning_rate, momentum):
            return optax.masked(optax.sgd(learning_rate, momentum=momentum),
                                _trainable_mask)

        opt = optax.inject_hyperparams(make_sgd)(
            learning_rate=schedule, momentum=cfg.momentum)
    else:
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    return opt


def create_train_state(variables, optimizer) -> TrainState:
    import jax.numpy as jnp

    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=variables["params"],
        batch_stats=variables.get("batch_stats", {}),
        opt_state=optimizer.init(variables["params"]),
    )


def per_device_state_bytes(state) -> dict:
    """``{device: bytes}`` of a PLACED train state — the FSDP storage
    accounting.  Replicated leaves count full-size on every device;
    model-axis-sharded leaves (parallel/sharding_map.py) count only
    their shard, so on a 2-D mesh the per-chip total visibly drops by
    the sharded fraction (asserted in tests/test_train_2d.py; logged at
    startup by train/loop.py).  Pure host-side inspection of committed
    arrays (``addressable_shards``) — no transfer, no device compute."""
    out: dict = {}
    for leaf in jax.tree_util.tree_leaves(state):
        for sh in getattr(leaf, "addressable_shards", ()):
            out[sh.device] = out.get(sh.device, 0) + sh.data.nbytes
    return out


# NOTE: the old ``current_lr(state)`` helper (read the injected
# hyperparam back from DEVICE) is gone: it was a host sync by
# construction and had no remaining callers — LR display everywhere
# uses the numpy host schedule (train/schedule.py build_host_schedule),
# which never touches device state.
