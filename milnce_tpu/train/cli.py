"""Training entry point: ``python -m milnce_tpu.train.cli --preset small``.

Replaces all three reference launchers (main_distributed.py, train.py,
train_small.py — the latter two being near-duplicate clones, one of them
import-broken, SURVEY.md §2.4) with one CLI over the typed config."""

from __future__ import annotations

from milnce_tpu.config import parse_cli
from milnce_tpu.train.loop import run_training


def main(argv=None):
    cfg = parse_cli(argv, description="milnce-tpu trainer")
    result = run_training(cfg)
    print(f"done: {result.steps} steps, final loss {result.last_loss:.4f}")


if __name__ == "__main__":
    main()
