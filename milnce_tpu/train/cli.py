"""Training entry point: ``python -m milnce_tpu.train.cli --preset small``.

Replaces all three reference launchers (main_distributed.py, train.py,
train_small.py — the latter two being near-duplicate clones, one of them
import-broken, SURVEY.md §2.4) with one CLI over the typed config.

Exit status: 0 on completion; ``DRAINED_EXIT_CODE`` (75, EX_TEMPFAIL)
when the run drained on a preemption signal — the checkpoint + stamps
are already on disk through the atomic tmp+rename discipline, and the
orchestrator's contract is to rerun with ``--train.resume true`` (on
any mesh shape whose batches divide; MIGRATING.md "Checkpoint
resharding")."""

from __future__ import annotations

from milnce_tpu.config import parse_cli
from milnce_tpu.elastic import DRAINED_EXIT_CODE
from milnce_tpu.train.loop import run_training


def main(argv=None):
    cfg = parse_cli(argv, description="milnce-tpu trainer")
    result = run_training(cfg)
    if result.drained:
        print(f"drained: {result.steps} steps, final loss "
              f"{result.last_loss:.4f} — checkpoint saved, resume with "
              f"--train.resume true (exit {DRAINED_EXIT_CODE})")
        raise SystemExit(DRAINED_EXIT_CODE)
    print(f"done: {result.steps} steps, final loss {result.last_loss:.4f}")


if __name__ == "__main__":
    main()
