"""Curriculum schedule: staged (frames, resolution, batch) training.

The paper's pretraining burns most of its FLOPs on full-rate clips from
step 0; a curriculum runs early training at low fps/resolution and only
graduates to the full operating point late (PAPERS.md: Arachne).  This
module is the pure-host half of that: parse ``train.curriculum``, turn
it into an exact step-level plan, and pre-flight every stage's memory
footprint before anything traces.  train/loop.py consumes the plan; the
module itself touches no devices (the pre-flight traces abstractly).

Grammar (``train.curriculum``, same loud-fail style as
``parse_conv_impl_map`` / the serving tier specs): stages separated by
``;``, each a comma list of ``key=value`` with keys ``num_frames``,
``resolution``, ``batch_size`` (optional — defaults to
``train.batch_size``), and exactly one of ``until_step`` /
``until_epoch`` on every stage but the last (the final stage is
open-ended and runs to the end of training)::

    num_frames=4,resolution=64,until_step=1000;\
    num_frames=8,resolution=112,until_step=3000;\
    num_frames=32,resolution=224

A spec containing no ``=`` is read as a JSON artifact path holding the
stage list (optionally under a ``"curriculum"`` key).  Unknown keys,
non-positive values, a bounded final stage, an unbounded middle stage,
or boundaries that leave a stage unreachable all raise ``ValueError``
naming the stage — never a silent fallback.

Plan semantics (:func:`plan_curriculum`): the plan simulates the epoch
loop exactly — ``until_epoch=E`` ends a stage when the epoch counter
reaches E; ``until_step=S`` ends it when the global optimizer step
reaches S (mid-epoch allowed).  A mid-epoch switch re-arms the loader
with ``skip_batches = ceil(samples_consumed / new_batch)`` so no sample
is trained twice in an epoch (a partial batch of samples may be dropped
at the boundary — the same drop-remainder semantics as the epoch tail).
The flat (no-curriculum) path is the SAME machinery with a single
open-ended stage built from the run config, so the loop has one code
path and the flat math (resume offsets, epoch progress, warmup totals)
is pinned equal to the historical helpers by tests/test_curriculum.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass
from typing import Optional

_STAGE_KEYS = ("num_frames", "resolution", "batch_size",
               "until_step", "until_epoch")

#: checkpoint sidecar (train/loop.py writes it next to the Orbax
#: rotation at every save) — Orbax's CheckpointManager carries no
#: metadata channel, and the resume guard needs the writing run's stage
#: shape to refuse a curriculum checkpoint resumed with the schedule
#: silently removed.
STAMP_NAME = "CURRICULUM_STAMP.json"


@dataclass(frozen=True)
class CurriculumStage:
    num_frames: int
    resolution: int
    batch_size: int
    until_step: Optional[int] = None    # stage ends when the global
    #                                     optimizer step reaches this
    until_epoch: Optional[int] = None   # stage ends entering this epoch

    def label(self) -> str:
        return (f"{self.num_frames}f@{self.resolution} "
                f"batch {self.batch_size}")


@dataclass(frozen=True)
class StageSegment:
    """One contiguous run of steps of one stage inside one epoch."""
    stage: int          # index into the plan's stages
    epoch: int
    skip_batches: int   # loader.epoch(epoch, skip_batches=...) offset
    start_step: int     # global optimizer step of the segment's first step
    n_steps: int

    @property
    def end_step(self) -> int:
        return self.start_step + self.n_steps


def parse_curriculum(spec: str, *,
                     default_batch_size: Optional[int] = None) -> list:
    """``train.curriculum`` -> ordered ``CurriculumStage`` list ('' ->
    []).  Inline grammar or a JSON artifact path — see module docstring.
    Every malformed input names its stage and raises; nothing falls back
    silently."""
    if not spec:
        return []
    if "=" in spec:
        raw = []
        for part in spec.split(";"):
            if not part.strip():
                continue
            d: dict = {}
            for item in part.split(","):
                if not item.strip():
                    continue
                if "=" not in item:
                    raise ValueError(
                        f"curriculum stage {len(raw)}: item {item!r} is "
                        "not key=value (keys: "
                        f"{', '.join(_STAGE_KEYS)})")
                k, v = item.split("=", 1)
                d[k.strip()] = v.strip()
            raw.append(d)
    else:
        if not os.path.exists(spec):
            raise ValueError(
                f"train.curriculum={spec!r}: no '=' so it must be a JSON "
                "artifact path, but no such file exists")
        with open(spec) as fh:
            payload = json.load(fh)
        raw = (payload.get("curriculum", payload)
               if isinstance(payload, dict) else payload)
        if not isinstance(raw, list):
            raise ValueError(
                f"curriculum artifact {spec}: expected a JSON list of "
                "stage objects (or {'curriculum': [...]}), got "
                f"{type(raw).__name__}")
    if not raw:
        return []
    stages = []
    for i, d in enumerate(raw):
        if not isinstance(d, dict):
            raise ValueError(f"curriculum stage {i}: expected an object "
                             f"of stage keys, got {type(d).__name__}")
        unknown = sorted(set(d) - set(_STAGE_KEYS))
        if unknown:
            raise ValueError(
                f"curriculum stage {i}: unknown key(s) "
                f"{', '.join(unknown)} (valid: {', '.join(_STAGE_KEYS)})")
        vals = {}
        for k, v in d.items():
            try:
                vals[k] = int(v)
            except (TypeError, ValueError):
                raise ValueError(
                    f"curriculum stage {i}: {k}={v!r} is not an integer")
            if vals[k] <= 0:
                raise ValueError(
                    f"curriculum stage {i}: {k}={vals[k]} must be > 0")
        for req in ("num_frames", "resolution"):
            if req not in vals:
                raise ValueError(
                    f"curriculum stage {i}: missing required key {req!r}")
        if "batch_size" not in vals:
            if default_batch_size is None:
                raise ValueError(
                    f"curriculum stage {i}: no batch_size and no default "
                    "to inherit")
            vals["batch_size"] = int(default_batch_size)
        has_s = "until_step" in vals
        has_e = "until_epoch" in vals
        last = i == len(raw) - 1
        if has_s and has_e:
            raise ValueError(
                f"curriculum stage {i}: sets BOTH until_step and "
                "until_epoch — exactly one bounds a non-final stage")
        if last and (has_s or has_e):
            raise ValueError(
                f"curriculum stage {i}: the final stage must be "
                "open-ended (it runs to the end of training) but sets "
                f"until_{'step' if has_s else 'epoch'}")
        if not last and not (has_s or has_e):
            raise ValueError(
                f"curriculum stage {i}: needs until_step or until_epoch "
                "(only the final stage is open-ended)")
        stages.append(CurriculumStage(**vals))
    return stages


def flat_stages(data_cfg, batch_size: int) -> list:
    """The no-curriculum run as a single open-ended stage — the loop's
    one code path covers both."""
    return [CurriculumStage(num_frames=data_cfg.num_frames,
                            resolution=data_cfg.video_size,
                            batch_size=int(batch_size))]


def stage_data_config(data_cfg, stage: CurriculumStage):
    """Per-stage DataConfig: only the decode shapes change; everything
    else (candidates, words, decode policy) rides the run config."""
    return dataclasses.replace(data_cfg, num_frames=stage.num_frames,
                               video_size=stage.resolution)


def stage_config(cfg, stage: CurriculumStage):
    """Full Config with the data shapes swapped to ``stage``'s — what
    build_source consumes when the loop re-arms the pipeline at a
    boundary."""
    return dataclasses.replace(cfg, data=stage_data_config(cfg.data, stage))


@dataclass
class CurriculumPlan:
    stages: tuple
    segments: tuple     # StageSegment, ordered by start_step
    num_samples: int
    epochs: int
    total_steps: int

    def segments_for_epoch(self, epoch: int) -> list:
        return [s for s in self.segments if s.epoch == epoch]

    def locate(self, step: int):
        """(segment, offset) containing global step ``step`` — the NEXT
        step to run, so a resume from a restored counter lands exactly
        where the saving run stopped.  ``step >= total_steps`` pins to
        the end of the final segment (a finished run resumes to no-op)."""
        for seg in self.segments:
            if seg.start_step <= step < seg.end_step:
                return seg, step - seg.start_step
        if step >= self.total_steps and self.segments:
            last = self.segments[-1]
            return last, last.n_steps
        raise ValueError(f"step {step} outside the plan "
                         f"(total_steps={self.total_steps})")

    def stage_at(self, step: int) -> int:
        return self.locate(step)[0].stage

    def epoch_start_step(self, epoch: int) -> int:
        segs = self.segments_for_epoch(epoch)
        return segs[0].start_step if segs else self.total_steps

    def epoch_end_step(self, epoch: int) -> int:
        segs = self.segments_for_epoch(epoch)
        return segs[-1].end_step if segs else self.total_steps

    def epoch_steps(self, epoch: int) -> int:
        return self.epoch_end_step(epoch) - self.epoch_start_step(epoch)


def plan_curriculum(stages, num_samples: int, epochs: int) -> CurriculumPlan:
    """Simulate the epoch loop over ``stages`` into an exact step-level
    plan.  Raises when a stage can never run (its predecessor's boundary
    lies past the end of training, or boundaries are non-monotone) —
    a schedule that silently never reaches full resolution is the worst
    possible failure mode of a curriculum."""
    stages = tuple(stages)
    if not stages:
        raise ValueError("plan_curriculum needs at least one stage")
    segments = []
    step = 0
    si = 0
    n = len(stages)
    for epoch in range(epochs):
        consumed = 0            # samples this epoch has trained on
        while True:
            # epoch-counter boundaries resolve at epoch entry
            while (si + 1 < n and stages[si].until_epoch is not None
                   and epoch >= stages[si].until_epoch):
                si += 1
            st = stages[si]
            spe = num_samples // st.batch_size
            if spe <= 0:
                raise ValueError(
                    f"curriculum stage {si} ({st.label()}): batch_size "
                    f"exceeds the dataset ({num_samples} samples)")
            bounded = si + 1 < n and st.until_step is not None
            if bounded and st.until_step <= step:
                si += 1         # boundary already passed (non-monotone
                continue        # specs drain here into "unreachable")
            skip = -(-consumed // st.batch_size)    # ceil div
            avail = spe - skip
            if bounded:
                avail = min(avail, st.until_step - step)
            if avail > 0:
                segments.append(StageSegment(si, epoch, skip, step, avail))
                step += avail
                consumed += avail * st.batch_size
            if bounded and step >= st.until_step:
                si += 1         # mid-epoch switch: stay in this epoch
                continue
            break               # epoch exhausted at the current stage
    reached = {seg.stage for seg in segments}
    for i, st in enumerate(stages):
        if i not in reached:
            raise ValueError(
                f"curriculum stage {i} ({st.label()}) is unreachable — "
                f"earlier boundaries consume the whole run ({step} steps "
                f"over {epochs} epoch(s)); lower until_step/until_epoch "
                "or raise optim.epochs")
    return CurriculumPlan(stages=stages, segments=tuple(segments),
                          num_samples=num_samples, epochs=epochs,
                          total_steps=step)


# ---------------------------------------------------------------------
# mem_plan pre-flight: refuse an over-budget stage BEFORE it traces
# ---------------------------------------------------------------------

def hbm_budget_bytes() -> Optional[int]:
    """Per-chip HBM budget the stage pre-flight gates against:
    ``MILNCE_HBM_GIB`` (explicit, wins — also how CPU runs arm the gate)
    else the backend's reported ``bytes_limit``; ``None`` disarms the
    pre-flight (hermetic CPU default)."""
    env = os.environ.get("MILNCE_HBM_GIB")
    if env:
        return int(float(env) * 2 ** 30)
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:  # graftlint: disable=GL007(best-effort backend probe — a backend without memory_stats (CPU, some tunnels) just disarms the pre-flight, the documented None contract; nothing to record)
        pass
    return None


def preflight_stages(step_fn, state, stages, *, num_candidates: int,
                     max_words: int, budget_bytes: int,
                     guard_on: bool = True) -> list:
    """Static-plan every stage's step (analysis/memplan.py, the PR 8
    autotune pre-flight) against ``budget_bytes`` and REFUSE the run if
    any stage's predicted per-chip peak doesn't fit — at startup, with
    the stage and top-3 contributors named, never an OOM mid-run.

    Traces abstractly (``jax.make_jaxpr`` over ShapeDtypeStructs): no
    device bytes move and the jitted step's executable cache stays
    empty, so refusal genuinely happens *before* any stage compiles.
    A planner crash (vs. an over-budget verdict) downgrades to an
    advisory note — the gate must not turn an analyzable-but-odd config
    into a false refusal.  Returns the per-stage verdict strings for
    the run log."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.analysis import memplan
    from milnce_tpu.train.step import STATE_DONATION_ARGNUMS

    del guard_on    # signature symmetry with the loop; the plan traces
    #                 whatever step_fn the run built (guarded or not)
    notes = []
    for i, st in enumerate(stages):
        b = st.batch_size
        args = (state,
                jax.ShapeDtypeStruct(
                    (b, st.num_frames, st.resolution, st.resolution, 3),
                    jnp.uint8),
                jax.ShapeDtypeStruct((b * num_candidates, max_words),
                                     jnp.int32),
                jax.ShapeDtypeStruct((b,), jnp.float32))
        entry = f"curriculum stage {i} ({st.label()})"
        try:
            plan = memplan.plan_fn(
                step_fn, args, argnames=("state", "video", "text", "start"),
                donate_argnums=STATE_DONATION_ARGNUMS, entry=entry)
        except Exception as exc:        # planner limitation, not verdict
            notes.append(f"{entry}: pre-flight planner failed "
                         f"({type(exc).__name__}: {exc}) — advisory only")
            continue
        fits, msg = memplan.budget_verdict(plan, budget_bytes / 2 ** 30)
        notes.append(msg)
        if not fits:
            raise ValueError(
                f"curriculum pre-flight refused {entry}: {msg} — shrink "
                "the stage's batch/resolution, enable remat/grad_accum, "
                "or raise the budget (MILNCE_HBM_GIB)")
    return notes


# ---------------------------------------------------------------------
# checkpoint stage stamp: the resume-compatibility guard's source of
# truth (satellite 3 — a curriculum checkpoint resumed with the
# schedule removed must fail LOUDLY, naming shapes, not silently train
# at full res)
# ---------------------------------------------------------------------

def write_stage_stamp(ckpt_dir: str, *, spec: str, stage_index: int,
                      stage: CurriculumStage, step: int) -> None:
    """Atomic sidecar write next to the Orbax rotation (process 0 only —
    the caller gates).  Overwritten at every save: the stamp describes
    the LATEST saved state, which is exactly what restore_latest hands
    back."""
    payload = {
        "schema": "milnce.curriculum/v1",
        "curriculum": spec,
        "stage": int(stage_index),
        "num_frames": int(stage.num_frames),
        "resolution": int(stage.resolution),
        "batch_size": int(stage.batch_size),
        "step": int(step),
    }
    path = os.path.join(ckpt_dir, STAMP_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1)
    os.replace(tmp, path)


def read_stage_stamp(ckpt_dir: str) -> Optional[dict]:
    path = os.path.join(ckpt_dir, STAMP_NAME)
    if not os.path.exists(path):
        return None         # pre-curriculum checkpoint: no guard to run
    with open(path) as fh:
        return json.load(fh)


def check_resume_compatible(stamp: Optional[dict], *, curriculum_spec: str,
                            flat_frames: int, flat_resolution: int,
                            flat_batch: int) -> None:
    """Refuse resuming a curriculum checkpoint with ``train.curriculum``
    removed.  The TrainState is shape-invariant across stages, so
    NOTHING else would fail — the run would silently continue at the
    flat config's full shape with the schedule's intent discarded."""
    if not stamp or not stamp.get("curriculum"):
        return      # flat checkpoint (or pre-curriculum): any config ok
    if curriculum_spec:
        return      # schedule present; the plan's locate() places us
    saved = (f"{stamp.get('num_frames')}f@{stamp.get('resolution')} "
             f"batch {stamp.get('batch_size')}")
    flat = f"{flat_frames}f@{flat_resolution} batch {flat_batch}"
    raise ValueError(
        "checkpoint was written by a curriculum run (stage "
        f"{stamp.get('stage')}: {saved}, schedule "
        f"{stamp.get('curriculum')!r}, step {stamp.get('step')}) but "
        "train.curriculum is unset — resuming would silently train at "
        f"the flat shape {flat} instead of the schedule's; restore with "
        "the original train.curriculum (or a deliberate replacement)")
