"""The jitted distributed train step.

One SPMD program replaces the reference's whole per-batch runtime
(TrainOneBatch, main_distributed.py:226-241): H2D copy + ``/255``
normalize + forward both towers + NCCL all-gather + MIL-NCE + DDP
all-reduce backward + Adam/SGD + scheduler step all fuse into a single
``shard_map``-ped XLA computation over the data mesh axis:

- batch arrives **uint8** and is normalized on device (parity with
  main_distributed.py:227-230; uint8 transfer = 4x less host->HBM
  traffic);
- global negatives: ``lax.all_gather`` inside the loss
  (milnce_tpu.losses.milnce) — the collective rides ICI;
- gradient reduction: explicit ``lax.psum`` (what DDP's bucketed
  all-reduce does implicitly, main_distributed.py:91);
- BatchNorm running stats are ``pmean``-merged across shards each step
  (the reference keeps per-GPU stats and checkpoints rank-0's,
  README.md:13 — merging is the same cost and strictly less arbitrary);
- the LR schedule is a pure function of ``state.step``
  (utils.py:26-38), no separate scheduler object.

2-D ``(data, model)`` mesh (ROADMAP item 2, SNIPPETS.md [1]-[3]): pass
``state_specs`` (a TrainState of PartitionSpec from
``parallel.sharding_map.state_partition_specs``) plus ``model_axis`` and
the step goes FSDP: the batch shards over BOTH axes (every chip is a
data shard — global-batch semantics are identical to the 1-D mesh, so
local BN needs no sync), large params arrive as model-axis shards and
are all_gathered per leaf right before the forward, and the grad
reduction runs per leaf — ``psum_scatter`` over the model axis (the
reduce-scatter half of the FSDP pair) + ``psum`` over data for sharded
leaves, a plain both-axes ``psum`` for replicated ones.  Per-leaf
reductions are independent collectives, so XLA's latency-hiding
scheduler can overlap each with the remainder of the backward instead
of draining into one terminal fused psum (``overlap_grad_reduce``).
The optimizer update then runs on the LOCAL shards: Adam moments for a
sharded kernel never materialize beyond ``1/model_parallel_size`` per
chip.  Collective counts for both 2-D steps are pinned in
analysis/trace_invariants.py (``train_step_milnce_2d``,
``grad_cache_2d``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from milnce_tpu.losses.milnce_chunked import build_milnce_loss
from milnce_tpu.parallel.compat import donation_argnums, shard_map
from milnce_tpu.resilience import faults
from milnce_tpu.train.state import TrainState

# The train-step donation contract, in ONE place: argument 0 (the
# TrainState) is consumed and returned, so its buffers are donated on
# accelerator backends (compat.donation_argnums gates CPU off).  The
# graftlint Pass 4 donation audit (analysis/memplan.py GL014) reads this
# as the declared TPU intent — a step factory that stops donating the
# state, or a new large aliasable argument left undonated, fails there.
STATE_DONATION_ARGNUMS = (0,)


def _apply_grad_poison(grads, step):
    """Device-side ``grad.nonfinite`` fault site: when armed at BUILD
    time, multiply the reduced gradients by NaN on scheduled optimizer
    steps (``state.step + 1`` is the 1-based occurrence index — see
    resilience/faults.py).  The schedule is baked into the trace as pure
    jnp ops on ``state.step``: deterministic, no host sync, and adds
    nothing at all when disarmed."""
    spec = faults.device_schedule("grad.nonfinite")
    if spec is None:
        return grads
    n = step + 1
    if spec.mode == "all":
        hit = jnp.bool_(True)
    elif spec.mode == "every":
        hit = (n % spec.every) == 0
    else:
        hit = jnp.any(n == jnp.asarray(spec.at, jnp.int32))
    poison = jnp.where(hit, jnp.float32(jnp.nan), jnp.float32(1.0))
    return jax.tree_util.tree_map(lambda g: g * poison.astype(g.dtype), grads)


def _all_finite(tree):
    """Scalar bool: every leaf of ``tree`` is all-finite.  Computed on
    the already-reduced (replicated) gradients, so no collective is
    needed and every shard reaches the same verdict."""
    ok = jnp.bool_(True)
    for leaf in jax.tree_util.tree_leaves(tree):
        ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def _select_tree(ok, new, old):
    """Leaf-wise ``jnp.where(ok, new, old)`` — the skip-update select of
    the finite guard (params / opt_state / batch_stats keep their
    pre-step values on a non-finite gradient)."""
    return jax.tree_util.tree_map(lambda n, o: jnp.where(ok, n, o), new, old)


def _gather_params(params, param_specs, model_axis):
    """FSDP gather: local model-axis shards -> full parameters, one
    ``all_gather`` per SHARDED leaf (replicated leaves pass through).
    Sits right before the forward so XLA can overlap each gather with
    compute on already-gathered layers."""
    from milnce_tpu.parallel import sharding_map as smap

    def gather(leaf, spec):
        d = smap.sharded_dim(spec, model_axis)
        if d is None:
            return leaf
        return lax.all_gather(leaf, model_axis, axis=d, tiled=True)

    return smap.map_with_specs(gather, params, param_specs)


def _reduce_grads_2d(grads, param_specs, data_axis, model_axis,
                     mesh_size: int, mean: bool, overlap: bool):
    """Cross-mesh gradient reduction for the 2-D step: full per-device
    grads -> fully-reduced LOCAL-shard grads.

    Sharded leaf (model@d): ``psum_scatter`` over the model axis along d
    (each chip keeps only ITS shard of the summed grad — the
    reduce-scatter half of the FSDP pair; its transpose-twin all_gather
    sits in :func:`_gather_params`) then ``psum`` over data.  Replicated
    leaf: one psum over both axes.  ``mean=True`` (the DTW family's
    pmean semantics) divides by the total mesh size after summing.

    ``overlap=True`` emits the replicated-leaf psums per leaf too, so
    every reduction is an independent collective the scheduler can
    overlap with the rest of the backward; ``overlap=False`` fuses the
    replicated subset into one terminal tree psum (the 1-D step's
    pinned shape) — sharded leaves are per-leaf either way, their
    scatter dimension differs."""
    from milnce_tpu.parallel import sharding_map as smap

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    specs = smap.spec_leaves(param_specs)
    assert len(leaves) == len(specs), (len(leaves), len(specs))
    out: list = [None] * len(leaves)
    fused_idx: list = []
    for i, (g, sp) in enumerate(zip(leaves, specs)):
        d = smap.sharded_dim(sp, model_axis)
        if d is not None:
            g = lax.psum_scatter(g, model_axis, scatter_dimension=d,
                                 tiled=True)
            out[i] = lax.psum(g, data_axis)
        elif overlap:
            out[i] = lax.psum(g, (data_axis, model_axis))
        else:
            fused_idx.append(i)
    if fused_idx:
        fused = lax.psum(tuple(leaves[i] for i in fused_idx),
                         (data_axis, model_axis))
        for i, g in zip(fused_idx, fused):
            out[i] = g
    if mean:
        out = [g / mesh_size for g in out]
    return treedef.unflatten(out)


def _uniform_finite_verdict(ok, model_axis):
    """The finite guard's verdict must be CLUSTER-UNIFORM, and on the
    2-D mesh each model column inspects only ITS shard of the reduced
    grads — a NaN landing in one column's shard would skip the update
    there and apply it elsewhere, silently desyncing the replicas.  One
    scalar psum over the model axis makes every column see every
    column's verdict.  (The data axis needs nothing: post-psum grads
    are identical along it.)"""
    bad = lax.psum((~ok).astype(jnp.float32), model_axis)
    return bad == 0


def _sequence_loss(loss_cfg, v_seq, t_seq, start, data_axis):
    """DTW-family losses on mesh-gathered sequence embeddings.

    The fork's losses score the FULL gathered batch on every rank
    (loss.py:20-134 after the all-gather at train.py:217-219); we gather
    over the mesh axis and compute the identical replicated loss."""
    from milnce_tpu.losses.dtw_losses import (cdtw_batch_loss, sdtw_3_loss,
                                              sdtw_cidm_loss,
                                              sdtw_negative_loss)

    v_all = lax.all_gather(v_seq, data_axis, axis=0, tiled=True)
    t_all = lax.all_gather(t_seq, data_axis, axis=0, tiled=True)
    start_all = lax.all_gather(start, data_axis, axis=0, tiled=True)
    common = dict(backend=getattr(loss_cfg, "sdtw_backend", "scan"),
                  dist=getattr(loss_cfg, "sdtw_dist", ""),
                  bandwidth=getattr(loss_cfg, "sdtw_bandwidth", 0))
    if loss_cfg.sdtw_gamma is not None:
        # None = each loss function's own reference-default gamma
        # (cdtw 1e-5, sdtw_* 0.1 — encoded in their signatures)
        common["gamma"] = loss_cfg.sdtw_gamma
    dispatch = {
        "cdtw": lambda: cdtw_batch_loss(v_all, t_all, **common),
        "sdtw_cidm": lambda: sdtw_cidm_loss(
            v_all, t_all, start_all, sigma=loss_cfg.cidm_sigma,
            lam=loss_cfg.cidm_lambda, **common),
        "sdtw_negative": lambda: sdtw_negative_loss(v_all, t_all, **common),
        "sdtw_3": lambda: sum(sdtw_3_loss(
            v_all, t_all,
            pair_chunk=getattr(loss_cfg, "sdtw_pair_chunk", 0), **common)),
    }
    # one source of truth: a loss added here without a KNOWN_LOSSES entry
    # (or vice versa) fails loudly at first trace, not per-name
    assert set(dispatch) == set(KNOWN_LOSSES) - {"milnce"}, (
        "sequence-loss dispatch and KNOWN_LOSSES diverged")
    return dispatch[loss_cfg.name]()


KNOWN_LOSSES = ("milnce", "cdtw", "sdtw_cidm", "sdtw_negative", "sdtw_3")


def _check_loss_name(loss_cfg) -> str:
    """Reject a bad loss name at step-BUILD time: inside the traced step
    the error would only surface after a full model trace (and on a real
    cluster, after an expensive XLA compile)."""
    name = getattr(loss_cfg, "name", "milnce")
    if name not in KNOWN_LOSSES:
        raise ValueError(f"unknown loss {name!r} (expected one of "
                         f"{', '.join(KNOWN_LOSSES)})")
    return name


def make_grad_cache_step(model, optimizer, mesh: Mesh,
                         micro_batches: int, data_axis: str = "data",
                         donate: bool = True, loss_cfg=None,
                         finite_guard: bool = False, state_specs=None,
                         model_axis=None, overlap_grad_reduce: bool = True):
    """Two-pass embedding-cache train step (GradCache-style) for every
    batch-contrastive loss: MIL-NCE and the DTW family.

    Contrastive losses don't decompose across plain gradient-accumulation
    microbatches — every clip must score against EVERY other clip in the
    effective batch.  The reference solved this with hardware (global
    batch 8192 across 64 TPUs, README.md:98-105); this step solves it in
    one SPMD program so the same recipe runs on any mesh size:

    1. embed all M microbatches under ``lax.scan`` (activations for one
       microbatch live at a time);
    2. compute the mesh-global loss and its gradient w.r.t. the CACHED
       embeddings — cheap: pooled (B, D) for MIL-NCE, sequence
       (B, T', D) for the DTW family (T' = temporal extent after the
       trunk, 8 frames -> 4);
    3. re-forward each microbatch seeding its VJP with the cached
       embedding gradients, accumulating parameter gradients.

    Cost: one extra forward (the pass-2 re-forward) — the same trade
    ``remat`` makes, but at 1/M activation memory with exact full-batch
    negatives/alignment pairs.  Each microbatch computes its own
    BatchNorm statistics, so a microbatch behaves exactly like an extra
    data-parallel shard with local BN (the reference's semantics,
    README.md:13): ``M microbatches x N chips == 1 microbatch x M*N
    chips`` to float tolerance (pinned in tests/test_train.py for both
    loss families).

    Gradient reduction follows make_train_step: ``psum`` for MIL-NCE
    (per-shard partial sums), ``pmean`` for the DTW family (the gathered
    loss is replicated on every shard, so the all_gather transpose
    already accumulates a mesh-size factor into the embedding grads).

    The cross-mesh reduction happens ONCE per optimizer step, AFTER the
    pass-2 scan has accumulated all M microbatches' local parameter
    grads — never per microbatch (a reduction inside the scan body
    would pay the collective M times for the same bytes: the ~25%
    ga=8 throughput hole BENCH_NOTES.md records).  The property is
    pinned structurally: the ``scan-reduction-free`` trace invariant
    asserts no collective primitive in any scan body of this program
    (analysis/trace_invariants.py).  With ``state_specs``/``model_axis``
    the same program runs FSDP on the 2-D mesh (module docstring):
    params gather once BEFORE pass 1, both scans run on the gathered
    tree, and the once-per-step reduction becomes the per-leaf
    psum_scatter+psum of :func:`_reduce_grads_2d`.
    """
    assert micro_batches > 1, "use make_train_step for micro_batches=1"
    loss_name = _check_loss_name(loss_cfg)
    # impl selection (dense cube / chunked stream / auto) resolves at
    # BUILD time from LossConfig; 'dense' (and loss_cfg=None) keeps the
    # traced program byte-identical to the pre-chunked step
    milnce_fn = build_milnce_loss(loss_cfg) if loss_name == "milnce" else None
    mesh_size = _check_2d_args(mesh, data_axis, model_axis, state_specs)
    fsdp = model_axis is not None
    batch_axes = (data_axis, model_axis) if fsdp else data_axis
    compute_dtype = jnp.dtype(getattr(model, "dtype", jnp.float32))

    def local_step(state: TrainState, video_u8, text_ids, start):
        b = video_u8.shape[0]
        assert b % micro_batches == 0, (b, micro_batches)
        bm = b // micro_batches
        k_rows = text_ids.shape[0] // b
        vids = video_u8.reshape((micro_batches, bm) + video_u8.shape[1:])
        txts = text_ids.reshape((micro_batches, bm * k_rows)
                                + text_ids.shape[1:])
        # FSDP: gather the full params ONCE, outside both scans — a
        # gather inside a scan body would re-ship every sharded kernel
        # per microbatch (and break the scan-reduction-free invariant)
        full_params = (_gather_params(state.params, state_specs.params,
                                      model_axis)
                       if fsdp else state.params)

        def fwd(params, batch_stats, vu8, tids):
            video = vu8.astype(compute_dtype) / jnp.asarray(255, compute_dtype)
            mode = {} if loss_name == "milnce" else {"mode": "sequence"}
            return model.apply({"params": params, "batch_stats": batch_stats},
                               video, tids, train=True,
                               mutable=["batch_stats"], **mode)

        # pass 1: embed every microbatch, cache embeddings only
        def embed_one(_, xs):
            vu8, tids = xs
            (v, t), mutated = fwd(full_params, state.batch_stats, vu8, tids)
            return None, (v, t, mutated["batch_stats"])

        _, (v_mb, t_mb, stats_mb) = lax.scan(embed_one, None, (vids, txts))
        # (M, bm, ...) -> (b, ...): pooled (b, D) or sequence (b, T', D)
        v_local = v_mb.reshape((b,) + v_mb.shape[2:])
        t_local = t_mb.reshape((b * k_rows,) + t_mb.shape[2:])

        # loss + gradients w.r.t. the cached embeddings (mesh-global
        # negatives/pairs exactly as the single-pass step)
        if loss_name == "milnce":
            def loss_of(v, t):
                return milnce_fn(v, t, batch_axes)
        else:
            def loss_of(v, t):
                t_seq = t.reshape(b, -1, t.shape[-1])      # (B, K, D)
                return _sequence_loss(loss_cfg, v, t_seq, start, batch_axes)

        loss, (g_v, g_t) = jax.value_and_grad(
            loss_of, argnums=(0, 1))(v_local, t_local)

        # pass 2: re-forward each microbatch, seed its VJP with the
        # cached embedding grads, accumulate LOCAL parameter grads —
        # the cross-mesh reduction stays outside the scan (docstring)
        g_v_mb = g_v.reshape((micro_batches, bm) + g_v.shape[1:])
        g_t_mb = g_t.reshape((micro_batches, bm * k_rows) + g_t.shape[1:])

        def grad_one(acc, xs):
            vu8, tids, gv, gt = xs

            def f(params):
                (v, t), _ = fwd(params, state.batch_stats, vu8, tids)
                return v, t

            _, vjp = jax.vjp(f, full_params)
            (g,) = vjp((gv, gt))
            return jax.tree_util.tree_map(jnp.add, acc, g), None

        zero = jax.tree_util.tree_map(jnp.zeros_like, full_params)
        grads, _ = lax.scan(grad_one, zero, (vids, txts, g_v_mb, g_t_mb))

        if fsdp:
            grads = _reduce_grads_2d(grads, state_specs.params, data_axis,
                                     model_axis, mesh_size,
                                     mean=loss_name != "milnce",
                                     overlap=overlap_grad_reduce)
        else:
            reduce = lax.psum if loss_name == "milnce" else lax.pmean
            grads = reduce(grads, data_axis)
        grads = _apply_grad_poison(grads, state.step)
        # merge BN stats over microbatches then shards: a microbatch is a
        # virtual shard, so mean-of-means matches the M*N-chip run
        new_stats = jax.tree_util.tree_map(
            lambda x: lax.pmean(jnp.mean(x, axis=0), batch_axes), stats_mb)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        if finite_guard:    # same skip-update semantics as make_train_step
            ok = _all_finite(grads)
            if fsdp:
                ok = _uniform_finite_verdict(ok, model_axis)
            new_params = _select_tree(ok, new_params, state.params)
            new_opt = _select_tree(ok, new_opt, state.opt_state)
            new_stats = _select_tree(ok, new_stats, state.batch_stats)
            return TrainState(step=state.step + 1, params=new_params,
                              batch_stats=new_stats,
                              opt_state=new_opt), loss, (~ok).astype(jnp.int32)
        return TrainState(step=state.step + 1, params=new_params,
                          batch_stats=new_stats, opt_state=new_opt), loss

    state_spec = state_specs if fsdp else P()
    batch_spec = P(batch_axes)
    tail = (P(), P()) if finite_guard else (P(),)
    sharded = shard_map(
        local_step, mesh=mesh,
        in_specs=(state_spec, batch_spec, batch_spec, batch_spec),
        out_specs=(state_spec,) + tail,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donation_argnums(
        *STATE_DONATION_ARGNUMS) if donate else ())


def _check_2d_args(mesh: Mesh, data_axis: str, model_axis, state_specs):
    """Build-time validation of the 2-D knobs: a phantom axis or a
    missing spec tree must fail HERE, not as a silent replication (the
    failure mode GL009 and sharding_map.build_param_specs also guard)."""
    if (model_axis is None) != (state_specs is None):
        raise ValueError(
            "2-D step needs BOTH model_axis and state_specs (build the "
            "spec tree with parallel.sharding_map.state_partition_specs)")
    if model_axis is None:
        return None
    for ax in (data_axis, model_axis):
        if ax not in mesh.axis_names:
            raise ValueError(
                f"step axis {ax!r} absent from mesh axes {mesh.axis_names}")
    import math

    return math.prod(mesh.shape.values())


def make_train_step(model, optimizer, mesh: Mesh, data_axis: str = "data",
                    donate: bool = True, loss_cfg=None, inner_steps: int = 1,
                    finite_guard: bool = False, state_specs=None,
                    model_axis=None, overlap_grad_reduce: bool = True):
    """Build the jitted train step.

    Returns ``step_fn(state, video_u8, text_ids, start) -> (state, loss)``:
    ``video_u8`` (B, T, H, W, 3) uint8, ``text_ids`` (B*K, W) int32,
    ``start`` (B,) float32 clip start-times (used by the CIDM loss; pass
    zeros otherwise) — all sharded on dim 0; ``state`` replicated.

    ``finite_guard=True`` folds a per-step all-finite gradient check into
    the jitted program and returns ``(state, loss, skipped)`` instead: a
    non-finite gradient keeps params/opt_state/batch_stats at their
    pre-step values via ``jnp.where`` (``skipped`` int32 1) — no host
    sync, no new collectives (pinned by the trace invariants).  The step
    counter still advances: it tracks batches CONSUMED, which the
    mid-epoch resume math relies on.

    Loss selection (LossConfig.name): 'milnce' scores pooled embeddings
    with per-shard partial sums psum'd inside the loss, so gradients are
    combined with ``psum``.  The DTW family scores the gathered batch
    identically on every shard (replicated loss), so gradients are
    combined with ``pmean`` — psum would overcount by the mesh size.

    ``inner_steps > 1`` runs that many optimizer steps on the SAME batch
    inside one XLA program (``lax.scan``) per dispatch.  Benchmark use
    only: it amortizes per-dispatch host latency (a remote-tunnel execute
    costs seconds) so the measurement reflects device throughput.

    ``state_specs``/``model_axis``/``overlap_grad_reduce``: the 2-D
    FSDP path (module docstring).  ``state_specs=None`` keeps the 1-D
    program byte-identical to before — its pinned collective counts
    never move.
    """
    loss_name = _check_loss_name(loss_cfg)
    milnce_fn = build_milnce_loss(loss_cfg) if loss_name == "milnce" else None
    mesh_size = _check_2d_args(mesh, data_axis, model_axis, state_specs)
    fsdp = model_axis is not None
    # the loss axes: on the 2-D mesh every chip is a data shard (the
    # batch shards over BOTH axes), so negatives gather and grads reduce
    # over the combined axes — global-batch semantics match the 1-D mesh
    # of the same device count exactly, local BN included
    batch_axes = (data_axis, model_axis) if fsdp else data_axis
    # normalize straight into the model's compute dtype: a bf16 model casts
    # the video to bf16 at conv1 anyway (Conv3D promote_dtype), so an f32
    # intermediate would only add HBM traffic on the largest activation
    compute_dtype = jnp.dtype(getattr(model, "dtype", jnp.float32))

    def local_step(state: TrainState, video_u8, text_ids, start):
        video = video_u8.astype(compute_dtype) / jnp.asarray(255, compute_dtype)
        full_params = (_gather_params(state.params, state_specs.params,
                                      model_axis)
                       if fsdp else state.params)

        def loss_fn(params):
            variables = {"params": params, "batch_stats": state.batch_stats}
            if loss_name == "milnce":
                (v_embd, t_embd), mutated = model.apply(
                    variables, video, text_ids, train=True,
                    mutable=["batch_stats"])
                loss = milnce_fn(v_embd, t_embd, batch_axes)
            else:
                (v_seq, t_embd), mutated = model.apply(
                    variables, video, text_ids, mode="sequence", train=True,
                    mutable=["batch_stats"])
                b = video.shape[0]
                t_seq = t_embd.reshape(b, -1, t_embd.shape[-1])  # (B, K, D)
                loss = _sequence_loss(loss_cfg, v_seq, t_seq, start,
                                      batch_axes)
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(full_params)
        if fsdp:
            grads = _reduce_grads_2d(grads, state_specs.params, data_axis,
                                     model_axis, mesh_size,
                                     mean=loss_name != "milnce",
                                     overlap=overlap_grad_reduce)
        else:
            reduce = lax.psum if loss_name == "milnce" else lax.pmean
            grads = reduce(grads, data_axis)
        grads = _apply_grad_poison(grads, state.step)
        new_stats = jax.tree_util.tree_map(
            lambda x: lax.pmean(x, batch_axes), new_stats)
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        if finite_guard:
            ok = _all_finite(grads)
            if fsdp:
                ok = _uniform_finite_verdict(ok, model_axis)
            new_params = _select_tree(ok, new_params, state.params)
            new_opt = _select_tree(ok, new_opt, state.opt_state)
            new_stats = _select_tree(ok, new_stats, state.batch_stats)
            new_state = TrainState(step=state.step + 1, params=new_params,
                                   batch_stats=new_stats, opt_state=new_opt)
            return new_state, loss, (~ok).astype(jnp.int32)
        new_state = TrainState(step=state.step + 1, params=new_params,
                               batch_stats=new_stats, opt_state=new_opt)
        return new_state, loss

    if inner_steps > 1:
        def local_loop(state, video_u8, text_ids, start):
            def body(st, _):
                out = local_step(st, video_u8, text_ids, start)
                return out[0], out[1:]

            state, outs = lax.scan(body, state, None, length=inner_steps)
            if finite_guard:
                return state, outs[0][-1], outs[1].sum()
            return state, outs[0][-1]

        local_fn = local_loop
    else:
        local_fn = local_step

    state_spec = state_specs if fsdp else P()
    batch_spec = P(batch_axes)
    tail = (P(), P()) if finite_guard else (P(),)
    sharded = shard_map(
        local_fn, mesh=mesh,
        in_specs=(state_spec, batch_spec, batch_spec, batch_spec),
        out_specs=(state_spec,) + tail,
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=donation_argnums(
        *STATE_DONATION_ARGNUMS) if donate else ())


def make_video_embed_fn(model, mesh: Mesh, data_axis: str = "data",
                        mixed5c: bool = False):
    """Jitted no-grad video-embedding extractor (counterpart of the
    reference eval loops' batched forwards, eval_msrvtt.py:61-66,
    eval_hmdb.py:75).  video_u8 sharded on dim 0; returns sharded embeds."""

    def local(variables, video_u8):
        dt = jnp.dtype(getattr(model, "dtype", jnp.float32))
        video = video_u8.astype(dt) / jnp.asarray(255, dt)
        return model.apply(variables, video, None, mode="video",
                           mixed5c=mixed5c)

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(), P(data_axis)),
        out_specs=P(data_axis), check_vma=False))


def make_text_embed_fn(model, mesh: Mesh, data_axis: str = "data"):
    def local(variables, text_ids):
        return model.apply(variables, None, text_ids, mode="text")

    return jax.jit(shard_map(
        local, mesh=mesh, in_specs=(P(), P(data_axis)),
        out_specs=P(data_axis), check_vma=False))
