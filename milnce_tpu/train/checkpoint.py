"""Checkpoint save/rotate/resume on Orbax.

Same semantics as the reference (main_distributed.py:192-200, 289-302):
one checkpoint per epoch, sliding retention window (default 10), resume
from the newest — but sharded/async via Orbax instead of rank-0
``torch.save`` of a monolithic state dict, so multi-host saves scale and
don't stall the step loop.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import orbax.checkpoint as ocp

from milnce_tpu.train.state import TrainState


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 10):
        directory = os.path.abspath(directory)
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep, create=True, enable_async_checkpointing=True)
        self._mgr = ocp.CheckpointManager(directory, options=options)

    def save(self, epoch: int, state: TrainState) -> None:
        self._mgr.save(epoch, args=ocp.args.StandardSave(state))

    def latest_epoch(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, epoch: int, template: TrainState) -> TrainState:
        return self._mgr.restore(epoch, args=ocp.args.StandardRestore(template))

    def restore_latest(self, template: TrainState) -> Tuple[int, TrainState]:
        """Returns (next_epoch, state); (0, template) when nothing saved —
        mirrors get_last_checkpoint's empty-string fallback
        (main_distributed.py:296-302)."""
        latest = self.latest_epoch()
        if latest is None:
            return 0, template
        return latest, self.restore(latest, template)

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
