"""Checkpoint save/rotate/resume on Orbax.

Same semantics as the reference (main_distributed.py:192-200, 289-302):
one checkpoint per epoch, sliding retention window (default 10), resume
from the newest — but sharded/async via Orbax instead of rank-0
``torch.save`` of a monolithic state dict, so multi-host saves scale and
don't stall the step loop.

Mesh-layout portability (MIGRATING.md "Checkpoint resharding"): a
checkpoint carries GLOBAL arrays, never a mesh layout, so restores are
layout-agnostic in both directions — a 1-D data-mesh run's checkpoint
opens on a 2-D ``(data, model)`` FSDP grid and vice versa.  ``restore``
reads straight into whatever sharding the template's arrays carry
(the rollback path passes the LIVE 2-D-sharded state); ``restore_latest``
callers that restore onto an unplaced template re-place afterwards
through the run's single placement path (train/loop.py ``place_state``
-> ``sharding_map.place_tree``), which performs the actual reshard.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import orbax.checkpoint as ocp

from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.resilience import faults
from milnce_tpu.train.state import TrainState

# Transient-save-failure telemetry (OBSERVABILITY.md): nonzero retries
# on a healthy store is the early-warning signal for flaky storage.
_OBS_SAVE_RETRIES = obs_metrics.registry().counter(
    "milnce_ckpt_save_retries_total",
    "checkpoint save submits retried after a transient OSError")


_STALE_PREFIX = "stale-epoch-"   # non-numeric => invisible to Orbax's step scan


class CheckpointManager:
    def __init__(self, directory: str, keep: int = 10, create: bool = True,
                 save_retries: int = 2, retry_backoff: float = 0.25):
        """``create=False`` opens read-only — export/inspection consumers
        must not mkdir a mistyped run directory as a side effect.

        ``save_retries``/``retry_backoff``: transient-I/O retry policy for
        saves — a preemption (SIGTERM) save races the grace window against
        storage that at pod scale IS flaky, and losing the whole partial
        epoch to one transient write error is the wrong trade.  OSError
        during the save submit is retried with exponential backoff
        (``retry_backoff * 2**attempt`` seconds) before re-raising."""
        directory = os.path.abspath(directory)
        self._directory = directory
        self.save_retries = max(0, int(save_retries))
        self.retry_backoff = float(retry_backoff)
        if create:
            self._recover_interrupted_replacements()
        options = ocp.CheckpointManagerOptions(
            max_to_keep=keep, create=create, read_only=not create,
            enable_async_checkpointing=True)
        # the explicit handler also makes item_metadata() work without a
        # restore template (restore_raw)
        self._mgr = ocp.CheckpointManager(
            directory, options=options,
            item_handlers=ocp.StandardCheckpointHandler())

    def _sync(self, tag: str) -> None:
        """Multi-host barrier around process-0 filesystem surgery; no-op
        single-process."""
        import jax

        if jax.process_count() > 1:
            ocp.multihost.sync_global_processes(f"milnce-ckpt-{tag}")

    def _recover_interrupted_replacements(self) -> None:
        """Finish any mid-epoch replacement (``save(force=True)``) that a
        kill interrupted.  The replacement protocol renames the old
        boundary checkpoint to ``stale-epoch-<n>`` before writing its
        successor, so a crash in the window leaves the backup on disk:
        if step ``<n>`` exists again the new save committed (Orbax's
        commit is an atomic tmp->step rename) and the backup is garbage;
        if it doesn't, restore the backup — the run keeps the boundary
        checkpoint it had, instead of falling back a whole epoch."""
        import re
        import shutil

        import jax

        try:
            entries = os.listdir(self._directory)
        except FileNotFoundError:
            # No run dir yet — nothing to recover, but DO fall through to
            # the sync below: an early return here raced multi-process
            # opens (a fast process skips the sync; a slower one sees the
            # directory the fast one's Orbax init just created, lists it,
            # and syncs — pairing its collective with some LATER one and
            # wedging the cluster at startup).  Every process must run
            # the same collective sequence unconditionally.
            entries = []
        if jax.process_index() == 0:
            for name in entries:
                m = re.fullmatch(_STALE_PREFIX + r"(\d+)", name)
                if not m:
                    continue
                backup = os.path.join(self._directory, name)
                step_dir = os.path.join(self._directory, m.group(1))
                if os.path.isdir(step_dir):
                    shutil.rmtree(backup)
                else:
                    os.rename(backup, step_dir)
        self._sync("recover")

    def _save_with_retry(self, epoch: int, state: TrainState,
                         force: bool) -> None:
        """One Orbax save submit, retried on transient I/O failure.  Only
        OSError is retried — Orbax protocol errors (StepAlreadyExists,
        bad args) are bugs and re-raise immediately.  The
        ``ckpt.save_ioerror`` fault site sits inside the retried region so
        chaos tests drive exactly this path (tests/test_resilience.py)."""
        import logging
        import time

        import jax

        # Single-process only: a per-host retry on a MULTI-host cluster
        # would desync the collective sequence (the failing host re-enters
        # Orbax's cross-process coordination while its peers have moved
        # on) — the same every-process-runs-the-same-collectives rule as
        # _recover_interrupted_replacements.  Making the retry verdict
        # cluster-uniform needs an agreement collective this layer
        # doesn't own; until then multi-process re-raises immediately.
        retries = self.save_retries if jax.process_count() == 1 else 0
        for attempt in range(retries + 1):
            try:
                faults.maybe_raise("ckpt.save_ioerror", OSError)
                self._mgr.save(epoch, args=ocp.args.StandardSave(state),
                               force=force)
                return
            except OSError as exc:
                if attempt >= retries:
                    raise
                _OBS_SAVE_RETRIES.inc()
                delay = self.retry_backoff * (2 ** attempt)
                logging.getLogger(__name__).warning(
                    "checkpoint save of epoch %d failed (%s: %s); retrying "
                    "in %.2fs (attempt %d/%d)", epoch, type(exc).__name__,
                    exc, delay, attempt + 1, retries)
                time.sleep(delay)

    def save(self, epoch: int, state: TrainState,
             force: bool = False) -> None:
        """``force=True`` is for MID-EPOCH stops (preemption/max_steps)
        labeled with the current epoch: the previous epoch's boundary
        save already holds that label, and Orbax both silently refuses a
        step <= the latest (should_save) and raises
        StepAlreadyExistsError on a forced same-step save — either way
        the partial epoch the preemption checkpoint exists to preserve
        would be dropped.  Replace the boundary state with the
        strictly-newer mid-epoch state (same run, larger step counter).

        Crash safety: the stale checkpoint is MOVED ASIDE (atomic
        rename to ``stale-epoch-<n>``), not deleted, before the new save
        starts, and only removed after the new save has committed — a
        SIGKILL anywhere in the window leaves either the old or the new
        checkpoint recoverable (``_recover_interrupted_replacements`` on
        the next open).  The forced path is synchronous; preemption
        callers wait() immediately anyway."""
        import shutil

        import jax

        if force and epoch in (self._mgr.all_steps() or []):
            self._mgr.wait_until_finished()
            stale = os.path.join(self._directory, str(epoch))
            backup = os.path.join(self._directory,
                                  f"{_STALE_PREFIX}{epoch}")
            have_backup = os.path.isdir(stale)
            if have_backup:
                self._sync("pre-rename")
                if jax.process_index() == 0:
                    os.rename(stale, backup)
                self._sync("renamed")
                self._mgr.reload()          # drop the cached step listing
            else:                           # step tracked but dir absent
                self._mgr.delete(epoch)     # (custom storage) — old path
            self._save_with_retry(epoch, state, force)
            self._mgr.wait_until_finished()  # commit before dropping backup
            if have_backup:
                if jax.process_index() == 0 and os.path.isdir(backup):
                    shutil.rmtree(backup)
                self._sync("committed")
            return
        self._save_with_retry(epoch, state, force)

    def latest_epoch(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, epoch: int, template: TrainState) -> TrainState:
        return self._mgr.restore(epoch, args=ocp.args.StandardRestore(template))

    def restore_latest(self, template: TrainState) -> Tuple[int, TrainState]:
        """Returns (next_epoch, state); (0, template) when nothing saved —
        mirrors get_last_checkpoint's empty-string fallback
        (main_distributed.py:296-302).

        If the stored tree's *optimizer* structure no longer matches the
        template (the optimizer tree evolves across releases — e.g. the
        ``optax.masked`` wrap for the frozen word2vec table changed
        opt_state from AdamState to MaskedState), a full StandardRestore
        fails.  Rather than strand an in-flight run, fall back to
        restoring only ``step``/``params``/``batch_stats`` from the
        checkpoint's own metadata and keep the template's freshly
        initialized opt_state, logging that the optimizer moments were
        dropped (a few hundred steps of Adam re-warmup, not a divergence)."""
        latest = self.latest_epoch()
        if latest is None:
            return 0, template
        try:
            return latest, self.restore(latest, template)
        except (ValueError, KeyError, TypeError) as exc:
            import logging

            import jax
            import jax.numpy as jnp

            # The fallback exists for ONE cause: the stored opt_state's
            # structure no longer matches the template's (optimizer tree
            # evolved across releases).  The same exception types can also
            # come from a transient Orbax failure on a perfectly
            # compatible checkpoint — dropping the moments there would be
            # silent data loss.  Discriminate with zero extra I/O by
            # comparing leaf fingerprints (shape+dtype multisets) of the
            # stored opt_state metadata vs the template's: identical
            # fingerprints mean the structures are almost certainly
            # compatible and the failure was something else — re-raise.
            if self._opt_state_fingerprint_matches(latest, template):
                raise
            _, raw = self.restore_raw(
                latest, subtrees={"step", "params", "batch_stats"})
            if not isinstance(raw, dict):  # a TrainState restored as object
                raw = {"step": raw.step, "params": raw.params,
                       "batch_stats": raw.batch_stats}
            # Only an *optimizer* mismatch is rescuable.  If the stored
            # params tree itself differs from the template's (model code
            # changed, corrupt checkpoint), installing it would defer the
            # crash to a confusing optax/jit error under a log line
            # claiming a benign optimizer reinit — re-raise instead.
            if (jax.tree_util.tree_structure(raw["params"])
                    != jax.tree_util.tree_structure(template.params)):
                raise
            stored_shapes = jax.tree_util.tree_map(
                lambda x: (tuple(x.shape), jnp.dtype(x.dtype).name),
                raw["params"])
            template_shapes = jax.tree_util.tree_map(
                lambda x: (tuple(x.shape), jnp.dtype(x.dtype).name),
                template.params)
            if stored_shapes != template_shapes:
                raise                   # same tree, resized leaves (e.g. a
                                        # grown vocab) — also not rescuable
            logging.getLogger(__name__).warning(
                "checkpoint %d has an incompatible optimizer-state "
                "structure (%s); restored weights only and reinitialized "
                "the optimizer — Adam/SGD moments were dropped", latest, exc)
            return latest, template.replace(
                step=jnp.asarray(raw["step"]),
                params=raw["params"],
                batch_stats=raw.get("batch_stats", template.batch_stats))

    def _opt_state_fingerprint_matches(self, epoch: int, template) -> bool:
        """True when the stored checkpoint's opt_state leaves (from
        metadata — no array I/O) sit at the same tree paths with the same
        shape+dtype as the template's.  Container types differ between
        the live pytree and Orbax metadata (optax NamedTuples serialize
        as dicts keyed by field name, tuples as lists, field-less states
        as None), so exact treedef equality is meaningless across that
        boundary — but path *names* survive: GetAttrKey('mu') on the
        live side becomes DictKey('mu') in metadata, SequenceKey indices
        are preserved.  Comparing (path, shape, dtype) sets therefore
        catches structure evolutions whose new states carry no array
        leaves (e.g. wrapping in optax.chain(clip_by_global_norm, ...)
        shifts every adam leaf's tuple index) that a flat leaf multiset
        would miss.  Any error while comparing counts as a mismatch (the
        fallback path then re-validates params structure strictly before
        committing)."""
        import jax

        def key_name(k):
            for attr in ("name", "key", "idx"):
                if hasattr(k, attr):
                    return str(getattr(k, attr))
            return str(k)

        def fp(tree):
            is_arr = lambda x: hasattr(x, "shape")  # noqa: E731
            flat, _ = jax.tree_util.tree_flatten_with_path(
                tree, is_leaf=is_arr)
            return sorted(
                (tuple(key_name(k) for k in path),
                 tuple(x.shape), str(jax.numpy.dtype(x.dtype)))
                for path, x in flat if is_arr(x))

        try:
            # Orbax's TreeMetadata supports __getitem__ like the saved
            # dict even though it is not a dict instance
            stored_opt = self._mgr.item_metadata(epoch)["opt_state"]
            return fp(stored_opt) == fp(template.opt_state)
        except Exception:
            # Failing to COMPUTE the fingerprint (metadata-API drift, a
            # checkpoint missing opt_state metadata) silently reverts to
            # the pre-fingerprint behavior — weights-only fallback even
            # on transient errors.  Make that regression visible.
            import logging
            logging.getLogger(__name__).warning(
                "opt_state fingerprint comparison for checkpoint %d "
                "failed; transient-vs-structural discrimination is "
                "disabled for this restore", epoch, exc_info=True)
            return False

    def restore_raw(self, epoch: Optional[int] = None,
                    subtrees: Optional[set] = None):
        """Restore WITHOUT a caller-provided template: (epoch, tree).
        For consumers that only need the arrays — e.g. exporting weights
        to the reference's torch format — where building a TrainState
        template would require knowing the run's model shapes.  The
        shape/dtype template comes from the checkpoint's own metadata, so
        a run saved on an 8-device mesh restores on a single-device host
        (restore-as-saved would demand the original devices).

        ``subtrees`` limits restore I/O to those top-level keys (e.g.
        ``{'params', 'batch_stats'}`` — skipping a real run's Adam state
        halves-to-thirds the bytes read); other keys restore as
        ``ocp.PLACEHOLDER``."""
        import jax

        latest = epoch if epoch is not None else self.latest_epoch()
        if latest is None:
            raise FileNotFoundError("no checkpoint saved in this run dir")
        meta = self._mgr.item_metadata(latest)
        # local_devices, not devices: on a multi-host cluster devices()[0]
        # belongs to process 0 only, and this path is reached by every
        # process when restore_latest falls back on an optimizer-structure
        # mismatch — a non-addressable sharding would crash the restore
        shard = jax.sharding.SingleDeviceSharding(jax.local_devices()[0])
        is_arr = lambda x: hasattr(x, "shape")  # noqa: E731
        template = jax.tree_util.tree_map(
            lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype, sharding=shard)
            if is_arr(m) else m, meta, is_leaf=is_arr)
        # older orbax has no PLACEHOLDER: fall back to restoring the full
        # template — same values, just without the skipped-subtree I/O
        # saving
        placeholder = getattr(ocp, "PLACEHOLDER", None)
        if (subtrees is not None and isinstance(template, dict)
                and placeholder is not None):
            template = {
                k: (v if k in subtrees else jax.tree_util.tree_map(
                    lambda _: placeholder, v, is_leaf=is_arr))
                for k, v in template.items()}
        return latest, self._mgr.restore(
            latest, args=ocp.args.StandardRestore(template))

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()
