"""Deterministic fault-injection registry.

At HowTo100M pod scale, corrupt files, wedged ffmpeg pipes, flaky
checkpoint storage and loss blow-ups are steady-state conditions, not
incidents (PAPER.md: the original ran on TPU v3 where preemption and
restart are routine).  The repo's failure paths — bounded resample,
decode watchdog, finite-update guard, checkpoint retry — are only
trustworthy if tests can *drive* them; this module makes every failure
injectable on a reproducible schedule, so each recovery path is a tier-1
chaos test instead of a hope (tests/test_resilience.py).

Sites (the catalogue ROBUSTNESS.md documents):

- ``decode.raise``     host; the decode entry raises :class:`InjectedFault`
                       (exercises the source's bounded resample).
- ``decode.hang``      host; the decode entry sleeps ``x`` seconds
                       (exercises the loader watchdog; default x=5).
- ``ckpt.save_ioerror`` host; the checkpoint save raises ``OSError``
                       (exercises the save retry/backoff).
- ``grad.nonfinite``   device; the train step multiplies the reduced
                       gradients by NaN on scheduled steps (exercises the
                       finite-update guard + rollback).  Build-time: the
                       schedule is baked into the jitted step, so firing
                       costs no host sync.

Serving sites (threaded through ``InferenceEngine._run`` — each fires
on whichever engine replica performs the scheduled dispatch, so chaos
tests can kill/hang/flake individual pool replicas deterministically;
serving/pool.py, ROBUSTNESS.md "Serving request path"):

- ``serve.dispatch_raise`` host; the embed dispatch raises
                       :class:`InjectedFault` (exercises the pool's
                       requeue + consecutive-error quarantine breaker).
- ``serve.dispatch_hang`` host; the dispatch sleeps ``x`` seconds
                       (exercises the latency-SLO breaker and hedged
                       dispatch; default x=5).
- ``serve.replica_dead`` host; the engine serving the scheduled
                       dispatch is PERMANENTLY killed (every later call
                       raises ``ReplicaDead`` — simulates a lost device/
                       process; the pool quarantines it and probes keep
                       failing).

Live-index sites (serving/live_index.py — chaos tests prove a failed
swap leaves the old generation serving and never wedges the builder):

- ``index.swap_raise``  host; the builder's generation publication
                       raises just before the atomic swap (exercises
                       the keep-old-generation + re-queue-rows + retry
                       path).
- ``index.ingest_hang`` host; ``LiveRetrievalIndex.add`` sleeps ``x``
                       seconds (a wedged ingest caller; queries must be
                       unaffected; default x=5).

Elastic sites (milnce_tpu/elastic/, threaded through the train loop —
the occurrence count of both is the optimizer step number, because the
loop polls/fires them exactly once per step):

- ``host.preempt``     host; delivers the drain signal at step N
                       (``host.preempt@N``) — the deterministic stand-in
                       for a TPU-VM maintenance SIGTERM: the loop
                       finishes the in-flight step, force-checkpoints,
                       writes ELASTIC_STAMP.json and exits drained.
- ``host.slow``        host; inflates THIS process's step wall time by
                       ``x`` seconds (default x=0.05) — a persistently
                       slow host for the straggler policy to flag and
                       demote (on a single process it simply stretches
                       the recorded step spans).

Spec grammar (config ``train.faults`` or env ``MILNCE_FAULTS``)::

    spec   := clause (';' clause)*
    clause := site '@' sched [':x=' float]
    sched  := '*'            every occurrence
            | '%' N          every Nth occurrence
            | i(,j,k...)     exact 1-based occurrence indices

For host sites an "occurrence" is the Nth invocation of the site in this
process (counted under a lock — decode sites fire from reader threads);
for ``grad.nonfinite`` it is the optimizer step number ``state.step + 1``
(deterministic across restarts: a resumed run continues the count).

Zero overhead disarmed: every site call is one function call and a
module-global ``None`` check; the device site adds nothing to the traced
step unless armed at build time.
"""

from __future__ import annotations

import os
import time
from contextlib import contextmanager
from dataclasses import dataclass, field

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import metrics as obs_metrics

KNOWN_SITES = ("decode.raise", "decode.hang", "ckpt.save_ioerror",
               "grad.nonfinite", "serve.dispatch_raise",
               "serve.dispatch_hang", "serve.replica_dead",
               "index.swap_raise", "index.ingest_hang",
               "host.preempt", "host.slow")

# Process-wide injection telemetry (OBSERVABILITY.md): chaos drills and
# failure-rate dashboards read how often each site actually fired.
_INJECTED = obs_metrics.registry().counter(
    "milnce_faults_injected_total",
    "fault-site occurrences that fired (scheduled hits)", ("site",))

ENV_VAR = "MILNCE_FAULTS"


class InjectedFault(RuntimeError):
    """Raised by an armed ``maybe_raise`` site."""


@dataclass
class SiteSpec:
    site: str
    mode: str                    # 'at' | 'every' | 'all'
    at: tuple[int, ...] = ()
    every: int = 0
    x: float = 0.0               # site parameter (hang sleep seconds)
    hits: int = field(default=0, compare=False)

    def scheduled(self, n: int) -> bool:
        """Does the 1-based occurrence index ``n`` fire?"""
        if self.mode == "all":
            return True
        if self.mode == "every":
            return n % self.every == 0
        return n in self.at


def parse_spec(spec: str) -> dict[str, SiteSpec]:
    """'site@sched[:x=F];...' -> {site: SiteSpec}.  Unknown sites and
    malformed schedules raise ValueError — a typo'd fault spec must fail
    the run at arm time, not silently inject nothing."""
    out: dict[str, SiteSpec] = {}
    for clause in filter(None, (c.strip() for c in spec.split(";"))):
        if "@" not in clause:
            raise ValueError(f"fault clause {clause!r} missing '@sched' "
                             "(grammar: site@sched[:x=float])")
        site, _, sched = clause.partition("@")
        site = site.strip()
        if site not in KNOWN_SITES:
            raise ValueError(f"unknown fault site {site!r} "
                             f"(sites: {', '.join(KNOWN_SITES)})")
        x = 0.0
        if ":" in sched:
            sched, _, param = sched.partition(":")
            key, _, val = param.partition("=")
            if key.strip() != "x":
                raise ValueError(f"unknown fault parameter {key!r} in "
                                 f"{clause!r} (only ':x=float')")
            x = float(val)
        sched = sched.strip()
        if sched == "*":
            out[site] = SiteSpec(site, "all", x=x)
        elif sched.startswith("%"):
            n = int(sched[1:])
            if n < 1:
                raise ValueError(f"bad every-N schedule in {clause!r}")
            out[site] = SiteSpec(site, "every", every=n, x=x)
        else:
            at = tuple(int(i) for i in sched.split(","))
            if not at or any(i < 1 for i in at):
                raise ValueError(f"bad occurrence indices in {clause!r} "
                                 "(1-based)")
            out[site] = SiteSpec(site, "at", at=at, x=x)
    return out


class FaultRegistry:
    def __init__(self, spec: str):
        self.sites = parse_spec(spec)
        self._lock = make_lock("resilience.faults")

    def fire(self, site: str) -> SiteSpec | None:
        """Count one occurrence of ``site``; return its spec if this
        occurrence is scheduled to fail."""
        s = self.sites.get(site)
        if s is None:
            return None
        with self._lock:
            s.hits += 1
            n = s.hits
        if not s.scheduled(n):
            return None
        _INJECTED.labels(site=site).inc()
        return s


_registry: FaultRegistry | None = None
_env_checked = False


def _active() -> FaultRegistry | None:
    global _registry, _env_checked
    if _registry is None and not _env_checked:
        _env_checked = True
        spec = os.environ.get(ENV_VAR, "")
        if spec:
            _registry = FaultRegistry(spec)
    return _registry


def arm(spec: str) -> FaultRegistry:
    """Install a registry from ``spec`` (replacing any active one)."""
    global _registry, _env_checked
    _env_checked = True          # explicit arming overrides the env
    _registry = FaultRegistry(spec)
    return _registry


def disarm() -> None:
    global _registry, _env_checked
    _env_checked = True          # a disarm must stay disarmed
    _registry = None


@contextmanager
def armed(spec: str):
    """Test helper: arm for the block, always disarm after."""
    reg = arm(spec)
    try:
        yield reg
    finally:
        disarm()


def maybe_raise(site: str, exc_type: type = InjectedFault) -> None:
    """Raise ``exc_type`` if ``site`` is armed and this occurrence is
    scheduled.  ``exc_type`` lets the call site match the failure class
    its handler is built for (OSError for checkpoint I/O)."""
    reg = _active()
    if reg is None:
        return
    s = reg.fire(site)
    if s is not None:
        raise exc_type(f"injected fault at {site} (occurrence {s.hits})")


def maybe_hang(site: str, default_sleep: float = 5.0) -> None:
    """Sleep ``x`` (spec parameter) seconds if scheduled — a stand-in for
    a wedged decode pipe, long enough to trip the loader watchdog."""
    reg = _active()
    if reg is None:
        return
    s = reg.fire(site)
    if s is not None:
        time.sleep(s.x or default_sleep)


def fire_site(site: str) -> bool:
    """Count one occurrence of ``site``; True when this occurrence is
    scheduled to fail — for call sites whose failure response is not an
    exception or a sleep (e.g. ``serve.replica_dead`` flips the engine's
    dead flag)."""
    reg = _active()
    if reg is None:
        return False
    return reg.fire(site) is not None


def device_schedule(site: str) -> SiteSpec | None:
    """The spec for a device-side site (``grad.nonfinite``), or None when
    disarmed.  Read at step-BUILD time: the jitted step bakes the
    schedule in as a traced function of ``state.step`` — firing costs no
    host sync and survives donation/caching."""
    reg = _active()
    return None if reg is None else reg.sites.get(site)
