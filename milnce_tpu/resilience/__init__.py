"""Fault-tolerant training runtime: deterministic fault injection plus
the recovery machinery it exercises (decode watchdog, finite-update
guard, checkpoint save retry).  See ROBUSTNESS.md for the failure
matrix: fault -> detection site -> response -> test."""

from milnce_tpu.resilience.faults import (FaultRegistry, InjectedFault,
                                          arm, armed, device_schedule,
                                          disarm, maybe_hang, maybe_raise)

__all__ = ["FaultRegistry", "InjectedFault", "arm", "armed",
           "device_schedule", "disarm", "maybe_hang", "maybe_raise"]
