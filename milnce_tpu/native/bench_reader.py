"""Decode-throughput harness: C++ ReaderPool vs Python ThreadPool+subprocess.

The pipeline's decode hot path streams rawvideo bytes out of a subprocess
(the reference does this through `ffmpeg-python` inside torch loader
workers, video_loader.py:85-88).  This tool measures the byte-pump cost
of both of our host-side implementations with a synthetic producer
(``head -c`` from /dev/zero — pure pipe throughput, no codec cost), so
the comparison isolates the transport:

    python -m milnce_tpu.native.bench_reader [n_jobs] [mb_per_job] [workers]

Prints one JSON line: MB/s for each path and the speedup.
"""

from __future__ import annotations

import concurrent.futures as cf
import json
import subprocess
import sys
import time

import numpy as np


def python_pool(commands, nbytes, workers) -> float:
    """The pure-Python transport: subprocess.run(capture stdout) on a
    thread pool (what data/pipeline.py does without the native reader)."""
    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(workers) as pool:
        def run(cmd):
            out = subprocess.run(cmd, shell=True, stdout=subprocess.PIPE,
                                 check=True).stdout
            return np.frombuffer(out, np.uint8)

        results = list(pool.map(run, commands))
    dt = time.perf_counter() - t0
    assert all(r.nbytes == nbytes for r in results)
    return len(commands) * nbytes / dt / 1e6


def native_pool(commands, nbytes, workers) -> float:
    from milnce_tpu.native.reader import ReaderPool

    pool = ReaderPool(workers=workers)
    buffers = [np.empty(nbytes, np.uint8) for _ in commands]
    t0 = time.perf_counter()
    got = pool.decode_into(commands, buffers)
    dt = time.perf_counter() - t0
    pool.close()
    assert all(g == nbytes for g in got), got[:4]
    return len(commands) * nbytes / dt / 1e6


def main(n_jobs: int = 64, mb_per_job: int = 8, workers: int = 8):
    nbytes = mb_per_job * 1_000_000
    commands = [f"head -c {nbytes} /dev/zero" for _ in range(n_jobs)]
    py = python_pool(commands, nbytes, workers)
    nat = native_pool(commands, nbytes, workers)
    rec = {"n_jobs": n_jobs, "mb_per_job": mb_per_job, "workers": workers,
           "python_MBps": round(py, 1), "native_MBps": round(nat, 1),
           "speedup": round(nat / py, 2)}
    print(json.dumps(rec))
    return rec


if __name__ == "__main__":
    main(*(int(a) for a in sys.argv[1:]))
