"""C++ soft-DTW CPU kernels (ctypes front-end).

Exact forward/backward DP threaded over the batch — the native
counterpart of the reference's numba ``nopython`` kernels
(soft_dtw_cuda.py:185-240).  Used as a host-side golden check and a fast
eval fallback; wired into JAX via ``jax.custom_vjp`` + ``pure_callback``
so it composes with ``grad`` (but not ``jit`` on TPU — it is a HOST
kernel by design)."""

from __future__ import annotations

import ctypes
from functools import partial

import numpy as np

from milnce_tpu.native.build import load_native_library


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def softdtw_forward_native(D: np.ndarray, gamma: float,
                           bandwidth: int = 0):
    """D: (B, N, M) float32 -> (value (B,), R (B, N+2, M+2))."""
    lib = load_native_library()
    assert lib is not None, "native library unavailable"
    D = np.ascontiguousarray(D, np.float32)
    b, n, m = D.shape
    R = np.empty((b, n + 2, m + 2), np.float32)
    value = np.empty((b,), np.float32)
    lib.softdtw_forward_cpu(_f32p(D), _f32p(R), _f32p(value), b, n, m,
                            ctypes.c_float(gamma), int(bandwidth))
    return value, R


def softdtw_backward_native(D: np.ndarray, R: np.ndarray,
                            grad_out: np.ndarray, gamma: float,
                            bandwidth: int = 0) -> np.ndarray:
    lib = load_native_library()
    assert lib is not None, "native library unavailable"
    D = np.ascontiguousarray(D, np.float32)
    R = np.ascontiguousarray(R, np.float32)
    grad_out = np.ascontiguousarray(grad_out, np.float32)
    b, n, m = D.shape
    E = np.empty((b, n, m), np.float32)
    lib.softdtw_backward_cpu(_f32p(D), _f32p(R), _f32p(grad_out), _f32p(E),
                             b, n, m, ctypes.c_float(gamma), int(bandwidth))
    return E


def softdtw_native(D: np.ndarray, gamma: float, bandwidth: int = 0):
    """Differentiable-by-hand numpy API: returns (value, vjp_fn)."""
    value, R = softdtw_forward_native(D, gamma, bandwidth)
    return value, partial(softdtw_backward_native, D, R, gamma=gamma,
                          bandwidth=bandwidth)
