"""Build + load the native C++ runtime library (ctypes, no pybind11).

Compiles ``native/milnce_native.cpp`` on first use into
``build/libmilnce_native.so`` (cached by source mtime).  Everything that
uses it degrades gracefully when no C++ toolchain is present.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import threading
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "milnce_native.cpp")
_OUT = os.path.join(_REPO_ROOT, "build", "libmilnce_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _compile() -> bool:
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None or not os.path.exists(_SRC):
        return False
    os.makedirs(os.path.dirname(_OUT), exist_ok=True)
    cmd = [cxx, "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
           "-o", _OUT, _SRC]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
        return True
    except subprocess.CalledProcessError as e:
        import sys

        print(f"milnce_native build failed:\n{e.stderr.decode()}",
              file=sys.stderr)
        return False


def load_native_library() -> Optional[ctypes.CDLL]:
    """Compile-if-stale and dlopen the native library; None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        stale = (not os.path.exists(_OUT)
                 or (os.path.exists(_SRC)
                     and os.path.getmtime(_SRC) > os.path.getmtime(_OUT)))
        if stale and not _compile():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(_OUT)
        except OSError:
            _load_failed = True
            return None
        _declare(lib)
        _lib = lib
        return _lib


def native_available() -> bool:
    return load_native_library() is not None


def _declare(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.reader_create.restype = ctypes.c_void_p
    lib.reader_create.argtypes = [ctypes.c_int]
    lib.reader_submit.restype = ctypes.c_long
    lib.reader_submit.argtypes = [ctypes.c_void_p, ctypes.c_char_p, u8p,
                                  ctypes.c_long]
    lib.reader_wait.restype = ctypes.c_long
    lib.reader_wait.argtypes = [ctypes.c_void_p, ctypes.c_long]
    lib.reader_destroy.restype = None
    lib.reader_destroy.argtypes = [ctypes.c_void_p]
    lib.softdtw_forward_cpu.restype = None
    lib.softdtw_forward_cpu.argtypes = [f32p, f32p, f32p, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_int,
                                        ctypes.c_float, ctypes.c_int]
    lib.softdtw_backward_cpu.restype = None
    lib.softdtw_backward_cpu.argtypes = [f32p, f32p, f32p, f32p, ctypes.c_int,
                                         ctypes.c_int, ctypes.c_int,
                                         ctypes.c_float, ctypes.c_int]
