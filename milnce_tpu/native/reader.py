"""Native subprocess pipe-reader pool (ctypes front-end).

The decode hot path of the input pipeline: worker threads in C++ popen()
decode commands and fread() their stdout straight into caller-provided
numpy buffers — no GIL, no Python-side byte copies (contrast: the
reference shuttles every frame through `ffmpeg-python`'s
``run(capture_stdout=True)`` inside loader worker processes,
video_loader.py:85-88).
"""

from __future__ import annotations

import ctypes
import shlex
from typing import Sequence

import numpy as np

from milnce_tpu.native.build import load_native_library


class ReaderPool:
    """Threaded pipe pump.  ``decode_into`` runs shell commands
    concurrently, filling each command's numpy buffer with its stdout."""

    def __init__(self, workers: int = 8):
        self._lib = load_native_library()
        if self._lib is None:
            raise RuntimeError("native library unavailable (no g++?)")
        self._pool = self._lib.reader_create(int(workers))
        if not self._pool:
            raise RuntimeError("reader_create failed")

    def decode_into(self, commands: Sequence[Sequence[str] | str],
                    buffers: Sequence[np.ndarray]) -> list[int]:
        """Run every command, filling buffers[i] (uint8, C-contiguous) with
        stdout bytes.  Returns bytes-read per job (-1 = spawn failure)."""
        assert len(commands) == len(buffers)
        jobs = []
        for cmd, buf in zip(commands, buffers):
            assert buf.dtype == np.uint8 and buf.flags.c_contiguous
            if not isinstance(cmd, str):
                cmd = " ".join(shlex.quote(c) for c in cmd)
            ptr = buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
            jobs.append(self._lib.reader_submit(
                self._pool, cmd.encode(), ptr, buf.nbytes))
        return [int(self._lib.reader_wait(self._pool, j)) for j in jobs]

    def close(self) -> None:
        if getattr(self, "_pool", None):
            self._lib.reader_destroy(self._pool)
            self._pool = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # graftlint: disable=GL007(finalizer during interpreter teardown: raising here only produces unraisable-exception noise; close() is best-effort by contract)
            pass
