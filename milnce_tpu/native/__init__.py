from milnce_tpu.native.build import load_native_library, native_available  # noqa: F401
