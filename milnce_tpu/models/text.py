"""Sentence tower: frozen word2vec embedding -> MLP -> max-pool over words.

Re-design of the reference Sentence_Embedding (/root/reference/s3dg.py:148-204):
lookup under no_grad (:199-200) becomes ``lax.stop_gradient``; the tokenizer
that the reference bundles into the model moves to ``milnce_tpu.data.tokenizer``
(host-side, where tokenization actually runs).

The max over the word axis includes pad positions (id 0), exactly like the
reference's ``th.max(x, dim=1)`` (s3dg.py:202) — row 0 of the embedding table
participates.  Checkpoint conversion must therefore keep row 0 intact.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from flax import linen as nn


class SentenceEmbedding(nn.Module):
    embd_dim: int = 512
    vocab_size: int = 66250
    word_embedding_dim: int = 300
    hidden_dim: int = 2048
    embedding_init: Optional[Callable] = None  # e.g. from a word2vec table
    kernel_init: Callable = nn.initializers.lecun_normal()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, tokens: jax.Array) -> jax.Array:
        """tokens: (B, max_words) int -> (B, embd_dim)."""
        emb_init = self.embedding_init or nn.initializers.normal(stddev=1.0)
        table = nn.Embed(self.vocab_size, self.word_embedding_dim,
                         embedding_init=emb_init, dtype=self.dtype,
                         name="word_embd")
        from milnce_tpu.models.initializers import torch_bias, torch_default_kernel

        x = jax.lax.stop_gradient(table(tokens))     # frozen, s3dg.py:199-200
        # Linears keep torch-default init in every init mode (the
        # reference's kaiming branch only touches convs/BN, s3dg.py:240-246).
        x = nn.relu(nn.Dense(self.hidden_dim, kernel_init=torch_default_kernel(),
                             bias_init=torch_bias(self.word_embedding_dim),
                             dtype=self.dtype, name="fc1")(x))
        x = jnp.max(x, axis=1)                       # max-pool over words
        return nn.Dense(self.embd_dim, kernel_init=torch_default_kernel(),
                        bias_init=torch_bias(self.hidden_dim),
                        dtype=self.dtype, name="fc2")(x)


def word2vec_embedding_init(table) -> Callable:
    """Build an embedding_init closing over a pretrained (V, 300) table."""
    import numpy as np

    table = np.asarray(table)

    def _init(key, shape, dtype=jnp.float32):
        assert tuple(shape) == table.shape, (shape, table.shape)
        return jnp.asarray(table, dtype=dtype)

    return _init
