"""3D convolution with selectable TPU lowering.

The whole S3D-G trunk (reference s3dg.py:61-111) is built from three
conv shapes: pointwise ``(1,1,1)``, spatial ``(1,k,k)``, and temporal
``(k,1,1)`` — plus the one full ``(3,7,7)`` stem conv.  ``impl`` picks
how they reach the MXU:

- ``"native"``: one ``lax.conv_general_dilated`` with 3 spatial dims
  (NDHWC).  XLA:TPU supports it, but its 3D-conv tiling with tiny
  temporal extents (T' = 8..2 deep in the trunk) is far less tuned than
  the 2D path.
- ``"fold2d"``: the same math expressed as 2D convolutions, the layout
  XLA:TPU's conv emitter is actually optimized for — spatial kernels
  fold T into the batch dim ((B,T,H,W,C) -> (B*T,H,W,C)), temporal
  kernels fold (H,W) into one spatial dim ((B,T,H*W,C)), and a full
  (kt,kh,kw) kernel decomposes into kt temporally-shifted 2D convs
  summed (valid because conv is linear in the kernel taps).

The parameter is a single ``kernel`` of shape ``(t, h, w, in, out)``
in BOTH impls, so checkpoints swap freely and the flag is purely a
performance choice (``scripts/stage_probe.py --conv_impl`` measures it
per stage).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax.linen.dtypes import promote_dtype
from jax import lax

Array = jax.Array

_DN3D = ("NDHWC", "DHWIO", "NDHWC")
_DN2D = ("NHWC", "HWIO", "NHWC")


class Conv3D(nn.Module):
    """Bias-free 3D conv with explicit symmetric padding per dim,
    matching the torch ``nn.Conv3d`` semantics every trunk conv uses."""

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] = (1, 1, 1)
    padding: Sequence[int] = (0, 0, 0)
    impl: str = "native"                  # 'native' | 'fold2d'
    kernel_init: Callable = nn.initializers.lecun_normal()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        kt, kh, kw = (int(v) for v in self.kernel_size)
        st, sh, sw = (int(v) for v in self.strides)
        pt, ph, pw = (int(v) for v in self.padding)
        kernel = self.param("kernel", self.kernel_init,
                            (kt, kh, kw, x.shape[-1], self.features),
                            jnp.float32)
        x, kernel = promote_dtype(x, kernel, dtype=self.dtype)

        if self.impl == "native":
            return lax.conv_general_dilated(
                x, kernel, (st, sh, sw), [(pt, pt), (ph, ph), (pw, pw)],
                dimension_numbers=_DN3D)
        if self.impl != "fold2d":
            raise ValueError(f"unknown conv impl {self.impl!r}")

        def conv2d(y, kern, strides, pads):
            return lax.conv_general_dilated(y, kern, strides, pads,
                                            dimension_numbers=_DN2D)

        b = x.shape[0]
        if kt == 1:
            # spatial/pointwise: T is inert -> fold it into batch
            assert pt == 0, "temporal padding with a 1-tap temporal kernel"
            if st > 1:
                x = x[:, ::st]
            t = x.shape[1]
            y = conv2d(x.reshape((b * t,) + x.shape[2:]), kernel[0],
                       (sh, sw), [(ph, ph), (pw, pw)])
            return y.reshape((b, t) + y.shape[1:])
        if kh == 1 and kw == 1:
            # temporal: (H,W) are inert -> fold into one spatial dim
            assert ph == 0 and pw == 0, (
                "spatial padding with a 1-tap spatial kernel")
            if sh > 1 or sw > 1:
                x = x[:, :, ::sh, ::sw]
            _, t, h, w, c = x.shape
            y = conv2d(x.reshape(b, t, h * w, c),
                       kernel.reshape(kt, 1, c, self.features),
                       (st, 1), [(pt, pt), (0, 0)])
            return y.reshape(b, y.shape[1], h, w, self.features)
        # full (kt,kh,kw) kernel (the conv1 stem): kt shifted 2D convs
        # summed — conv is linear in the kernel taps, so
        # out[t'] = sum_dt conv2d(x[st*t' + dt - pt], kernel[dt]).
        xp = jnp.pad(x, ((0, 0), (pt, pt), (0, 0), (0, 0), (0, 0)))
        t_out = (x.shape[1] + 2 * pt - kt) // st + 1
        out = None
        for dt in range(kt):
            xs = lax.slice_in_dim(xp, dt, dt + st * (t_out - 1) + 1, st,
                                  axis=1)
            y = conv2d(xs.reshape((b * t_out,) + xs.shape[2:]), kernel[dt],
                       (sh, sw), [(ph, ph), (pw, pw)])
            out = y if out is None else out + y
        return out.reshape((b, t_out) + out.shape[1:])
