"""3D convolution with selectable TPU lowering.

The whole S3D-G trunk (reference s3dg.py:61-111) is built from three
conv shapes: pointwise ``(1,1,1)``, spatial ``(1,k,k)``, and temporal
``(k,1,1)`` — plus the one full ``(3,7,7)`` stem conv.  ``impl`` picks
how they reach the MXU:

- ``"native"``: one ``lax.conv_general_dilated`` with 3 spatial dims
  (NDHWC).  XLA:TPU supports it, but its 3D-conv tiling with tiny
  temporal extents (T' = 8..2 deep in the trunk) is far less tuned than
  the 2D path.
- ``"fold2d"``: the same math expressed as 2D convolutions, the layout
  XLA:TPU's conv emitter is actually optimized for — spatial kernels
  fold T into the batch dim ((B,T,H,W,C) -> (B*T,H,W,C)), temporal
  kernels fold (H,W) into one spatial dim ((B,T,H*W,C)), and a full
  (kt,kh,kw) kernel decomposes into kt temporally-shifted 2D convs
  summed (valid because conv is linear in the kernel taps).
- ``"im2col"``: bypass the conv emitter entirely — extract every
  receptive-field patch into a ``(B, T', H', W', kt*kh*kw*Cin)`` tensor
  and hit the MXU with ONE ``dot_general``, the op XLA:TPU tiles best.
  Built for the two non-separable stem convs (3x7x7 stride-2 conv1 runs
  at 1% of peak under the native lowering, 102x over its roofline bound
  — STAGE_PROBE_native_fwdbwd.md): their tiny 3/24-channel input gives
  the conv tiler nothing to put on the 128-wide MXU lanes, while the
  im2col contraction dim (441*3 = 1323 for conv1) fills them.  A custom
  VJP keeps the BACKWARD in matmul form too (PERF.md puts the step's
  backward near 13% MFU, so a forward-only fix is half the win):
  dW = patches(x)^T @ dY and dX = patches(dilate(dY)) @ flip(W)^T — the
  standard conv-transpose-as-conv identity, expressed as im2col again.
  Cost: the patch tensor materializes prod(k)/prod(s) x the input
  (~55x for conv1) in HBM; conv1's activations are small enough that
  this stays ~0.5 GB/clip-batch-32 in bf16, but it is why im2col is a
  per-STAGE choice, not a global one — deep trunk stages with big C
  would blow HBM for no tiling gain.

The parameter is a single ``kernel`` of shape ``(t, h, w, in, out)``
in ALL impls, so checkpoints swap freely and the flag is purely a
performance choice (``scripts/stage_probe.py --conv_impl`` measures one
impl per stage; ``--autotune`` measures all three and emits the winning
per-stage map).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax.linen.dtypes import promote_dtype
from jax import lax

Array = jax.Array

_DN3D = ("NDHWC", "DHWIO", "NDHWC")
_DN2D = ("NHWC", "HWIO", "NHWC")

IMPLS = ("native", "fold2d", "im2col")


def _extract_patches(x: Array, ksize: Tuple[int, int, int],
                     strides: Tuple[int, int, int],
                     pads) -> Array:
    """(B,T,H,W,C) -> (B,T',H',W', kt*kh*kw*C) patch tensor.

    ``pads`` is one (lo, hi) pair per spatial dim (the backward's
    transposed conv needs asymmetric padding).  Tap order along the last
    axis is (dt, dh, dw, c) — exactly ``kernel.reshape(-1, features)``'s
    row order, so the caller can contract with one reshaped matmul.
    The taps are strided slices of ONE padded array; XLA fuses them into
    the dot operand's loads rather than 147 separate copies.
    """
    kt, kh, kw = ksize
    st, sh, sw = strides
    b, _, _, _, c = x.shape
    xp = jnp.pad(x, ((0, 0),) + tuple(pads) + ((0, 0),))
    outs = [(xp.shape[i + 1] - k) // s + 1
            for i, (k, s) in enumerate(zip(ksize, strides))]
    to, ho, wo = outs
    taps = []
    for dt in range(kt):
        for dh in range(kh):
            for dw in range(kw):
                taps.append(lax.slice(
                    xp,
                    (0, dt, dh, dw, 0),
                    (b, dt + st * (to - 1) + 1, dh + sh * (ho - 1) + 1,
                     dw + sw * (wo - 1) + 1, c),
                    (1, st, sh, sw, 1)))
    return jnp.concatenate(taps, axis=-1)


def _patch_matmul(patches: Array, kernel_mat: Array) -> Array:
    """(B,T',H',W',K) x (K,F) -> (B,T',H',W',F): the one large MXU dot."""
    return lax.dot_general(patches, kernel_mat, (((4,), (0,)), ((), ())))


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _im2col_conv(x: Array, kernel: Array, strides: Tuple[int, int, int],
                 padding: Tuple[int, int, int]) -> Array:
    kt, kh, kw, ci, co = kernel.shape
    patches = _extract_patches(x, (kt, kh, kw), strides,
                               [(p, p) for p in padding])
    return _patch_matmul(patches, kernel.reshape(kt * kh * kw * ci, co))


def _im2col_fwd(x, kernel, strides, padding):
    # residual is (x, kernel), NOT the patch tensor: patches are
    # prod(k)/prod(s) x the input and recomputing them in the backward
    # (pure data movement) is far cheaper than holding them across the
    # whole step.
    return _im2col_conv(x, kernel, strides, padding), (x, kernel)


def _im2col_bwd(strides, padding, res, g):
    x, kernel = res
    kt, kh, kw, ci, co = kernel.shape
    st, sh, sw = strides
    b = x.shape[0]
    to, ho, wo = g.shape[1:4]

    # dW: contract the recomputed patches against dY over every output
    # position — one (K, B*S) x (B*S, F) matmul.
    patches = _extract_patches(x, (kt, kh, kw), strides,
                               [(p, p) for p in padding])
    dw = lax.dot_general(patches, g,
                         (((0, 1, 2, 3), (0, 1, 2, 3)), ((), ())))
    dw = dw.reshape(kt, kh, kw, ci, co).astype(kernel.dtype)

    # dX: the transposed conv, AS im2col — dilate dY by the stride,
    # pad (k-1-p) low / (in_size + p - dilated_size) high, then one
    # matmul against the spatially-flipped, in/out-transposed kernel.
    # (pad_hi >= 0 always: p <= k-1 for every trunk conv, and it absorbs
    # input columns a non-dividing stride never touched.)
    dil = (st * (to - 1) + 1, sh * (ho - 1) + 1, sw * (wo - 1) + 1)
    gd = jnp.zeros((b,) + dil + (co,), g.dtype)
    gd = gd.at[:, ::st, ::sh, ::sw].set(g)
    pads = [(k - 1 - p, size + p - d)
            for k, p, size, d in zip((kt, kh, kw), padding, x.shape[1:4], dil)]
    gpatches = _extract_patches(gd, (kt, kh, kw), (1, 1, 1), pads)
    wflip = kernel[::-1, ::-1, ::-1].transpose(0, 1, 2, 4, 3)
    dx = _patch_matmul(gpatches, wflip.reshape(kt * kh * kw * co, ci))
    return dx.astype(x.dtype), dw


_im2col_conv.defvjp(_im2col_fwd, _im2col_bwd)


class Conv3D(nn.Module):
    """Bias-free 3D conv with explicit symmetric padding per dim,
    matching the torch ``nn.Conv3d`` semantics every trunk conv uses."""

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] = (1, 1, 1)
    padding: Sequence[int] = (0, 0, 0)
    impl: str = "native"                  # 'native' | 'fold2d' | 'im2col'
    kernel_init: Callable = nn.initializers.lecun_normal()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        kt, kh, kw = (int(v) for v in self.kernel_size)
        st, sh, sw = (int(v) for v in self.strides)
        pt, ph, pw = (int(v) for v in self.padding)
        kernel = self.param("kernel", self.kernel_init,
                            (kt, kh, kw, x.shape[-1], self.features),
                            jnp.float32)
        x, kernel = promote_dtype(x, kernel, dtype=self.dtype)

        if self.impl == "native":
            return lax.conv_general_dilated(
                x, kernel, (st, sh, sw), [(pt, pt), (ph, ph), (pw, pw)],
                dimension_numbers=_DN3D)
        if self.impl == "im2col":
            return _im2col_conv(x, kernel, (st, sh, sw), (pt, ph, pw))
        if self.impl != "fold2d":
            raise ValueError(f"unknown conv impl {self.impl!r}")

        def conv2d(y, kern, strides, pads):
            return lax.conv_general_dilated(y, kern, strides, pads,
                                            dimension_numbers=_DN2D)

        b = x.shape[0]
        if kt == 1:
            # spatial/pointwise: T is inert -> fold it into batch
            assert pt == 0, "temporal padding with a 1-tap temporal kernel"
            if st > 1:
                x = x[:, ::st]
            t = x.shape[1]
            y = conv2d(x.reshape((b * t,) + x.shape[2:]), kernel[0],
                       (sh, sw), [(ph, ph), (pw, pw)])
            return y.reshape((b, t) + y.shape[1:])
        if kh == 1 and kw == 1:
            # temporal: (H,W) are inert -> fold into one spatial dim
            assert ph == 0 and pw == 0, (
                "spatial padding with a 1-tap spatial kernel")
            if sh > 1 or sw > 1:
                x = x[:, :, ::sh, ::sw]
            _, t, h, w, c = x.shape
            y = conv2d(x.reshape(b, t, h * w, c),
                       kernel.reshape(kt, 1, c, self.features),
                       (st, 1), [(pt, pt), (0, 0)])
            return y.reshape(b, y.shape[1], h, w, self.features)
        # full (kt,kh,kw) kernel (the conv1 stem): kt shifted 2D convs
        # summed — conv is linear in the kernel taps, so
        # out[t'] = sum_dt conv2d(x[st*t' + dt - pt], kernel[dt]).
        xp = jnp.pad(x, ((0, 0), (pt, pt), (0, 0), (0, 0), (0, 0)))
        t_out = (x.shape[1] + 2 * pt - kt) // st + 1
        out = None
        for dt in range(kt):
            xs = lax.slice_in_dim(xp, dt, dt + st * (t_out - 1) + 1, st,
                                  axis=1)
            y = conv2d(xs.reshape((b * t_out,) + xs.shape[2:]), kernel[dt],
                       (sh, sw), [(ph, ph), (pw, pw)])
            out = y if out is None else out + y
        return out.reshape((b, t_out) + out.shape[1:])
