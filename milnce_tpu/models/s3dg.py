"""S3D-G (Gated Separable-3D Inception) video tower, TPU-native.

A ground-up Flax re-design of the capability of the reference model
(/root/reference/s3dg.py:11-328).  Differences from the reference are
deliberate TPU-first choices, not omissions:

- **Channels-last** ``(B, T, H, W, C)`` layout: XLA:TPU tiles NDHWC convs
  straight onto the MXU (the reference is NCDHW for cuDNN).
- 3D convolutions via ``flax.linen.Conv`` -> ``lax.conv_general_dilated``
  (MXU); no cuDNN benchmark flags needed — XLA autotunes.
- TF-SAME max-pooling via ``nn.max_pool(..., padding='SAME')``; the
  reference emulates TF-SAME by hand with ConstantPad3d(0)+ceil_mode
  (s3dg.py:114-146).  Padding with ``-inf`` (ours) equals padding with 0
  (theirs) because every pooled tensor here is post-ReLU/post-sigmoid-gate,
  hence non-negative.
- BatchNorm is either local (parity with the GPU reference, README.md:13)
  or cross-replica over a mesh axis (``axis_name='data'``) as in the
  original DeepMind TPU run — a flag, not a fork.
- The reference cannot actually disable gating (`self.gating` is
  overwritten with a module at s3dg.py:220, making the flag always truthy
  — SURVEY.md §2.4); here ``gating=False`` genuinely disables it.

Parameter-shape map to the reference (for checkpoint conversion):
torch ``Conv3d.weight (O, I, t, h, w)`` <-> flax ``kernel (t, h, w, I, O)``.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax import lax

from milnce_tpu.models.conv3d import Conv3D
from milnce_tpu.models.initializers import (kernel_init_for,
                                            torch_bias,
                                            torch_default_kernel)
from milnce_tpu.models.text import SentenceEmbedding

Array = jax.Array


def _triple(v) -> Tuple[int, int, int]:
    if isinstance(v, (tuple, list)):
        assert len(v) == 3
        return tuple(int(x) for x in v)
    return (int(v),) * 3


class SelfGating(nn.Module):
    """Feature gating, the "G" in S3D-G (reference s3dg.py:47-59):
    squeeze over (T,H,W) -> dense -> sigmoid -> channel rescale.

    Dense layers keep the torch-default kernel/bias init in both init
    modes — the reference's kaiming_normal branch re-inits only Conv3d
    and BatchNorm (s3dg.py:240-246), leaving Linears at torch defaults.
    """

    kernel_init: Callable = torch_default_kernel()
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array) -> Array:
        squeezed = jnp.mean(x, axis=(1, 2, 3))
        weights = nn.Dense(x.shape[-1], kernel_init=torch_default_kernel(),
                           bias_init=torch_bias(x.shape[-1]),
                           dtype=self.dtype, name="fc")(squeezed)
        weights = jax.nn.sigmoid(weights)
        return weights[:, None, None, None, :] * x


class STConv3D(nn.Module):
    """(Optionally separable) spatio-temporal conv + BN + ReLU
    (reference s3dg.py:61-111).

    ``separable=True`` factorizes a (t,k,k) kernel into a spatial (1,k,k)
    conv followed by a temporal (t,1,1) conv, each with its own BN+ReLU.
    Padding is torch-style symmetric (explicit per-dim), matching the
    reference's nn.Conv3d semantics exactly.
    """

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] | int = 1
    padding: Sequence[int] | int = 0
    separable: bool = False
    bn_axis_name: Optional[str] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    conv_impl: str = "native"
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        k = _triple(self.kernel_size)
        s = _triple(self.strides)
        p = _triple(self.padding)

        def conv(y, feat, kern, stride, pad, name):
            return Conv3D(
                feat, kernel_size=kern, strides=stride, padding=pad,
                impl=self.conv_impl, kernel_init=self.kernel_init,
                dtype=self.dtype, name=name,
            )(y)

        def bn(y, name):
            return nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                axis_name=self.bn_axis_name if train else None,
                dtype=self.dtype, name=name,
            )(y)

        if self.separable and k[0] != 1:
            x = conv(x, self.features, (1, k[1], k[2]), (1, s[1], s[2]),
                     (0, p[1], p[2]), "conv_spatial")
            x = nn.relu(bn(x, "bn_spatial"))
            x = conv(x, self.features, (k[0], 1, 1), (s[0], 1, 1),
                     (p[0], 0, 0), "conv_temporal")
            x = nn.relu(bn(x, "bn_temporal"))
        else:
            x = conv(x, self.features, k, s, p, "conv")
            x = nn.relu(bn(x, "bn"))
        return x


class InceptionBlock(nn.Module):
    """Four-branch 3D Inception block with per-branch self-gating
    (reference s3dg.py:11-45).

    Branches: (0) 1x1x1; (1) 1x1x1 -> separable 3x3x3; (2) same as (1);
    (3) 3x3x3 maxpool stride 1 -> 1x1x1.  Channel-concat at the end.
    """

    num_outputs_0_0a: int
    num_outputs_1_0a: int
    num_outputs_1_0b: int
    num_outputs_2_0a: int
    num_outputs_2_0b: int
    num_outputs_3_0b: int
    gating: bool = True
    bn_axis_name: Optional[str] = None
    kernel_init: Callable = nn.initializers.lecun_normal()
    conv_impl: str = "native"
    dtype: Any = jnp.float32

    @property
    def output_dim(self) -> int:
        return (self.num_outputs_0_0a + self.num_outputs_1_0b
                + self.num_outputs_2_0b + self.num_outputs_3_0b)

    @nn.compact
    def __call__(self, x: Array, train: bool = False) -> Array:
        common = dict(bn_axis_name=self.bn_axis_name,
                      kernel_init=self.kernel_init,
                      conv_impl=self.conv_impl, dtype=self.dtype)
        b0 = STConv3D(self.num_outputs_0_0a, (1, 1, 1), name="conv_b0",
                      **common)(x, train)
        b1 = STConv3D(self.num_outputs_1_0a, (1, 1, 1), name="conv_b1_a",
                      **common)(x, train)
        b1 = STConv3D(self.num_outputs_1_0b, (3, 3, 3), padding=1,
                      separable=True, name="conv_b1_b", **common)(b1, train)
        b2 = STConv3D(self.num_outputs_2_0a, (1, 1, 1), name="conv_b2_a",
                      **common)(x, train)
        b2 = STConv3D(self.num_outputs_2_0b, (3, 3, 3), padding=1,
                      separable=True, name="conv_b2_b", **common)(b2, train)
        # stride-1 3x3x3 maxpool w/ symmetric pad 1 == SAME padding.
        b3 = nn.max_pool(x, (3, 3, 3), strides=(1, 1, 1), padding="SAME")
        b3 = STConv3D(self.num_outputs_3_0b, (1, 1, 1), name="conv_b3_b",
                      **common)(b3, train)
        if self.gating:
            b0 = SelfGating(self.kernel_init, self.dtype, name="gating_b0")(b0)
            b1 = SelfGating(self.kernel_init, self.dtype, name="gating_b1")(b1)
            b2 = SelfGating(self.kernel_init, self.dtype, name="gating_b2")(b2)
            b3 = SelfGating(self.kernel_init, self.dtype, name="gating_b3")(b3)
        return jnp.concatenate([b0, b1, b2, b3], axis=-1)


def _tf_same_max_pool(x: Array, window: Tuple[int, int, int],
                      strides: Tuple[int, int, int]) -> Array:
    """Reference-exact "TF-SAME" 3D max-pool over (T,H,W) of NDHWC.

    The reference's MaxPool3dTFPadding (s3dg.py:114-146) pads each dim by
    ``max(k - s, 0)`` split low-first, then pools with ceil_mode.  For
    stride-divisible sizes that coincides with XLA 'SAME'; for odd sizes
    it does NOT (XLA SAME centers differently), so we reproduce the
    reference padding explicitly plus the ceil-mode tail.  Padding with
    ``-inf`` (window init value) equals the reference's zero-pad because
    every pooled tensor here is post-ReLU/gate, hence non-negative.
    """
    dims = (1,) + tuple(window) + (1,)
    strd = (1,) + tuple(strides) + (1,)
    padding = [(0, 0)]
    for size, k, s in zip(x.shape[1:4], window, strides):
        pad_along = max(k - s, 0)
        lo = pad_along // 2
        hi = pad_along - lo
        ceil_extra = (-(size + lo + hi - k)) % s      # ceil_mode tail
        padding.append((lo, hi + ceil_extra))
    padding.append((0, 0))
    return lax.reduce_window(x, -jnp.inf, lax.max, dims, strd, padding)


def space_to_depth(video: Array) -> Array:
    """2x2x2 space-to-depth stem rearrangement (reference s3dg.py:248-253),
    channels-last: (B,T,H,W,C) -> (B,T/2,H/2,W/2,8C) with channel order
    (t2,h2,w2,C) — matches the torch permute for checkpoint parity."""
    b, t, h, w, c = video.shape
    video = video.reshape(b, t // 2, 2, h // 2, 2, w // 2, 2, c)
    video = video.transpose(0, 1, 3, 5, 2, 4, 6, 7)
    return video.reshape(b, t // 2, h // 2, w // 2, 8 * c)


class S3D(nn.Module):
    """S3D-G two-tower model: video CNN + word2vec sentence tower
    (reference s3dg.py:207-328).

    ``__call__(video, text, mode, mixed5c, train)``:

    - video: (B, T, H, W, 3) float in [0, 1] (normalize on device).
    - text:  (B', max_words) int token ids.
    - mode 'all' -> (video_embd (B, D), text_embd (B', D));
      'video' -> video embedding (or 1024-d pooled mixed_5c features when
      ``mixed5c=True``, used by the linear probe — s3dg.py:323-325);
      'text' -> text embedding.
    """

    num_classes: int = 512
    gating: bool = True
    use_space_to_depth: bool = False
    inception_blocks: int = 9           # trunk depth: first N of the 9
                                        # Inception blocks (9 = reference
                                        # s3dg.py:223-233; smaller values
                                        # give cheap variants for dryruns)
    vocab_size: int = 66250
    word_embedding_dim: int = 300
    text_hidden_dim: int = 2048
    weight_init: str = "uniform"
    bn_axis_name: Optional[str] = None
    conv_impl: str = "native"           # 'native' 3D convs | 'fold2d' |
                                        # 'im2col' (see models/conv3d.py)
    conv_impl_map: Optional[Tuple[Tuple[str, str], ...]] = None
                                        # per-stage (stage, impl) overrides at
                                        # probe granularity (conv1, conv_2b,
                                        # conv_2c, mixed_*) — tuple of pairs,
                                        # not a dict, so the module stays
                                        # hashable; unnamed stages use
                                        # conv_impl.  build_model constructs
                                        # it from ModelConfig.conv_impl_map.
    embedding_init: Optional[Callable] = None
    remat: bool = False                 # rematerialize Inception blocks to
                                        # trade FLOPs for HBM at big batches
    dtype: Any = jnp.float32

    def setup(self):
        assert 1 <= self.inception_blocks <= 9, (
            f"inception_blocks must be in [1, 9], got {self.inception_blocks}")
        ki = kernel_init_for(self.weight_init)
        # per-stage impl resolution: the map (autotune output) wins over
        # the uniform conv_impl for the stages it names
        impl_map = dict(self.conv_impl_map or ())

        def impl(stage: str) -> str:
            return impl_map.get(stage, self.conv_impl)

        common = dict(bn_axis_name=self.bn_axis_name, kernel_init=ki,
                      dtype=self.dtype)
        block_cls = (nn.remat(InceptionBlock, static_argnums=(2,))
                     if self.remat else InceptionBlock)
        if self.use_space_to_depth:
            # reference s3dg.py:215 (+ the post-conv crop in forward_video)
            self.conv1 = STConv3D(64, (2, 4, 4), strides=1, padding=(1, 2, 2),
                                  conv_impl=impl("conv1"), name="conv1",
                                  **common)
        else:
            # reference s3dg.py:217
            self.conv1 = STConv3D(64, (3, 7, 7), strides=2, padding=(1, 3, 3),
                                  conv_impl=impl("conv1"), name="conv1",
                                  **common)
        self.conv_2b = STConv3D(64, (1, 1, 1), conv_impl=impl("conv_2b"),
                                name="conv_2b", **common)
        self.conv_2c = STConv3D(192, (3, 3, 3), padding=1, separable=True,
                                conv_impl=impl("conv_2c"), name="conv_2c",
                                **common)
        self.stem_gating = SelfGating(ki, self.dtype, name="gating")
        blocks = dict(gating=self.gating, **common)
        self.mixed_3b = block_cls(64, 96, 128, 16, 32, 32,
                                  conv_impl=impl("mixed_3b"),
                                  name="mixed_3b", **blocks)
        self.mixed_3c = block_cls(128, 128, 192, 32, 96, 64,
                                  conv_impl=impl("mixed_3c"),
                                  name="mixed_3c", **blocks)
        self.mixed_4b = block_cls(192, 96, 208, 16, 48, 64,
                                  conv_impl=impl("mixed_4b"),
                                  name="mixed_4b", **blocks)
        self.mixed_4c = block_cls(160, 112, 224, 24, 64, 64,
                                  conv_impl=impl("mixed_4c"),
                                  name="mixed_4c", **blocks)
        self.mixed_4d = block_cls(128, 128, 256, 24, 64, 64,
                                  conv_impl=impl("mixed_4d"),
                                  name="mixed_4d", **blocks)
        self.mixed_4e = block_cls(112, 144, 288, 32, 64, 64,
                                  conv_impl=impl("mixed_4e"),
                                  name="mixed_4e", **blocks)
        self.mixed_4f = block_cls(256, 160, 320, 32, 128, 128,
                                  conv_impl=impl("mixed_4f"),
                                  name="mixed_4f", **blocks)
        self.mixed_5b = block_cls(256, 160, 320, 32, 128, 128,
                                  conv_impl=impl("mixed_5b"),
                                  name="mixed_5b", **blocks)
        self.mixed_5c = block_cls(384, 192, 384, 48, 128, 128,
                                  conv_impl=impl("mixed_5c"),
                                  name="mixed_5c", **blocks)
        # Linear layers stay at torch defaults in both init modes
        # (s3dg.py:240-246 re-inits only convs/BN); fan-in = output dim of
        # the last active block (1024 for the full mixed_5c trunk).
        all_blocks = (self.mixed_3b, self.mixed_3c, self.mixed_4b,
                      self.mixed_4c, self.mixed_4d, self.mixed_4e,
                      self.mixed_4f, self.mixed_5b, self.mixed_5c)
        trunk_dim = all_blocks[self.inception_blocks - 1].output_dim
        self.fc = nn.Dense(self.num_classes, kernel_init=torch_default_kernel(),
                           bias_init=torch_bias(trunk_dim),
                           dtype=self.dtype, name="fc")
        self.text_module = SentenceEmbedding(
            embd_dim=self.num_classes,
            vocab_size=self.vocab_size,
            word_embedding_dim=self.word_embedding_dim,
            hidden_dim=self.text_hidden_dim,
            embedding_init=self.embedding_init,
            kernel_init=ki,
            dtype=self.dtype,
            name="text_module",
        )

    def _trunk(self, video: Array, train: bool) -> Array:
        """Conv trunk up to mixed_5c (B, T', H', W', 1024), mirrors
        reference s3dg.py:265-321."""
        net = video
        if self.use_space_to_depth:
            net = space_to_depth(net)
        net = self.conv1(net, train)
        if self.use_space_to_depth:
            net = net[:, 1:, 1:, 1:, :]  # s3dg.py:271-272
        net = _tf_same_max_pool(net, (1, 3, 3), (1, 2, 2))   # maxpool_2a
        net = self.conv_2b(net, train)
        net = self.conv_2c(net, train)
        if self.gating:
            net = self.stem_gating(net)
        net = _tf_same_max_pool(net, (1, 3, 3), (1, 2, 2))   # maxpool_3a
        blocks = (self.mixed_3b, self.mixed_3c, self.mixed_4b, self.mixed_4c,
                  self.mixed_4d, self.mixed_4e, self.mixed_4f, self.mixed_5b,
                  self.mixed_5c)
        # maxpool_4a before block idx 2, maxpool_5a before idx 7
        # (reference s3dg.py:223-233 ordering)
        pools_before = {2: ((3, 3, 3), (2, 2, 2)), 7: ((2, 2, 2), (2, 2, 2))}
        for idx, block in enumerate(blocks[:self.inception_blocks]):
            if idx in pools_before:
                win, strd = pools_before[idx]
                net = _tf_same_max_pool(net, win, strd)
            net = block(net, train)
        return net

    def forward_video(self, video: Array, mixed5c: bool = False,
                      train: bool = False) -> Array:
        """Pooled video embedding (reference s3dg.py:323-328)."""
        net = jnp.mean(self._trunk(video, train), axis=(1, 2, 3))
        if mixed5c:
            return net                                       # (B, 1024)
        return self.fc(net)                                  # (B, num_classes)

    def forward_video_sequence(self, video: Array,
                               train: bool = False) -> Array:
        """Temporal sequence of frame-group embeddings: pool mixed_5c over
        space only -> (B, T', num_classes).

        This is the sequence view the fork's (soft-)DTW losses align
        (loss.py:20-134 operate on (B, n, d) sequences); the reference
        never committed the model change that produces them — we make it a
        first-class mode.
        """
        net = jnp.mean(self._trunk(video, train), axis=(2, 3))
        return self.fc(net)

    def forward_text(self, tokens: Array) -> Array:
        return self.text_module(tokens)

    def __call__(self, video: Optional[Array], text: Optional[Array],
                 mode: str = "all", mixed5c: bool = False,
                 train: bool = False):
        if mode == "all":
            return self.forward_video(video, train=train), self.forward_text(text)
        if mode == "video":
            return self.forward_video(video, mixed5c=mixed5c, train=train)
        if mode == "text":
            return self.forward_text(text)
        if mode == "sequence":
            # (video seq (B, T', D), per-candidate text (B', D))
            return (self.forward_video_sequence(video, train=train),
                    self.forward_text(text))
        raise NotImplementedError(mode)
