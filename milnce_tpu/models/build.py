"""Model factory: ModelConfig -> S3D module (+ optional pretrained word2vec).

Replaces the reference's constructor-side file IO (s3dg.py:235-238, where the
model loads word2vec.pth and dict.npy itself): file loading lives here, the
module stays pure.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

from milnce_tpu.config import ModelConfig, parse_conv_impl_map
from milnce_tpu.models.s3dg import S3D
from milnce_tpu.models.text import word2vec_embedding_init


def load_word2vec_table(path: str) -> np.ndarray:
    """Load a pretrained (V, 300) embedding table from .npy/.npz, or from
    the reference's torch-saved ``word2vec.pth`` (s3dg.py:159)."""
    if path.endswith((".pth", ".pt", ".tar")):
        import torch

        return torch.load(path, map_location="cpu",
                          weights_only=False).numpy()
    if path.endswith(".npz"):
        with np.load(path) as z:
            return z[list(z.files)[0]]
    return np.load(path)


def build_model(cfg: ModelConfig, bn_axis_name: str | None = None) -> S3D:
    embedding_init = None
    vocab_size = cfg.vocab_size
    if cfg.word2vec_path and os.path.exists(cfg.word2vec_path):
        table = load_word2vec_table(cfg.word2vec_path)
        vocab_size = table.shape[0]
        embedding_init = word2vec_embedding_init(table)
    return S3D(
        num_classes=cfg.embedding_dim,
        gating=cfg.gating,
        use_space_to_depth=cfg.space_to_depth,
        inception_blocks=cfg.inception_blocks,
        vocab_size=vocab_size,
        word_embedding_dim=cfg.word_embedding_dim,
        text_hidden_dim=cfg.text_hidden_dim,
        weight_init=cfg.weight_init,
        bn_axis_name=bn_axis_name if cfg.sync_batchnorm else None,
        conv_impl=cfg.conv_impl,
        # hashable form (tuple of pairs) so the module stays usable as a
        # static jit argument; S3D turns it back into a lookup
        conv_impl_map=tuple(sorted(
            parse_conv_impl_map(cfg.conv_impl_map).items())) or None,
        embedding_init=embedding_init,
        remat=cfg.remat,
        dtype=jnp.dtype(cfg.dtype),
    )
