"""Weight initializers.

The reference's ``init='uniform'`` is the torch default (kaiming-uniform with
a=sqrt(5), i.e. U(±sqrt(3/ (3*fan_in)))); ``init='kaiming_normal'`` is
``nn.init.kaiming_normal_(mode='fan_in', nonlinearity='relu')``
(s3dg.py:240-246).  We expose both as JAX initializers.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax.nn import initializers as init


def torch_default_kernel():
    """torch's Conv/Linear default: kaiming_uniform(a=sqrt(5)) == uniform
    variance scaling with gain 1/3."""
    return init.variance_scaling(1.0 / 3.0, "fan_in", "uniform")


def kaiming_normal_kernel():
    """kaiming_normal_(mode='fan_in', nonlinearity='relu'): N(0, 2/fan_in)."""
    return init.variance_scaling(2.0, "fan_in", "normal")


def torch_bias(fan_in: int):
    """torch default bias: U(±1/sqrt(fan_in))."""
    bound = 1.0 / (fan_in ** 0.5)

    def _init(key, shape, dtype=jnp.float32):
        import jax.random as jr

        return jr.uniform(key, shape, dtype, -bound, bound)

    return _init


def kernel_init_for(name: str):
    if name == "kaiming_normal":
        return kaiming_normal_kernel()
    return torch_default_kernel()
