from milnce_tpu.models.s3dg import S3D, InceptionBlock, STConv3D, SelfGating  # noqa: F401
from milnce_tpu.models.text import SentenceEmbedding  # noqa: F401
from milnce_tpu.models.build import build_model  # noqa: F401
