"""Host input pipeline: per-host sharded, threaded, device-prefetched.

Replaces torch ``DataLoader + DistributedSampler`` (main_distributed.py:
127-141) with a TPU-VM-shaped design:

- the global sample index space is shuffled per epoch with a seed
  (``DistributedSampler.set_epoch`` parity, main_distributed.py:187) and
  partitioned by host process, then each host draws only its shard;
- a thread pool of ``num_reader_threads`` decodes samples concurrently
  (the decode cost is ffmpeg-subprocess-bound, so Python threads scale —
  same reasoning as torch's worker processes but without pickling);
- batches stay **uint8** end-to-end and are handed to
  :func:`device_prefetch`, which keeps ``depth`` batches in flight on
  device (async ``device_put``) so host decode overlaps device compute;
- ``drop_last=True`` semantics: only full GLOBAL batches are emitted
  (a short epoch tail never stalls a pod step — SURVEY.md §7 hard part 2).
"""

from __future__ import annotations

import concurrent.futures as cf
import itertools
from typing import Callable, Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class ShardedLoader:
    """Iterates a source (len + sample(idx, rng)) as per-host batches."""

    def __init__(self, source, global_batch_size: int, seed: int = 0,
                 num_threads: int = 8, shuffle: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 drop_last: bool = True):
        self.source = source
        self.global_batch = int(global_batch_size)
        self.seed = seed
        self.num_threads = max(1, int(num_threads))
        self.shuffle = shuffle
        self.pi = jax.process_index() if process_index is None else process_index
        self.pc = jax.process_count() if process_count is None else process_count
        assert self.global_batch % self.pc == 0, (global_batch_size, self.pc)
        self.local_batch = self.global_batch // self.pc
        self.drop_last = drop_last

    def steps_per_epoch(self) -> int:
        n = len(self.source)
        return n // self.global_batch if self.drop_last else -(-n // self.global_batch)

    def epoch(self, epoch: int) -> Iterator[dict]:
        """Yield this host's batches for one epoch (dicts of stacked np)."""
        n = len(self.source)
        order = np.arange(n)
        if self.shuffle:
            np.random.RandomState(self.seed + epoch).shuffle(order)
        usable = (n // self.global_batch) * self.global_batch
        order = order[:usable]
        # host h takes rows h, h+pc, h+2pc... of each global batch
        local = order.reshape(-1, self.global_batch)[:, self.pi::self.pc]

        rng_base = self.seed * 100_003 + epoch
        with cf.ThreadPoolExecutor(self.num_threads) as pool:
            def fetch(idx):
                return self.source.sample(
                    int(idx), np.random.RandomState((rng_base + int(idx)) % (2**31)))

            for batch_ids in local:
                samples = list(pool.map(fetch, batch_ids))
                yield {k: np.stack([s[k] for s in samples]) for k in samples[0]}


def device_prefetch(iterator: Iterator[dict], mesh: Mesh,
                    axis: str = "data", depth: int = 2) -> Iterator[dict]:
    """Keep ``depth`` batches in flight on device, sharded on dim 0.
    ``device_put`` is async, so this overlaps H2D transfer with compute."""
    sharding = NamedSharding(mesh, P(axis))
    put = lambda b: jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), b)
    queue = []
    for batch in iterator:
        queue.append(put(batch))
        if len(queue) > depth:
            yield queue.pop(0)
    yield from queue


def flatten_text(batch: dict) -> tuple:
    """{'video': (B,T,H,W,3) u8, 'text': (B,K,W) i32} ->
    (video, text reshaped (B*K, W)) — the reference's flatten at
    main_distributed.py:229."""
    text = batch["text"]
    return batch["video"], text.reshape(-1, text.shape[-1])
