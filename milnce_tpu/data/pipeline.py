"""Host input pipeline: per-host sharded, threaded, device-prefetched.

Replaces torch ``DataLoader + DistributedSampler`` (main_distributed.py:
127-141) with a TPU-VM-shaped design:

- the global sample index space is shuffled per epoch with a seed
  (``DistributedSampler.set_epoch`` parity, main_distributed.py:187) and
  partitioned by host process, then each host draws only its shard;
- a thread pool of ``num_reader_threads`` decodes samples concurrently
  (the decode cost is ffmpeg-subprocess-bound, so Python threads scale —
  same reasoning as torch's worker processes but without pickling);
- batches stay **uint8** end-to-end and are handed to
  :func:`device_prefetch`, which keeps ``depth`` batches in flight on
  device (async ``device_put``) so host decode overlaps device compute;
- only full GLOBAL batches are emitted (torch drop_last=True semantics:
  a short epoch tail can't shard evenly over the mesh and would need its
  own compiled step — SURVEY.md §7 hard part 2).
"""

from __future__ import annotations

import concurrent.futures as cf
import sys
from typing import Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.obs import spans as obs_spans

# Decode-watchdog telemetry on the process-wide registry (host-side
# counters incremented from reader threads — OBSERVABILITY.md).
_OBS_TIMEOUTS = obs_metrics.registry().counter(
    "milnce_data_decode_timeouts_total",
    "decode futures that exceeded the watchdog timeout (wedged decodes)")
_OBS_RETRIES = obs_metrics.registry().counter(
    "milnce_data_decode_retries_total",
    "fresh decode attempts resubmitted by the watchdog")
# Data-wait attribution (goodput ledger, OBSERVABILITY.md): seconds the
# CONSUMER (the train loop pulling device_prefetch) spent blocked on
# the next batch.  Incremented on the consumer thread itself — create-
# or-get means the loop reads window deltas off the same child for the
# live goodput gauge.
_OBS_DATA_WAIT = obs_metrics.registry().counter(
    "milnce_data_wait_seconds_total",
    "host seconds the training loop blocked waiting for batch data")

_EXHAUSTED = object()


class ShardedLoader:
    """Iterates a source (len + sample(idx, rng)) as per-host batches.

    Decode is PIPELINED across batch boundaries: a sliding window of
    ``(1 + lookahead_batches) * local_batch`` sample futures stays in
    flight, so the reader threads are already decoding batch k+1 (and
    k+2) while batch k is being stacked/consumed — a per-batch
    ``pool.map`` would drain to a barrier at every batch edge and idle
    the readers exactly when the device is waiting on data.  Sample
    content is a pure function of (seed, epoch, index), so scheduling
    never changes what a batch contains."""

    def __init__(self, source, global_batch_size: int, seed: int = 0,
                 num_threads: int = 8, shuffle: bool = True,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 lookahead_batches: int = 2,
                 sample_timeout: float = 0.0,
                 timeout_retries: int = 2,
                 log_fn=None):
        self.source = source
        self.global_batch = int(global_batch_size)
        self.seed = seed
        self.num_threads = max(1, int(num_threads))
        self.shuffle = shuffle
        self.pi = jax.process_index() if process_index is None else process_index
        self.pc = jax.process_count() if process_count is None else process_count
        assert self.global_batch % self.pc == 0, (global_batch_size, self.pc)
        self.local_batch = self.global_batch // self.pc
        self.lookahead_batches = max(0, int(lookahead_batches))
        # Decode watchdog: a wedged decode (hung ffmpeg pipe, stuck NFS
        # read) would otherwise park THIS host on fut.result() forever
        # and wedge the whole pod at its next collective.  0 disables.
        self.sample_timeout = float(sample_timeout)
        self.timeout_retries = max(0, int(timeout_retries))
        self.decode_timeouts = 0         # host-side counter (display line)
        self._log = log_fn or (lambda m: print(m, file=sys.stderr))
        self._logged_timeouts = 0

    LOGGED_TIMEOUTS = 5                  # log detail for at most this many

    def _await_sample(self, fut, idx, pool, fetch):
        """Watchdog around one decode future: the timeout doubles per
        retry (exponential backoff — a slow-but-alive store gets more
        headroom each attempt), each retry is a FRESH decode of the same
        index, and exhaustion escalates to the source's black-frame
        fallback so one wedged pipe can't stall the pod.  The hung worker
        thread is left to finish in the background — Python can't kill
        it, but the pool simply runs one thread short until it returns."""
        if not self.sample_timeout:
            return fut.result()
        for attempt in range(self.timeout_retries + 1):
            try:
                return fut.result(timeout=self.sample_timeout * (2 ** attempt))
            except cf.TimeoutError:     # builtin TimeoutError on 3.11+
                # Cancel before resubmitting: a future still QUEUED would
                # otherwise run ANYWAY alongside its replacement —
                # duplicate decode work arriving exactly when the pool is
                # backlogged (positive feedback).  cancel() succeeding
                # also means the sample never STARTED — that is queue
                # backlog, not a wedged decode, so it doesn't count
                # toward the wedge telemetry.
                wedged = not fut.cancel()
                if wedged:
                    self.decode_timeouts += 1
                    _OBS_TIMEOUTS.inc()
                    obs_spans.get_recorder().event(
                        "decode.timeout", sample=int(idx),
                        attempt=attempt + 1,
                        timeout_s=self.sample_timeout * (2 ** attempt))
                    if self._logged_timeouts < self.LOGGED_TIMEOUTS:
                        self._logged_timeouts += 1
                        self._log(
                            f"[data] decode watchdog: sample {int(idx)} "
                            f"timed out after "
                            f"{self.sample_timeout * (2 ** attempt):.1f}s "
                            f"(attempt {attempt + 1}/"
                            f"{self.timeout_retries + 1}; total timeouts: "
                            f"{self.decode_timeouts})")
                if attempt < self.timeout_retries:
                    _OBS_RETRIES.inc()
                    obs_spans.get_recorder().event(
                        "decode.retry", sample=int(idx),
                        attempt=attempt + 2)
                    fut = pool.submit(fetch, idx)
        fallback = getattr(self.source, "fallback_sample", None)
        if fallback is not None:
            return fallback()
        raise TimeoutError(
            f"decode of sample {int(idx)} exceeded the watchdog timeout "
            f"{self.timeout_retries + 1}x and the source has no "
            "fallback_sample()")

    def steps_per_epoch(self) -> int:
        # Tail always dropped: a short global batch cannot shard evenly
        # over the mesh, and the SPMD step compiles for ONE static batch
        # shape — there is deliberately no drop_last=False (a ragged tail
        # would need its own XLA program per tail size).
        return len(self.source) // self.global_batch

    def epoch(self, epoch: int, skip_batches: int = 0) -> Iterator[dict]:
        """Yield this host's batches for one epoch (dicts of stacked np).

        ``skip_batches`` drops the first N global batches at the INDEX
        level — nothing is decoded for them — so a mid-epoch resume
        (train/loop.py) continues at the exact data position: sample
        content is a pure function of (seed, epoch, index), making the
        epoch's order reproducible across processes and restarts."""
        import collections

        n = len(self.source)
        order = np.arange(n)
        if self.shuffle:
            np.random.RandomState(self.seed + epoch).shuffle(order)
        usable = (n // self.global_batch) * self.global_batch
        order = order[:usable]
        # host h takes rows h, h+pc, h+2pc... of each global batch
        local = order.reshape(-1, self.global_batch)[:, self.pi::self.pc]
        if skip_batches:
            local = local[skip_batches:]
        flat = local.reshape(-1)

        rng_base = self.seed * 100_003 + epoch
        pool = cf.ThreadPoolExecutor(self.num_threads)
        try:
            def fetch(idx):
                return self.source.sample(
                    int(idx), np.random.RandomState((rng_base + int(idx)) % (2**31)))

            futs: "collections.deque" = collections.deque()
            window = self.local_batch * (1 + self.lookahead_batches)
            submitted = 0
            for start in range(0, len(flat), self.local_batch):
                while submitted < len(flat) and submitted < start + window:
                    idx = flat[submitted]
                    futs.append((pool.submit(fetch, idx), idx))
                    submitted += 1
                samples = []
                for _ in range(self.local_batch):
                    fut, idx = futs.popleft()
                    samples.append(self._await_sample(fut, idx, pool, fetch))
                yield {k: np.stack([s[k] for s in samples]) for k in samples[0]}
        finally:
            # generator may be closed mid-epoch (max_steps / preemption):
            # drop queued decodes instead of draining them, and reap the
            # already-spawned ffmpeg children — cancel_futures only stops
            # work that hasn't started (data/video.py inflight registry)
            pool.shutdown(wait=False, cancel_futures=True)
            from milnce_tpu.data.video import kill_inflight_decoders

            kill_inflight_decoders()


def shard_placer(mesh: Mesh, axis: str = "data"):
    """``x -> jax.Array`` explicitly placed sharded on dim 0 over the
    mesh.  Multi-process: each host holds only ITS shard, so the global
    array is assembled from process-local data —
    ``device_put(local, sharding)`` would demand the same (global) value
    on every process.  One definition for every hot-loop placement
    (device_prefetch batches, train/loop.py's hoisted start fallback) so
    the single-vs-multi-process branch can't silently diverge."""
    sharding = NamedSharding(mesh, P(axis))
    if jax.process_count() == 1:
        return lambda x: jax.device_put(x, sharding)

    def place(x):
        # THE deliberate pipeline H2D of the multi-process path (the
        # exact counterpart of the explicit device_put above), but
        # make_array_from_process_local_data lowers through
        # batched_device_put, which the steady-state
        # transfer_guard("disallow") classifies as implicit — found by
        # the 2-process production-loop chaos run wedging at its first
        # prefetch.  Scope the escape to this one call.
        with jax.transfer_guard("allow"):
            return jax.make_array_from_process_local_data(sharding, x)

    return place


def device_prefetch(iterator: Iterator[dict], mesh: Mesh,
                    axis: str = "data", depth: int = 2) -> Iterator[dict]:
    """Keep ``depth`` batches in flight on device, sharded on dim 0.
    ``device_put`` is async, so this overlaps H2D transfer with compute.

    The batch rows land in device order (process-blocked) rather than
    the loader's strided index assignment; the contrastive losses are
    row-permutation-invariant and video/text/start shard identically, so
    pairing is preserved.

    Data-wait attribution (the goodput ledger's ``data_wait`` category,
    OBSERVABILITY.md): every pull of the upstream iterator — the host
    blocking on decode/stack of the next batch — is timed as a
    ``data.wait`` span and accumulated on the
    ``milnce_data_wait_seconds_total`` counter.  Pulls run on the
    CONSUMER's thread, strictly between its step dispatches, so span
    time never overlaps the ``step`` spans (the ledger relies on
    that).  The recorder is resolved per pull, so a run installing its
    file-backed recorder mid-process diverts these spans with it."""
    place = shard_placer(mesh, axis)
    put = lambda b: jax.tree_util.tree_map(place, b)
    queue = []
    it = iter(iterator)
    n_pull = 0
    while True:
        rec = obs_spans.get_recorder()
        with rec.span("data.wait", batch=n_pull) as sp:
            batch = next(it, _EXHAUSTED)
        _OBS_DATA_WAIT.inc(sp["dur_ms"] / 1e3)
        if batch is _EXHAUSTED:
            break
        n_pull += 1
        queue.append(put(batch))
        if len(queue) > depth:
            yield queue.pop(0)
    yield from queue


def flatten_text(batch: dict) -> tuple:
    """{'video': (B,T,H,W,3) u8, 'text': (B,K,W) i32} ->
    (video, text reshaped (B*K, W)) — the reference's flatten at
    main_distributed.py:229."""
    text = batch["text"]
    return batch["video"], text.reshape(-1, text.shape[-1])
