"""Training-manifest tooling: build/validate the train CSV.

The reference trains from ``csv/all_videos.csv`` / ``csv/howto100m_videos.csv``
(video_loader.py:27, args_small.py:5) but ships neither (stripped as large
blobs); a user standing up training must produce a manifest themselves.
This CLI builds one from a video tree and validates it against the
caption store:

    python -m milnce_tpu.data.manifest build /data/videos --out train.csv
    python -m milnce_tpu.data.manifest validate train.csv \
        --video_root /data/videos --caption_root /data/caption_json

Schema: one ``video_path`` column, paths relative to ``video_root``
(exactly what HowTo100MSource reads, data/datasets.py).
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys

VIDEO_EXTS = (".mp4", ".mkv", ".webm", ".avi")


def build(video_root: str, out: str, caption_root: str = "",
          exts=VIDEO_EXTS) -> tuple[int, int]:
    """Scan ``video_root`` recursively; write relative paths of every
    video file.  With ``caption_root``, only videos whose ``<id>.json``
    caption track exists are listed.  Returns (written, skipped)."""
    rows, skipped = [], 0
    for dirpath, _, files in os.walk(video_root):
        for name in sorted(files):
            if not name.lower().endswith(exts):
                continue
            rel = os.path.relpath(os.path.join(dirpath, name), video_root)
            if caption_root:
                vid = os.path.basename(name).rsplit(".", 1)[0]
                if not os.path.exists(os.path.join(caption_root,
                                                   vid + ".json")):
                    skipped += 1
                    continue
            rows.append(rel)
    rows.sort()
    with open(out, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(["video_path"])
        for rel in rows:
            w.writerow([rel])
    return len(rows), skipped


def validate(manifest: str, video_root: str = "",
             caption_root: str = "") -> dict:
    """Check every row: file exists (when video_root given), caption JSON
    parses with start/end/text keys (when caption_root given)."""
    from milnce_tpu.data.datasets import read_csv

    rows = read_csv(manifest)
    report = {"rows": len(rows), "missing_video": 0, "missing_captions": 0,
              "bad_captions": 0}
    assert rows and "video_path" in rows[0], f"{manifest}: no video_path column"
    for row in rows:
        rel = row["video_path"]
        if video_root and not os.path.exists(os.path.join(video_root, rel)):
            report["missing_video"] += 1
        if caption_root:
            vid = os.path.basename(rel).rsplit(".", 1)[0]
            cap = os.path.join(caption_root, vid + ".json")
            if not os.path.exists(cap):
                report["missing_captions"] += 1
                continue
            try:
                data = json.load(open(cap))
                assert {"start", "end", "text"} <= set(data)
            except Exception:  # graftlint: disable=GL007(the failure IS recorded — counted into report['bad_captions']; a dict counter the rule's recorder heuristic can't see)
                report["bad_captions"] += 1
    return report


def main(argv=None):
    p = argparse.ArgumentParser(description="milnce-tpu manifest tool")
    sub = p.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("build")
    b.add_argument("video_root")
    b.add_argument("--out", required=True)
    b.add_argument("--caption_root", default="")
    v = sub.add_parser("validate")
    v.add_argument("manifest")
    v.add_argument("--video_root", default="")
    v.add_argument("--caption_root", default="")
    args = p.parse_args(argv)
    if args.cmd == "build":
        n, skipped = build(args.video_root, args.out, args.caption_root)
        print(f"wrote {args.out}: {n} videos"
              + (f" ({skipped} skipped, no captions)" if skipped else ""))
    else:
        rep = validate(args.manifest, args.video_root, args.caption_root)
        print(json.dumps(rep))
        if rep["missing_video"] or rep["bad_captions"]:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
