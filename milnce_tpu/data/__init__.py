from milnce_tpu.data.tokenizer import Tokenizer  # noqa: F401
