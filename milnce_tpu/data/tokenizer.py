"""Word-level tokenizer over the HowTo100M word2vec vocabulary.

Behavioral parity with the reference tokenizer that lives (twice) inside
s3dg.py:164-194 and video_loader.py:97-117:

- vocabulary: an array of words; word -> index+1 (0 is the pad id),
  s3dg.py:167-168.
- split: regex ``[\\w']+`` over the stringified sentence, s3dg.py:180-182.
- unknown words are dropped (not mapped to UNK), s3dg.py:185.
- pad/truncate to ``max_words`` with 0, s3dg.py:170-175.
- a sentence with no in-vocab words tokenizes to all-pad, s3dg.py:189-190.

Host-side, numpy-only: tokenization happens in the input pipeline, never
under jit.

Thread safety (audited for the concurrent serving request path,
ISSUE 4): a :class:`Tokenizer` is safe for unlimited concurrent
``encode`` / ``encode_batch`` calls WITHOUT external locking —

- ``word_to_id`` and ``max_words`` are written once in ``__init__`` and
  only read afterwards (no method mutates instance state);
- the module-level ``_WORD_RE`` compiled pattern is stateless per call
  (CPython ``re`` pattern objects are documented thread-safe);
- every call builds fresh local lists/arrays; nothing is shared between
  calls.

The one excluded pattern: mutating ``word_to_id`` / ``max_words`` after
construction while requests are in flight — build a NEW Tokenizer and
swap the reference instead (reference assignment is atomic).  Pinned by
the hammer test (tests/test_tokenizer.py: N threads x concurrent
encodes == serial goldens).
"""

from __future__ import annotations

import re
from typing import Iterable, Sequence

import numpy as np

_WORD_RE = re.compile(r"[\w']+")

PAD_ID = 0


class Tokenizer:
    """Maps sentences to fixed-length int32 id arrays."""

    def __init__(self, vocab: Sequence[str], max_words: int = 20):
        self.word_to_id = {w: i + 1 for i, w in enumerate(vocab)}
        self.max_words = int(max_words)
        self.vocab_size = len(vocab) + 1  # + pad row 0

    @classmethod
    def from_npy(cls, path: str, max_words: int = 20) -> "Tokenizer":
        """Load the reference's ``dict.npy`` vocabulary (s3dg.py:166)."""
        vocab = np.load(path, allow_pickle=True)
        return cls([str(w) for w in vocab], max_words=max_words)

    @staticmethod
    def split(sentence: str) -> list[str]:
        return _WORD_RE.findall(str(sentence))

    def encode(self, sentence: str, max_words: int | None = None) -> np.ndarray:
        """One sentence -> (max_words,) int32, zero-padded."""
        size = self.max_words if max_words is None else int(max_words)
        ids = [self.word_to_id[w] for w in self.split(sentence) if w in self.word_to_id]
        out = np.zeros((size,), dtype=np.int32)
        if ids:
            ids = ids[:size]
            out[: len(ids)] = ids
        return out

    def encode_batch(self, sentences: Iterable[str], max_words: int | None = None) -> np.ndarray:
        """Batch of sentences -> (B, max_words) int32 (s3dg.py:192-194)."""
        rows = [self.encode(s, max_words) for s in sentences]
        if not rows:
            size = self.max_words if max_words is None else int(max_words)
            return np.zeros((0, size), dtype=np.int32)
        return np.stack(rows, axis=0)


def synthetic_vocab(size: int = 128) -> list[str]:
    """Deterministic toy vocabulary for hermetic tests."""
    return [f"word{i}" for i in range(size)]
