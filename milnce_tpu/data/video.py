"""Host-side video decode via the ffmpeg binary.

The reference drives ffmpeg through the `ffmpeg-python` graph builder
(video_loader.py:58-88); we build the same filter graph as plain
subprocess args — fewer moving parts on a TPU-VM host image.  The decode
stays on the host CPU feeding the device pipeline (the BASELINE.json
north star keeps ffmpeg on the host).

Filter-graph parity with video_loader.py:60-88:
- seek: ``-ss start -t num_sec+0.1`` on the INPUT side;
- ``fps=<fps>`` filter;
- crop: either direct ``size x size`` crop at a fractional offset
  (crop_only, :69-74) or largest-square crop + bilinear scale (:75-82);
- optional horizontal flip (:83-84);
- rawvideo rgb24 on stdout -> numpy.

Output is channels-LAST ``(T, H, W, 3) uint8`` (our model layout; the
reference permutes to torch's (3,T,H,W) at video_loader.py:91), zero-
padded/truncated to ``num_frames`` (:92-95).

Everything is injectable for tests: :class:`FakeDecoder` yields
deterministic frames with no ffmpeg present.
"""

from __future__ import annotations

import shutil
import subprocess
from dataclasses import dataclass
from typing import Protocol

import numpy as np

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.resilience import faults


# In-flight decoder children, registered for kill-on-close: a mid-epoch
# stop (max_steps / preemption) cancels QUEUED decode futures, but the
# ffmpeg children already spawned would keep decoding to completion —
# orphaned CPU burn racing the preemption grace window.  Every
# subprocess-backed decode registers its Popen here for the duration of
# the pipe read; ShardedLoader's generator close calls
# :func:`kill_inflight_decoders`.
_INFLIGHT: set = set()
_INFLIGHT_LOCK = make_lock("data.video.inflight")


def _register_inflight(proc) -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT.add(proc)


def _unregister_inflight(proc) -> None:
    with _INFLIGHT_LOCK:
        _INFLIGHT.discard(proc)


def kill_inflight_decoders(grace: float = 0.2) -> int:
    """SIGTERM (then SIGKILL after ``grace``) every registered in-flight
    decode child; returns how many were signalled.  The owning decode()
    call then fails its pipe read — callers are already past caring (the
    epoch generator is closing).  Process-wide by design: at close time
    the training epoch owns every live training decode."""
    with _INFLIGHT_LOCK:
        procs = list(_INFLIGHT)
    for proc in procs:
        if proc.poll() is None:
            proc.terminate()
    killed = 0
    for proc in procs:
        if proc.poll() is None:
            try:
                proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                proc.kill()
        killed += 1
    return killed


class ClipDecoder(Protocol):
    def decode(self, path: str, start_seek: float, num_sec: float,
               fps: int, size: int, aw: float, ah: float, crop_only: bool,
               hflip: bool) -> np.ndarray: ...

    def duration(self, path: str) -> float: ...


def _crop_expr(size: int, aw: float, ah: float, crop_only: bool) -> str:
    # ffmpeg crop filter is crop=w:h:x:y
    if crop_only:
        return f"crop={size}:{size}:(iw-{size})*{aw}:(ih-{size})*{ah}"
    return (f"crop=min(iw\\,ih):min(iw\\,ih)"
            f":(iw-min(iw\\,ih))*{aw}:(ih-min(iw\\,ih))*{ah}"
            f",scale={size}:{size}")


@dataclass
class FFmpegDecoder:
    binary: str = "ffmpeg"
    probe_binary: str = "ffprobe"

    def available(self) -> bool:
        return shutil.which(self.binary) is not None

    def command(self, path: str, start_seek: float, num_sec: float,
                fps: int, size: int, aw: float = 0.5, ah: float = 0.5,
                crop_only: bool = False, hflip: bool = False) -> list[str]:
        """The decode argv (rawvideo rgb24 on stdout) — shared by the
        subprocess path below and the native ReaderPool path."""
        vf = f"fps={fps},{_crop_expr(size, aw, ah, crop_only)}"
        if hflip:
            vf += ",hflip"
        return [self.binary, "-nostdin", "-loglevel", "error",
                "-ss", f"{start_seek}", "-t", f"{num_sec + 0.1}",
                "-i", path, "-vf", vf,
                "-f", "rawvideo", "-pix_fmt", "rgb24", "pipe:"]

    def decode(self, path: str, start_seek: float, num_sec: float,
               fps: int, size: int, aw: float = 0.5, ah: float = 0.5,
               crop_only: bool = False, hflip: bool = False) -> np.ndarray:
        if not self.available():
            raise RuntimeError(
                "ffmpeg binary not found — install it on the host or use the "
                "synthetic data source (data.synthetic=True)")
        cmd = self.command(path, start_seek, num_sec, fps, size, aw, ah,
                           crop_only, hflip)
        # Popen (not subprocess.run) so the child is registered while its
        # pipe is being pumped: kill_inflight_decoders() can reap it on a
        # mid-epoch generator close instead of orphaning a full decode.
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.DEVNULL)
        _register_inflight(proc)
        try:
            out, _ = proc.communicate()
        finally:
            _unregister_inflight(proc)
        if proc.returncode != 0:        # parity with subprocess.run(check=True)
            raise subprocess.CalledProcessError(proc.returncode, cmd)
        n = len(out) // (size * size * 3)
        return np.frombuffer(out[: n * size * size * 3],
                             np.uint8).reshape(n, size, size, 3)

    def duration(self, path: str) -> float:
        """Container duration in seconds (the reference uses
        ``ffmpeg.probe``, msrvtt_loader.py:117-119)."""
        cmd = [self.probe_binary, "-v", "error", "-show_entries",
               "format=duration", "-of",
               "default=noprint_wrappers=1:nokey=1", path]
        return float(subprocess.run(cmd, stdout=subprocess.PIPE,
                                    check=True).stdout.strip())


class NativeFFmpegDecoder(FFmpegDecoder):
    """FFmpegDecoder whose byte pumping runs in the C++ ReaderPool
    (native/milnce_native.cpp): worker threads popen() the decode command
    and fread() rawvideo straight into a caller-owned numpy buffer — no
    GIL, no Python-side byte copies.  Enable with
    ``DataConfig.use_native_reader``.

    The pool is shared across the loader's Python threads; each decode()
    submits one job and blocks only its own thread (reader_wait drops the
    GIL inside ctypes), so ``workers`` C++ threads pump pipes while
    Python threads do tokenization etc.

    A decode whose output exactly fills the buffer is treated as
    truncated (raise) rather than silently cropped; the buffer is sized
    with slack frames so a correct decode never hits that.
    """

    SLACK_FRAMES = 4

    def __init__(self, binary: str = "ffmpeg", probe_binary: str = "ffprobe",
                 workers: int = 8):
        super().__init__(binary=binary, probe_binary=probe_binary)
        from milnce_tpu.native.reader import ReaderPool

        self._pool = ReaderPool(workers=workers)

    def decode(self, path: str, start_seek: float, num_sec: float,
               fps: int, size: int, aw: float = 0.5, ah: float = 0.5,
               crop_only: bool = False, hflip: bool = False) -> np.ndarray:
        if not self.available():
            raise RuntimeError(
                "ffmpeg binary not found — install it on the host or use the "
                "synthetic data source (data.synthetic=True)")
        import shlex

        cmd = self.command(path, start_seek, num_sec, fps, size, aw, ah,
                           crop_only, hflip)
        # the pool popen()s through /bin/sh with inherited fds: route any
        # remaining decoder chatter away from the training logs (the
        # subprocess path gets the same via stderr=DEVNULL)
        cmd_str = " ".join(shlex.quote(c) for c in cmd) + " 2>/dev/null"
        frame_bytes = size * size * 3
        max_frames = int(np.ceil((num_sec + 0.1) * fps)) + self.SLACK_FRAMES
        buf = np.empty((max_frames * frame_bytes,), np.uint8)
        got = self._pool.decode_into([cmd_str], [buf])[0]
        if got < 0:
            raise RuntimeError(f"native decode spawn failed: {path}")
        if got == 0:
            raise RuntimeError(f"native decode produced no frames: {path}")
        if got >= buf.nbytes:
            raise RuntimeError(f"native decode overflow (buffer too small "
                               f"for {path}; got >= {buf.nbytes} bytes)")
        n = got // frame_bytes
        return buf[: n * frame_bytes].reshape(n, size, size, 3).copy()


class Cv2Decoder:
    """In-process decode via OpenCV's bundled ffmpeg libraries — the
    production decode path on hosts with no ffmpeg *binary* (cv2 links
    libavcodec/libavformat directly, cap_ffmpeg_impl).

    Same clip semantics as :class:`FFmpegDecoder`'s filter graph
    (video_loader.py:58-88): input-side seek, constant-rate fps resample
    (duplicate/drop against source timestamps), fractional-offset square
    crop — direct ``size``-crop (crop_only, :69-74) or largest-square
    crop + resize (:75-82) — and optional hflip, with two known
    one-frame-scale divergences from the ffmpeg binary (ADVICE r3):

    - the resample emits the LAST source frame with pts <= output pts
      (floor), while ffmpeg's ``fps=`` filter default rounds to the
      NEAREST source frame — for non-integer src/target fps ratios the
      backends can select adjacent frames;
    - ``CAP_PROP_POS_MSEC`` seek accuracy is container/keyframe
      dependent, unlike ffmpeg's accurate input-side seek, so a clip may
      start a frame or two off.

    Both are below the granularity the model sees (clips are seconds
    long at 5-16 fps with random jitter in training), but exact
    frame-index parity across backends is NOT guaranteed and tests must
    not assert it.  Decode runs in the calling loader thread with the GIL
    released inside cv2, so the thread pool scales like the pipe-pump
    path but with zero subprocess spawns and no rawvideo pipe traffic
    (a size-224 rgb24 frame is 150 KB on the pipe; cv2 hands back the
    decoded buffer in place).
    """

    def available(self) -> bool:
        try:
            import cv2  # noqa: F401
            return True
        except ImportError:
            return False

    def decode(self, path: str, start_seek: float, num_sec: float,
               fps: int, size: int, aw: float = 0.5, ah: float = 0.5,
               crop_only: bool = False, hflip: bool = False) -> np.ndarray:
        import cv2

        cap = cv2.VideoCapture(path)
        if not cap.isOpened():
            raise RuntimeError(f"cv2 failed to open video: {path}")
        try:
            src_fps = cap.get(cv2.CAP_PROP_FPS)
            if not src_fps or src_fps <= 0:
                src_fps = float(fps)
            if start_seek > 0:
                cap.set(cv2.CAP_PROP_POS_MSEC, float(start_seek) * 1000.0)
            max_out = int(np.ceil((num_sec + 0.1) * fps))
            ok, frame = cap.read()
            if not ok:
                raise RuntimeError(f"cv2 decoded no frames: {path} "
                                   f"(seek {start_seek}s)")
            out = []
            src_idx = 0                 # source frames consumed since seek
            exhausted = False
            for k in range(max_out):
                target = k / float(fps)   # output pts, relative to the seek
                # the fps-filter rule: emit the last source frame whose
                # timestamp is <= the output timestamp
                while not exhausted and (src_idx + 1) / src_fps <= target:
                    ok, nxt = cap.read()
                    if not ok:
                        exhausted = True
                        break
                    frame = nxt
                    src_idx += 1
                if exhausted and target >= (src_idx + 1) / src_fps:
                    break               # past the last frame's span: stop,
                                        # like ffmpeg at EOF (caller pads)
                out.append(self._process(frame, size, aw, ah, crop_only,
                                         hflip))
            return np.stack(out, axis=0)
        finally:
            cap.release()

    @staticmethod
    def _process(frame: np.ndarray, size: int, aw: float, ah: float,
                 crop_only: bool, hflip: bool) -> np.ndarray:
        import cv2

        ih, iw = frame.shape[:2]
        if crop_only:
            if iw < size or ih < size:
                # ffmpeg's crop filter fails such frames outright; match
                # it so both backends feed the same decode-failure
                # resampling path instead of silently upscaling here
                raise RuntimeError(
                    f"crop_only: frame {iw}x{ih} smaller than crop "
                    f"size {size}")
            x = int((iw - size) * aw)
            y = int((ih - size) * ah)
            frame = frame[y:y + size, x:x + size]
        else:
            s = min(iw, ih)
            x = int((iw - s) * aw)
            y = int((ih - s) * ah)
            frame = cv2.resize(frame[y:y + s, x:x + s], (size, size),
                               interpolation=cv2.INTER_LINEAR)
        frame = cv2.cvtColor(frame, cv2.COLOR_BGR2RGB)
        if hflip:
            frame = frame[:, ::-1]
        return np.ascontiguousarray(frame)

    def duration(self, path: str) -> float:
        import cv2

        cap = cv2.VideoCapture(path)
        if not cap.isOpened():
            raise RuntimeError(f"cv2 failed to open video: {path}")
        try:
            n = cap.get(cv2.CAP_PROP_FRAME_COUNT)
            fps = cap.get(cv2.CAP_PROP_FPS)
            if not n or not fps or fps <= 0:
                raise RuntimeError(f"cv2 could not probe duration: {path}")
            return float(n) / float(fps)
        finally:
            cap.release()


def build_decoder(backend: str = "auto", use_native_reader: bool = False,
                  workers: int = 8) -> ClipDecoder:
    """Production decoder factory.  ``auto`` prefers the ffmpeg binary
    (reference's tool, and the native ReaderPool needs an argv to popen)
    and falls back to in-process cv2 when no binary is installed.
    ``fake`` is the hermetic backend (deterministic pseudo-frames, zero
    I/O) — dry runs and the chaos tests drive the REAL source/loader
    stack through it without touching a codec."""
    requested = backend
    if backend == "fake":
        return FakeDecoder()
    if backend == "auto":
        # an explicit native-reader request implies the ffmpeg pipe-pump
        # path: honor it rather than silently resolving to cv2 — but fail
        # HERE if the binary is missing.  A decoder whose every decode
        # raises would be swallowed by the source's per-sample resampling
        # (black-frame fallback) and the run would silently train on
        # garbage frames.
        if use_native_reader and not FFmpegDecoder().available():
            raise RuntimeError(
                "use_native_reader needs the ffmpeg binary (the C++ "
                "ReaderPool pumps ffmpeg subprocess pipes) but none is on "
                "PATH — install ffmpeg, or drop use_native_reader to let "
                "'auto' fall back to in-process cv2 decode")
        backend = ("ffmpeg" if use_native_reader or FFmpegDecoder().available()
                   else "cv2")
    if backend == "ffmpeg":
        if use_native_reader:
            return NativeFFmpegDecoder(workers=workers)
        return FFmpegDecoder()
    if backend == "cv2":
        dec = Cv2Decoder()
        if not dec.available():
            if requested == "auto":
                raise RuntimeError(
                    "decoder auto-selection failed: no ffmpeg binary on "
                    "PATH (install ffmpeg — the usual fix) and cv2 is not "
                    "importable either")
            raise RuntimeError("decoder backend 'cv2' requested but cv2 is "
                               "not importable")
        if use_native_reader:
            import warnings

            warnings.warn(
                "use_native_reader applies only to the ffmpeg-binary "
                "backend (the C++ ReaderPool pumps subprocess pipes); "
                "cv2 decodes in-process — flag ignored", stacklevel=2)
        return dec
    raise ValueError(f"unknown decoder backend {backend!r} "
                     "(expected auto|ffmpeg|cv2|fake)")


@dataclass
class FakeDecoder:
    """Deterministic pseudo-decoder for hermetic tests: frame values are a
    function of (path hash, frame index)."""

    frames_per_clip: int = 64
    fixed_duration: float = 30.0

    def decode(self, path: str, start_seek: float, num_sec: float,
               fps: int, size: int, aw: float = 0.5, ah: float = 0.5,
               crop_only: bool = False, hflip: bool = False) -> np.ndarray:
        n = min(self.frames_per_clip, max(1, int(round(num_sec * fps))))
        seed = (hash(path) ^ int(start_seek * 7 + fps)) % (2 ** 31)
        rng = np.random.RandomState(seed)
        frames = rng.randint(0, 255, size=(n, size, size, 3), dtype=np.uint8)
        if hflip:
            frames = frames[:, :, ::-1, :]
        return frames

    def duration(self, path: str) -> float:
        return self.fixed_duration


def black_sample(cfg) -> dict:
    """Black frames + empty caption bag + zero start: a valid, if
    useless, sample with the exact training batch contract.  The ONE
    definition of that fallback shape — the sources' bounded-resample
    last resort and the loader watchdog's escalation target
    (data/pipeline.py) both delegate here, so the contract can't fork."""
    return {"video": np.zeros((cfg.num_frames, cfg.video_size,
                               cfg.video_size, 3), np.uint8),
            "text": np.zeros((cfg.num_candidates, cfg.max_words), np.int32),
            "start": np.float32(0.0)}


def pad_or_trim(frames: np.ndarray, num_frames: int) -> np.ndarray:
    """Zero-pad the tail / truncate to exactly ``num_frames``
    (video_loader.py:92-95)."""
    t = frames.shape[0]
    if t >= num_frames:
        return frames[:num_frames]
    pad = np.zeros((num_frames - t,) + frames.shape[1:], frames.dtype)
    return np.concatenate([frames, pad], axis=0)


def sample_clip(decoder: ClipDecoder, path: str, start: float, end: float,
                num_frames: int, fps: int, size: int,
                rng: np.random.RandomState, crop_only: bool,
                center_crop: bool, random_flip: bool) -> np.ndarray:
    """Random training clip draw within [start, end]
    (video_loader.py:58-95): random seek, random or center fractional
    crop offset, coin-flip hflip."""
    # Fault sites at the decode chokepoint (backend-agnostic, inside the
    # source's resample/retry scope): chaos tests drive the bounded
    # resample and the loader watchdog through here — zero-cost disarmed.
    faults.maybe_raise("decode.raise")
    faults.maybe_hang("decode.hang")
    num_sec = num_frames / float(fps)
    hi = int(max(start, end - num_sec))
    start_seek = rng.randint(int(start), hi + 1)
    if center_crop:
        aw = ah = 0.5
    else:
        aw, ah = rng.uniform(0, 1), rng.uniform(0, 1)
    hflip = bool(random_flip and rng.uniform(0, 1) > 0.5)
    frames = decoder.decode(path, start_seek, num_sec, fps, size, aw, ah,
                            crop_only, hflip)
    return pad_or_trim(frames, num_frames)


def eval_windows(decoder: ClipDecoder, path: str, start: float, end: float,
                 num_clip: int, num_frames: int, fps: int,
                 size: int) -> np.ndarray:
    """``num_clip`` deterministic center-cropped windows linspaced over
    [start, end] (youcook_loader.py:52-57) -> (num_clip, T, H, W, 3) u8."""
    num_sec = num_frames / float(fps)
    starts = np.linspace(start, max(start, end - num_sec), num_clip)  # graftlint: disable=GL004(host-side seek seconds handed to the decoder as python floats; never reaches a device)
    clips = [pad_or_trim(decoder.decode(path, float(s), num_sec, fps, size,
                                        0.5, 0.5, False, False), num_frames)
             for s in starts]
    return np.stack(clips, axis=0)
