"""Dataset sources: HowTo100M training + YouCook2 / MSR-VTT / HMDB-51 eval.

Re-designs of the four reference loaders (video_loader.py,
youcook_loader.py, msrvtt_loader.py, hmdb_loader.py) as plain host-side
sources with an injectable decoder (hermetic tests run on
:class:`milnce_tpu.data.video.FakeDecoder`; production uses
:class:`FFmpegDecoder`).

Manifest schemas (identical to the reference csv/ files):
- train:   column ``video_path`` (video_loader.py:155-157), one caption
  JSON per video id under ``caption_root``;
- youcook: end,start,task,text,video_id (3,350 rows), videos resolved as
  ``validation/<task>/<id>.{mp4,mkv,webm}`` (youcook_loader.py:124-131);
- msrvtt:  key,vid_key,video_id,sentence (1,000 rows), windows over the
  whole container duration (msrvtt_loader.py:117-119);
- hmdb:    video_id,label,split1..3 (6,766 rows; 1=train 2=test per
  official split), label from the id minus the ``_test`` suffix
  (hmdb_loader.py:91-95).

The reference's hmdb flip branch computes the flipped copy and then
returns the un-flipped tensor (hmdb_loader.py:81-83 — latent bug,
SURVEY.md §2.4); here ``with_flip`` honestly returns both orientations.
"""

from __future__ import annotations

import csv as csv_mod
import os
from collections import OrderedDict
from typing import Optional

import numpy as np

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.config import DataConfig, ModelConfig
from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.data.captions import CaptionTrack, sample_caption
from milnce_tpu.data.tokenizer import Tokenizer, synthetic_vocab
from milnce_tpu.data.video import (ClipDecoder, black_sample, build_decoder,
                                   eval_windows, sample_clip)


def read_csv(path: str) -> list[dict]:
    with open(path, newline="") as f:
        return list(csv_mod.DictReader(f))


# Decode-failure telemetry on the process-wide registry (incremented
# from reader threads; the display log line keeps its own per-source
# counter for the human-facing totals — OBSERVABILITY.md).
_OBS_DECODE_FAILURES = obs_metrics.registry().counter(
    "milnce_data_decode_failures_total",
    "samples whose caption load or decode raised (before resample)")


class DataHealthError(RuntimeError):
    """The decode-failure fraction exceeded ``data.max_failure_rate``:
    the dataset (or its storage) is broken enough that continuing would
    mean silently training on black-frame fallbacks.  Deliberately NOT
    caught by the per-sample resampling — it must kill the run."""


def build_tokenizer(model_cfg: ModelConfig, max_words: int) -> Tokenizer:
    """Tokenizer from the configured dict.npy vocabulary
    (``model.token_dict_path``), or a synthetic vocab for hermetic runs."""
    if model_cfg.token_dict_path and os.path.exists(model_cfg.token_dict_path):
        return Tokenizer.from_npy(model_cfg.token_dict_path, max_words)
    return Tokenizer(synthetic_vocab(model_cfg.vocab_size - 1), max_words)


class HowTo100MSource:
    """Training source: one (video clip, MIL caption bag) per draw
    (video_loader.py:154-160).

    Unlike the reference — where one corrupt file raises through the
    DataLoader worker and kills the epoch on every node (video_loader.py:
    85-88 has no error handling; SURVEY.md §7 hard part 2) — a failed
    caption load or decode resamples a different index (bounded retries),
    falling back to black frames so a pod step can never stall on a bad
    video.  Failures are counted in ``decode_failures`` and the first few
    are logged."""

    CAPTION_CACHE_SIZE = 4096   # bounded: 1.2M videos/epoch would otherwise
                                # accumulate every parsed caption JSON in RAM
    MAX_RETRIES = 3             # resample attempts before black-frame fallback
    LOGGED_FAILURES = 5         # log at most this many failure details
    FAILURE_RATE_MIN_ATTEMPTS = 20   # don't judge max_failure_rate on noise

    def __init__(self, cfg: DataConfig, model_cfg: ModelConfig,
                 decoder: Optional[ClipDecoder] = None,
                 tokenizer: Optional[Tokenizer] = None,
                 log_fn=None):
        self.cfg = cfg
        self.rows = read_csv(cfg.train_csv)
        assert self.rows and "video_path" in self.rows[0], cfg.train_csv
        if decoder is None:
            decoder = build_decoder(cfg.decoder_backend,
                                    use_native_reader=cfg.use_native_reader,
                                    workers=cfg.num_reader_threads)
        self.decoder = decoder
        self.tokenizer = tokenizer or build_tokenizer(model_cfg, cfg.max_words)
        self._caption_cache: "OrderedDict[str, CaptionTrack]" = OrderedDict()
        self._cache_lock = make_lock("data.caption_cache")
        self.decode_failures = 0
        self.decode_attempts = 0
        self._stats_lock = make_lock("data.decode_stats")
        # failure details route through the run's logger when the loop
        # provides it (satellite: no raw stderr prints from the source);
        # standalone uses keep the stderr default
        import sys

        self._log = log_fn or (lambda m: print(m, file=sys.stderr))

    def __len__(self) -> int:
        return len(self.rows)

    def _captions(self, video_id: str) -> CaptionTrack:
        with self._cache_lock:
            if video_id in self._caption_cache:
                self._caption_cache.move_to_end(video_id)
                return self._caption_cache[video_id]
        path = os.path.join(self.cfg.caption_root, video_id + ".json")
        track = CaptionTrack.from_json_file(path)
        with self._cache_lock:
            self._caption_cache[video_id] = track
            while len(self._caption_cache) > self.CAPTION_CACHE_SIZE:
                self._caption_cache.popitem(last=False)
        return track

    def _sample_one(self, idx: int, rng: np.random.RandomState) -> dict:
        c = self.cfg
        video_file = self.rows[idx]["video_path"]
        video_id = os.path.basename(video_file).split(".")[0]
        track = self._captions(video_id)
        tokens, start, end = sample_caption(
            track, rng, self.tokenizer, c.num_candidates, c.max_words,
            c.min_time)
        video = sample_clip(self.decoder,
                            os.path.join(c.video_root, video_file),
                            start, end, c.num_frames, c.fps, c.video_size,
                            rng, c.crop_only, c.center_crop, c.random_flip)
        return {"video": video, "text": tokens,
                "start": np.float32(start)}   # CIDM loss input (loss.py:56)

    def _record_failure(self, idx: int, exc: Exception) -> None:
        _OBS_DECODE_FAILURES.inc()
        with self._stats_lock:
            self.decode_failures += 1
            count = self.decode_failures
        if count <= self.LOGGED_FAILURES:
            self._log(f"[data] sample {idx} failed "
                      f"({type(exc).__name__}: {exc}); resampling "
                      f"(total failures: {count})")

    def _check_health(self, exc: Exception) -> None:
        """Abort the run when decode failures stop being the long tail
        and become the dataset: without this, a 90%-corrupt manifest
        "trains" on black frames with a green loss curve."""
        limit = getattr(self.cfg, "max_failure_rate", 1.0)
        if limit >= 1.0:
            return
        with self._stats_lock:
            attempts, failures = self.decode_attempts, self.decode_failures
        if attempts < self.FAILURE_RATE_MIN_ATTEMPTS:
            return
        rate = failures / attempts
        if rate > limit:
            raise DataHealthError(
                f"decode-failure rate {rate:.2f} ({failures}/{attempts} "
                f"attempts) exceeds data.max_failure_rate={limit} — the "
                "dataset/storage is broken, refusing to train on "
                "black-frame fallbacks") from exc

    def fallback_sample(self) -> dict:
        """The black-frame batch-contract fallback (data/video.py
        black_sample): the bounded-resample last resort below and the
        loader's decode-watchdog escalation (data/pipeline.py)."""
        return black_sample(self.cfg)

    def sample(self, idx: int, rng: np.random.RandomState) -> dict:
        for _ in range(self.MAX_RETRIES + 1):
            try:
                with self._stats_lock:
                    self.decode_attempts += 1
                return self._sample_one(idx, rng)
            except Exception as exc:
                self._record_failure(idx, exc)
                self._check_health(exc)
                idx = int(rng.randint(len(self.rows)))
        # Last resort (MAX_RETRIES+1 distinct bad draws)
        return self.fallback_sample()


class YouCookSource:
    """Zero-shot retrieval eval: per row, ``num_clip`` windows over the GT
    segment + the tokenized caption (youcook_loader.py:14-134)."""

    VIDEO_EXTS = (".mp4", ".mkv", ".webm")

    def __init__(self, csv_path: str, video_root: str, cfg: DataConfig,
                 tokenizer: Tokenizer, num_clip: int = 4,
                 decoder: Optional[ClipDecoder] = None,
                 max_words: int = 30):
        self.rows = read_csv(csv_path)
        self.video_root = video_root
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.num_clip = num_clip
        self.decoder = decoder or build_decoder(cfg.decoder_backend)
        self.max_words = max_words

    def __len__(self) -> int:
        return len(self.rows)

    def _resolve_video(self, row: dict) -> str:
        base = os.path.join(self.video_root, "validation", row["task"],
                            row["video_id"])
        for ext in self.VIDEO_EXTS:
            if os.path.exists(base + ext):
                return base + ext
        return base + self.VIDEO_EXTS[0]

    def sample(self, idx: int, rng=None) -> dict:
        row = self.rows[idx]
        c = self.cfg
        video = eval_windows(self.decoder, self._resolve_video(row),
                             float(row["start"]), float(row["end"]),
                             self.num_clip, c.num_frames, c.fps, c.video_size)
        tokens = self.tokenizer.encode(row["text"], self.max_words)
        return {"video": video, "text": tokens[None]}


class MSRVTTSource:
    """Zero-shot retrieval eval over full-video windows
    (msrvtt_loader.py:13-128); duration comes from the container probe."""

    def __init__(self, csv_path: str, video_root: str, cfg: DataConfig,
                 tokenizer: Tokenizer, num_clip: int = 4,
                 decoder: Optional[ClipDecoder] = None, max_words: int = 30):
        self.rows = read_csv(csv_path)
        self.video_root = video_root
        self.cfg = cfg
        self.tokenizer = tokenizer
        self.num_clip = num_clip
        self.decoder = decoder or build_decoder(cfg.decoder_backend)
        self.max_words = max_words

    def __len__(self) -> int:
        return len(self.rows)

    def sample(self, idx: int, rng=None) -> dict:
        row = self.rows[idx]
        c = self.cfg
        path = os.path.join(self.video_root, row["video_id"] + ".mp4")
        duration = self.decoder.duration(path)
        video = eval_windows(self.decoder, path, 0.0, duration, self.num_clip,
                             c.num_frames, c.fps, c.video_size)
        tokens = self.tokenizer.encode(row["sentence"], self.max_words)
        return {"video": video, "text": tokens[None]}


class HMDBSource:
    """Linear-probe eval: windows over the whole video + class label +
    the three official split assignments (hmdb_loader.py:14-95)."""

    def __init__(self, csv_path: str, video_root: str, cfg: DataConfig,
                 num_clip: int = 10, decoder: Optional[ClipDecoder] = None,
                 with_flip: bool = False):
        self.rows = read_csv(csv_path)
        self.video_root = video_root
        self.cfg = cfg
        self.num_clip = num_clip
        self.decoder = decoder or build_decoder(cfg.decoder_backend)
        self.with_flip = with_flip

    def __len__(self) -> int:
        return len(self.rows)

    @staticmethod
    def label_of(label: str) -> str:
        # the csv label column carries a '_test' suffix (hmdb_loader.py:91-95)
        return label.rsplit("_test", 1)[0] if label.endswith("_test") else label

    def sample(self, idx: int, rng=None) -> dict:
        row = self.rows[idx]
        c = self.cfg
        # video_id already carries its extension (csv/hmdb51.csv)
        path = os.path.join(self.video_root, row["video_id"])
        duration = self.decoder.duration(path)
        video = eval_windows(self.decoder, path, 0.0, duration, self.num_clip,
                             c.num_frames, c.fps, c.video_size)
        if self.with_flip:
            video = np.concatenate([video, video[:, :, :, ::-1, :]], axis=0)
        return {"video": video,
                "label": self.label_of(row["label"]),
                "splits": np.array([int(row["split1"]), int(row["split2"]),
                                    int(row["split3"])], np.int32)}
