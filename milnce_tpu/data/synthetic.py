"""Hermetic in-memory video-text source (no ffmpeg, no files).

The reference has no hermetic path at all — its smallest config still
needs real videos + caption JSONs (SURVEY.md §4).  This source emits
deterministic pseudo-video (uint8) and token ids with the exact same
batch contract as the real HowTo100M source, so the full train loop,
sharding, checkpointing, and bench run anywhere.
"""

from __future__ import annotations

import numpy as np

from milnce_tpu.config import DataConfig
from milnce_tpu.resilience import faults


class SyntheticVideoTextSource:
    """len() + sample(idx, rng) -> {'video': (T,H,W,3) u8, 'text': (K,W) i32}."""

    def __init__(self, cfg: DataConfig, vocab_size: int = 128,
                 num_samples: int | None = None):
        self.cfg = cfg
        self.vocab_size = vocab_size
        self.num_samples = num_samples or cfg.synthetic_num_samples

    def __len__(self) -> int:
        return self.num_samples

    def fallback_sample(self) -> dict:
        """The black-frame batch-contract fallback (data/video.py
        black_sample) — the loader's decode-watchdog escalation target,
        so chaos tests can drive the hang path hermetically."""
        from milnce_tpu.data.video import black_sample

        return black_sample(self.cfg)

    def sample(self, idx: int, rng: np.random.RandomState) -> dict:
        # The same fault chokepoint the real decode path has
        # (data/video.py sample_clip): ``train.faults`` decode clauses
        # drive the watchdog/fallback machinery on fully hermetic runs —
        # the goodput chaos test injects its decode-timeouts here.
        # Zero-cost disarmed.
        faults.maybe_raise("decode.raise")
        faults.maybe_hang("decode.hang")
        c = self.cfg
        base = np.random.RandomState(idx % 1000)
        video = base.randint(0, 255, size=(c.num_frames, c.video_size,
                                           c.video_size, 3), dtype=np.uint8)
        text = base.randint(1, self.vocab_size,
                            size=(c.num_candidates, c.max_words)).astype(np.int32)
        # zero-pad tail like real captions
        text[:, c.max_words // 2:] = 0
        return {"video": video, "text": text,
                "start": np.float32(idx % 100)}
