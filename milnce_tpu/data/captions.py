"""Caption sampling + MIL candidate-window selection.

Behavioral parity with the reference's text path
(video_loader.py:119-152), as pure host-side functions:

- a caption store is ``{'start': [...], 'end': [...], 'text': [...]}``
  parsed from the per-video JSON;
- :func:`nearest_candidate_window` greedily grows a window of
  ``num_candidates`` temporally-nearest captions around the sampled one
  (the MIL bag of positives, video_loader.py:119-133);
- :func:`widen_to_min_time` stretches short clips to ``min_time``
  seconds, clamping at 0 (video_loader.py:148-151).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

import numpy as np


@dataclass
class CaptionTrack:
    start: np.ndarray   # (N,) float seconds
    end: np.ndarray     # (N,) float seconds
    text: list[str]

    def __len__(self) -> int:
        return len(self.text)

    @classmethod
    def from_json_file(cls, path: str) -> "CaptionTrack":
        with open(path) as f:
            raw = json.load(f)
        # caption timestamps stay f64 on HOST: multi-hour videos at
        # sub-frame precision exceed f32's ~1e-7 relative resolution;
        # only the sampled clip-relative starts (small floats) ever
        # reach the device, as f32.
        return cls(start=np.asarray(raw["start"], dtype=np.float64),   # graftlint: disable=GL004(host-only timestamp precision; device sees clip-relative f32)
                   end=np.asarray(raw["end"], dtype=np.float64),       # graftlint: disable=GL004(host-only timestamp precision; device sees clip-relative f32)
                   text=[str(t) for t in raw["text"]])


def nearest_candidate_window(track: CaptionTrack, ind: int,
                             num_candidates: int) -> int:
    """Return the start index of the ``num_candidates``-wide window of
    captions temporally nearest to caption ``ind``.

    Greedy growth: at each step extend to whichever side keeps the window's
    time span smaller; clamp at the track edges (video_loader.py:119-133,
    including its edge behaviors: hitting index 0 returns 0, hitting the
    last caption back-fills from the left)."""
    start = end = ind
    n_candidate = 1
    while n_candidate < num_candidates:
        if start == 0:
            return 0
        if end == len(track) - 1:
            return start - (num_candidates - n_candidate)
        if (track.end[end] - track.start[start - 1]
                < track.end[end + 1] - track.start[start]):
            start -= 1
        else:
            end += 1
        n_candidate += 1
    return start


def widen_to_min_time(start: float, end: float,
                      min_time: float) -> tuple[int, int]:
    """Stretch [start, end] to at least ``min_time`` seconds, shifting the
    start back by half the deficit but never below 0; returns ints like
    the reference (video_loader.py:148-152)."""
    if end - start < min_time:
        diff = min_time - end + start
        start = max(0.0, start - diff / 2)
        end = start + min_time
    return int(start), int(end)


def sample_caption(track: CaptionTrack, rng: np.random.RandomState,
                   tokenizer, num_candidates: int, max_words: int,
                   min_time: float) -> tuple[np.ndarray, int, int]:
    """One training text draw: random caption, candidate bag, tokenize,
    widen (video_loader.py:135-152).

    Returns (tokens (K, max_words) int32, start, end)."""
    ind = rng.randint(0, len(track))
    if num_candidates == 1:
        tokens = tokenizer.encode(track.text[ind], max_words)[None]
    else:
        tokens = np.zeros((num_candidates, max_words), np.int32)
        cap_start = nearest_candidate_window(track, ind, num_candidates)
        last = len(track) - 1
        for i in range(num_candidates):
            j = max(0, min(last, cap_start + i))
            tokens[i] = tokenizer.encode(track.text[j], max_words)
    start, end = widen_to_min_time(track.start[ind], track.end[ind], min_time)
    return tokens, start, end
