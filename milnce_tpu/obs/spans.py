"""Monotonic-clock span/event recorder: RUN_EVENTS.jsonl + in-memory ring.

The span taxonomy (OBSERVABILITY.md) covers the moments that explain a
run after the fact: ``step`` (hot-loop dispatch), ``decode.timeout`` /
``decode.retry`` (watchdog escalations), ``batcher.flush`` (serving
micro-batches), ``ladder.warmup`` (engine pre-trace sweep),
``ckpt.save`` / ``ckpt.restore`` / ``rollback`` (checkpoint lifecycle),
``display`` (the train loop's cadenced fetch).

Durability has two tiers:

- the **ring** (``tail()``) always records — bounded memory, surfaced
  over HTTP by the serving front (``GET /obs/events``);
- the **JSONL file** records when a path is configured (the train loop
  writes ``<log_root>/RUN_EVENTS.jsonl``): append-only, one JSON object
  per line, line-buffered so a crash loses at most the current line.

Durations come from ``time.monotonic`` (wall-clock ``ts`` is attached
for human correlation only).  A span around a jitted call measures
HOST-SIDE dispatch, not device work — that is deliberate: the recorder
must never block on the device (the same host-side-only invariant as
the metrics registry).  For device truth, the opt-in
``profiler_bridge=True`` wraps each span in
``jax.profiler.TraceAnnotation`` so spans land in real TPU traces
(jax is imported lazily, only when the bridge is on).

Thread-safe: ring appends and file writes are lock-guarded (spans fire
from reader threads, the batcher worker and request threads).
"""

from __future__ import annotations

import contextlib
import json
import time
from collections import deque
from typing import Optional

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import runctx


def _now() -> float:
    """Monotonic seconds (single helper so span timing has one clock —
    and so tests can monkeypatch it)."""
    return time.monotonic()


def _wall() -> float:
    return time.time()


class SpanRecorder:
    def __init__(self, path: Optional[str] = None, ring: int = 2048,
                 profiler_bridge: bool = False):
        self.path = path or None
        self.profiler_bridge = bool(profiler_bridge)
        self._ring: deque = deque(maxlen=max(1, int(ring)))
        self._lock = make_lock("obs.spans.recorder")
        self._mono_last = 0.0
        self._fh = None
        if self.path:
            # line-buffered append handle, opened ONCE (the RunLogger
            # reopen-per-line pathology is the anti-pattern)
            self._fh = open(self.path, "a", buffering=1)

    # ---- recording -------------------------------------------------------

    def _record(self, rec: dict) -> None:
        # run identity stamped at RECORD time (not construction): the
        # owning entry point installs the context once, and every line —
        # including library events from reader/worker threads — carries
        # it, so obs_report can split a shared append-only stream by run
        # and aggregate.py can merge a pod's streams by process
        run_id, pi = runctx.get_run_context()
        if run_id is not None and "run_id" not in rec:
            rec["run_id"] = run_id
        if pi is not None and "process_index" not in rec:
            rec["process_index"] = pi
        with self._lock:
            # append-order monotonic cursor (``GET /obs/events?since=``):
            # stamped under the lock, and forced STRICTLY increasing —
            # two back-to-back records rounding to the same microsecond
            # would otherwise let a poller whose cursor lands between
            # them miss the second one forever (tail()'s filter is a
            # strict '>')
            mono = round(_now(), 6)
            if mono <= self._mono_last:
                mono = round(self._mono_last + 1e-6, 6)
            self._mono_last = mono
            rec["mono"] = mono
            self._ring.append(rec)
            if self._fh is not None:
                self._fh.write(json.dumps(rec) + "\n")

    def event(self, name: str, **attrs) -> None:
        """Point-in-time occurrence (a retry, a rollback, a display)."""
        rec = {"kind": "event", "name": name, "ts": _wall()}
        rec.update(attrs)
        self._record(rec)

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Timed region; records on exit with ``dur_ms`` (host-side
        elapsed).  Exceptions propagate — the span still records, with
        ``error`` naming the exception type."""
        if self.profiler_bridge:
            import jax

            bridge = jax.profiler.TraceAnnotation(name)
        else:
            bridge = contextlib.nullcontext()
        t0 = _now()
        rec = {"kind": "span", "name": name, "ts": _wall()}
        rec.update(attrs)
        try:
            with bridge:
                yield rec
        except BaseException as exc:
            rec["error"] = type(exc).__name__
            raise
        finally:
            rec["dur_ms"] = round((_now() - t0) * 1e3, 4)
            self._record(rec)

    # ---- reading / lifecycle --------------------------------------------

    def tail(self, n: Optional[int] = None,
             since: Optional[float] = None) -> list[dict]:
        """Most recent ``n`` records, oldest first (the whole ring by
        default); ``n <= 0`` is an empty list, not the whole ring (a
        bare ``out[-0:]`` would invert the limit's meaning).

        ``since`` keeps only records appended strictly after that
        ``mono`` cursor (the append-order monotonic stamp every record
        carries) — pollers pass their last-seen ``mono`` back instead of
        re-downloading the whole ring (``GET /obs/events?since=``)."""
        with self._lock:
            out = list(self._ring)
        if since is not None:
            cut = float(since)
            out = [r for r in out if r.get("mono", 0.0) > cut]
        if n is None:
            return out
        n = int(n)
        return out[-n:] if n > 0 else []

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __del__(self):
        try:
            self.close()
        except Exception:  # graftlint: disable=GL007(interpreter-teardown finalizer: close is best-effort, raising only makes unraisable-exception noise)
            pass


# ---------------------------------------------------------------------------
# the process-default recorder
# ---------------------------------------------------------------------------

_default = SpanRecorder()           # ring-only until a run installs a file
_install_lock = make_lock("obs.spans.install")


def get_recorder() -> SpanRecorder:
    """The process-default recorder.  Library call sites (data pipeline
    watchdog, serving batcher/engine) record here; the train loop
    installs a file-backed recorder for the run's lifetime."""
    return _default


def install(rec: SpanRecorder) -> SpanRecorder:
    """Swap the process-default recorder; returns the previous one so
    the caller can restore it (the train loop does, in its finally)."""
    global _default
    with _install_lock:
        prev = _default
        _default = rec
        return prev
