"""Process-wide run identity: ``run_id`` + ``process_index`` tags.

OBSERVABILITY.md documented a real ambiguity: RUN_EVENTS.jsonl is
append-only BY DESIGN, so two runs sharing one ``obs_dir`` interleave
into a stream no tool can split, and a pod run's per-process snapshots
carry nothing that says which host produced them.  This module is the
fix's single source of truth: the entry points that own a run (the
train loop, ``milnce-serve``, ``bench.py``, ``serve_bench``) call
:func:`set_run_context` once at startup, and from then on

- every record the span recorder writes (obs/spans.py) and
- every ``milnce.obs/v1`` snapshot (obs/export.py)

is stamped with ``run_id`` + ``process_index``.  ``obs_report`` splits
event streams on ``run_id`` (mixed-run streams are a loud error) and
``obs/aggregate.py`` refuses to merge snapshots from different runs.

Pure stdlib, no jax/numpy — the same import-anywhere contract as the
rest of ``obs/``; the caller passes ``jax.process_index()`` in.
Thread-safe: the context is read from recorder/export call sites on
arbitrary threads while the owning entry point installs it.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from milnce_tpu.analysis.lockrt import make_lock

_lock = make_lock("obs.runctx")
_run_id: Optional[str] = None           # guarded-by: _lock
_process_index: Optional[int] = None    # guarded-by: _lock


def auto_run_id(prefix: str = "r") -> str:
    """A fresh process-local run id: start-second + 2 random bytes —
    unique across restarts on one host.  NOT cluster-uniform: a
    multi-process run must share ONE id, so the train loop broadcasts
    process 0's value (parallel/mesh.broadcast_str) or the operator
    passes ``--train.run_id`` explicitly."""
    return f"{prefix}{int(time.time())}-{os.urandom(2).hex()}"


def set_run_context(run_id: Optional[str],
                    process_index: Optional[int]) -> tuple:
    """Install the process-wide run identity; returns the previous
    ``(run_id, process_index)`` so scoped owners (the train loop's
    ``finally``) can restore it."""
    global _run_id, _process_index
    with _lock:
        prev = (_run_id, _process_index)
        _run_id = str(run_id) if run_id is not None else None
        _process_index = (int(process_index)
                          if process_index is not None else None)
        return prev


def get_run_context() -> tuple:
    """``(run_id, process_index)`` — both None until an owner installs
    them (library-only processes, unit tests)."""
    with _lock:
        return _run_id, _process_index
