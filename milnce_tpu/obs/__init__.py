"""Unified observability: metrics registry, span tracing, exposition.

One subsystem replaces the three ad-hoc counter paths that grew with the
stack (the train loop's display log line, the serving ``/healthz`` dict,
per-tool JSON artifacts with incompatible schemas):

- :mod:`milnce_tpu.obs.metrics` — process-wide, thread-safe typed
  registry (Counter / Gauge / Histogram, labeled families);
- :mod:`milnce_tpu.obs.spans` — monotonic-clock span/event recorder
  (append-only ``RUN_EVENTS.jsonl`` + in-memory ring, opt-in
  ``jax.profiler.TraceAnnotation`` bridge);
- :mod:`milnce_tpu.obs.export` — Prometheus text exposition and the
  versioned JSON snapshot schema shared by bench.py, serve_bench.py
  and the train loop.

The attribution tier (ISSUE 9) builds on those streams:

- :mod:`milnce_tpu.obs.runctx` — ``run_id`` + ``process_index``
  stamped on every record and snapshot;
- :mod:`milnce_tpu.obs.goodput` — the goodput ledger: run wall time
  partitioned into compute / data-wait / checkpoint / skipped /
  rollback-lost badput categories;
- :mod:`milnce_tpu.obs.anomaly` / :mod:`milnce_tpu.obs.capture` —
  EWMA spike detection arming a bounded one-shot ``jax.profiler``
  capture;
- :mod:`milnce_tpu.obs.aggregate` — pod-level merging (summed
  counters, min/median/max gauges, straggler skew).

The load-bearing invariant (OBSERVABILITY.md): **recording is host-side
only and never adds a device sync**.  Nothing in this package imports
jax at module scope; recording a device value is a :class:`TypeError`,
not a silent ``float()`` sync; and the ``train_step_milnce_instrumented``
trace invariant pins the instrumented train step's collectives identical
to the uninstrumented step under ``jax.transfer_guard("disallow")``.
"""

from milnce_tpu.obs import export, metrics, spans  # noqa: F401
