"""Goodput ledger: partition a run's wall time into attribution buckets.

The obs layer so far records *what* happened (counters, spans); this
module answers *where the time went*.  It consumes the span/event
stream a run already emits (RUN_EVENTS.jsonl lines or the recorder
ring — train/loop.py + data/pipeline.py instrumentation) and produces
a ledger that partitions the run's wall clock into:

| category       | fed by                                             |
|----------------|----------------------------------------------------|
| ``compute``    | ``step`` span dispatch + ``sync`` spans (the        |
|                | display-cadence ``device_get`` where the async     |
|                | pipeline's device work surfaces on the host),      |
|                | minus the skipped / rollback-lost reattributions   |
| ``compile``    | the FIRST ``step`` span per process — dispatch     |
|                | blocks on trace+compile there, and calling that    |
|                | compute would flatter every short run's goodput    |
| ``stage_switch`` | curriculum boundaries: each ``stage.switch``     |
|                | span (prefetcher drain + pipeline rebuild) plus    |
|                | the first ``step`` span after it — that dispatch   |
|                | blocks on the new stage's trace+compile, so the    |
|                | curriculum's overhead is measured, not guessed     |
| ``data_wait``  | ``data.wait`` spans (device_prefetch pulls: host   |
|                | blocked assembling/decoding the next batch)        |
| ``checkpoint`` | ``ckpt.save`` + ``ckpt.restore`` spans             |
| ``drain``      | ``elastic.drain`` spans: the forced stop-save of a |
|                | preemption drain (used INSTEAD of ``ckpt.save``    |
|                | there — the save is badput the preemption caused,  |
|                | not routine checkpoint overhead)                   |
| ``reshard``    | ``elastic.resume`` spans: restoring a rotation     |
|                | onto a (possibly different) mesh layout — used     |
|                | INSTEAD of ``ckpt.restore`` when a topology stamp  |
|                | is present, so resize cost is attributable         |
| ``skipped``    | step time of finite-guard-skipped updates (badput: |
|                | the chip ran, the update was discarded), prorated  |
|                | from the display events' ``skipped_total`` deltas  |
| ``rollback_lost`` | step time of updates a circuit-breaker rollback |
|                | discarded (``rollback`` events' ``lost_updates``)  |
| ``unattributed`` | the remainder: loop overhead, display logging,   |
|                | eval, init between the run markers                 |

``goodput_fraction`` = compute / wall: the fraction of the run's wall
clock that produced *kept* training progress.  All categories sum to
``wall_s`` by construction **unless spans double-count** (overlapping
attribution would push the attributed total past wall and the
``unattributed`` floor at zero makes the sum exceed wall) — the chaos
acceptance test pins the sum against an externally measured wall time
within 5%, so a future instrumentation change that overlaps spans
fails loudly instead of quietly inventing time.

Wall time comes from the ``run.start`` / ``run.end`` markers the train
loop emits (falling back to first/last record timestamps for foreign
streams).  All inputs are host-side wall/monotonic stamps that already
exist in the stream: building a ledger costs zero device syncs.

Stdlib-only (importable by scripts/obs_report.py's jax-free gate).
"""

from __future__ import annotations

from dataclasses import dataclass, field

CATEGORIES = ("compute", "compile", "stage_switch", "data_wait",
              "checkpoint", "drain", "reshard", "skipped",
              "rollback_lost", "unattributed")

# span name -> raw bucket (before the skipped/rollback reattribution)
_SPAN_BUCKETS = {
    "step": "compute",
    "sync": "compute",
    "data.wait": "data_wait",
    "ckpt.save": "checkpoint",
    "ckpt.restore": "checkpoint",
    "stage.switch": "stage_switch",
    "elastic.drain": "drain",
    "elastic.resume": "reshard",
}


@dataclass
class GoodputLedger:
    run_id: str | None
    process_index: int | None
    wall_s: float
    categories: dict = field(default_factory=dict)  # name -> seconds
    steps: int = 0
    skipped_steps: int = 0
    rollbacks: int = 0
    lost_updates: int = 0
    decode_timeouts: int = 0
    anomalies: int = 0
    captures: int = 0
    stage_switches: int = 0     # curriculum boundaries crossed

    @property
    def goodput_fraction(self) -> float:
        return (self.categories.get("compute", 0.0) / self.wall_s
                if self.wall_s > 0 else 0.0)

    def to_extra(self) -> dict:
        """Top-level keys for the ``milnce.obs/v1`` ledger snapshot
        (kind=``goodput``) — ``goodput_fraction`` rides at top level so
        ``obs_report --check`` gates it like clips/s."""
        return {
            "wall_s": round(self.wall_s, 4),
            "categories_s": {k: round(v, 4)
                             for k, v in self.categories.items()},
            "goodput_fraction": round(self.goodput_fraction, 5),
            "steps": self.steps,
            "skipped_steps": self.skipped_steps,
            "rollbacks": self.rollbacks,
            "lost_updates": self.lost_updates,
            "decode_timeouts": self.decode_timeouts,
            "anomalies": self.anomalies,
            "captures": self.captures,
            "stage_switches": self.stage_switches,
        }

    def summary_line(self) -> str:
        frac = {k: (v / self.wall_s if self.wall_s > 0 else 0.0)
                for k, v in self.categories.items()}
        parts = ", ".join(f"{k} {frac[k]:.1%}" for k in CATEGORIES
                          if self.categories.get(k))
        return (f"goodput ledger: wall {self.wall_s:.1f}s, goodput "
                f"{self.goodput_fraction:.1%} ({parts}; steps "
                f"{self.steps}, skipped {self.skipped_steps}, "
                f"rollbacks {self.rollbacks})")


def split_runs(records: list) -> dict:
    """Group a (possibly shared, append-only) stream by ``run_id``.
    Untagged records (pre-tagging streams) group under ``None``."""
    runs: dict = {}
    for rec in records:
        runs.setdefault(rec.get("run_id"), []).append(rec)
    return runs


def select_run(records: list, run_id: str | None = None) -> list:
    """One run's records out of a stream.  ``run_id=None`` requires the
    stream to hold EXACTLY one run — a mixed stream is the documented
    cross-run append ambiguity and raises loudly instead of silently
    diluting percentiles across runs."""
    runs = split_runs(records)
    if run_id is not None:
        if run_id not in runs:
            raise ValueError(
                f"run_id {run_id!r} not in stream (present: "
                f"{sorted(str(k) for k in runs)})")
        return runs[run_id]
    if len(runs) > 1:
        raise ValueError(
            f"mixed-run stream: {len(runs)} run_ids present "
            f"({sorted(str(k) for k in runs)}) — pass run_id= (CLI: "
            "--run-id) or point at a fresh obs_dir per run "
            "(OBSERVABILITY.md 'Run identity')")
    return next(iter(runs.values())) if runs else []


def _span_window(records: list) -> tuple[float, float]:
    """(start, end) wall seconds covered by the stream.  Prefers the
    explicit ``run.start`` / ``run.end`` markers — FIRST start, LAST
    end: a crashed run re-launched under the same explicit run_id
    appends a second marker pair, and the window must cover every
    session whose spans the categories sum over (keeping only the last
    pair made attributed time exceed wall and pushed the gated
    goodput_fraction past 1.0).  Falls back to the first/last record
    stamps (spans end at ``ts + dur_ms``) for marker-less streams."""
    start = end = None
    lo, hi = float("inf"), float("-inf")
    for rec in records:
        ts = float(rec.get("ts", 0.0))
        if rec.get("name") == "run.start":
            start = ts if start is None else min(start, ts)
        elif rec.get("name") == "run.end":
            end = ts if end is None else max(end, ts)
        lo = min(lo, ts)
        hi = max(hi, ts + float(rec.get("dur_ms", 0.0)) / 1e3)
    if not records:
        return 0.0, 0.0
    return (start if start is not None else lo,
            end if end is not None else hi)


def compute_ledger(records: list, run_id: str | None = None,
                   process_index: int | None = None) -> GoodputLedger:
    """Build the ledger for one run (and optionally one process) out of
    a record stream."""
    records = select_run(records, run_id)
    if process_index is not None:
        records = [r for r in records
                   if r.get("process_index", process_index)
                   == process_index]
    if not records:
        raise ValueError("empty record stream — nothing to attribute")
    t0, t1 = _span_window(records)
    wall = max(0.0, t1 - t0)

    cats = {k: 0.0 for k in CATEGORIES}
    steps = 0
    step_durs: list[float] = []
    skipped = 0
    rollbacks = 0
    lost_updates = 0
    anomalies = 0
    captures = 0
    timeouts = 0
    stage_switches = 0
    seen_first_step = False
    pending_switch = False
    for rec in records:
        name = rec.get("name", "")
        if rec.get("kind") == "span":
            dur = float(rec.get("dur_ms", 0.0)) / 1e3
            if name == "step":
                steps += 1
                if not seen_first_step:
                    # first dispatch blocks on trace+compile — its own
                    # category, or a 2-step CPU run reads as 95% compute
                    seen_first_step = True
                    cats["compile"] += dur
                elif pending_switch:
                    # first step of a NEW curriculum stage: dispatch
                    # blocks on that stage's trace+compile — boundary
                    # cost, not steady-state compute (and excluded from
                    # the mean-step-time pool like the compile step)
                    pending_switch = False
                    cats["stage_switch"] += dur
                else:
                    step_durs.append(dur)
                    cats["compute"] += dur
            else:
                bucket = _SPAN_BUCKETS.get(name)
                if bucket is not None:
                    cats[bucket] += dur
                if name == "stage.switch":
                    stage_switches += 1
                    pending_switch = True
        elif rec.get("kind") == "event":
            if name == "display":
                skipped = max(skipped,
                              int(rec.get("skipped_total", 0) or 0))
            elif name == "rollback":
                rollbacks += 1
                lost_updates += int(rec.get("lost_updates", 0) or 0)
            elif name == "anomaly":
                anomalies += 1
            elif name == "capture.start":
                captures += 1
            elif name == "decode.timeout":
                timeouts += 1

    # Reattribute badput OUT of compute: the chip ran these steps but
    # the updates were discarded.  Prorated by the run-level skip
    # fraction / mean post-compile step time — the stream doesn't say
    # WHICH steps skipped (that would cost a per-step host sync), and a
    # ledger needs totals, not per-step labels.
    # compute-pool step count: total minus the compile step and the
    # per-switch compile steps already attributed to stage_switch
    post_compile = max(1, steps - 1 - stage_switches)
    if skipped and cats["compute"] > 0:
        frac = min(1.0, skipped / post_compile)
        moved = cats["compute"] * frac
        cats["skipped"] = moved
        cats["compute"] -= moved
    if lost_updates and step_durs:
        mean_step = sum(step_durs) / len(step_durs)
        moved = min(cats["compute"], mean_step * lost_updates)
        cats["rollback_lost"] = moved
        cats["compute"] -= moved

    attributed = sum(v for k, v in cats.items() if k != "unattributed")
    cats["unattributed"] = max(0.0, wall - attributed)

    rid = records[0].get("run_id") if run_id is None else run_id
    pi = process_index
    if pi is None:
        pis = {r.get("process_index") for r in records} - {None}
        pi = pis.pop() if len(pis) == 1 else None
    return GoodputLedger(run_id=rid, process_index=pi, wall_s=wall,
                         categories=cats, steps=steps,
                         skipped_steps=skipped, rollbacks=rollbacks,
                         lost_updates=lost_updates,
                         decode_timeouts=timeouts, anomalies=anomalies,
                         captures=captures, stage_switches=stage_switches)


def ledger_to_registry(ledger: GoodputLedger, registry) -> None:
    """Export the ledger as ``milnce.obs/v1`` gauges on ``registry`` —
    the per-run summary a scrape (or the final snapshot) carries."""
    fam = registry.gauge("milnce_goodput_seconds",
                         "per-run wall-time attribution (goodput ledger)",
                         labels=("category",))
    for cat in CATEGORIES:
        fam.labels(category=cat).set(ledger.categories.get(cat, 0.0))
    registry.gauge("milnce_goodput_wall_seconds",
                   "total wall time the ledger attributes over"
                   ).set(ledger.wall_s)
    registry.gauge("milnce_goodput_fraction",
                   "kept-compute fraction of run wall time"
                   ).set(ledger.goodput_fraction)
