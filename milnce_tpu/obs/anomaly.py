"""EWMA spike detector: step-time / latency anomalies, host-side only.

A step-time regression on real hardware is invisible until someone
re-runs under a manually armed profiler.  This detector watches a
stream of host-side measurements (window step time at display cadence,
batcher flush latency on the serving side), keeps an exponentially
weighted mean + deviation, and on a spike emits an ``anomaly`` event
and fires a callback — obs/capture.py's bounded one-shot
``jax.profiler`` capture, so the trace of the *anomalous* period exists
without anyone watching.

Spike criterion (both must hold, after ``warmup`` samples):

- ``value > ewma * ratio`` — a relative floor, so the near-zero
  variance of a healthy steady state (step times flat to the ms) does
  not turn scheduler jitter into pages;
- ``value > ewma + sigma * std`` — a deviation gate, so a noisy
  baseline (shared CPU containers) widens its own threshold.

Anomalous samples do NOT update the EWMA: a genuine regression keeps
firing against the healthy baseline instead of teaching the detector
that slow is normal.  A ``cooldown_s`` window suppresses repeat events
so a bad run pages once per episode, not per display.

Recording is host-side (the registry/recorder invariant); the
callback runs OUTSIDE the detector lock — callbacks take their own
locks (ProfilerCapture) and calling through while holding ours would
stack this lock above theirs in the order graph (GL011/GL012
discipline).  ``observe`` may be called from any thread (the train
loop, the batcher worker).
"""

from __future__ import annotations

import math
import time
from typing import Callable, Optional

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import spans as obs_spans


class EwmaSpikeDetector:
    """Feed host-side samples; get at most one anomaly per episode.

    - ``name``: what the samples measure (``train.step_ms``,
      ``serve.flush_ms``) — lands in the event and the metric label;
    - ``ratio``: relative spike floor (value vs EWMA);
    - ``sigma``: deviation gate width;
    - ``alpha``: EWMA weight of the newest sample;
    - ``warmup``: samples before the detector may fire (the first
      windows include compile and cache-cold effects);
    - ``cooldown_s``: suppression window after a firing;
    - ``on_anomaly``: callback ``(value, ewma) -> None`` invoked outside
      the lock (arm a capture, log, page);
    - ``recorder``: span recorder for the ``anomaly`` event (None = the
      process default, resolved per firing);
    - ``time_fn`` / injectable clock: tests drive the cooldown without
      sleeping.
    """

    def __init__(self, name: str, *, ratio: float = 2.0,
                 sigma: float = 4.0, alpha: float = 0.2, warmup: int = 3,
                 cooldown_s: float = 300.0,
                 on_anomaly: Optional[Callable[[float, float], None]] = None,
                 recorder: Optional[obs_spans.SpanRecorder] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        if ratio <= 1.0:
            raise ValueError(f"ratio must be > 1 (got {ratio}): a spike "
                             "threshold at or below the mean fires forever")
        self.name = name
        self.ratio = float(ratio)
        self.sigma = float(sigma)
        self.alpha = float(alpha)
        self.warmup = max(1, int(warmup))
        self.cooldown_s = float(cooldown_s)
        self._on_anomaly = on_anomaly
        self._recorder = recorder
        self._time = time_fn
        self._lock = make_lock("obs.anomaly.detector")
        self._n = 0                     # guarded-by: _lock
        self._ewma = 0.0                # guarded-by: _lock
        self._var = 0.0                 # guarded-by: _lock
        self._last_fire = -math.inf     # guarded-by: _lock
        self._fired = 0                 # guarded-by: _lock

    def observe(self, value: float, **attrs) -> bool:
        """Record one sample; returns True when this sample fired an
        anomaly (event emitted + callback invoked)."""
        value = float(value)
        now = self._time()
        fire = False
        with self._lock:
            if self._n >= self.warmup:
                std = math.sqrt(max(0.0, self._var))
                spike = (value > self._ewma * self.ratio
                         and value > self._ewma + self.sigma * std)
                if spike:
                    if now - self._last_fire >= self.cooldown_s:
                        fire = True
                        self._last_fire = now
                        self._fired += 1
                    # anomalous samples never update the baseline —
                    # suppressed or not, slow must not become normal
                    ewma = self._ewma
                else:
                    ewma = self._update(value)
            else:
                ewma = self._update(value)
        if fire:
            rec = (self._recorder if self._recorder is not None
                   else obs_spans.get_recorder())
            rec.event("anomaly", detector=self.name, value=round(value, 4),
                      ewma=round(ewma, 4), **attrs)
            cb = self._on_anomaly
            if cb is not None:
                cb(value, ewma)
        return fire

    # guarded-by: _lock
    def _update(self, value: float) -> float:
        # helper-relies-on-caller's-lock: observe() holds _lock across
        # every call (the annotated contract graftlint Pass 3 checks)
        if self._n == 0:
            self._ewma = value
        else:
            delta = value - self._ewma
            self._ewma += self.alpha * delta
            self._var = (1 - self.alpha) * (self._var
                                            + self.alpha * delta * delta)
        self._n += 1
        return self._ewma

    def stats(self) -> dict:
        with self._lock:
            return {"name": self.name, "samples": self._n,
                    "ewma": self._ewma,
                    "std": math.sqrt(max(0.0, self._var)),
                    "anomalies": self._fired}
