"""Exposition: Prometheus text format + the versioned JSON snapshot.

Two render targets off the same registry:

- :func:`to_prometheus` — text exposition (format 0.0.4) for pull-based
  scraping (``GET /metrics`` on the serving front);
- :func:`snapshot` — the versioned JSON document every artifact in this
  repo now shares (``schema`` = :data:`SNAPSHOT_SCHEMA`, ``kind``
  discriminates producers): registry snapshots, ``SERVE_BENCH_*.json``
  (scripts/serve_bench.py), the train bench line (bench.py).  One
  schema means ``scripts/obs_report.py`` can summarize and
  regression-gate any of them.

Format notes (pinned by the exposition golden in tests/test_obs.py):
integral values print without a decimal point; histogram buckets follow
the Prometheus cumulative-``le`` convention with a ``+Inf`` bucket and
``_sum`` / ``_count`` series; label values are escaped per the spec.
"""

from __future__ import annotations

import json
import math

from milnce_tpu.obs import runctx
from milnce_tpu.obs.metrics import MetricsRegistry

SNAPSHOT_SCHEMA = "milnce.obs/v1"

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt(v: float) -> str:
    f = float(v)
    if not math.isfinite(f):
        # legal Prometheus sample values (a guarded train window's loss
        # gauge is nan by construction) — one non-finite sample must
        # never 500 the whole scrape
        return "NaN" if math.isnan(f) else ("+Inf" if f > 0 else "-Inf")
    return str(int(f)) if f == int(f) else repr(f)


def _escape(s: str) -> str:
    return s.replace("\\", r"\\").replace('"', r'\"').replace("\n", r"\n")


def _labelstr(names: tuple, values: tuple, extra: tuple = ()) -> str:
    pairs = [f'{n}="{_escape(str(v))}"' for n, v in zip(names, values)]
    pairs += [f'{n}="{_escape(str(v))}"' for n, v in extra]
    return "{" + ",".join(pairs) + "}" if pairs else ""


def to_prometheus(registry: MetricsRegistry) -> str:
    lines: list[str] = []
    for fam in registry.collect():
        if fam.help:
            lines.append(f"# HELP {fam.name} {fam.help}")
        lines.append(f"# TYPE {fam.name} {fam.type}")
        for values, child in fam.items():
            if fam.type in ("counter", "gauge"):
                lines.append(f"{fam.name}"
                             f"{_labelstr(fam.labelnames, values)} "
                             f"{_fmt(child.value)}")
                continue
            snap = child.snapshot()
            cum = 0
            for edge, n in zip(snap["edges"], snap["counts"]):
                cum += n
                lines.append(
                    f"{fam.name}_bucket"
                    f"{_labelstr(fam.labelnames, values, (('le', _fmt(edge)),))}"
                    f" {cum}")
            cum += snap["counts"][-1]
            lines.append(
                f"{fam.name}_bucket"
                f"{_labelstr(fam.labelnames, values, (('le', '+Inf'),))}"
                f" {cum}")
            lines.append(f"{fam.name}_sum"
                         f"{_labelstr(fam.labelnames, values)} "
                         f"{_fmt(snap['sum'])}")
            lines.append(f"{fam.name}_count"
                         f"{_labelstr(fam.labelnames, values)} "
                         f"{snap['count']}")
    return "\n".join(lines) + "\n"


def snapshot(registry: MetricsRegistry, kind: str = "metrics",
             extra: dict | None = None, run_id: str | None = None,
             process_index: int | None = None) -> dict:
    """Versioned JSON document of the registry's current state.

    ``kind`` names the producer (``metrics`` for a raw registry dump;
    serve_bench / bench stamp their own).  ``extra`` merges additional
    top-level keys (latency tables, run config) — the ``schema`` /
    ``kind`` / ``metrics`` keys are reserved.

    Run identity: the document is stamped with ``run_id`` +
    ``process_index`` from the installed run context (obs/runctx.py) —
    every artifact-producing entry point installs one, so pod-level
    aggregation (obs/aggregate.py) can verify same-run/distinct-process
    before merging.  Explicit keyword args override the context."""
    metrics: dict = {}
    for fam in registry.collect():
        values = []
        for labelvalues, child in fam.items():
            labels = dict(zip(fam.labelnames, labelvalues))
            if fam.type == "histogram":
                values.append({"labels": labels, **child.snapshot()})
            else:
                values.append({"labels": labels, "value": child.value})
        metrics[fam.name] = {"type": fam.type, "help": fam.help,
                             "values": values}
    doc = {"schema": SNAPSHOT_SCHEMA, "kind": kind, "metrics": metrics}
    ctx_run, ctx_pi = runctx.get_run_context()
    run_id = run_id if run_id is not None else ctx_run
    process_index = process_index if process_index is not None else ctx_pi
    if run_id is not None:
        doc["run_id"] = str(run_id)
    if process_index is not None:
        doc["process_index"] = int(process_index)
    for k, v in (extra or {}).items():
        if k in doc:
            raise ValueError(f"snapshot extra key {k!r} is reserved")
        doc[k] = v
    return doc


def write_snapshot(path: str, registry: MetricsRegistry,
                   kind: str = "metrics", extra: dict | None = None,
                   run_id: str | None = None,
                   process_index: int | None = None) -> dict:
    doc = snapshot(registry, kind, extra, run_id=run_id,
                   process_index=process_index)
    with open(path, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return doc
