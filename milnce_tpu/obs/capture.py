"""Bounded one-shot ``jax.profiler`` capture, armed by anomalies.

The missing diagnostic loop: a step-time spike on real hardware is
only explainable from a profiler trace of the *spiking* period, but
traces are expensive (hundreds of MB, host overhead) so nobody runs
them always-on.  :class:`ProfilerCapture` holds a disarmed profiler
that anything host-side may arm — the EWMA detector (obs/anomaly.py),
``SIGUSR1`` on the train loop, ``POST /obs/capture`` on the serving
front — and that then stops ITSELF after a bounded duration.

Discipline (why a bad run captures once, not forever):

- at most one capture in flight (arming while active is refused);
- ``cooldown_s`` between captures;
- ``max_captures`` per process lifetime (default 1: the first anomaly
  of a run is the interesting one; operators re-arm by restarting or
  raising the budget).

Every transition emits ``capture.start`` / ``capture.stop`` events so
the run's event stream says exactly which wall-clock window the trace
covers.  State transitions happen under the lock; the profiler
start/stop callables run OUTSIDE it (they do real I/O — blocking under
a lock is the GL012 class of bug), with the ``starting``/``stopping``
states keeping concurrent armers out meanwhile.  ``start_fn`` /
``stop_fn`` are injectable for tests; the defaults import jax lazily
(the module stays importable in jax-free tools).
"""

from __future__ import annotations

import os
import re
import threading
import time
from typing import Callable, Optional

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import spans as obs_spans

_REASON_SLUG = re.compile(r"[^A-Za-z0-9_-]+")


def _slug(reason: str) -> str:
    """Filesystem-safe capture-directory label.  ``reason`` reaches
    here from the NETWORK (``POST /obs/capture``): anything outside
    [A-Za-z0-9_-] — path separators, ``..``, whitespace — is squashed
    so a request body can never direct the trace write outside
    ``out_dir``."""
    return _REASON_SLUG.sub("_", str(reason)).strip("_")[:48] or "manual"


def _default_start(trace_dir: str) -> None:
    import jax

    jax.profiler.start_trace(trace_dir)


def _default_stop() -> None:
    import jax

    jax.profiler.stop_trace()


class ProfilerCapture:
    """Armable, self-stopping, budgeted profiler capture.

    - ``out_dir``: capture root; each capture lands in a numbered
      ``capture_NNN-<reason>/`` subdirectory;
    - ``duration_s``: the capture stops itself this long after arming
      (a daemon timer thread calls the stop path);
    - ``cooldown_s`` / ``max_captures``: the re-arm budget;
    - ``recorder``: event destination (None = process default, resolved
      per event);
    - ``start_fn(trace_dir)`` / ``stop_fn()``: the profiler backend
      (default: ``jax.profiler`` start/stop_trace);
    - ``time_fn``: injectable clock for cooldown tests.
    """

    def __init__(self, out_dir: str, *, duration_s: float = 2.0,
                 cooldown_s: float = 600.0, max_captures: int = 1,
                 recorder: Optional[obs_spans.SpanRecorder] = None,
                 start_fn: Callable[[str], None] = _default_start,
                 stop_fn: Callable[[], None] = _default_stop,
                 time_fn: Callable[[], float] = time.monotonic):
        self.out_dir = out_dir
        self.duration_s = float(duration_s)
        self.cooldown_s = float(cooldown_s)
        self.max_captures = int(max_captures)
        self._recorder = recorder
        self._start_fn = start_fn
        self._stop_fn = stop_fn
        self._time = time_fn
        self._lock = make_lock("obs.capture")
        self._state = "idle"        # guarded-by: _lock  (idle | starting
        #                             | active | stopping)
        self._captures = 0          # guarded-by: _lock
        self._last_done = None      # guarded-by: _lock  (monotonic s)
        self._timer = None          # guarded-by: _lock
        self._stop_requested = False  # guarded-by: _lock  (stop() raced
        #                               an arm still in 'starting')

    # ---- arming ----------------------------------------------------------

    def arm(self, reason: str = "manual", **attrs) -> dict:
        """Try to start a capture.  Returns ``{"armed": bool, ...}``
        with the refusal reason when not armed — callers surface it
        (the serving endpoint returns it as JSON) instead of guessing."""
        now = self._time()
        with self._lock:
            if self._state != "idle":
                return {"armed": False, "reason": f"capture {self._state}"}
            if self._captures >= self.max_captures:
                return {"armed": False,
                        "reason": f"budget exhausted "
                                  f"({self._captures}/{self.max_captures} "
                                  "captures this process)"}
            if (self._last_done is not None
                    and now - self._last_done < self.cooldown_s):
                remaining = self.cooldown_s - (now - self._last_done)
                return {"armed": False,
                        "reason": f"cooldown ({remaining:.0f}s remaining)"}
            self._state = "starting"
            n = self._captures + 1
        trace_dir = os.path.join(self.out_dir,
                                 f"capture_{n:03d}-{_slug(reason)}")
        try:
            os.makedirs(trace_dir, exist_ok=True)
            self._start_fn(trace_dir)
        except Exception as exc:
            with self._lock:
                self._state = "idle"
                self._stop_requested = False
            self._event("capture.error", reason=reason,
                        error=f"{type(exc).__name__}: {exc}")
            return {"armed": False,
                    "reason": f"profiler start failed: "
                              f"{type(exc).__name__}: {exc}"}
        with self._lock:
            self._captures = n
            if self._stop_requested:
                # a stop()/close() landed while _start_fn ran: honor it
                # NOW — leaving the trace running with only a daemon
                # timer to stop it would lose the capture on exit
                self._stop_requested = False
                self._state = "stopping"
                timer = None
            else:
                self._state = "active"
                timer = threading.Timer(self.duration_s, self._auto_stop)
                timer.daemon = True
                self._timer = timer
        if timer is None:
            try:
                self._stop_fn()
            finally:
                with self._lock:
                    self._state = "idle"
                    self._last_done = self._time()
            self._event("capture.stop", cause="stopped-during-start")
            return {"armed": False,
                    "reason": "stop requested while the capture was "
                              "starting (trace flushed)"}
        timer.start()
        self._event("capture.start", reason=reason, trace_dir=trace_dir,
                    duration_s=self.duration_s, capture=n, **attrs)
        return {"armed": True, "trace_dir": trace_dir, "capture": n}

    # ---- stopping --------------------------------------------------------

    def _auto_stop(self) -> None:
        self.stop(cause="duration")

    def stop(self, cause: str = "manual") -> bool:
        """Stop an active capture (idempotent; the duration timer and a
        manual/final stop may race — exactly one wins)."""
        with self._lock:
            if self._state == "starting":
                # arm() is inside _start_fn on another thread: flag it —
                # the armer stops the trace itself the moment the start
                # completes (the 'stopped-during-start' path)
                self._stop_requested = True
                return False
            if self._state != "active":
                return False
            self._state = "stopping"
            timer, self._timer = self._timer, None
        if timer is not None:
            timer.cancel()
        try:
            self._stop_fn()
        finally:
            with self._lock:
                self._state = "idle"
                self._last_done = self._time()
        self._event("capture.stop", cause=cause)
        return True

    def close(self) -> None:
        """Owner teardown: stop a still-active capture so a run that
        ends mid-capture flushes its trace instead of corrupting it."""
        self.stop(cause="close")

    # ---- reading ---------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"state": self._state, "captures": self._captures,
                    "max_captures": self.max_captures,
                    "out_dir": self.out_dir}

    def _event(self, name: str, **attrs) -> None:
        rec = (self._recorder if self._recorder is not None
               else obs_spans.get_recorder())
        rec.event(name, **attrs)
