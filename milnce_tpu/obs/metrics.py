"""Process-wide, thread-safe typed metrics registry.

Three metric types, Prometheus-shaped on purpose (export.py renders the
text exposition straight off these objects):

- :class:`Counter` — monotonically increasing (requests, failures);
- :class:`Gauge` — set-to-current-value (loss, learning rate), with an
  optional collect-time callback for values that live elsewhere (e.g.
  the serving engine's ``recompiles()``, the cache hit rate);
- :class:`Histogram` — fixed bucket edges given at creation (batch
  occupancy, latencies); cumulative bucket counts at exposition.

Every metric belongs to a *family* (name + help + label names); a family
with no labels has exactly one child and the registry helpers return the
child directly so the common case reads ``REG.counter(...).inc()``.

Hard invariants:

- **host-side only**: recording a value that quacks like a device array
  (``block_until_ready``) raises ``TypeError`` instead of letting a
  ``float()`` smuggle a device sync into a hot path.  Train-side values
  are fed from the existing display-cadence ``device_get`` (train/
  loop.py); the ``train_step_milnce_instrumented`` trace invariant pins
  that recording adds no collectives and no transfers.
- **thread-safe**: every mutation takes the metric's lock (decode
  failures arrive from reader threads, serving counters from request
  threads and the batcher worker — the exact race the old ``/healthz``
  dict had); the hammer test in tests/test_obs.py pins exact final
  counts under contention.

No jax, no numpy — pure stdlib, importable anywhere (including the
jax-free AST lint pass).  Every lock is created through
``analysis.lockrt.make_lock``, so ``MILNCE_LOCK_SANITIZE=1`` swaps in
the order-checking :class:`~milnce_tpu.analysis.lockrt.SanitizedLock`
across the whole registry (ANALYSIS.md, Pass 3b).
"""

from __future__ import annotations

import bisect
from typing import Callable, Optional, Sequence

from milnce_tpu.analysis.lockrt import make_lock

METRIC_TYPES = ("counter", "gauge", "histogram")


def _host_number(value) -> float:
    """Reject device arrays at the recording boundary: ``float()`` of a
    jax array is a blocking device sync — exactly the class of hidden
    stall this registry must never introduce.  Host numbers (int, float,
    numpy scalars) pass through."""
    if hasattr(value, "block_until_ready"):
        raise TypeError(
            "refusing to record a device array: metrics recording is "
            "host-side only (fetch at display cadence first — "
            "OBSERVABILITY.md 'host-side only' invariant)")
    return float(value)


class Counter:
    """Monotonic counter child.  ``inc(amount)`` with ``amount >= 0``."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = make_lock("obs.metrics.counter")
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        amount = _host_number(amount)
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Set-to-current-value child; ``fn`` makes it collect-time computed
    (reads delegate to the callback, ``set`` becomes an error).

    ``_fn`` shares ``_value``'s guard: ``bind()`` arrives from component
    (re)construction while scrape threads read — an unlocked swap raced
    both (graftlint GL010).  The callback itself is invoked OUTSIDE the
    lock: callbacks read other components' stats (engine recompiles,
    cache hit rate) that take their own locks, and calling through while
    holding ours would put this gauge's lock above every one of theirs
    in the order graph for no benefit (GL012 discipline)."""

    __slots__ = ("_lock", "_value", "_fn")

    def __init__(self, fn: Optional[Callable[[], float]] = None):
        self._lock = make_lock("obs.metrics.gauge")
        self._value = 0.0
        self._fn = fn

    def set(self, value: float) -> None:
        value = _host_number(value)
        with self._lock:
            if self._fn is not None:
                raise ValueError("callback gauge: the value comes from its "
                                 "fn at collect time, set() is meaningless")
            self._value = value

    def inc(self, amount: float = 1.0) -> None:
        amount = _host_number(amount)
        with self._lock:
            if self._fn is not None:
                raise ValueError("callback gauge cannot be incremented")
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-_host_number(amount))

    def bind(self, fn: Callable[[], float]) -> None:
        """(Re)bind the collect-time callback — create-or-get semantics
        mean a long-lived registry may outlive the object a callback
        reads; the latest binding wins."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        # callbacks go through the same host-side-only boundary as
        # set(): a callback returning a device array would otherwise
        # smuggle a blocking sync into every scrape/snapshot
        return _host_number(fn())


class Histogram:
    """Fixed-bucket histogram child.

    ``edges`` are the ascending upper bounds of the finite buckets; an
    implicit +Inf bucket catches the rest.  ``counts()`` returns
    per-bucket (non-cumulative) counts — export.py cumulates for the
    Prometheus ``le`` convention."""

    __slots__ = ("edges", "_lock", "_counts", "_sum", "_count")

    def __init__(self, edges: Sequence[float]):
        edges = tuple(float(e) for e in edges)
        if not edges or any(b <= a for a, b in zip(edges, edges[1:])):
            raise ValueError(f"histogram edges must be non-empty and "
                             f"strictly ascending, got {edges}")
        self.edges = edges
        self._lock = make_lock("obs.metrics.histogram")
        self._counts = [0] * (len(edges) + 1)
        self._sum = 0.0
        self._count = 0

    def observe(self, value: float) -> None:
        value = _host_number(value)
        i = bisect.bisect_left(self.edges, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"edges": list(self.edges),
                    "counts": list(self._counts),
                    "sum": self._sum, "count": self._count}

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum


class Family:
    """name + type + help + label names -> children keyed by label values."""

    def __init__(self, name: str, mtype: str, help: str,
                 labelnames: tuple = (), edges: Sequence[float] = ()):
        assert mtype in METRIC_TYPES, mtype
        self.name = name
        self.type = mtype
        self.help = help
        self.labelnames = tuple(labelnames)
        self.edges = tuple(edges)
        self._lock = make_lock("obs.metrics.family")
        self._children: dict[tuple, object] = {}
        if not self.labelnames:          # unlabeled: materialize the child
            self.labels()

    def _make_child(self):
        if self.type == "counter":
            return Counter()
        if self.type == "gauge":
            return Gauge()
        return Histogram(self.edges)

    def labels(self, **labelvalues):
        """Child for this label-value combination (created on first use).
        Label names must match the family's declaration exactly."""
        if set(labelvalues) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labelvalues)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(labelvalues[n]) for n in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def items(self):
        """[(label-values tuple, child)] in creation order."""
        with self._lock:
            return list(self._children.items())


class MetricsRegistry:
    """Create-or-get registry of metric families.

    Re-registering an existing name with the same (type, labelnames,
    edges) returns the existing family — module-level call sites and
    repeated component construction in one process stay idempotent; a
    conflicting re-registration raises (two meanings for one exposition
    name is exactly the incompatible-schema mess this subsystem ends).
    """

    def __init__(self):
        self._lock = make_lock("obs.metrics.registry")
        self._families: dict[str, Family] = {}

    def _family(self, name: str, mtype: str, help: str, labels: tuple,
                edges: Sequence[float] = ()) -> Family:
        labels = tuple(labels)
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = Family(name, mtype, help, labels, edges)
                self._families[name] = fam
                return fam
        if (fam.type, fam.labelnames, fam.edges) != (mtype, labels,
                                                     tuple(edges)):
            raise ValueError(
                f"metric {name!r} already registered as {fam.type}"
                f"{fam.labelnames} buckets={fam.edges}; conflicting "
                f"re-registration as {mtype}{labels} buckets={tuple(edges)}")
        return fam

    def counter(self, name: str, help: str = "", labels: tuple = ()):
        """Unlabeled: returns the Counter child; labeled: the Family
        (call ``.labels(...)`` for children)."""
        fam = self._family(name, "counter", help, labels)
        return fam if labels else fam.labels()

    def gauge(self, name: str, help: str = "", labels: tuple = (),
              fn: Optional[Callable[[], float]] = None):
        fam = self._family(name, "gauge", help, labels)
        if labels:
            if fn is not None:
                raise ValueError("callback gauges are unlabeled (bind fn "
                                 "on the child instead)")
            return fam
        child = fam.labels()
        if fn is not None:
            child.bind(fn)
        return child

    def histogram(self, name: str, help: str = "", *,
                  buckets: Sequence[float], labels: tuple = ()):
        fam = self._family(name, "histogram", help, labels, buckets)
        return fam if labels else fam.labels()

    def collect(self) -> list[Family]:
        with self._lock:
            return list(self._families.values())


# ---------------------------------------------------------------------------
# the process-wide default registry
# ---------------------------------------------------------------------------

_DEFAULT = MetricsRegistry()


def registry() -> MetricsRegistry:
    """THE process-wide registry: train loop, data pipeline, fault
    injection and the ``milnce-serve`` CLI all record here, so one
    scrape/snapshot answers "what is this process doing".  Components
    that need isolation (tests, multiple service instances in one
    process) construct a private :class:`MetricsRegistry` instead."""
    return _DEFAULT
