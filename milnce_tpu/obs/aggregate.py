"""Pod-level telemetry aggregation: N per-process views -> one pod view.

A pod run produces one snapshot / event stream PER PROCESS (each host
records only what it saw — the host-side-only invariant means there is
deliberately no cross-host collective in the telemetry path).  This
module merges them after the fact:

- :func:`merge_snapshots` — N ``milnce.obs/v1`` documents (same run,
  distinct processes) -> one ``pod_<kind>`` document: counters summed
  across hosts, gauges reported as min/median/max (a pod gauge has no
  single true value — the spread IS the signal), histograms summed
  bucket-wise, and every shared numeric top-level extra (qps, clips/s,
  ``goodput_fraction``, ``mfu``...) carried as its median with the
  spread alongside — so ``obs_report --check`` gates the pod view with
  the same gate metrics as a single-process artifact.
- :func:`merge_event_streams` — N record streams -> per-process step
  stats + **straggler detection**: cross-host step-span skew (max/min
  of per-process step p50) with the slow hosts named.  One straggler
  chip sets the pace of every collective — the skew number says which
  host to look at before anyone stares at a profile.

Both refuse loudly on mixed ``run_id``s or duplicate
``process_index``es (obs/runctx.py tagging): merging across runs or
double-counting a host produces confident nonsense, which is worse
than an error.  Stdlib-only (obs_report's jax-free gate imports this).
"""

from __future__ import annotations

from milnce_tpu.obs.export import SNAPSHOT_SCHEMA
from milnce_tpu.obs.goodput import split_runs

# default skew ratio above which a host is called a straggler: p50 step
# span > STRAGGLER_RATIO * the fastest host's p50
STRAGGLER_RATIO = 1.25


def _median(vals: list) -> float:
    vals = sorted(vals)
    n = len(vals)
    mid = n // 2
    return (vals[mid] if n % 2 else (vals[mid - 1] + vals[mid]) / 2.0)


def _check_identity(docs: list, what: str) -> tuple:
    """Verify same-run / distinct-process across the inputs; returns
    (run_id, sorted process indices)."""
    if len(docs) < 2:
        raise ValueError(f"pod merge needs >= 2 {what}, got {len(docs)}")
    run_ids = {d.get("run_id") for d in docs}
    if None in run_ids:
        raise ValueError(
            f"{what} without a run_id tag cannot be pod-merged — "
            "regenerate with the current tools (OBSERVABILITY.md "
            "'Run identity')")
    if len(run_ids) > 1:
        raise ValueError(
            f"mixed-run merge refused: {what} carry run_ids "
            f"{sorted(run_ids)} — a pod view spans ONE run")
    pis = [d.get("process_index") for d in docs]
    if None in pis:
        raise ValueError(f"{what} without a process_index tag cannot "
                         "be pod-merged")
    if len(set(pis)) != len(pis):
        raise ValueError(
            f"duplicate process_index in merge inputs ({sorted(pis)}) — "
            "the same host's view counted twice is not a pod view")
    return run_ids.pop(), sorted(pis)


def merge_snapshots(docs: list) -> dict:
    """N same-run, distinct-process ``milnce.obs/v1`` docs -> one
    ``pod_<kind>`` doc (schema unchanged, so obs_report gates it)."""
    for d in docs:
        if d.get("schema") != SNAPSHOT_SCHEMA:
            raise ValueError(f"cannot merge unversioned/foreign doc "
                             f"(schema {d.get('schema')!r})")
    kinds = {d.get("kind") for d in docs}
    if len(kinds) > 1:
        raise ValueError(f"cannot merge snapshots of different kinds "
                         f"{sorted(kinds)}")
    kind = kinds.pop()
    run_id, pis = _check_identity(docs, "snapshots")

    merged_metrics: dict = {}
    names = sorted({n for d in docs for n in (d.get("metrics") or {})})
    for name in names:
        fams = [d["metrics"][name] for d in docs
                if name in (d.get("metrics") or {})]
        mtype = fams[0]["type"]
        if any(f["type"] != mtype for f in fams):
            raise ValueError(f"metric {name!r} has conflicting types "
                             "across processes")
        # children keyed by their label dict (JSON-stable)
        by_label: dict = {}
        for fam in fams:
            for v in fam["values"]:
                key = tuple(sorted(v["labels"].items()))
                by_label.setdefault(key, []).append(v)
        values = []
        for key, vs in sorted(by_label.items()):
            labels = dict(key)
            if mtype == "counter":
                values.append({"labels": labels,
                               "value": sum(v["value"] for v in vs)})
            elif mtype == "gauge":
                nums = [float(v["value"]) for v in vs]
                values.append({"labels": labels,
                               "value": _median(nums),
                               "min": min(nums), "max": max(nums),
                               "processes": len(nums)})
            else:                       # histogram: bucket-wise sum
                edges = vs[0]["edges"]
                if any(v["edges"] != edges for v in vs):
                    raise ValueError(
                        f"histogram {name!r} has mismatched bucket edges "
                        "across processes — not mergeable")
                counts = [sum(col) for col in
                          zip(*(v["counts"] for v in vs))]
                values.append({"labels": labels, "edges": edges,
                               "counts": counts,
                               "sum": sum(v["sum"] for v in vs),
                               "count": sum(v["count"] for v in vs)})
        merged_metrics[name] = {"type": mtype, "help": fams[0]["help"],
                                "values": values}

    out = {"schema": SNAPSHOT_SCHEMA, "kind": f"pod_{kind}",
           "run_id": run_id, "processes": len(docs),
           "process_indices": pis, "metrics": merged_metrics}

    # top-level numeric extras shared by every process: median at the
    # gate key (obs_report reads it exactly like a single-process doc),
    # spread alongside so a pod gate failure is attributable to a host
    reserved = {"schema", "kind", "metrics", "run_id", "process_index"}
    spread: dict = {}
    for key in sorted(set(docs[0]) - reserved):
        vals = [d.get(key) for d in docs]
        if all(isinstance(v, (int, float)) and not isinstance(v, bool)
               for v in vals):
            nums = [float(v) for v in vals]
            out[key] = _median(nums)
            spread[key] = {"min": min(nums), "median": _median(nums),
                           "max": max(nums)}
    if spread:
        out["spread"] = spread
    return out


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return float("nan")
    if len(sorted_vals) == 1:
        return float(sorted_vals[0])
    pos = (q / 100.0) * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return float(sorted_vals[lo] * (1 - frac) + sorted_vals[hi] * frac)


def merge_event_streams(streams: list,
                        straggler_ratio: float = STRAGGLER_RATIO) -> dict:
    """N per-process record streams -> pod step-time view + stragglers.

    Each stream must be single-run (same run across all) and
    single-process; ``straggler_ratio`` is the p50 multiple over the
    fastest host above which a host is flagged."""
    docs = []
    for records in streams:
        runs = split_runs(records)
        if len(runs) != 1:
            raise ValueError(
                f"stream holds {len(runs)} runs "
                f"({sorted(str(k) for k in runs)}) — split on run_id "
                "first (obs_report --run-id)")
        pis = {r.get("process_index") for r in records} - {None}
        docs.append({
            "run_id": next(iter(runs)),
            "process_index": pis.pop() if len(pis) == 1 else None,
            "records": records,
        })
    run_id, pis = _check_identity(docs, "event streams")

    per_process: dict = {}
    for d in docs:
        durs = sorted(float(r.get("dur_ms", 0.0)) for r in d["records"]
                      if r.get("kind") == "span" and r.get("name") == "step")
        per_process[d["process_index"]] = {
            "steps": len(durs),
            "step_ms_p50": round(_percentile(durs, 50), 4),
            "step_ms_p99": round(_percentile(durs, 99), 4),
        }
    p50s = {pi: s["step_ms_p50"] for pi, s in per_process.items()
            if s["steps"] > 0}
    if not p50s:
        raise ValueError("no step spans in any stream — nothing to skew")
    fastest = min(p50s.values())
    skew = (max(p50s.values()) / fastest) if fastest > 0 else float("inf")
    stragglers = sorted(pi for pi, p in p50s.items()
                        if fastest > 0 and p > straggler_ratio * fastest)
    return {"run_id": run_id, "processes": len(docs),
            "process_indices": pis, "per_process": per_process,
            "step_p50_skew": round(skew, 4),
            "straggler_ratio": straggler_ratio,
            "stragglers": stragglers}
