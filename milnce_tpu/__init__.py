"""milnce_tpu — a TPU-native (JAX / XLA / Pallas / pjit) framework for MIL-NCE
video-text representation learning on HowTo100M.

A ground-up redesign (not a port) of the capabilities of
KoDohwan/MIL-NCE_HowTo100M:

- ``milnce_tpu.models``   — S3D-G video tower + word2vec sentence tower (Flax).
- ``milnce_tpu.losses``   — MIL-NCE with mesh-wide negatives, (soft-)DTW losses.
- ``milnce_tpu.ops``      — soft-DTW Pallas TPU kernel + lax.scan golden impl,
                            hard DTW.
- ``milnce_tpu.parallel`` — device-mesh / sharding helpers (ICI+DCN via GSPMD).
- ``milnce_tpu.data``     — tokenizer, MIL candidate sampling, ffmpeg host
                            decode, synthetic sources, sharded prefetch.
- ``milnce_tpu.train``    — jitted train step, LR schedules, Orbax checkpoints.
- ``milnce_tpu.eval``     — retrieval metrics, zero-shot eval, linear probe.
"""

__version__ = "0.1.0"
