"""Temporal-alignment loss family built on soft-DTW — the KoDohwan fork's
delta over upstream (reference loss.py:20-134).

All four variants are re-designed as *pure, batch-size-generic* functions
of sequence embeddings ``(B, n, d)`` — the reference hardcodes world-size-
dependent shapes (160/8/1288 at loss.py:81-88, ``repeat(8, ...)`` at :30)
and reads ``args.rank`` inside the loss (:28-29, 98), which SURVEY.md §1
flags as the design smell to fix.  For mesh-wide batches, all_gather the
sequence embeddings over the data axis first, then call these.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from milnce_tpu.ops.softdtw import SoftDTW, _cosine_sim


def cdtw_batch_loss(video_seq: jax.Array, text_seq: jax.Array,
                    gamma: float = 1e-5, backend: str = "scan",
                    dist: str = "", bandwidth: int = 0) -> jax.Array:
    """Batch-mean contrastive DTW: the reference's CDTW (loss.py:20-32)
    scores only the ``args.rank``-th anchor per step; averaging over every
    anchor is the batch-generic equivalent (identical in expectation)."""
    sdtw = SoftDTW(gamma=gamma, dist_func=dist or "cosine",
                   bandwidth=bandwidth, backend=backend)
    pairs = _all_pairs_sdtw(video_seq, text_seq, sdtw)     # pairs[i,j] =
    pos = jnp.diagonal(pairs)                              #   sdtw(v_j, t_i)
    # reference anchor r scores its VIDEO against every text
    # (loss.py:29-30) -> lse over texts = column r = axis 0
    neg = jax.nn.logsumexp(pairs, axis=0)
    return jnp.mean(pos - neg)


def cdtw_loss(video_seq: jax.Array, text_seq: jax.Array, index: jax.Array | int,
              gamma: float = 1e-5, backend: str = "scan",
              dist: str = "", bandwidth: int = 0) -> jax.Array:
    """Contrastive DTW for one anchor row (reference CDTW, loss.py:20-32):
    soft-DTW(v_i, t_i) vs logsumexp over soft-DTW(v_i, t_j) for all j.

    ``index`` generalizes the reference's ``args.rank`` anchor choice.
    """
    sdtw = SoftDTW(gamma=gamma, dist_func=dist or "cosine",
                   bandwidth=bandwidth, backend=backend)
    b = video_seq.shape[0]
    v_i = jax.lax.dynamic_index_in_dim(video_seq, index, 0, keepdims=True)
    t_i = jax.lax.dynamic_index_in_dim(text_seq, index, 0, keepdims=True)
    pos = sdtw(v_i, t_i)
    neg = sdtw(jnp.broadcast_to(v_i, (b,) + v_i.shape[1:]), text_seq)
    return pos - jax.nn.logsumexp(neg, axis=0)


def sdtw_cidm_loss(video_seq: jax.Array, text_seq: jax.Array,
                   start: jax.Array, gamma: float = 0.1, sigma: float = 10.0,
                   lam: float = 1.0, backend: str = "scan",
                   dist: str = "", bandwidth: int = 0,
                   exact_broadcast: bool = False) -> jax.Array:
    """Soft-DTW + Clip-Interval-Distance-Metric regularizers (reference
    SDTW_CIDM, loss.py:34-68).

    Clips whose start times differ by more than ``sigma`` are pushed apart
    (hinge on cosine distance), near clips are pulled together, with
    interval-distance-dependent weights; plus the soft-DTW video-text
    alignment term.

    The reference's attract/repel terms only broadcast when the clip count
    equals the frame count (its (B,B) interval mask multiplies a (B,n,n)
    frame-distance tensor, loss.py:59-66) and then mix sample with frame
    indices; we define the clip-pair distance cleanly as the cosine
    distance between frame-mean embeddings.

    ``exact_broadcast=True`` reproduces the reference computation
    bit-for-bit at the ONLY shape where it is defined (B == n): torch
    right-aligns the (B,B) mask to (1,B,B), so
    ``I_x[s] = sum_{i,j} mask(i,j)-weighted frame-distance D_x[s,i,j]``
    — clip-pair weights applied to FRAME-pair distances.  Kept for
    numerical parity audits against the reference
    (tests/test_dtw_reference_golden.py); training uses the cleaned
    form, which is shape-generic.
    """
    sdtw = SoftDTW(gamma=gamma, dist_func=dist or "cosine",
                   bandwidth=bandwidth, backend=backend)
    interval = jnp.abs(start[:, None] - start[None, :])      # (B, B)
    far = jnp.where(interval > sigma, 1.0, 0.0)
    w_ = interval + 1.0
    w = 1.0 / w_
    if exact_broadcast:
        b, n, m = video_seq.shape[0], video_seq.shape[1], text_seq.shape[1]
        if not (b == n == m):
            raise ValueError(
                f"exact_broadcast reproduces the reference's (B,B)x(B,n,n) "
                f"broadcast, defined only when B == n (got B={b}, "
                f"video n={n}, text m={m}); use the default cleaned form "
                "for generic shapes")
        # per-sample frame-pair cosine distances (loss.py:40-47): (B, n, n)
        d_x = 1.0 - _cosine_sim(video_seq, video_seq, 1e-8)
        d_y = 1.0 - _cosine_sim(text_seq, text_seq, 1e-8)
        weight = lambda d: (far[None] * w_[None] * jax.nn.relu(lam - d)
                            + (1 - far[None]) * w[None] * d)  # noqa: E731
        i_x = weight(d_x).sum(axis=(1, 2))
        i_y = weight(d_y).sum(axis=(1, 2))
    else:
        v_mean = jnp.mean(video_seq, axis=1)
        t_mean = jnp.mean(text_seq, axis=1)
        d_x = 1.0 - _cosine_sim(v_mean[None], v_mean[None], 1e-8)[0]  # (B, B)
        d_y = 1.0 - _cosine_sim(t_mean[None], t_mean[None], 1e-8)[0]
        i_x = (far * w_ * jax.nn.relu(lam - d_x)
               + (1 - far) * w * d_x).sum(axis=1)
        i_y = (far * w_ * jax.nn.relu(lam - d_y)
               + (1 - far) * w * d_y).sum(axis=1)
    dtw = sdtw(video_seq, text_seq)
    return jnp.mean(i_x + i_y + dtw)


def sdtw_negative_loss(video_seq: jax.Array, text_seq: jax.Array,
                       gamma: float = 0.1, backend: str = "scan",
                       dist: str = "", bandwidth: int = 0) -> jax.Array:
    """Soft-DTW positives + frame-level InfoNCE-style negatives (reference
    SDTW_negative, loss.py:70-91), batch-generic.

    The reference's 160/8/1288 chunk-and-mask dance (loss.py:81-88) zeroes
    the within-clip n x n blocks of the (B*n, B*n) video-frame/text-frame
    dot matrix; we mask the block diagonal directly.
    """
    sdtw = SoftDTW(gamma=gamma, dist_func=dist or "cosine",
                   bandwidth=bandwidth, backend=backend)
    b, n, d = video_seq.shape
    m = text_seq.shape[1]
    pos = sdtw(video_seq, text_seq)                          # (B,)
    pairwise = jnp.matmul(video_seq.reshape(b * n, d),
                          text_seq.reshape(b * m, d).T)      # (B*n, B*m)
    clip_row = jnp.repeat(jnp.arange(b), n)
    clip_col = jnp.repeat(jnp.arange(b), m)
    same_clip = clip_row[:, None] == clip_col[None, :]
    pairwise = jnp.where(same_clip, 0.0, pairwise)           # zero, not -inf:
    # parity with loss.py:84 (zeros still contribute exp(0)=1 to the sum)
    negative = jnp.exp(pairwise).sum(axis=1).reshape(b, n).sum(axis=1)  # graftlint: disable=GL017(reference parity: loss.py:84 exponentiates raw frame dots, and cosine-normalized frames bound them in [-1,1] — exp stays under e)
    return jnp.mean(pos + negative / jnp.maximum(b - 1, 1))


def _all_pairs_sdtw(a: jax.Array, b_seq: jax.Array, sdtw: SoftDTW) -> jax.Array:
    """(B, n, d) x (B, m, d) -> (B, B) soft-DTW of every (row, col) pair
    via the reference's expand/reshape trick (loss.py:103-106)."""
    b = a.shape[0]
    rows = jnp.broadcast_to(a[None], (b,) + a.shape).reshape((-1,) + a.shape[1:])
    cols = jnp.broadcast_to(b_seq[:, None], (b, b) + b_seq.shape[1:])
    cols = cols.reshape((-1,) + b_seq.shape[1:])
    return sdtw(rows, cols).reshape(b, b)


def _all_pairs_sdtw_lse(a: jax.Array, b_seq: jax.Array, sdtw: SoftDTW,
                        pair_chunk: int) -> jax.Array:
    """``logsumexp_j(-sdtw(a_j, b_i))`` per row i of ``b_seq`` WITHOUT
    the B x B pair batch: the same streaming-logsumexp treatment the
    chunked MIL-NCE applies to its similarity cube
    (losses/milnce_chunked.py), pure-jax only.

    ``_all_pairs_sdtw`` broadcasts both sequences to a B^2 pair batch,
    so its DP runs (and AD saves) B^2 tables at once — the worst small
    offender of the loss family.  Here chunks of ``pair_chunk`` ``a``
    rows are scored per ``lax.scan`` step (a (B * pair_chunk) pair
    batch) into per-chunk partial logsumexps, combined at the end; the
    body runs under ``jax.checkpoint`` so the backward RECOMPUTES each
    chunk's DP instead of keeping B^2 saved tables.  Peak pair-batch
    memory drops from O(B^2) to O(B * pair_chunk); parity (value and
    grad) vs the dense form is pinned in tests/test_dtw_losses.py."""
    from milnce_tpu.ops.softdtw import BIG

    b = a.shape[0]
    nc = -(-b // pair_chunk)
    pad = nc * pair_chunk - b
    a_pad = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
    a_ch = a_pad.reshape((nc, pair_chunk) + a.shape[1:])
    starts = jnp.arange(nc, dtype=jnp.int32) * pair_chunk

    def body(carry, xs):
        a_c, start = xs
        rows = jnp.broadcast_to(a_c[None], (b,) + a_c.shape)
        rows = rows.reshape((-1,) + a_c.shape[1:])
        cols = jnp.broadcast_to(b_seq[:, None],
                                (b, pair_chunk) + b_seq.shape[1:])
        cols = cols.reshape((-1,) + b_seq.shape[1:])
        vals = -sdtw(rows, cols).reshape(b, pair_chunk)
        ok = (start + jnp.arange(pair_chunk)) < b      # pad rows -> -BIG
        vals = jnp.where(ok[None, :], vals, -BIG)
        return carry, jax.nn.logsumexp(vals, axis=1)

    _, parts = lax.scan(jax.checkpoint(body), None, (a_ch, starts))
    return jax.nn.logsumexp(parts, axis=0)             # (nc, B) -> (B,)


def sdtw_3_loss(video_seq: jax.Array, text_seq: jax.Array, gamma: float = 0.1,
                backend: str = "scan", dist: str = "",
                bandwidth: int = 0, pair_chunk: int = 0
                ) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Three NCE-over-soft-DTW terms — video<->video, video<->text,
    text<->text (reference SDTW_3, loss.py:93-134), negative-dot distance.

    ``pair_chunk > 0`` streams each term's negative logsumexp over
    chunks of that many anchor rows (:func:`_all_pairs_sdtw_lse`)
    instead of materializing the full B x B pair batch; 0 keeps the
    dense all-pairs form (and the pinned ``train_step_sdtw3`` trace)."""
    sdtw = SoftDTW(gamma=gamma, dist_func=dist or "negative_dot",
                   bandwidth=bandwidth, backend=backend)

    def nce(x, y):
        pos = -sdtw(x, y)
        if 0 < pair_chunk < x.shape[0]:
            neg = _all_pairs_sdtw_lse(x, y, sdtw, pair_chunk)
        else:
            neg = jax.nn.logsumexp(-_all_pairs_sdtw(x, y, sdtw), axis=1)
        return jnp.mean(neg - pos)

    return (nce(video_seq, video_seq), nce(video_seq, text_seq),
            nce(text_seq, text_seq))
