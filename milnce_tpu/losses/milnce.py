"""MIL-NCE loss with mesh-wide negatives.

Semantics of the reference (loss.py:6-18 + the AllGather wrapping at
main_distributed.py:234-236, utils.py:8-24), re-designed as a *pure,
mesh-aware function*:

- similarity cube ``x[i, j, k] = v_i . t_{j,k}`` over the GLOBAL batch
  (B video rows, B*K candidate text rows);
- numerator_i   = logsumexp_k x[i, i, k]          (positive candidate bag);
- denominator_i = logsumexp over row i AND column i of the cube (both
  retrieval directions — the reference's ``cat((x, x^T), dim=1)``), which
  counts the positives twice, exactly as the reference does;
- loss = mean_i (denominator_i - numerator_i).

Distributed form: instead of materializing the (Bg, Bg*K) matrix on every
chip after an NCCL all-gather, each shard gathers embeddings over the mesh
axis (one XLA collective over ICI) but scores only its LOCAL rows and
columns — per-chip memory O(B_local * B_global * K) — then psum-reduces.
This is mathematically identical to the reference's replicated loss.

Memory bound at the baseline scale (Bg=8192, K=5, 64 chips -> B_local=128):
two (B_local, Bg, K) f32 cubes = 2 x 128*8192*5*4 B ~ 42 MB per chip
(the replicated reference form would need ~1.3 GB per GPU for x plus its
transpose concat, loss.py:16).  The denominator combines two separate
logsumexp reductions with logaddexp, so no (B, 2*Bg*K) concat is ever
materialized; tests/test_milnce.py pins the compiled per-chip temp size
at Bg=8192.

The cubes are NOT free, though — an earlier revision of this docstring
called the gather+local-score form "already HBM-trivial", which the
PR 8 static planner disproved once AD residuals are counted: reverse
mode saves both cubes (and their softmax intermediates) for the
backward, so the loss side really holds ~4 cubes plus the lse-transpose
scatter.  Measured by the GL013 memplan pins (analysis/memplan.py, the
``milnce_loss_dense`` / ``milnce_loss_chunked`` entries at B_local=64,
Bg=512, K=5, D=16): this dense form peaks at 2,863,940 B/chip with the
(B_local, Bg*K) cube ops as the named top contributors, vs 703,276
B/chip for the chunked stream — O(B_local * Bg * K) vs
O(B_local * chunk), a gap that grows linearly in Bg/chunk.  At the
Bg=8192 what-if (``mem_plan --what-if --batch 8192 --mesh data=64``),
the loss side (gathered-text transpose + cube matmul) becomes the
step's top per-chip contributor as soon as the video/text towers stop
dominating (grad-accum recipe, low-res curriculum stages, larger K) —
dense 1.046 GiB/chip vs chunked 0.791 GiB/chip at the 8f@64 K=32 point
(BENCH_MILNCE_LOSS.md has the full table).

When the cubes matter, use ``losses/milnce_chunked.py``
(``loss.milnce_impl = chunked | auto``): identical semantics and
collective structure, with the cube streamed through running
logsumexp accumulators and recomputed chunk-by-chunk in the backward
(scan form, plus a fused Pallas kernel in ops/milnce_pallas.py).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def milnce_loss(video_embd: jax.Array, text_embd: jax.Array,
                axis_name: Optional[str] = None) -> jax.Array:
    """MIL-NCE loss.

    Args:
      video_embd: (B, D) local video embeddings.
      text_embd: (B*K, D) local candidate text embeddings, sample-major
        (sample 0's K candidates first, like the flattened (B, K, W) batch).
      axis_name: mesh axis to gather negatives over; None = single shard.

    Returns: scalar loss (identical on every shard when distributed).
    """
    b = video_embd.shape[0]
    assert text_embd.shape[0] % b == 0, (video_embd.shape, text_embd.shape)

    if axis_name is None:
        v_all, t_all = video_embd, text_embd
        offset = 0
        b_global = b
    else:
        v_all = lax.all_gather(video_embd, axis_name, axis=0, tiled=True)
        t_all = lax.all_gather(text_embd, axis_name, axis=0, tiled=True)
        offset = lax.axis_index(axis_name) * b
        b_global = v_all.shape[0]

    # Local rows of the cube: (B, Bg, K)
    rows = jnp.matmul(video_embd, t_all.T).reshape(b, b_global, -1)
    # Local columns of the cube: cols[j, i, k] = x[j, offset+i, k] -> (Bg, B, K)
    cols = jnp.matmul(v_all, text_embd.T).reshape(b_global, b, -1)

    diag = rows[jnp.arange(b), offset + jnp.arange(b), :]          # (B, K)
    numerator = jax.nn.logsumexp(diag, axis=1)
    # lse over row i AND column i of the cube.  Two separate reductions
    # combined with logaddexp == lse of the concatenation (the reference's
    # ``cat((x, x^T), dim=1)``), without materializing a (B, 2*Bg*K) copy —
    # peak per-chip logits memory stays at the two (B_local, Bg, K) cubes.
    denominator = jnp.logaddexp(
        jax.nn.logsumexp(rows.reshape(b, -1), axis=1),
        jax.nn.logsumexp(jnp.swapaxes(cols, 0, 1).reshape(b, -1), axis=1))

    local_sum = jnp.sum(denominator - numerator)
    if axis_name is not None:
        # Value: the mesh-global sum.  Gradient: identity to the LOCAL
        # term only — jax versions disagree on the psum transpose when
        # grad is taken inside the shard_map body (old jax overcounts
        # the replicated cotangent by the axis size), so the reduction
        # goes through the version-aware compat helper.  Both versions
        # then agree with the unsharded reference once the train step
        # psums the param grads
        # (tests/test_milnce.py::test_sharded_gradients_match_unsharded).
        from milnce_tpu.parallel.compat import psum_with_identity_grad

        local_sum = psum_with_identity_grad(local_sum, axis_name)
    return local_sum / b_global
