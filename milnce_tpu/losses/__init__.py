from milnce_tpu.losses.milnce import milnce_loss  # noqa: F401
from milnce_tpu.losses.milnce_chunked import (  # noqa: F401
    build_milnce_loss, milnce_loss_chunked)
from milnce_tpu.losses.dtw_losses import (  # noqa: F401
    cdtw_loss, sdtw_3_loss, sdtw_cidm_loss, sdtw_negative_loss)
