"""Memory-efficient MIL-NCE: chunked streaming loss (never materialize
the global similarity cube).

``milnce_loss`` (losses/milnce.py) scores each shard's local rows and
columns of the global similarity cube as two dense ``(B_local, Bg, K)``
logits cubes.  At the baseline operating point (Bg=8192, K=5) the cubes
plus their AD-saved twins are the dominant *loss-side* term the PR 8
static planner attributes to the step — and they are pure intermediates:
the loss only ever needs per-row logsumexps of them.

This module computes those logsumexps **without the cubes** — the
memory-efficient-contrastive / FlashAttention move applied to MIL-NCE:

- the gathered negatives ``(Bg, D)`` / ``(Bg*K, D)`` are split into
  chunks of ``chunk`` global samples; a ``lax.scan`` streams the chunks,
  keeping only running ``(B_local,)`` / ``(B_local*K,)`` online-softmax
  accumulators (max + rescaled sum, numerically identical to one global
  logsumexp up to summation order);
- a ``jax.custom_vjp`` recomputes each chunk's logits in the backward
  (softmax weights from the saved row logsumexps), so AD saves only the
  gathered embeddings — which are live anyway — and nothing
  O(B_local * Bg * K);
- semantics are IDENTICAL to ``milnce_loss``: positive-bag logsumexp
  numerator, row+column denominator with double-counted positives, the
  same 2 ``all_gather`` collectives (whose AD transposes stay the same 2
  reduce_scatters), and the same ``psum_with_identity_grad`` reduction.

Backend gate (the soft-DTW playbook, ops/softdtw.py ``SoftDTW``):
``backend='scan'`` is this module's pure-jax stream; ``'pallas'`` is the
fused TPU kernel (ops/milnce_pallas.py — chunk matmul + max/rescale +
accumulate in VMEM, its own custom VJP); ``'auto'`` picks per shape via
``milnce_pallas.prefers_pallas`` (trace-stable: the rule is a pure
function of static shapes, pinned no-recompile by the
``milnce_chunked_dispatch`` trace-invariant entry).  Impl selection
(dense cube vs this stream) rides config: ``loss.milnce_impl``,
``loss.milnce_chunk``, ``loss.milnce_backend`` -> :func:`build_milnce_loss`
-> every train step (plain / guarded / grad-cache / 2-D FSDP).

Measured peaks and chunk-size guidance: PERF.md "Memory-efficient loss",
BENCH_MILNCE_LOSS.md; per-chip pins: the ``milnce_loss_dense`` /
``milnce_loss_chunked`` GL013 memplan entries (analysis/memplan.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from milnce_tpu.losses.milnce import milnce_loss
from milnce_tpu.ops.softdtw import BIG

MILNCE_IMPLS = ("dense", "chunked", "auto")
MILNCE_BACKENDS = ("auto", "scan", "pallas")

# impl='auto' switches to the stream once the dense cubes STOP being
# cheap: two (B_local, Bg, K) f32 cubes plus their AD-saved twins beyond
# this budget.  64 MiB keeps dense (fewer matmul passes — the stream's
# backward recompute costs ~2 extra chunk matmuls) for every small-mesh
# run while the Bg=8192 recipe (4 cubes ~ 84 MiB at B_local=128, K=5)
# goes chunked.
DENSE_CUBE_BUDGET_BYTES = 64 * 2 ** 20

# chunk=0 targets this many row-logits elements per streamed block
# (B_local * chunk * K f32 ~ 2 MiB): big enough that the chunk matmul is
# MXU-shaped, small enough that a block is VMEM-resident for the fused
# kernel.
_CHUNK_TARGET_ELEMS = 512 * 1024


def milnce_default_chunk(b_local: int, k: int, b_global: int) -> int:
    """The chunk=0 rule: global samples per streamed block, sublane-
    aligned (multiple of 8) and never larger than the gathered batch."""
    if b_global <= 8:
        return b_global
    c = max(8, min(b_global, _CHUNK_TARGET_ELEMS // max(1, b_local * k)))
    return max(8, c // 8 * 8)


def prefers_chunked(b_local: int, b_global: int, k: int) -> bool:
    """impl='auto' shape rule: stream once the dense cubes + AD twins
    exceed :data:`DENSE_CUBE_BUDGET_BYTES`."""
    return 4 * b_local * b_global * k * 4 > DENSE_CUBE_BUDGET_BYTES


def _axis_prod(axis_name) -> int:
    """Static mesh extent of ``axis_name`` (None = 1, tuple = product) —
    legal inside the shard_map body, where mesh axis sizes are static."""
    from milnce_tpu.parallel.compat import axis_size

    if axis_name is None:
        return 1
    names = axis_name if isinstance(axis_name, (tuple, list)) else (axis_name,)
    n = 1
    for name in names:
        n *= int(axis_size(name))
    return n


def _chunked_negatives(v_all: jax.Array, t_all: jax.Array, k: int,
                       chunk: int):
    """The scan stream's chunk layout, shared by forward AND backward
    (one copy — the two passes must agree on it or gradients silently
    skew): zero-pad the gathered negatives up to a whole number of
    chunks (the uneven-last-chunk case) and reshape into per-chunk
    blocks with their start offsets.  Padding columns are masked to
    ``-BIG`` in every logits block, so they contribute exp(-BIG - m) = 0
    to the running sums and exactly 0 to every chunk-recomputed
    gradient.  Stays in the INPUT dtype — upcasting the gathered arrays
    here would materialize O(Bg*D) f32 copies, exactly the class of
    buffer this loss exists to avoid; each block promotes to f32 inside
    its matmul instead."""
    bg, d = v_all.shape
    nc = -(-bg // chunk)
    pad = nc * chunk - bg
    if pad:
        v_all = jnp.pad(v_all, ((0, pad), (0, 0)))
        t_all = jnp.pad(t_all, ((0, pad * k), (0, 0)))
    return (v_all.reshape(nc, chunk, d), t_all.reshape(nc, chunk * k, d),
            jnp.arange(nc, dtype=jnp.int32) * chunk, nc)


# --------------------------------------------------------------- scan path
def _scan_forward(v, t, v_all, t_all, chunk):
    """Streaming forward: (row_lse (B,), col_lse_flat (B*K,)), f32.

    Accumulators are the online-softmax pair (running max m, rescaled sum
    s): one new chunk of logits x updates ``m' = max(m, max x)``,
    ``s' = s * exp(m - m') + sum exp(x - m')`` — associative, so the
    result equals the one-shot logsumexp up to summation order."""
    b, d = v.shape
    bk = t.shape[0]
    k = bk // b
    bg = v_all.shape[0]
    f32 = jnp.float32
    vf, tf = v.astype(f32), t.astype(f32)
    v_ch, t_ch, starts, _nc = _chunked_negatives(v_all, t_all, k, chunk)

    def body(carry, xs):
        rm, rs, cm, cs = carry
        v_c, t_c, start = xs
        # rows: local videos vs this chunk's candidate texts
        x = jnp.matmul(vf, t_c.T.astype(f32))            # (B, chunk*K)
        ok = (start * k + jnp.arange(chunk * k)) < bg * k
        x = jnp.where(ok[None, :], x, -BIG)
        m = jnp.maximum(rm, jnp.max(x, axis=1))
        rs = rs * jnp.exp(rm - m) + jnp.sum(jnp.exp(x - m[:, None]), axis=1)
        rm = m
        # cols: local candidate texts vs this chunk's videos
        y = jnp.matmul(tf, v_c.T.astype(f32))            # (B*K, chunk)
        ok = (start + jnp.arange(chunk)) < bg
        y = jnp.where(ok[None, :], y, -BIG)
        m = jnp.maximum(cm, jnp.max(y, axis=1))
        cs = cs * jnp.exp(cm - m) + jnp.sum(jnp.exp(y - m[:, None]), axis=1)
        cm = m
        return (rm, rs, cm, cs), None

    init = (jnp.full((b,), -jnp.inf, f32), jnp.zeros((b,), dtype=f32),
            jnp.full((bk,), -jnp.inf, f32), jnp.zeros((bk,), dtype=f32))
    (rm, rs, cm, cs), _ = lax.scan(body, init, (v_ch, t_ch, starts))
    return rm + jnp.log(rs), cm + jnp.log(cs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _stream_lse_scan(v, t, v_all, t_all, chunk):
    """(row_lse (B,), col_lse_flat (B*K,)): logsumexp of each local row /
    column of the similarity cube, streamed over negative chunks."""
    out, _ = _stream_lse_scan_fwd(v, t, v_all, t_all, chunk)
    return out


def _stream_lse_scan_fwd(v, t, v_all, t_all, chunk):
    row_lse, col_lse = _scan_forward(v, t, v_all, t_all, chunk)
    # residuals: embeddings (live anyway) + the (B,)/(B*K,) logsumexps —
    # NOTHING sized O(Bg) beyond the inputs themselves
    return (row_lse, col_lse), (v, t, v_all, t_all, row_lse, col_lse)


def _stream_lse_scan_bwd(chunk, res, cots):
    """Recompute each chunk's logits; softmax weights w = exp(x - lse)
    turn the lse cotangents into embedding grads, chunk by chunk."""
    v, t, v_all, t_all, row_lse, col_lse = res
    g_row, g_col = cots
    b, d = v.shape
    bk = t.shape[0]
    k = bk // b
    bg = v_all.shape[0]
    f32 = jnp.float32
    vf, tf = v.astype(f32), t.astype(f32)
    gr = g_row.astype(f32)[:, None]
    gc = g_col.astype(f32)[:, None]
    rls = row_lse[:, None]
    cls = col_lse[:, None]
    v_ch, t_ch, starts, nc = _chunked_negatives(v_all, t_all, k, chunk)

    def body(carry, xs):
        g_v, g_t = carry
        v_c, t_c, start = xs
        t_cf = t_c.astype(f32)
        v_cf = v_c.astype(f32)
        x = jnp.matmul(vf, t_cf.T)                       # (B, chunk*K)
        ok = (start * k + jnp.arange(chunk * k)) < bg * k
        w = jnp.where(ok[None, :], jnp.exp(x - rls), 0.0) * gr
        g_v = g_v + jnp.matmul(w, t_cf)
        g_tc = jnp.matmul(w.T, vf)                       # (chunk*K, D)
        y = jnp.matmul(tf, v_cf.T)                       # (B*K, chunk)
        ok = (start + jnp.arange(chunk)) < bg
        u = jnp.where(ok[None, :], jnp.exp(y - cls), 0.0) * gc
        g_t = g_t + jnp.matmul(u, v_cf)
        g_vc = jnp.matmul(u.T, tf)                       # (chunk, D)
        # per-chunk downcast: the stacked grads land in the input dtype,
        # never as an O(Bg*K*D) f32 twin
        return (g_v, g_t), (g_vc.astype(v_all.dtype),
                            g_tc.astype(t_all.dtype))

    init = (jnp.zeros((b, d), dtype=f32), jnp.zeros((bk, d), dtype=f32))
    (g_v, g_t), (g_va_ch, g_ta_ch) = lax.scan(
        body, init, (v_ch, t_ch, starts))
    g_va = g_va_ch.reshape(nc * chunk, d)[:bg]
    g_ta = g_ta_ch.reshape(nc * chunk * k, d)[:bg * k]
    return (g_v.astype(v.dtype), g_t.astype(t.dtype), g_va, g_ta)


_stream_lse_scan.defvjp(_stream_lse_scan_fwd, _stream_lse_scan_bwd)


# ------------------------------------------------------------- public loss
def milnce_loss_chunked(video_embd: jax.Array, text_embd: jax.Array,
                        axis_name=None, chunk: int = 0,
                        backend: str = "auto") -> jax.Array:
    """MIL-NCE loss, identical semantics to :func:`milnce_loss`, with
    the similarity cube streamed instead of materialized.

    Args:
      video_embd: (B, D) local video embeddings.
      text_embd: (B*K, D) local candidate text embeddings, sample-major.
      axis_name: mesh axis (or axis tuple) to gather negatives over;
        None = single shard.
      chunk: global samples per streamed block (0 = the
        :func:`milnce_default_chunk` rule).  Bg % chunk != 0 is handled
        by a masked pad chunk.
      backend: 'scan' | 'pallas' | 'auto' (shape rule:
        ops/milnce_pallas.prefers_pallas).

    Returns: scalar loss (identical on every shard when distributed).
    """
    b, d = video_embd.shape
    bk = text_embd.shape[0]
    assert bk % b == 0, (video_embd.shape, text_embd.shape)
    k = bk // b
    if backend not in MILNCE_BACKENDS:
        raise ValueError(f"unknown milnce backend {backend!r} (expected "
                         f"one of {', '.join(MILNCE_BACKENDS)})")

    if axis_name is None:
        v_all, t_all = video_embd, text_embd
    else:
        v_all = lax.all_gather(video_embd, axis_name, axis=0, tiled=True)
        t_all = lax.all_gather(text_embd, axis_name, axis=0, tiled=True)
    b_global = v_all.shape[0]

    if chunk <= 0:
        chunk = milnce_default_chunk(b, k, b_global)
    chunk = min(int(chunk), b_global)
    if backend == "auto":
        from milnce_tpu.ops.milnce_pallas import prefers_pallas

        backend = "pallas" if prefers_pallas(b, b_global, k, d,
                                             chunk) else "scan"
    if backend == "pallas":
        from milnce_tpu.ops.milnce_pallas import milnce_stream_pallas

        row_lse, col_flat = milnce_stream_pallas(video_embd, text_embd,
                                                 v_all, t_all, chunk)
    else:
        row_lse, col_flat = _stream_lse_scan(video_embd, text_embd,
                                             v_all, t_all, chunk)

    # positive bag: diag[i, k] = v_i . t_{i,k} — local by construction
    # (the dense path reads the same values out of its rows cube at the
    # shard offset; the all_gather transpose routes that cotangent back
    # to the local shard, so taking it directly is gradient-identical)
    diag = jnp.einsum("bd,bkd->bk", video_embd,
                      text_embd.reshape(b, k, d)).astype(jnp.float32)
    numerator = jax.nn.logsumexp(diag, axis=1)
    # column denominator half: lse over (Bg, K) = lse over K of the
    # per-(i,k) streamed lse
    col_lse = jax.nn.logsumexp(col_flat.reshape(b, k), axis=1)
    denominator = jnp.logaddexp(row_lse, col_lse)
    local_sum = jnp.sum(denominator - numerator)
    if axis_name is not None:
        from milnce_tpu.parallel.compat import psum_with_identity_grad

        local_sum = psum_with_identity_grad(local_sum, axis_name)
    return local_sum / b_global


def build_milnce_loss(loss_cfg):
    """LossConfig -> ``fn(video_embd, text_embd, axis_name)``.

    The train-step factories (train/step.py) call this ONCE at build
    time: ``milnce_impl='dense'`` (and loss_cfg=None) keeps the traced
    program byte-identical to the pre-chunked step — its pinned
    collective counts and memory plans never move — while 'chunked' /
    'auto' route through :func:`milnce_loss_chunked`.  Bad knob values
    fail here, at build time, not after a full model trace."""
    impl = getattr(loss_cfg, "milnce_impl", "dense") or "dense"
    chunk = int(getattr(loss_cfg, "milnce_chunk", 0) or 0)
    backend = getattr(loss_cfg, "milnce_backend", "auto") or "auto"
    if impl not in MILNCE_IMPLS:
        raise ValueError(f"unknown loss.milnce_impl {impl!r} (expected "
                         f"one of {', '.join(MILNCE_IMPLS)})")
    if backend not in MILNCE_BACKENDS:
        raise ValueError(f"unknown loss.milnce_backend {backend!r} "
                         f"(expected one of {', '.join(MILNCE_BACKENDS)})")

    def loss_fn(video_embd, text_embd, axis_name: Optional[str] = None):
        use = impl
        if use == "auto":
            b = video_embd.shape[0]
            k = text_embd.shape[0] // b
            use = ("chunked" if prefers_chunked(b, b * _axis_prod(axis_name),
                                                k) else "dense")
        if use == "dense":
            return milnce_loss(video_embd, text_embd, axis_name=axis_name)
        return milnce_loss_chunked(video_embd, text_embd,
                                   axis_name=axis_name, chunk=chunk,
                                   backend=backend)

    return loss_fn
