"""Straggler-adaptive scheduling: the skew metric, made a live policy.

``obs_report --merge`` already computes cross-host step-time skew after
the fact (obs/aggregate.py: per-process step-span p50s, max/min skew,
slow hosts named).  This module feeds the SAME math into a live policy
object: per-process window step times stream in (each host's display
cadence feeds its own; merged event streams feed every host's at once),
and a host whose p50 stays above ``ratio`` x the fastest host's for
``window`` consecutive evaluations is **demoted** — a ``straggler``
event per flagged evaluation, a ``straggler.demote`` event at the
threshold, and the demoted set lands in the goodput ledger snapshot so
the badput is attributable to a named host.  Behind
``train.straggler_resize``, a demotion also emits a
``straggler.resize_recommended`` event: drain (elastic/drain.py) and
resume at a capacity that excludes the slow host — the serving twin is
the replica pool's DEGRADED state, but training can't route around a
host mid-collective, so the recommendation is drain-and-resize, never
a live eviction.

One straggler chip sets the pace of every collective; the policy names
the host to act on before anyone stares at a profile.  Host-side only
and stdlib+repo-pure: evaluating costs zero device syncs.
"""

from __future__ import annotations

from milnce_tpu.obs.aggregate import STRAGGLER_RATIO, _percentile


class StragglerPolicy:
    """Live straggler detection over per-process step-time observations.

    ``observe(process_index, step_ms, step=)`` records one window's
    mean step wall time for one host; ``evaluate(step=)`` compares the
    per-host p50s over the observation history (the aggregate module's
    percentile, the aggregate module's ratio rule) and advances the
    per-host flag streaks.  With fewer than two hosts reporting there
    is nothing to compare and evaluation is a no-op — skew is a
    cross-host property."""

    def __init__(self, ratio: float = STRAGGLER_RATIO, window: int = 3,
                 recommend_resize: bool = False, recorder=None,
                 history: int = 32):
        if ratio <= 1.0:
            raise ValueError(f"straggler ratio must be > 1.0, got {ratio}")
        if window < 1:
            raise ValueError(f"straggler window must be >= 1, got {window}")
        self.ratio = float(ratio)
        self.window = int(window)
        self.recommend_resize = bool(recommend_resize)
        self._rec = recorder
        self._history = int(history)
        self._obs: dict = {}        # process_index -> [step_ms, ...]
        self._streaks: dict = {}    # process_index -> consecutive flags
        self.demoted: list = []     # process indices, demotion order
        self.last_skew: float = 1.0

    # -- feeds ----------------------------------------------------------
    def observe(self, process_index: int, step_ms: float,
                step: int = 0) -> None:
        """One window observation for one host, then evaluate."""
        buf = self._obs.setdefault(int(process_index), [])
        buf.append(float(step_ms))
        del buf[:-self._history]
        self.evaluate(step=step)

    def feed_merged(self, merged: dict, step: int = 0) -> None:
        """Feed a pod view from ``obs_report --merge`` /
        ``aggregate.merge_event_streams``: every host's step p50 in one
        call — the post-hoc twin of per-display ``observe`` feeds."""
        for pi, stats in (merged.get("per_process") or {}).items():
            if stats.get("steps"):
                buf = self._obs.setdefault(int(pi), [])
                buf.append(float(stats["step_ms_p50"]))
                del buf[:-self._history]
        self.evaluate(step=step)

    # -- the verdict ----------------------------------------------------
    def _p50s(self) -> dict:
        return {pi: _percentile(sorted(buf), 50)
                for pi, buf in self._obs.items() if buf}

    def evaluate(self, step: int = 0) -> list:
        """Advance streaks; returns the processes flagged THIS round."""
        p50s = self._p50s()
        if len(p50s) < 2:
            return []
        fastest = min(p50s.values())
        if fastest <= 0:
            return []
        self.last_skew = max(p50s.values()) / fastest
        flagged = sorted(pi for pi, p in p50s.items()
                         if p > self.ratio * fastest)
        for pi in list(self._streaks):
            if pi not in flagged:
                self._streaks[pi] = 0
        for pi in flagged:
            self._streaks[pi] = self._streaks.get(pi, 0) + 1
            if self._rec is not None:
                self._rec.event("straggler", process=pi, step=int(step),
                                p50_ms=round(p50s[pi], 4),
                                skew=round(p50s[pi] / fastest, 4),
                                streak=self._streaks[pi])
            if (self._streaks[pi] >= self.window
                    and pi not in self.demoted):
                self.demoted.append(pi)
                if self._rec is not None:
                    self._rec.event("straggler.demote", process=pi,
                                    step=int(step),
                                    skew=round(p50s[pi] / fastest, 4))
                if self.recommend_resize and self._rec is not None:
                    self._rec.event("straggler.resize_recommended",
                                    process=pi, step=int(step),
                                    reason=(f"host {pi} p50 > "
                                            f"{self.ratio}x fastest for "
                                            f"{self.window} windows — "
                                            "drain and resume without it"))
        return flagged

    def ledger_extra(self) -> dict:
        """Keys for the GOODPUT snapshot: the demotion verdict rides the
        ledger so pod badput is attributable to named hosts."""
        if not self._obs:
            return {}
        return {"straggler_skew": round(self.last_skew, 4),
                "demoted_hosts": list(self.demoted)}
