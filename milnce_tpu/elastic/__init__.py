"""Elastic pod training: preemption-aware drain, cross-topology resume,
and straggler-adaptive scheduling (ROADMAP item 4, ELASTIC.md section of
ROBUSTNESS.md).

The resilience story (PR 3) survives bad *steps* within a fixed mesh;
this package survives capacity changes of the mesh itself.  It composes
pieces the repo already has — ``place_tree`` reshards checkpoints
across layouts in both directions, the goodput ledger attributes
badput, ``obs_report --merge`` names slow hosts — into three runtime
behaviors threaded through train/loop.py:

- **drain** (:mod:`milnce_tpu.elastic.drain`): a preemption signal
  (SIGTERM, a drain signal file, or the ``host.preempt`` fault site)
  finishes the in-flight optimizer step, forces a rotation checkpoint
  through the existing atomic tmp+rename discipline, writes the
  versioned ``ELASTIC_STAMP.json`` sidecar, and exits with a distinct
  "drained" status (``DRAINED_EXIT_CODE``, EX_TEMPFAIL: rerun with
  ``--train.resume true``).
- **cross-topology resume** (:mod:`milnce_tpu.elastic.stamp`): the next
  boot may use a DIFFERENT mesh shape (8-way -> 4x2 -> 4-way); the FSDP
  sharding map is re-derived for the new layout, the checkpoint
  reshards through the restore-template path, and the plan cursor
  (``plan.locate``) is mesh-independent so the data stream never skips
  or repeats a batch.  Indivisible batches and schedule-removed resumes
  refuse loudly.
- **straggler policy** (:mod:`milnce_tpu.elastic.straggler`): the
  cross-host step-time skew metric ``obs_report --merge`` computes
  feeds a live policy that emits ``straggler`` events, demotes a
  persistently slow host in the goodput ledger, and (behind a knob)
  recommends a drain-and-resize.
"""

from milnce_tpu.elastic.drain import DRAINED_EXIT_CODE, DrainController
from milnce_tpu.elastic.stamp import (ELASTIC_STAMP_NAME,
                                      check_topology_resume,
                                      read_elastic_stamp,
                                      write_elastic_stamp)
from milnce_tpu.elastic.straggler import StragglerPolicy

__all__ = [
    "DRAINED_EXIT_CODE", "DrainController", "ELASTIC_STAMP_NAME",
    "check_topology_resume", "read_elastic_stamp", "write_elastic_stamp",
    "StragglerPolicy",
]
