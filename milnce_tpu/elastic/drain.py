"""Preemption-aware drain: one latched verdict from three signals.

TPU-VM maintenance events deliver SIGTERM; orchestrators that can't
signal (or tests that must be deterministic) drop a file or arm the
``host.preempt`` fault site.  All three converge on one latched flag
the train loop polls once per step: the in-flight optimizer step
finishes, the stop path forces a rotation checkpoint through the
existing atomic tmp+rename discipline, writes ``ELASTIC_STAMP.json``,
and the run exits with the distinct drained status.

Multi-process: the controller is per-host; the loop all-reduces the
polled flag with ``make_flag_reducer`` every ``preempt_sync_steps`` so
one drained worker checkpoints the whole cluster cooperatively
(unchanged from the PR 3 SIGTERM path — this module just widens what
can raise the local flag).
"""

from __future__ import annotations

import os
import signal

from milnce_tpu.resilience import faults

#: distinct process exit status of a drained run (train/cli.py): 75 is
#: BSD sysexits' EX_TEMPFAIL — "temporary failure, retry" — which is
#: exactly the contract: rerun with ``--train.resume true`` (on any
#: mesh shape whose batches divide).
DRAINED_EXIT_CODE = 75


class DrainController:
    """Latched drain verdict for one training process.

    ``poll()`` is called once per optimizer step by the train loop:
    cheap (one dict read + one disarmed-fault check + an optional
    ``os.path.exists``), and the ``host.preempt`` occurrence count is
    therefore the step number — ``host.preempt@N`` delivers the drain
    signal at step N, deterministically, with no real signal involved
    (signal handlers can't install from non-main threads, and a chaos
    test must not depend on kernel delivery timing)."""

    def __init__(self, signal_file: str = "", recorder=None):
        self._signal_file = signal_file
        self._rec = recorder
        self._flag = False
        self._source = ""
        self._announced = False
        self._prev_handler = None

    # -- signal plumbing ------------------------------------------------
    def install(self):
        """Install the SIGTERM handler (restore with :meth:`uninstall`).
        Non-main-thread installation (tests) degrades to the other two
        signal sources, same as the historical inline handler."""
        def _on_sigterm(signum, frame):
            self._trip("sigterm")

        try:
            self._prev_handler = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:          # non-main thread
            self._prev_handler = None
        return self._prev_handler

    def uninstall(self) -> None:
        if self._prev_handler is not None:
            signal.signal(signal.SIGTERM, self._prev_handler)
            self._prev_handler = None

    # -- the per-step check ---------------------------------------------
    def _trip(self, source: str) -> None:
        if not self._flag:
            self._flag = True
            self._source = source

    def poll(self, step: int = 0) -> bool:
        """Latched drain verdict; counts one ``host.preempt`` occurrence
        per call while untripped.  The ``preempt.signal`` event is
        emitted HERE (loop thread), never from the signal handler —
        recorder IO in signal context is how handlers deadlock."""
        if not self._flag:
            if faults.fire_site("host.preempt"):
                self._trip("host.preempt")
            elif self._signal_file and os.path.exists(self._signal_file):
                self._trip("signal_file")
        if self._flag and not self._announced:
            self._announced = True
            if self._rec is not None:
                self._rec.event("preempt.signal", source=self._source,
                                step=int(step))
        return self._flag

    @property
    def draining(self) -> bool:
        return self._flag

    @property
    def source(self) -> str:
        """What delivered the drain signal: ``sigterm`` |
        ``host.preempt`` | ``signal_file`` | '' (not draining)."""
        return self._source
