"""ELASTIC_STAMP.json: the topology sidecar of a checkpoint rotation.

Orbax checkpoints carry GLOBAL arrays, never a mesh layout
(MIGRATING.md "Checkpoint resharding") — which is exactly what makes
cross-topology resume possible, and exactly why a resume can't tell
from the rotation alone what layout wrote it.  The stamp records the
writing run's mesh shape, sharding-map hash and plan cursor next to the
rotation (same atomic tmp+``os.replace`` discipline as
``CURRICULUM_STAMP.json``), so a resume onto a different mesh is a
*logged, validated* topology change instead of a silent one, and the
two sidecars can be cross-checked: both must agree on the plan cursor,
or one of them is stale.

Stdlib-only on purpose (mirrors train/curriculum.py's stamp half):
jax-free tooling can read a run dir's topology history.
"""

from __future__ import annotations

import json
import os
from typing import Optional

#: checkpoint sidecar, written by process 0 next to the Orbax rotation
#: at every save (train/loop.py) — overwritten each time: it describes
#: the LATEST saved state, which is what ``restore_latest`` hands back.
ELASTIC_STAMP_NAME = "ELASTIC_STAMP.json"

SCHEMA = "milnce.elastic/v1"


def write_elastic_stamp(ckpt_dir: str, *, mesh_shape: dict,
                        sharding_hash: str, step: int, stage_index: int,
                        batch_offset: int, drained: bool) -> None:
    """Atomic sidecar write (process 0 only — the caller gates).

    ``mesh_shape`` is the named mesh's axis->size dict (e.g.
    ``{"data": 8}`` or ``{"data": 4, "model": 2}``); ``sharding_hash``
    is the FSDP sharding map's layout hash ('' on a 1-D mesh);
    ``step``/``stage_index``/``batch_offset`` are the plan cursor —
    the global optimizer step plus where ``plan.locate(step)`` places
    it, pinned identical across topology changes."""
    payload = {
        "schema": SCHEMA,
        "mesh": {str(k): int(v) for k, v in mesh_shape.items()},
        "n_devices": int(_mesh_size(mesh_shape)),
        "sharding_hash": str(sharding_hash),
        "step": int(step),
        "stage": int(stage_index),
        "batch_offset": int(batch_offset),
        "drained": bool(drained),
    }
    path = os.path.join(ckpt_dir, ELASTIC_STAMP_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, indent=1)
    os.replace(tmp, path)


def read_elastic_stamp(ckpt_dir: str) -> Optional[dict]:
    path = os.path.join(ckpt_dir, ELASTIC_STAMP_NAME)
    if not os.path.exists(path):
        return None         # pre-elastic checkpoint: nothing to validate
    with open(path) as fh:
        return json.load(fh)


def _mesh_size(mesh_shape: dict) -> int:
    n = 1
    for v in mesh_shape.values():
        n *= int(v)
    return n


def check_topology_resume(stamp: Optional[dict], *, mesh_shape: dict,
                          batch_sizes, curriculum_stamp: Optional[dict]
                          ) -> Optional[str]:
    """Validate a resume against the stamp's topology; returns a log
    line describing the topology change (None when the layout is
    unchanged or there is no stamp to compare).

    Two loud refusals, both BEFORE any Orbax I/O:

    - **mesh-indivisible batch**: every stage's global batch must divide
      over the new mesh's device count — sharded step inputs would
      otherwise fail deep inside jit with a shape error that never
      names the topology change that caused it;
    - **stale sidecar pair**: ``CURRICULUM_STAMP.json`` and
      ``ELASTIC_STAMP.json`` are written together at every save; a
      plan-cursor disagreement means one sidecar survived a crash the
      other didn't, and resuming on either cursor could skip or repeat
      batches.
    """
    n_dev = _mesh_size(mesh_shape)
    for i, b in enumerate(batch_sizes):
        if int(b) % n_dev != 0:
            raise ValueError(
                f"elastic resume refused: stage {i} batch_size {b} does "
                f"not divide over the {n_dev}-device mesh "
                f"{dict(mesh_shape)} — a resized resume must keep every "
                "stage's global batch divisible by the new device count "
                "(adjust parallel.num_devices or the batch sizes)")
    if stamp is None:
        return None
    if curriculum_stamp is not None:
        saved = int(stamp.get("step", -1))
        cur = int(curriculum_stamp.get("step", -1))
        if saved != cur:
            raise ValueError(
                "elastic resume refused: ELASTIC_STAMP.json (step "
                f"{saved}) and CURRICULUM_STAMP.json (step {cur}) "
                "disagree on the plan cursor — the sidecar pair is "
                "stale (a crash between stamp writes?); inspect the "
                "rotation and delete the stale stamp to proceed")
    old = {str(k): int(v) for k, v in (stamp.get("mesh") or {}).items()}
    new = {str(k): int(v) for k, v in mesh_shape.items()}
    if old == new:
        return None
    return (f"elastic resume: topology change {old or '?'} -> {new} "
            f"(checkpoint step {stamp.get('step')}, "
            f"sharding hash {stamp.get('sharding_hash') or 'none'} -> "
            "re-derived for the new layout; state reshards through the "
            "restore-template path)")
