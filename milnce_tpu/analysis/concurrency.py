"""graftlint Pass 3a: pure-AST concurrency analysis for the serving/obs
thread mesh.

The serving and observability layers are a real multithreaded system —
batcher worker, HTTP request threads, data reader threads and the train
loop all share mutable state behind ``threading.Lock``s — and three of
the last four PRs shipped post-review fixes for races a reviewer
happened to notice (the unlocked ``/healthz`` dict, the batcher
``stats()`` race, the RunLogger log-vs-close deref).  This pass turns
that class of review luck into failing tier-1 tests, the same move
Pass 1 made for host syncs:

- **GL010 unguarded-shared-state** — per class, infer the shared
  mutable attributes (assigned in ``__init__``, reachable from >= 2
  thread roots) and the guard map (which lock protects which attribute,
  from ``with self._lock:`` blocks plus explicit ``# guarded-by:
  <lock>`` annotations), then flag writes outside the guard always, and
  lock-free reads of guarded attributes unless the attribute is
  write-once-in-``__init__`` (the audited tokenizer pattern);
- **GL011 lock-order-cycle** — build the static lock-acquisition graph
  (lock B acquired while lock A is held, including through same-module
  calls and across modules via imported module-level locks) and fail on
  cycles: a cycle is a latent ABBA deadlock whether or not today's
  thread interleavings hit it;
- **GL012 blocking-under-lock** — ``future.result()``, ``.join()`` /
  ``.wait()``, ``open()``, ``time.sleep()`` or device dispatch while
  holding a lock: every contender stalls for the duration (device
  dispatch is exempt under locks whose *name* contains ``dispatch`` —
  serializing dispatch is ``DEVICE_DISPATCH_LOCK``'s entire job).

Like Pass 1 this imports no jax and is heuristic by design; the scope
rules and documented limitations live in ANALYSIS.md ("Pass 3 scope
heuristics").  The runtime twin — an instrumented lock that checks the
same ordering discipline on live threads — is
:mod:`milnce_tpu.analysis.lockrt`.

Annotation syntax (parsed from real comment tokens, like suppressions):

- on an ``__init__`` assignment line, ``# guarded-by: _lock`` declares
  the attribute's guard explicitly (for guards the inference can't see,
  or write-once attributes whose lock-free reads are audited);
- on a ``def`` line (or the line above), ``# guarded-by: _lock``
  declares that callers hold ``_lock`` for the whole method (the
  helper-relies-on-caller's-lock pattern).

A ``guarded-by`` naming a lock the module doesn't declare is itself a
finding (GL000) — annotations must not typo-rot.

CLI: ``python -m milnce_tpu.analysis.concurrency [paths]`` prints the
inferred guard map as markdown (the source of SERVING.md's "Threading
model" table).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field

from milnce_tpu.analysis.rules import RULES
from milnce_tpu.analysis.astlint import Finding, _terminal_and_root

# Constructors that make an attribute/module global a lock (threading's,
# plus the sanitizer's drop-ins and the env-switched factory).
_LOCK_CTORS = {"Lock", "RLock", "SanitizedLock", "SanitizedRLock",
               "make_lock"}
# Class-scope triggers: constructing worker threads / reader pools, or
# serving HTTP (one handler thread per connection).
_THREAD_CTORS = {"Thread"}
_POOL_CTORS = {"ThreadPoolExecutor"}
_HTTP_METHOD = re.compile(r"^do_[A-Z]+$")
# An imported ALL-CAPS name containing LOCK is treated as a module-level
# lock defined by the import's source module (DEVICE_DISPATCH_LOCK).
_IMPORTED_LOCK = re.compile(r"^[A-Z_]*LOCK[A-Z_]*$")
_GUARDED_BY = re.compile(r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_]\w*)")

# GL012 verb sets.  `.join`/`.wait` only count with thread-ish args
# (no args, a numeric timeout, or a timeout= kwarg) so `"x".join(parts)`
# and `os.path.join(a, b)` never trip it.
_BLOCK_METHOD_VERBS = {"result", "join", "wait"}
_DEVICE_VERBS = {"device_put", "device_get", "block_until_ready"}


def _module_key(path: str) -> str:
    base = os.path.basename(path)
    return base[:-3] if base.endswith(".py") else base


def _guard_comments(src: str) -> dict[int, str]:
    """line -> lock name for every real ``# guarded-by:`` comment."""
    out: dict[int, str] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(src).readline)
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                m = _GUARDED_BY.search(tok.string)
                if m:
                    out[tok.start[0]] = m.group("lock")
    except (tokenize.TokenError, SyntaxError):  # pragma: no cover
        pass
    return out


@dataclass
class LockGraph:
    """Static lock-order graph: edge (A, B) = "B acquired while A held",
    with the first acquisition site kept per edge.  Merged across every
    module in the lint scope before cycle detection, so an AB / BA split
    across two files still fails."""

    edges: dict = field(default_factory=dict)   # (src, dst) -> (path, line)

    def add(self, src: str, dst: str, path: str, line: int) -> None:
        key = (src, dst)
        if key not in self.edges or (path, line) < self.edges[key]:
            self.edges[key] = (path, line)

    def merge(self, other: "LockGraph") -> None:
        for (src, dst), (path, line) in other.edges.items():
            self.add(src, dst, path, line)

    @property
    def locks(self) -> set:
        return {n for edge in self.edges for n in edge}

    def cycle_findings(self) -> list[Finding]:
        """One GL011 finding per strongly-connected component (plus
        self-loops), anchored at the latest acquisition site in the
        cycle — the edge that *inverted* the established order."""
        adj: dict[str, set] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
        sccs = _tarjan(adj)
        findings = []
        for comp in sorted(sccs, key=lambda c: sorted(c)):
            comp = set(comp)
            internal = sorted(
                (u, v, self.edges[(u, v)]) for (u, v) in self.edges
                if u in comp and v in comp)
            if len(comp) == 1 and not any(u == v for u, v, _ in internal):
                continue
            anchor = max(site for _, _, site in internal)
            chain = "; ".join(f"{u} -> {v} @ {site[0]}:{site[1]}"
                              for u, v, site in internal)
            findings.append(Finding(
                anchor[0], anchor[1], RULES["GL011"],
                f"lock-order cycle among {sorted(comp)} — some thread "
                f"interleaving deadlocks (acquisition edges: {chain})"))
        return findings


def _tarjan(adj: dict) -> list[list]:
    """Strongly-connected components, iterative (lint runs on arbitrary
    user modules — no recursion-limit surprises)."""
    index: dict[str, int] = {}
    low: dict[str, int] = {}
    on_stack: set = set()
    stack: list = []
    out: list[list] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(sorted(adj[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(adj[nxt]))))
                    advanced = True
                    break
                if nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


# ---------------------------------------------------------------------------
# per-function walk
# ---------------------------------------------------------------------------

@dataclass
class _Access:
    attr: str
    write: bool
    line: int
    held: tuple


@dataclass
class _CallSite:
    callee: tuple          # ("func", name) | ("method", m) | ("ctor", Cls)
    line: int
    held: tuple


@dataclass
class _Blocking:
    verb: str
    line: int
    held: tuple
    device: bool


@dataclass
class _FnReport:
    accesses: list = field(default_factory=list)
    calls: list = field(default_factory=list)
    blocking: list = field(default_factory=list)
    direct_locks: set = field(default_factory=set)
    edges: list = field(default_factory=list)      # (src, dst, line)
    spawn_targets: set = field(default_factory=set)
    uses_threads: bool = False


class _FnWalker:
    """Walks one function/method body tracking the set of held locks."""

    def __init__(self, lock_resolver, initial_held: tuple = ()):
        self._resolve = lock_resolver        # expr -> lock id | None
        self.report = _FnReport()
        self._initial = initial_held

    def walk(self, fn: ast.FunctionDef) -> _FnReport:
        self._stmts(fn.body, self._initial)
        return self.report

    # ---- statements ------------------------------------------------------

    def _stmts(self, body: list, held: tuple) -> None:
        for stmt in body:
            self._stmt(stmt, held)

    def _stmt(self, stmt: ast.stmt, held: tuple) -> None:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = held
            for item in stmt.items:
                self._expr(item.context_expr, inner)
                lk = self._resolve(item.context_expr)
                if lk is not None:
                    for h in inner:
                        self.report.edges.append((h, lk, stmt.lineno))
                    self.report.direct_locks.add(lk)
                    inner = inner + (lk,)
            self._stmts(stmt.body, inner)
            return
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later, on whatever thread calls it — no
            # inherited lock context; accesses still count toward the
            # enclosing method (spawn closures touch shared state)
            self._stmts(stmt.body, ())
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            # a bare annotation (`self.x: int`, no value) assigns nothing
            if not (isinstance(stmt, ast.AnnAssign) and stmt.value is None):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    self._write_target(t, held)
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._write_target(t, held)
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt):
                self._stmt(node, held)
            elif isinstance(node, ast.expr):
                self._expr(node, held)
            elif isinstance(node, ast.ExceptHandler):
                self._stmts(node.body, held)

    def _write_target(self, target: ast.expr, held: tuple) -> None:
        """self.x = / self.x[...] = / del self.x — container item
        assignment mutates the attribute's value; method calls on an
        attribute are deliberately NOT writes (opaque: `.inc()` on a
        registry counter is internally locked)."""
        if self._is_self_attr(target):
            self.report.accesses.append(
                _Access(target.attr, True, target.lineno, held))
        elif (isinstance(target, ast.Subscript)
                and self._is_self_attr(target.value)):
            self.report.accesses.append(
                _Access(target.value.attr, True, target.lineno, held))
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._write_target(elt, held)

    @staticmethod
    def _is_self_attr(node: ast.expr) -> bool:
        return (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self")

    # ---- expressions -----------------------------------------------------

    def _expr(self, expr: ast.expr, held: tuple) -> None:
        for node in ast.walk(expr):
            if (self._is_self_attr(node)
                    and isinstance(node.ctx, ast.Load)):
                self.report.accesses.append(
                    _Access(node.attr, False, node.lineno, held))
            elif isinstance(node, ast.Call):
                self._call(node, held)

    def _call(self, call: ast.Call, held: tuple) -> None:
        terminal, root = _terminal_and_root(call.func)
        # `.acquire()` on a resolvable lock counts as holding it from
        # here on is NOT modeled (manual acquire/release pairs are rare
        # — the codebase idiom is `with`); it still counts as an
        # acquisition edge and a scope trigger.
        if terminal == "acquire" and isinstance(call.func, ast.Attribute):
            lk = self._resolve(call.func.value)
            if lk is not None:
                for h in held:
                    self.report.edges.append((h, lk, call.lineno))
                self.report.direct_locks.add(lk)
        if terminal in _THREAD_CTORS or terminal in _POOL_CTORS:
            self.report.uses_threads = True
        # spawn targets: Thread(target=self.m) / pool.submit(self.m, ..)
        if terminal in _THREAD_CTORS:
            for kw in call.keywords:
                if kw.arg == "target" and self._is_self_attr(kw.value):
                    self.report.spawn_targets.add(kw.value.attr)
        if terminal == "submit" and call.args \
                and self._is_self_attr(call.args[0]):
            self.report.spawn_targets.add(call.args[0].attr)
        # callee resolution for interprocedural lock propagation
        if self._is_self_attr(call.func):
            self.report.calls.append(
                _CallSite(("method", call.func.attr), call.lineno, held))
        elif isinstance(call.func, ast.Name):
            self.report.calls.append(
                _CallSite(("name", call.func.id), call.lineno, held))
        # GL012 blocking verbs
        blocking = None
        device = False
        if terminal in _DEVICE_VERBS:
            blocking, device = f"{terminal}()", True
        elif (terminal in _BLOCK_METHOD_VERBS
                and isinstance(call.func, ast.Attribute)
                and self._threadish_args(call)):
            blocking = f".{terminal}()"
        elif terminal == "sleep" and root == "time":
            blocking = "time.sleep()"
        elif terminal == "open" and isinstance(call.func, ast.Name):
            blocking = "open()"
        if blocking and held:
            self.report.blocking.append(
                _Blocking(blocking, call.lineno, held, device))

    @staticmethod
    def _threadish_args(call: ast.Call) -> bool:
        """join/wait/result signatures: no args, a numeric timeout, or a
        timeout= kwarg.  `sep.join(parts)` / `os.path.join(a, b)` have
        non-numeric positional args and never match."""
        if any(kw.arg == "timeout" for kw in call.keywords):
            return True
        if not call.args and not call.keywords:
            return True
        return (len(call.args) == 1 and not call.keywords
                and isinstance(call.args[0], ast.Constant)
                and isinstance(call.args[0].value, (int, float)))


# ---------------------------------------------------------------------------
# per-class analysis
# ---------------------------------------------------------------------------

@dataclass
class ClassReport:
    """The inferred threading model of one class (also the data behind
    the guard-map CLI / SERVING.md table)."""

    module: str
    name: str
    in_scope: bool
    roots: list
    lock_attrs: list
    guards: dict            # attr -> lock id ('' = unguarded)
    write_once: set
    shared: set             # attrs reachable from >= 2 roots


class _ModulePass:
    def __init__(self, src: str, path: str):
        self.src = src
        self.path = path
        self.mod = _module_key(path)
        self.tree = ast.parse(src)
        self.comments = _guard_comments(src)
        self.findings: list[Finding] = []
        self.graph = LockGraph()
        self.class_reports: list[ClassReport] = []
        # module-level locks: own definitions + imported LOCK names
        self.module_locks: dict[str, str] = {}
        self.module_funcs: dict[str, ast.FunctionDef] = {}
        self.classes: dict[str, ast.ClassDef] = {}
        self._discover()

    # ---- discovery -------------------------------------------------------

    @staticmethod
    def _is_lock_ctor(value: ast.expr) -> bool:
        if not isinstance(value, ast.Call):
            return False
        terminal, _root = _terminal_and_root(value.func)
        return terminal in _LOCK_CTORS

    def _discover(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and self._is_lock_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks[t.id] = f"{self.mod}:{t.id}"
            elif isinstance(node, ast.ImportFrom) and node.module:
                src_mod = node.module.split(".")[-1]
                for alias in node.names:
                    name = alias.asname or alias.name
                    if _IMPORTED_LOCK.match(alias.name):
                        self.module_locks[name] = f"{src_mod}:{alias.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                self.classes[node.name] = node

    # ---- driver ----------------------------------------------------------

    def run(self) -> None:
        fn_reports: dict[tuple, _FnReport] = {}
        # module-level functions
        for name, fn in self.module_funcs.items():
            rep = self._walk_fn(fn, None, set())
            fn_reports[("func", name)] = rep
            for src, dst, line in rep.edges:
                self.graph.add(src, dst, self.path, line)
        for cname, cls in self.classes.items():
            self._run_class(cname, cls, fn_reports)
        self._interprocedural_edges(fn_reports)
        self._emit_gl012(fn_reports)

    def _walk_fn(self, fn, cls_name, lock_attrs) -> _FnReport:
        resolver = self._make_resolver(cls_name, lock_attrs)
        initial = ()
        guard = self._method_guard(fn, cls_name, lock_attrs)
        if guard:
            initial = (guard,)
        return _FnWalker(resolver, initial).walk(fn)

    def _make_resolver(self, cls_name, lock_attrs):
        def resolve(expr):
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and expr.attr in lock_attrs):
                return f"{self.mod}:{cls_name}.{expr.attr}"
            if isinstance(expr, ast.Name) and expr.id in self.module_locks:
                return self.module_locks[expr.id]
            return None
        return resolve

    def _resolve_guard_name(self, name, cls_name, lock_attrs, line):
        """A ``guarded-by:`` lock name -> canonical id; unknown names
        are GL000 findings (annotations must not typo-rot)."""
        if name in lock_attrs:
            return f"{self.mod}:{cls_name}.{name}"
        if name in self.module_locks:
            return self.module_locks[name]
        self.findings.append(Finding(
            self.path, line, RULES["GL000"],
            f"guarded-by names unknown lock {name!r} (declare the lock "
            "in this module, or fix the annotation)"))
        return None

    def _method_guard(self, fn, cls_name, lock_attrs):
        for line in (fn.lineno, fn.lineno - 1):
            name = self.comments.get(line)
            if name:
                return self._resolve_guard_name(name, cls_name, lock_attrs,
                                                line)
        return None

    # ---- class analysis --------------------------------------------------

    def _run_class(self, cname, cls, fn_reports) -> None:
        methods = {n.name: n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        init = methods.get("__init__")
        init_attrs: dict[str, int] = {}
        lock_attrs: set = set()
        annotated: dict[str, str] = {}
        if init is not None:
            for node in ast.walk(init):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    init_attrs.setdefault(t.attr, t.lineno)
                    if node.value is not None \
                            and self._is_lock_ctor(node.value):
                        lock_attrs.add(t.attr)
        # annotations ride the __init__ assignment lines
        for attr, line in init_attrs.items():
            name = self.comments.get(line)
            if name and attr not in lock_attrs:
                guard = self._resolve_guard_name(name, cname, lock_attrs,
                                                 line)
                if guard:
                    annotated[attr] = guard

        reports = {}
        for mname, fn in methods.items():
            rep = self._walk_fn(fn, cname, lock_attrs)
            reports[mname] = rep
            fn_reports[("method", cname, mname)] = rep
            for src, dst, line in rep.edges:
                self.graph.add(src, dst, self.path, line)

        in_scope = self._class_in_scope(methods, reports)
        roots = self._thread_roots(methods, reports)
        reach = self._attr_reachability(methods, reports, roots)
        guards, write_once, shared = self._guard_map(
            init_attrs, lock_attrs, annotated, reports, reach)
        self.class_reports.append(ClassReport(
            self.mod, cname, in_scope, sorted(roots), sorted(
                f"{self.mod}:{cname}.{a}" for a in lock_attrs),
            guards, write_once, shared))
        if in_scope:
            self._emit_gl010(cname, init_attrs, lock_attrs, reports,
                             guards, write_once, shared)

    def _class_in_scope(self, methods, reports) -> bool:
        """Thread-shared classes only: the class spawns threads or a
        reader pool, serves HTTP (one thread per connection), or one of
        its methods acquires a lock (owning a lock IS declaring that
        concurrent callers exist)."""
        if any(_HTTP_METHOD.match(m) for m in methods):
            return True
        return any(r.uses_threads or r.direct_locks
                   for r in reports.values())

    @staticmethod
    def _thread_roots(methods, reports) -> set:
        roots = {m for m in methods
                 if not m.startswith("_") or _HTTP_METHOD.match(m)}
        for rep in reports.values():
            roots.update(t for t in rep.spawn_targets if t in methods)
        roots.discard("__init__")
        return roots

    @staticmethod
    def _attr_reachability(methods, reports, roots) -> dict:
        """attr -> set of roots whose transitive same-class call
        closure touches it (reads or writes; ``__init__`` excluded —
        construction is single-threaded by contract)."""
        out: dict[str, set] = {}
        for root in roots:
            seen = set()
            queue = [root]
            while queue:
                m = queue.pop()
                if m in seen or m not in reports:
                    continue
                seen.add(m)
                rep = reports[m]
                for acc in rep.accesses:
                    out.setdefault(acc.attr, set()).add(root)
                for call in rep.calls:
                    if call.callee[0] == "method" \
                            and call.callee[1] in methods:
                        queue.append(call.callee[1])
        return out

    @staticmethod
    def _guard_map(init_attrs, lock_attrs, annotated, reports, reach):
        """Infer attr -> guard: the most common lock held across the
        attribute's locked non-``__init__`` writes; explicit
        ``guarded-by`` annotations win.  write-once = never directly
        written outside ``__init__``."""
        writes: dict[str, list] = {a: [] for a in init_attrs}
        for mname, rep in reports.items():
            if mname == "__init__":
                continue
            for acc in rep.accesses:
                if acc.write and acc.attr in writes:
                    writes[acc.attr].append(acc)
        guards: dict[str, str] = {}
        write_once: set = set()
        shared: set = set()
        for attr in init_attrs:
            if attr in lock_attrs:
                continue
            if not writes[attr]:
                write_once.add(attr)
            if len(reach.get(attr, ())) >= 2:
                shared.add(attr)
            if attr in annotated:
                guards[attr] = annotated[attr]
                continue
            counts: dict[str, int] = {}
            for acc in writes[attr]:
                for lk in acc.held:
                    counts[lk] = counts.get(lk, 0) + 1
            if counts:
                guards[attr] = max(sorted(counts), key=lambda k: counts[k])
        return guards, write_once, shared

    def _emit_gl010(self, cname, init_attrs, lock_attrs, reports,
                    guards, write_once, shared) -> None:
        emitted: set = set()

        def emit(attr, line, msg):
            if (attr, line) not in emitted:       # one finding per
                emitted.add((attr, line))         # attr-line (a += hits
                self.findings.append(Finding(     # read+write at once)
                    self.path, line, RULES["GL010"], msg))

        for mname, rep in sorted(reports.items()):
            if mname == "__init__":
                continue
            # writes first: a line that both reads and writes reports
            # as the (stronger) write finding
            for acc in sorted(rep.accesses,
                              key=lambda a: (a.line, not a.write)):
                attr = acc.attr
                if attr in lock_attrs or attr not in init_attrs:
                    continue
                guard = guards.get(attr)
                if guard:
                    if acc.write and guard not in acc.held:
                        emit(attr, acc.line,
                             f"{cname}.{attr} written outside its guard "
                             f"{guard} (in {mname}) — racing every "
                             "guarded access")
                    elif (not acc.write and guard not in acc.held
                            and attr not in write_once):
                        emit(attr, acc.line,
                             f"lock-free read of {cname}.{attr} (guard: "
                             f"{guard}, in {mname}) — not write-once, so "
                             "the read races the guarded writes")
                elif attr in shared and acc.write and not acc.held:
                    touched = "/".join(sorted(
                        self._methods_touching(attr, reports)))
                    emit(attr, acc.line,
                         f"unguarded write to shared {cname}.{attr} "
                         f"(in {mname}; touched from {touched}, "
                         "reachable from >= 2 thread roots) — add a lock "
                         "or a guarded-by annotation")

    @staticmethod
    def _methods_touching(attr, reports):
        return {m for m, rep in reports.items()
                if any(a.attr == attr for a in rep.accesses)
                and m != "__init__"}

    # ---- interprocedural lock edges -------------------------------------

    def _interprocedural_edges(self, fn_reports) -> None:
        """Locks acquired by a callee count as acquired at a locked call
        site: ``with A: self.helper()`` where helper takes B adds the
        A -> B edge.  Same-module resolution only (bare names, self
        methods, ClassName() constructors)."""
        memo: dict[tuple, set] = {}

        def locks_of(key, trail):
            if key in memo:
                return memo[key]
            if key in trail or key not in fn_reports:
                return set()
            rep = fn_reports[key]
            out = set(rep.direct_locks)
            for call in rep.calls:
                for ck in self._candidate_keys(key, call):
                    out |= locks_of(ck, trail | {key})
            memo[key] = out
            return out

        for key, rep in fn_reports.items():
            for call in rep.calls:
                if not call.held:
                    continue
                for ck in self._candidate_keys(key, call):
                    for lk in locks_of(ck, {key}):
                        for h in call.held:
                            if h != lk:
                                self.graph.add(h, lk, self.path, call.line)

    def _candidate_keys(self, caller_key, call):
        kind, name = call.callee
        if kind == "method" and caller_key[0] == "method":
            yield ("method", caller_key[1], name)
        elif kind == "name":
            if name in self.module_funcs:
                yield ("func", name)
            if name in self.classes:
                yield ("method", name, "__init__")

    # ---- GL012 -----------------------------------------------------------

    def _emit_gl012(self, fn_reports) -> None:
        for key, rep in sorted(fn_reports.items()):
            where = key[-1] if key[0] != "method" else f"{key[1]}.{key[2]}"
            for b in rep.blocking:
                if b.device and all("dispatch" in h.lower()
                                    for h in b.held):
                    continue    # serializing dispatch is that lock's job
                self.findings.append(Finding(
                    self.path, b.line, RULES["GL012"],
                    f"{b.verb} while holding {b.held[-1]} (in {where}) — "
                    "every contender stalls for the full "
                    + ("device dispatch" if b.device else "blocking call")))


def lint_concurrency_source(src: str, path: str = "<string>"
                            ) -> tuple[list[Finding], LockGraph,
                                       list[ClassReport]]:
    """Pass 3a for one module: (findings [GL010/GL012 + annotation
    GL000s], this module's lock graph, per-class reports).  GL011 cycle
    findings come from the MERGED graph — the caller (astlint) detects
    cycles after merging every module in scope."""
    mp = _ModulePass(src, path)
    mp.run()
    mp.findings.sort(key=lambda f: (f.line, f.rule.id))
    return mp.findings, mp.graph, mp.class_reports


# ---------------------------------------------------------------------------
# guard-map CLI (the SERVING.md "Threading model" table source)
# ---------------------------------------------------------------------------

def guard_map_markdown(paths: list[str]) -> str:
    """Markdown table of every in-scope class's inferred threading
    model, derived from the same analysis the lint runs."""
    from milnce_tpu.analysis.astlint import _discover_files

    lines = ["| class | thread roots | attribute | discipline |",
             "|---|---|---|---|"]
    for fname in _discover_files(paths):
        with open(fname) as fh:
            _, _, reports = lint_concurrency_source(fh.read(), fname)
        for rep in reports:
            if not rep.in_scope:
                continue
            rows = []
            attrs = sorted(set(rep.guards) | rep.write_once | rep.shared)
            for attr in attrs:
                guard = rep.guards.get(attr)
                if guard:
                    disc = f"guarded by `{guard.split(':')[-1]}`"
                elif attr in rep.write_once:
                    disc = "write-once in `__init__` (lock-free reads ok)"
                else:
                    disc = "shared, unguarded"
                rows.append((attr, disc))
            if not rows:
                rows = [("—", "stateless (no shared attributes)")]
            roots = ", ".join(f"`{r}`" for r in rep.roots) or "—"
            for i, (attr, disc) in enumerate(rows):
                cls = f"`{rep.module}.{rep.name}`" if i == 0 else ""
                rts = roots if i == 0 else ""
                lines.append(f"| {cls} | {rts} | `{attr}` | {disc} |")
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="print the inferred per-class guard map as markdown")
    ap.add_argument("paths", nargs="*", default=["milnce_tpu"])
    args = ap.parse_args(argv)
    print(guard_map_markdown(args.paths or ["milnce_tpu"]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
