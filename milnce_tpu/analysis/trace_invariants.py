"""graftlint Pass 2: trace-level invariants over the registered entry points.

Where Pass 1 reads source, this pass reads *jaxprs*: every hot-path entry
point (train step variants, soft-DTW ops, eval retrieval embedders, the
serving engine's bucket ladder + sharded top-k retrieval) is
traced on a hermetic CPU mesh (the same 8-virtual-device layout the test
suite uses) and checked for the regressions that erase TPU throughput
without failing any functional test:

- **no-f64**: no value of dtype float64 anywhere in the jaxpr, and no
  ``convert_element_type`` targeting it — one f64 operand upcasts every
  downstream op (2x HBM traffic, off the MXU fast path);
- **collectives**: the exact multiset of collective primitives per step
  is pinned for the 8-way data mesh.  A diff means the communication
  structure changed — sometimes intended (then re-pin the number in
  ``EXPECTED_COLLECTIVES``, consciously), often a silent extra gather
  or a lost psum;
- **treedef**: the three conv formulations (native / fold2d / im2col)
  must init byte-identical param trees — the per-stage impl map
  (ModelConfig.conv_impl_map) and checkpoint portability both rely on
  it;
- **recompile**: each executable entry point is called twice with fresh
  same-shaped inputs and must hit the jit cache the second time — a
  miss is the seed of a recompilation storm (weak-type drift, unstable
  static argument, non-hashable closure).

Everything here must run under ``JAX_PLATFORMS=cpu`` in tier-1 time:
the model is a 1-block S3D at 4 frames / 32 px.  jax imports live
inside functions so ``astlint`` stays importable without jax.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

# Collective primitives whose per-step count we pin.  ``reduce_scatter``
# is what ``lax.psum_scatter`` lowers to on this jax — the FSDP grad
# reduction's signature primitive (train/step.py _reduce_grads_2d).
COLLECTIVES = ("psum", "all_gather", "psum_scatter", "reduce_scatter",
               "all_to_all", "ppermute", "pbroadcast")

# Pinned per-entry collective multisets for the 8-way data mesh (absent
# primitive = expected 0).  Derived by tracing on the tiny entry config;
# the invariant is that they never change SILENTLY — a deliberate
# communication-structure change re-pins the number in the same commit.
#
# Reading the milnce step: 2 all_gathers (video+text negatives ride ICI
# once each); the 26 psums are the scalar loss reduction, the leaf-wise
# grad psum, and the pmean-lowered BatchNorm stat merges; the 2
# reduce_scatters are the AD transposes of the loss's embedding gathers
# (every grad-bearing step has them — they were always in the program,
# uncounted until ISSUE 6 added reduce_scatter to COLLECTIVES for the
# FSDP entries, a conscious same-commit re-pin of every entry below).
# sdtw_3 trades one psum for a third all_gather (clip start-times feed
# the alignment; the start gather carries no gradient).
EXPECTED_COLLECTIVES = {
    "train_step_milnce": {"all_gather": 2, "psum": 26,
                          "reduce_scatter": 2},
    # the finite-update guard (ISSUE 3) must add NO collectives and no
    # host sync: its all-finite check runs on the already-psum'd
    # (replicated) grads and the skip is a jnp.where select — the pin
    # being IDENTICAL to the unguarded step is the invariant
    "train_step_milnce_guarded": {"all_gather": 2, "psum": 26,
                                  "reduce_scatter": 2},
    # the obs span instrumentation (ISSUE 5) wraps the step DISPATCH in
    # a host-side recorder (train/loop.py `rec.span("step")`); it must
    # add NO collectives, no transfers, no sync — the pin being
    # IDENTICAL to the uninstrumented step is the tentpole invariant,
    # and the entry also EXECUTES it under transfer_guard("disallow")
    "train_step_milnce_instrumented": {"all_gather": 2, "psum": 26,
                                       "reduce_scatter": 2},
    # curriculum step (ISSUE 16): ONE step_fn serves every stage; each
    # stage's (frames, resolution, batch) shape is its own jit entry,
    # compiled once at stage entry.  The invariant is twofold: every
    # stage's traced program carries the SAME collective multiset as the
    # single-stage step (shapes scale tensors, never communication
    # structure), and within a stage the cache never grows (zero
    # recompiles; entering stage 2 adds exactly one entry)
    "train_step_curriculum": {"all_gather": 2, "psum": 26,
                              "reduce_scatter": 2},
    # chunked MIL-NCE (ISSUE 12): the streaming loss must keep the DENSE
    # step's exact communication structure — the same 2 negative
    # all_gathers (whose AD transposes stay the same 2 reduce_scatters)
    # and the same psum census; the chunk scan adds compute structure,
    # never collectives (its body is pinned collective-free by the
    # scan-reduction-free check on these entries).  The pins being
    # IDENTICAL to train_step_milnce / train_step_milnce_2d is the
    # invariant, exactly like the guarded/instrumented twins above.
    "train_step_milnce_chunked": {"all_gather": 2, "psum": 26,
                                  "reduce_scatter": 2},
    "train_step_milnce_chunked_2d": {"all_gather": 22, "psum": 78,
                                     "reduce_scatter": 22},
    # elastic 4-way layout (ISSUE 20): the DOWNSIZED data mesh a drained
    # run resumes onto (parallel.num_devices=4 on an 8-device host).
    # The multiset is pinned IDENTICAL to the 8-way step by construction
    # — collective STRUCTURE is a function of the program, not the axis
    # size (4-way vs 8-way only changes shard extents) — and pinning it
    # per layout is what makes a topology change's communication plan a
    # deliberate re-pin instead of an accident.
    "train_step_milnce@4way": {"all_gather": 2, "psum": 26,
                               "reduce_scatter": 2},
    "train_step_sdtw3": {"all_gather": 3, "psum": 25,
                         "reduce_scatter": 2},
    "grad_cache_step_milnce": {"all_gather": 2, "psum": 26,
                               "reduce_scatter": 2},
    # 2-D (data, model) FSDP step on the 4x2 grid (ISSUE 6): 22
    # all_gathers = 20 sharded-param materializations before the forward
    # + the 2 loss negative gathers; 22 reduce_scatters = the 20
    # model-axis halves of the per-leaf grad reduction (GSPMD's textbook
    # gather/reduce-scatter pair, here explicit and therefore countable)
    # + the 2 loss-gather transposes; the psums are the per-leaf
    # data-axis grad reductions plus the replicated leaves' both-axes
    # psums (overlap_grad_reduce=True emits them per leaf so the
    # scheduler can overlap each with the backward) and the loss/BN
    # reductions.  The guarded 2-D step adds exactly ONE psum — the
    # model-axis finite-verdict reduction that keeps the skip decision
    # uniform across model columns.  Counts are a function of the tiny
    # entry model's leaf census under _FSDP_MIN_SIZE — a model/threshold
    # change re-pins them in the same commit, like every other entry.
    "train_step_milnce_2d": {"all_gather": 22, "psum": 78,
                             "reduce_scatter": 22},
    "train_step_milnce_2d_guarded": {"all_gather": 22, "psum": 79,
                                     "reduce_scatter": 22},
    # grad-cache on the 2-D mesh: identical communication to the
    # single-pass 2-D step — the whole point of the once-per-step
    # property (gather before pass 1, reduce after pass 2, NOTHING per
    # microbatch; the scan-reduction-free check pins the structure)
    "grad_cache_2d": {"all_gather": 22, "psum": 78, "reduce_scatter": 22},
    "video_embed": {},
    "text_embed": {},
    "softdtw_scan_grad": {},
    # serving (ISSUE 4): the engine's embed entries are the same
    # shard_map programs as offline eval — collective-free by
    # construction; the sharded top-k retrieval ships exactly the two
    # (Q, k) candidate gathers (scores + global indices), never the
    # (Q, R_local) score matrix
    "serve_text_embed": {},
    "serve_video_embed": {},
    "serve_index_topk": {"all_gather": 2},
    # replica pool (ISSUE 10): each replica's engine runs on its OWN
    # mesh (single-device on the CPU backend) — its embed programs must
    # stay collective-free like the single-engine entries
    "serve_pool_text_embed": {},
    "serve_pool_video_embed": {},
    # live index (ISSUE 14): the generation-swapped index runs the SAME
    # top-k program — identical pinned communication, whatever
    # generation is live
    "serve_live_index": {"all_gather": 2},
    # quantized edge tier (ISSUE 19): the int8 engine is the same embed
    # program with an in-jit dequantize prologue (i8 -> f32 convert +
    # scale multiply, quant/quantize.py) — it must stay collective-free
    # like every other embed entry, and GL016-clean by construction:
    # every matmul accumulates in f32 because the ONLY low-precision
    # dtype in the program is int8 storage, never a compute dtype
    "serve_quant_text_embed": {},
    "serve_quant_video_embed": {},
}


@dataclass
class CheckResult:
    entry: str
    check: str              # no-f64 | collectives | treedef | recompile
    ok: bool
    detail: str = ""

    def format(self) -> str:
        mark = "ok" if self.ok else "FAIL"
        tail = f" — {self.detail}" if self.detail else ""
        return f"[{mark}] {self.entry}/{self.check}{tail}"


# --------------------------------------------------------------------------
# jaxpr utilities
# --------------------------------------------------------------------------

def iter_eqns(jaxpr):
    """Every eqn in a (possibly nested) jaxpr, including the inner jaxprs
    of pjit / shard_map / scan / custom_vjp / pallas_call params."""
    import jax

    for eqn in jaxpr.eqns:
        yield eqn
        for p in eqn.params.values():
            vals = p if isinstance(p, (list, tuple)) else [p]
            for v in vals:
                if isinstance(v, jax.core.ClosedJaxpr):
                    yield from iter_eqns(v.jaxpr)
                elif isinstance(v, jax.core.Jaxpr):
                    yield from iter_eqns(v)


def collective_counts(jaxpr) -> dict:
    out: dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name in COLLECTIVES:
            out[eqn.primitive.name] = out.get(eqn.primitive.name, 0) + 1
    return out


def scan_collective_counts(jaxpr) -> dict:
    """Collective counts INSIDE ``lax.scan`` bodies, anywhere in the
    nest — the once-per-optimizer-step grad-reduction pin (ISSUE 6): a
    cross-mesh reduction that slips under the microbatch scan executes
    M times per step and silently re-pays the collective for the same
    bytes (the structure behind the ga=8 throughput hole BENCH_NOTES.md
    records).  Sibling scans accumulate; nested scans would double-count
    through their parent (none exist in the pinned programs)."""
    import jax

    out: dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "scan":
            continue
        body = eqn.params.get("jaxpr")
        inner = body.jaxpr if isinstance(body, jax.core.ClosedJaxpr) else body
        for name, n in collective_counts(inner).items():
            out[name] = out.get(name, 0) + n
    return out


def f64_sites(jaxpr) -> list[str]:
    """Primitive names whose inputs or outputs carry float64."""
    import numpy as np

    sites = []
    for eqn in iter_eqns(jaxpr):
        for v in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(v, "aval", None)
            if getattr(aval, "dtype", None) == np.float64:  # graftlint: disable=GL004(dtype comparison constant — this IS the f64 detector)
                sites.append(f"{eqn.primitive.name}: {aval}")
        if (eqn.primitive.name == "convert_element_type"
                and str(eqn.params.get("new_dtype", "")) == "float64"):
            sites.append("convert_element_type -> float64")
    return sites


# --------------------------------------------------------------------------
# tiny entry config (shared across entry points; built once per process)
# --------------------------------------------------------------------------

_TINY = dict(embedding_dim=16, vocab_size=32, word_embedding_dim=8,
             text_hidden_dim=16, inception_blocks=1)
_FRAMES, _SIZE, _WORDS = 4, 32, 5


@functools.lru_cache(maxsize=1)
def _setup():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from milnce_tpu.config import OptimConfig, ParallelConfig
    from milnce_tpu.models import S3D
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state

    ndev = len(jax.devices())
    assert ndev >= 2, (
        "trace invariants need a multi-device mesh (run under the test "
        "conftest or scripts/graft_lint.py, which force 8 virtual CPU "
        f"devices); got {ndev}")
    model = S3D(num_classes=_TINY["embedding_dim"],
                vocab_size=_TINY["vocab_size"],
                word_embedding_dim=_TINY["word_embedding_dim"],
                text_hidden_dim=_TINY["text_hidden_dim"],
                inception_blocks=_TINY["inception_blocks"])
    b = 2 * ndev                      # 2 per shard: grad-cache can split M=2
    variables = model.init(
        jax.random.PRNGKey(0),
        jnp.zeros((2, _FRAMES, _SIZE, _SIZE, 3), jnp.float32),
        jnp.zeros((2, _WORDS), jnp.int32))
    opt = build_optimizer(OptimConfig(warmup_steps=2),
                          build_schedule(OptimConfig(warmup_steps=2), 10))
    state = create_train_state(variables, opt)
    mesh = build_mesh(ParallelConfig())

    def batch(seed: int = 0):
        rng = np.random.default_rng(seed)
        video = rng.integers(0, 255, (b, _FRAMES, _SIZE, _SIZE, 3),
                             dtype=np.uint8)
        text = rng.integers(0, _TINY["vocab_size"], (b, _WORDS)).astype(
            np.int32)
        start = np.zeros((b,), np.float32)
        return video, text, start

    return model, opt, mesh, state, batch


def _jaxpr_checks(name: str, fn, args, scan_reduction_free: bool = False
                  ) -> list[CheckResult]:
    import jax

    jaxpr = jax.make_jaxpr(fn)(*args).jaxpr
    bad = f64_sites(jaxpr)
    got = collective_counts(jaxpr)
    want = EXPECTED_COLLECTIVES[name]
    out = [
        CheckResult(name, "no-f64", not bad,
                    "; ".join(bad[:4]) if bad else ""),
        CheckResult(name, "collectives", got == want,
                    "" if got == want else f"expected {want}, traced {got} "
                    "(communication structure changed — if intended, re-pin "
                    "EXPECTED_COLLECTIVES)"),
    ]
    if scan_reduction_free:
        inside = scan_collective_counts(jaxpr)
        out.append(CheckResult(
            name, "scan-reduction-free", not inside,
            "" if not inside else
            f"collectives inside scan bodies: {inside} — the cross-mesh "
            "grad reduction must run ONCE per optimizer step, after the "
            "microbatch scan, never per microbatch"))
    return out


def _recompile_check(name: str, fn, make_args, call=None) -> CheckResult:
    """Execute twice with fresh same-shaped inputs; the second call must
    hit the jit cache.  ``call`` adapts calling conventions."""
    call = call or (lambda f, a: f(*a))
    if not hasattr(fn, "_cache_size"):
        return CheckResult(name, "recompile", True,
                           "skipped: no _cache_size on this jax")
    call(fn, make_args(0))
    call(fn, make_args(1))
    n = fn._cache_size()
    return CheckResult(
        name, "recompile", n == 1,
        "" if n == 1 else f"{n} cache entries after two same-shape calls — "
        "something retraces per call (weak-type or static-arg drift)")


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------

def _entry_train_step_milnce() -> list[CheckResult]:
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup()
    step = make_train_step(model, opt, mesh, donate=False)
    name = "train_step_milnce"
    out = _jaxpr_checks(name, step, (state,) + batch())
    out.append(_recompile_check(name, step,
                                lambda s: (state,) + batch(s)))
    return out


def _entry_train_step_milnce_guarded() -> list[CheckResult]:
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup()
    step = make_train_step(model, opt, mesh, donate=False, finite_guard=True)
    name = "train_step_milnce_guarded"
    out = _jaxpr_checks(name, step, (state,) + batch())
    out.append(_recompile_check(name, step,
                                lambda s: (state,) + batch(s)))
    return out


def _entry_train_step_curriculum() -> list[CheckResult]:
    """ISSUE 16: the per-stage re-traced curriculum step.  Two stage
    shapes (4f and 8f at the tiny size) through ONE step_fn:

    - collectives: both stages' traced programs must match the pinned
      single-stage multiset — a curriculum changes tensor shapes, never
      communication structure;
    - one-entry-per-stage: two same-shape calls per stage, cache size
      must go 1 -> 2 across the boundary (zero recompiles WITHIN a
      stage, exactly one fresh jit entry per stage entered — the
      runtime guarantee train/loop.py's boundary relies on)."""
    import jax
    import numpy as np

    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup()
    step = make_train_step(model, opt, mesh, donate=False)
    name = "train_step_curriculum"
    ndev = len(jax.devices())
    b = 2 * ndev

    def stage_batch(frames: int, seed: int = 0):
        rng = np.random.default_rng(seed)
        video = rng.integers(0, 255, (b, frames, _SIZE, _SIZE, 3),
                             dtype=np.uint8)
        text = rng.integers(0, _TINY["vocab_size"], (b, _WORDS)).astype(
            np.int32)
        return video, text, np.zeros((b,), np.float32)

    out = _jaxpr_checks(name, step, (state,) + stage_batch(_FRAMES))
    got2 = collective_counts(
        jax.make_jaxpr(step)(state, *stage_batch(2 * _FRAMES)).jaxpr)
    want = EXPECTED_COLLECTIVES[name]
    out.append(CheckResult(
        name, "collectives-stage2", got2 == want,
        "" if got2 == want else
        f"stage-2 shape traced {got2}, expected {want} — a stage "
        "boundary changed the step's communication structure"))
    if hasattr(step, "_cache_size"):
        step(state, *stage_batch(_FRAMES, 0))
        step(state, *stage_batch(_FRAMES, 1))
        n1 = step._cache_size()
        step(state, *stage_batch(2 * _FRAMES, 0))
        step(state, *stage_batch(2 * _FRAMES, 1))
        n2 = step._cache_size()
        ok = n1 == 1 and n2 == 2
        out.append(CheckResult(
            name, "one-entry-per-stage", ok,
            "" if ok else f"cache sizes {n1} -> {n2} across two stages; "
            "expected 1 -> 2 (one jit entry per stage, zero recompiles "
            "within a stage)"))
    else:
        out.append(CheckResult(name, "one-entry-per-stage", True,
                               "skipped: no _cache_size on this jax"))
    return out


def _entry_train_step_milnce_instrumented() -> list[CheckResult]:
    """ISSUE 5 tentpole invariant: the obs instrumentation is free.

    Wraps the step dispatch in a live :class:`SpanRecorder` span exactly
    the way ``train/loop.py`` does, then (a) pins the traced program's
    collectives IDENTICAL to ``train_step_milnce`` (the recorder must
    not change what the device runs), and (b) EXECUTES the instrumented
    dispatch twice under ``jax.transfer_guard("disallow")`` with
    explicitly placed inputs — a hidden ``device_get`` in the recorder
    or a smuggled implicit H2D raises here instead of stalling a real
    run — while the double-call recompile detector confirms the span
    doesn't retrace the step."""
    import jax

    from milnce_tpu.data.pipeline import shard_placer
    from milnce_tpu.obs import spans as obs_spans
    from milnce_tpu.parallel.mesh import replicate_to_mesh
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup()
    step = make_train_step(model, opt, mesh, donate=False)
    rec = obs_spans.SpanRecorder()          # ring-only, like a test run
    name = "train_step_milnce_instrumented"

    def instrumented(s, video, text, start):
        with rec.span("step"):
            return step(s, video, text, start)

    out = _jaxpr_checks(name, instrumented, (state,) + batch())
    same = (EXPECTED_COLLECTIVES[name]
            == EXPECTED_COLLECTIVES["train_step_milnce"])
    out.append(CheckResult(
        name, "identical-to-uninstrumented", same,
        "" if same else "pins diverged — instrumented and plain step "
        "must share one communication structure"))
    place = shard_placer(mesh)
    placed = replicate_to_mesh(state, mesh)

    def make_args(seed):
        video, text, start = batch(seed)
        return (placed, place(video), place(text), place(start))

    try:
        with jax.transfer_guard("disallow"):
            # execute the guarded dispatches OURSELVES: the recompile
            # helper skips execution entirely on jax builds without
            # _cache_size, and the span-count assertion below must hold
            # on those builds too
            instrumented(*make_args(0))
            instrumented(*make_args(1))
            recompile = _recompile_check(
                name, step, make_args, call=lambda _f, a: instrumented(*a))
        spans = [r for r in rec.tail() if r.get("name") == "step"]
        guard = CheckResult(
            name, "transfer-guard", len(spans) >= 2,
            "" if len(spans) >= 2 else f"only {len(spans)} step spans "
            "recorded across two guarded dispatches")
    except Exception as exc:
        recompile = None
        guard = CheckResult(
            name, "transfer-guard", False,
            f"instrumented dispatch broke the steady-state guard — the "
            f"recorder added a host sync/transfer: "
            f"{type(exc).__name__}: {exc}")
    out.append(guard)
    if recompile is not None:
        out.append(recompile)
    return out


# FSDP threshold for the 2-D entries: low enough that the tiny entry
# model actually SHARDS several kernels on the 4x2 grid (the production
# default, 65536 elements, would shard nothing at this scale and the
# entries would pin a vacuously-replicated program).
_FSDP_MIN_SIZE = 256


@functools.lru_cache(maxsize=1)
def _setup_2d():
    """The 4x2 ``(data, model)`` twin of :func:`_setup`: same tiny model
    and state, mesh reshaped, state sharded per the FSDP map and placed."""
    from milnce_tpu.config import ParallelConfig
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.parallel.sharding_map import shard_and_place_state

    model, opt, _mesh1, state, batch = _setup()
    mesh = build_mesh(ParallelConfig(model_axis="model",
                                     model_parallel_size=2))
    placement = shard_and_place_state(state, mesh, "model",
                                      min_size=_FSDP_MIN_SIZE)
    assert placement.n_sharded > 0, (
        "2-D entry setup shards nothing — the pinned program would be "
        f"pure replication (threshold {_FSDP_MIN_SIZE})")
    return model, opt, mesh, placement.specs, placement.state, batch


@functools.lru_cache(maxsize=1)
def _setup_4way():
    """The downsized elastic twin of :func:`_setup`: same tiny model and
    state, 1-D data mesh over the FIRST 4 of the host's 8 virtual
    devices — the layout ``parallel.num_devices=4`` builds for a
    drained run resuming at half capacity (milnce_tpu/elastic/)."""
    import jax
    import numpy as np

    from milnce_tpu.config import ParallelConfig
    from milnce_tpu.parallel.mesh import build_mesh

    model, opt, _mesh8, state, _batch8 = _setup()
    assert len(jax.devices()) >= 8, "4-way elastic entry needs 8 devices"
    mesh = build_mesh(ParallelConfig(), devices=jax.devices()[:4])
    b = 2 * 4                         # 2 per shard on the smaller mesh

    def batch(seed: int = 0):
        rng = np.random.default_rng(seed)
        video = rng.integers(0, 255, (b, _FRAMES, _SIZE, _SIZE, 3),
                             dtype=np.uint8)
        text = rng.integers(0, _TINY["vocab_size"], (b, _WORDS)).astype(
            np.int32)
        start = np.zeros((b,), np.float32)
        return video, text, start

    return model, opt, mesh, state, batch


def _entry_train_step_4way() -> list[CheckResult]:
    """ISSUE 20: the elastic resume layout's per-layout pins — the
    4-way step must keep the 8-way collective multiset (a topology
    change rescales shard extents, never communication structure) and
    compile exactly once (the acceptance's 0-recompiles-per-topology-
    segment, at the trace layer)."""
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup_4way()
    step = make_train_step(model, opt, mesh, donate=False)
    name = "train_step_milnce@4way"
    out = _jaxpr_checks(name, step, (state,) + batch())
    out.append(_recompile_check(name, step,
                                lambda s: (state,) + batch(s)))
    return out


def _entry_train_step_2d() -> list[CheckResult]:
    """ISSUE 6 tentpole pins: the 2-D FSDP step's all_gather /
    reduce_scatter pairs and per-leaf psums, the double-call recompile
    check, and the guarded variant costing exactly ONE extra psum (the
    model-axis finite-verdict reduction)."""
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, specs, state, batch = _setup_2d()
    step = make_train_step(model, opt, mesh, donate=False,
                           state_specs=specs, model_axis="model")
    name = "train_step_milnce_2d"
    out = _jaxpr_checks(name, step, (state,) + batch())
    out.append(_recompile_check(name, step, lambda s: (state,) + batch(s)))
    gstep = make_train_step(model, opt, mesh, donate=False,
                            finite_guard=True, state_specs=specs,
                            model_axis="model")
    out += _jaxpr_checks("train_step_milnce_2d_guarded", gstep,
                         (state,) + batch())
    return out


def _entry_grad_cache_2d() -> list[CheckResult]:
    """2-D grad-cache: pinned collectives PLUS the once-per-step
    structural pin — zero collectives inside the microbatch scans (the
    param gather runs before pass 1, the reduction after pass 2)."""
    from milnce_tpu.config import LossConfig
    from milnce_tpu.train.step import make_grad_cache_step

    model, opt, mesh, specs, state, batch = _setup_2d()
    step = make_grad_cache_step(model, opt, mesh, 2, donate=False,
                                loss_cfg=LossConfig(name="milnce"),
                                state_specs=specs, model_axis="model")
    return _jaxpr_checks("grad_cache_2d", step, (state,) + batch(),
                         scan_reduction_free=True)


def _chunked_loss_cfg():
    """The chunked-step entries' LossConfig: scan backend (the pinned
    program must not depend on the host platform) and chunk=6 on the
    16-clip entry batch — 3 chunks with a masked uneven tail, so the
    pinned program exercises the Bg % chunk != 0 path."""
    from milnce_tpu.config import LossConfig

    return LossConfig(name="milnce", milnce_impl="chunked",
                      milnce_chunk=6, milnce_backend="scan")


def _entry_train_step_milnce_chunked() -> list[CheckResult]:
    """ISSUE 12 tentpole pins: the chunked streaming MIL-NCE step keeps
    the dense step's collective multiset (2 gathers / 2 reduce_scatter
    transposes / same psums), its chunk scan is collective-free, and
    the double-call recompile check holds."""
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup()
    step = make_train_step(model, opt, mesh, donate=False,
                           loss_cfg=_chunked_loss_cfg())
    name = "train_step_milnce_chunked"
    out = _jaxpr_checks(name, step, (state,) + batch(),
                        scan_reduction_free=True)
    same = (EXPECTED_COLLECTIVES[name]
            == EXPECTED_COLLECTIVES["train_step_milnce"])
    out.append(CheckResult(
        name, "identical-to-dense", same,
        "" if same else "pins diverged — the chunked and dense steps "
        "must share one communication structure (the stream changes "
        "memory, never collectives)"))
    out.append(_recompile_check(name, step,
                                lambda s: (state,) + batch(s)))
    return out


def _entry_train_step_milnce_chunked_2d() -> list[CheckResult]:
    """The 4x2 FSDP twin: chunked loss under the 2-D step keeps the 2-D
    dense pins (gather/reduce-scatter pairs + per-leaf psums) with a
    collective-free chunk scan."""
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, specs, state, batch = _setup_2d()
    step = make_train_step(model, opt, mesh, donate=False,
                           loss_cfg=_chunked_loss_cfg(),
                           state_specs=specs, model_axis="model")
    name = "train_step_milnce_chunked_2d"
    out = _jaxpr_checks(name, step, (state,) + batch(),
                        scan_reduction_free=True)
    same = (EXPECTED_COLLECTIVES[name]
            == EXPECTED_COLLECTIVES["train_step_milnce_2d"])
    out.append(CheckResult(
        name, "identical-to-dense", same,
        "" if same else "pins diverged — the chunked and dense 2-D "
        "steps must share one communication structure"))
    out.append(_recompile_check(name, step,
                                lambda s: (state,) + batch(s)))
    return out


def _entry_milnce_chunked_dispatch() -> list[CheckResult]:
    """ISSUE 12 acceptance: ``milnce_loss_chunked(backend='auto')``
    keeps a stable compiled path across its shape-dispatch rule — the
    probed shapes straddle ``milnce_pallas.prefers_pallas`` (one fused-
    kernel shape, one scan shape) and a second same-shape call of the
    jitted value-and-grad must hit the jit cache (the sdtw_pallas_
    dispatch gate discipline)."""
    import jax
    import numpy as np

    from milnce_tpu.losses.milnce_chunked import (milnce_default_chunk,
                                                  milnce_loss_chunked)
    from milnce_tpu.ops.milnce_pallas import prefers_pallas

    name = "milnce_chunked_dispatch"
    fn = jax.jit(jax.value_and_grad(
        lambda v, t: milnce_loss_chunked(v, t, backend="auto"),
        argnums=(0, 1)))
    # (B, K, D): one shape where the auto rule picks the fused kernel
    # (lane-aligned D, VMEM-resident blocks), one where it picks the
    # scan (D off the lane grid) — re-derive with prefers_pallas if the
    # rule moves
    shapes = [(8, 2, 128), (8, 2, 16)]
    sides = set()
    for b, k, d in shapes:
        chunk = milnce_default_chunk(b, k, b)
        sides.add(prefers_pallas(b, b, k, d, chunk))
    out = [CheckResult(
        name, "dispatch-coverage", sides == {True, False},
        "" if sides == {True, False} else
        f"probe shapes no longer straddle the auto rule ({sides}) — "
        "re-pick shapes so both backends stay gated")]

    def args(b, k, d, seed):
        r = np.random.default_rng(seed)
        return (r.standard_normal((b, d)).astype(np.float32),
                r.standard_normal((b * k, d)).astype(np.float32))

    if not hasattr(fn, "_cache_size"):
        out.append(CheckResult(name, "recompile", True,
                               "skipped: no _cache_size on this jax"))
        return out
    for b, k, d in shapes:
        fn(*args(b, k, d, 0))
        fn(*args(b, k, d, 1))
    n_entries = fn._cache_size()
    out.append(CheckResult(
        name, "recompile", n_entries == len(shapes),
        "" if n_entries == len(shapes) else
        f"{n_entries} jit-cache entries for {len(shapes)} dispatch "
        "shapes called twice each — the auto backend retraces per call "
        "(unstable dispatch input)"))
    return out


def _entry_sdtw_pallas_dispatch() -> list[CheckResult]:
    """ROADMAP item 1 loose end: ``SoftDTW(backend='auto')`` must keep a
    STABLE compiled path across its shape-dispatch rule — one jit-cache
    entry per dispatch shape, the second same-shape call a cache hit
    (no recompiles), with the probed shapes covering BOTH sides of
    ``prefers_pallas`` so the gate exercises kernel and scan paths alike
    (the same gate discipline as the conv impls; BENCH_SOFTDTW.md)."""
    import jax
    import numpy as np

    from milnce_tpu.ops.softdtw import SoftDTW
    from milnce_tpu.ops.softdtw_pallas import prefers_pallas

    name = "sdtw_pallas_dispatch"
    sd = SoftDTW(gamma=0.1, dist_func="negative_dot", backend="auto")
    fn = jax.jit(jax.value_and_grad(lambda x, y: sd(x, y).sum()))
    # (B, N, M): one shape where the auto rule picks the Pallas kernel
    # (batch-on-lanes regime), one where it picks the scan (tables past
    # the VMEM budget) — re-derive with prefers_pallas if the rule moves
    shapes = [(64, 4, 4), (2, 160, 160)]
    sides = {prefers_pallas(b, n, m) for b, n, m in shapes}
    out = [CheckResult(
        name, "dispatch-coverage", sides == {True, False},
        "" if sides == {True, False} else
        f"probe shapes no longer straddle the auto rule ({sides}) — "
        "re-pick shapes so both backends stay gated")]

    def args(b, n, m, seed):
        r = np.random.default_rng(seed)
        return (r.standard_normal((b, n, 8)).astype(np.float32),
                r.standard_normal((b, m, 8)).astype(np.float32))

    if not hasattr(fn, "_cache_size"):
        out.append(CheckResult(name, "recompile", True,
                               "skipped: no _cache_size on this jax"))
        return out
    for b, n, m in shapes:
        fn(*args(b, n, m, 0))
        fn(*args(b, n, m, 1))
    n_entries = fn._cache_size()
    out.append(CheckResult(
        name, "recompile", n_entries == len(shapes),
        "" if n_entries == len(shapes) else
        f"{n_entries} jit-cache entries for {len(shapes)} dispatch "
        "shapes called twice each — the auto backend retraces per call "
        "(unstable dispatch input)"))
    return out


def _entry_train_step_sdtw3() -> list[CheckResult]:
    from milnce_tpu.config import LossConfig
    from milnce_tpu.train.step import make_train_step

    model, opt, mesh, state, batch = _setup()
    step = make_train_step(model, opt, mesh, donate=False,
                           loss_cfg=LossConfig(name="sdtw_3",
                                               sdtw_backend="scan"))
    return _jaxpr_checks("train_step_sdtw3", step, (state,) + batch())


def _entry_grad_cache_step() -> list[CheckResult]:
    from milnce_tpu.config import LossConfig
    from milnce_tpu.train.step import make_grad_cache_step

    model, opt, mesh, state, batch = _setup()
    step = make_grad_cache_step(model, opt, mesh, 2, donate=False,
                                loss_cfg=LossConfig(name="milnce"))
    return _jaxpr_checks("grad_cache_step_milnce", step, (state,) + batch(),
                         scan_reduction_free=True)


def _entry_retrieval_embed() -> list[CheckResult]:
    from milnce_tpu.train.step import (make_text_embed_fn,
                                       make_video_embed_fn)

    model, _opt, mesh, state, batch = _setup()
    varz = {"params": state.params, "batch_stats": state.batch_stats}
    vfn = make_video_embed_fn(model, mesh)
    tfn = make_text_embed_fn(model, mesh)
    out = _jaxpr_checks("video_embed", vfn, (varz, batch()[0]))
    out += _jaxpr_checks("text_embed", tfn, (varz, batch()[1]))
    out.append(_recompile_check("video_embed", vfn,
                                lambda s: (varz, batch(s)[0])))
    out.append(_recompile_check("text_embed", tfn,
                                lambda s: (varz, batch(s)[1])))
    return out


def _entry_softdtw_scan() -> list[CheckResult]:
    import jax
    import numpy as np

    from milnce_tpu.ops.softdtw import softdtw_scan

    name = "softdtw_scan_grad"

    def value(D, gamma):
        return softdtw_scan(D, gamma).sum()

    def make_D(seed):
        return np.abs(np.random.default_rng(seed).standard_normal(
            (4, 9, 7))).astype(np.float32)

    grad_fn = jax.jit(jax.value_and_grad(value))
    out = _jaxpr_checks(name, grad_fn, (make_D(0), np.float32(0.5)))
    out.append(_recompile_check(
        name, grad_fn, lambda s: (make_D(s), np.float32(0.5))))
    return out


def _entry_param_treedef() -> list[CheckResult]:
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import CONV_IMPLS, ModelConfig
    from milnce_tpu.models.build import build_model

    shapes = {}
    for impl in CONV_IMPLS:
        m = build_model(ModelConfig(conv_impl=impl, **_TINY))
        shapes[impl] = jax.eval_shape(
            m.init, jax.random.PRNGKey(0),
            jnp.zeros((2, _FRAMES, _SIZE, _SIZE, 3), jnp.float32),
            jnp.zeros((2, _WORDS), jnp.int32))
    ref_impl = CONV_IMPLS[0]
    ref = shapes[ref_impl]
    ref_td = jax.tree_util.tree_structure(ref)
    ref_leaves = jax.tree_util.tree_leaves(ref)
    out = []
    for impl in CONV_IMPLS[1:]:
        td = jax.tree_util.tree_structure(shapes[impl])
        leaves = jax.tree_util.tree_leaves(shapes[impl])
        same = (td == ref_td and len(leaves) == len(ref_leaves) and all(
            a.shape == b.shape and a.dtype == b.dtype
            for a, b in zip(leaves, ref_leaves)))
        out.append(CheckResult(
            "param_treedef", f"{ref_impl}-vs-{impl}", same,
            "" if same else "param trees diverged — the per-stage impl map "
            "and checkpoint portability both require identical layouts"))
    return out


def _entry_serve_embed_ladder() -> list[CheckResult]:
    """The serving engine's no-recompile-across-the-bucket-ladder gate
    (ISSUE 4 acceptance): after the startup warmup sweep, a FULL sweep of
    both embed entries over every bucket — including non-bucket request
    sizes that pad up — must create zero new jit-cache entries.  Also
    pins the entries' jaxprs collective-free at the top bucket."""
    import numpy as np

    from milnce_tpu.serving.engine import InferenceEngine

    model, _opt, mesh, state, _batch = _setup()
    varz = {"params": state.params, "batch_stats": state.batch_stats}
    import jax

    ndev = len(jax.devices())
    engine = InferenceEngine(model, varz, mesh, text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=2 * ndev)   # 2-rung ladder
    rng = np.random.default_rng(0)
    sizes = list(engine.buckets) + [1, engine.buckets[0] + 1]  # pad paths
    for n in sizes:
        engine.embed_text(rng.integers(
            0, _TINY["vocab_size"], (n, _WORDS)).astype(np.int32))
        engine.embed_video(rng.integers(
            0, 255, (n, _FRAMES, _SIZE, _SIZE, 3), dtype=np.uint8))
    n_re = engine.recompiles()
    out = [CheckResult(
        "serve_embed_ladder", "recompile", n_re == 0,
        "" if n_re == 0 else f"{n_re} jit-cache entries appeared AFTER the "
        "warmup bucket sweep — a request shape is escaping the ladder "
        "(weak-type drift, or a pad path missing)")]
    b = engine.buckets[-1]
    entries = engine.jit_entries()      # the supported analysis surface
    out += _jaxpr_checks("serve_text_embed", entries["text"],
                         (varz, np.zeros((b, _WORDS), np.int32)))
    out += _jaxpr_checks("serve_video_embed", entries["video"],
                         (varz, np.zeros((b, _FRAMES, _SIZE, _SIZE, 3),
                                         np.uint8)))
    return out


def _entry_serve_pool_embed() -> list[CheckResult]:
    """Pooled serving (ISSUE 10 acceptance): a 2-replica pool — single-
    device engines on the CPU backend, each with its own dispatch lock —
    sweeps the FULL bucket ladder (every rung plus pad-path sizes), both
    per-replica and routed through the pool, and must create ZERO
    jit-cache entries after warmup on EVERY replica.  Also pins each
    replica's embed jaxprs collective-free (a one-device shard_map ships
    nothing)."""
    import numpy as np

    from milnce_tpu.serving.pool import ReplicaPool

    model, _opt, _mesh, state, _batch = _setup()
    varz = {"params": state.params, "batch_stats": state.batch_stats}
    pool = ReplicaPool.build(model, varz, 2, text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=4, min_bucket=2,
                             probe_interval_s=60.0)
    try:
        rng = np.random.default_rng(0)
        sizes = list(pool.buckets) + [1, pool.buckets[0] + 1]  # pad paths

        def t_rows(n):
            return rng.integers(0, _TINY["vocab_size"],
                                (n, _WORDS)).astype(np.int32)

        def v_rows(n):
            return rng.integers(0, 255, (n, _FRAMES, _SIZE, _SIZE, 3),
                                dtype=np.uint8)

        for r in pool.replicas:           # every replica, every rung
            for n in sizes:
                r.engine.embed_text(t_rows(n))
                r.engine.embed_video(v_rows(n))
        for n in sizes:                   # and routed through the pool
            pool.embed_text(t_rows(n))
            pool.embed_video(v_rows(n))
        out = []
        for r in pool.replicas:
            n_re = r.engine.recompiles()
            out.append(CheckResult(
                "serve_pool_embed", f"recompile-replica{r.rid}", n_re == 0,
                "" if n_re == 0 else f"{n_re} jit-cache entries appeared "
                f"AFTER the warmup sweep on replica {r.rid} — a request "
                "shape is escaping the replica's ladder"))
        b = pool.buckets[-1]
        entries = pool.replicas[0].engine.jit_entries()
        out += _jaxpr_checks("serve_pool_text_embed", entries["text"],
                             (varz, np.zeros((b, _WORDS), np.int32)))
        out += _jaxpr_checks("serve_pool_video_embed", entries["video"],
                             (varz, np.zeros((b, _FRAMES, _SIZE, _SIZE, 3),
                                             np.uint8)))
        return out
    finally:
        pool.close()


def _entry_serve_quant_embed_ladder() -> list[CheckResult]:
    """Quantized edge engine (ISSUE 19): the int8 tower behind the SAME
    bucket ladder — quantize the tiny model per the readiness rule, run
    the full post-warmup sweep (every rung plus pad-path sizes), and
    require zero new jit-cache entries; then pin both entries' jaxprs
    collective-free.  The in-jit dequantize must change neither the
    recompile story nor the communication structure — that is what makes
    a quantized export a drop-in replica class in a mixed pool."""
    import numpy as np

    from milnce_tpu.quant.quantize import (QuantizedModel,
                                           quantize_variables)
    from milnce_tpu.serving.engine import InferenceEngine

    model, _opt, mesh, state, _batch = _setup()
    varz = {"params": state.params, "batch_stats": state.batch_stats}
    qvarz = quantize_variables(varz)
    qmodel = QuantizedModel(model)
    import jax

    ndev = len(jax.devices())
    engine = InferenceEngine(qmodel, qvarz, mesh, text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=2 * ndev)   # 2-rung ladder
    rng = np.random.default_rng(0)
    sizes = list(engine.buckets) + [1, engine.buckets[0] + 1]  # pad paths
    for n in sizes:
        engine.embed_text(rng.integers(
            0, _TINY["vocab_size"], (n, _WORDS)).astype(np.int32))
        engine.embed_video(rng.integers(
            0, 255, (n, _FRAMES, _SIZE, _SIZE, 3), dtype=np.uint8))
    n_re = engine.recompiles()
    out = [CheckResult(
        "serve_quant_embed_ladder", "recompile", n_re == 0,
        "" if n_re == 0 else f"{n_re} jit-cache entries appeared AFTER "
        "the warmup bucket sweep on the QUANTIZED engine — the dequant "
        "prologue is destabilizing the jit cache (scales tree drift?)")]
    b = engine.buckets[-1]
    entries = engine.jit_entries()      # the supported analysis surface
    out += _jaxpr_checks("serve_quant_text_embed", entries["text"],
                         (qvarz, np.zeros((b, _WORDS), np.int32)))
    out += _jaxpr_checks("serve_quant_video_embed", entries["video"],
                         (qvarz, np.zeros((b, _FRAMES, _SIZE, _SIZE, 3),
                                          np.uint8)))
    return out


def _entry_serve_index_topk() -> list[CheckResult]:
    """Sharded retrieval: exactly 2 all_gathers (the (Q, k) score and
    index candidate lists), no f64, and the double-call recompile check
    on the jitted top-k program."""
    import jax
    import numpy as np

    from milnce_tpu.serving.index import DeviceRetrievalIndex

    _model, _opt, mesh, _state, _batch = _setup()
    ndev = len(jax.devices())
    rng = np.random.default_rng(0)
    corpus = rng.standard_normal((3 * ndev - 2, _TINY["embedding_dim"]))
    index = DeviceRetrievalIndex(mesh, corpus.astype(np.float32), k=3,
                                 query_buckets=(ndev,))
    name = "serve_index_topk"
    fn, operands = index.topk_program()  # the supported analysis surface

    def make_q(seed):
        # committed to the index's replicated query sharding — an
        # uncommitted host array would key a SEPARATE jit-cache entry
        # and false-positive the recompile detector
        r = np.random.default_rng(seed)
        return jax.device_put(
            r.standard_normal((ndev, index.dim)).astype(np.float32),
            index.query_sharding)

    out = _jaxpr_checks(name, fn, operands + (make_q(0),))
    out.append(_recompile_check(
        name, fn, lambda s: operands + (make_q(s),)))
    return out


def _entry_serve_live_index() -> list[CheckResult]:
    """Generation-swapped live index (ISSUE 14): the SAME pinned
    program as ``serve_index_topk`` (2 all_gathers, no f64), plus the
    tentpole's recompile story — two ingest+swap cycles INSIDE a corpus
    rung followed by queries must leave the query path's jit cache
    untouched (``recompiles() == 0``), because swapped generations at
    one rung are shape-identical."""
    import jax
    import numpy as np

    from milnce_tpu.serving.live_index import LiveRetrievalIndex

    _model, _opt, mesh, _state, _batch = _setup()
    ndev = len(jax.devices())
    rng = np.random.default_rng(0)
    dim = _TINY["embedding_dim"]
    corpus = rng.standard_normal((3 * ndev - 2, dim)).astype(np.float32)
    index = LiveRetrievalIndex(mesh, corpus, k=3, query_buckets=(ndev,))
    name = "serve_live_index"
    try:
        q = rng.standard_normal((ndev, dim)).astype(np.float32)
        index.topk(q)
        for _ in range(2):              # two swaps inside the boot rung
            index.add(rng.standard_normal((2, dim)).astype(np.float32))
            if not index.flush(30.0):
                return [CheckResult(name, "swap", False,
                                    "ingest flush timed out — the "
                                    "builder never published")]
            index.topk(q)
        n_re = index.recompiles()
        out = [CheckResult(
            name, "recompile-across-swaps", n_re == 0,
            "" if n_re == 0 else f"{n_re} jit-cache entries appeared on "
            "the QUERY path across generation swaps — a swap is leaking "
            "a compile (rung rule broken, or the builder stopped "
            "warming new shapes)")]
        fn, operands = index.topk_program()
        qd = jax.device_put(q, index.query_sharding)
        out += _jaxpr_checks(name, fn, operands + (qd,))
        return out
    finally:
        index.close()


ENTRY_POINTS = {
    "train_step_milnce": _entry_train_step_milnce,
    "train_step_milnce_guarded": _entry_train_step_milnce_guarded,
    "train_step_milnce_instrumented": _entry_train_step_milnce_instrumented,
    "train_step_curriculum": _entry_train_step_curriculum,
    "train_step_sdtw3": _entry_train_step_sdtw3,
    "grad_cache_step_milnce": _entry_grad_cache_step,
    "train_step_milnce@4way": _entry_train_step_4way,
    "train_step_milnce_2d": _entry_train_step_2d,
    "grad_cache_2d": _entry_grad_cache_2d,
    "train_step_milnce_chunked": _entry_train_step_milnce_chunked,
    "train_step_milnce_chunked_2d": _entry_train_step_milnce_chunked_2d,
    "milnce_chunked_dispatch": _entry_milnce_chunked_dispatch,
    "sdtw_pallas_dispatch": _entry_sdtw_pallas_dispatch,
    "retrieval_embed": _entry_retrieval_embed,
    "softdtw_scan": _entry_softdtw_scan,
    "param_treedef": _entry_param_treedef,
    "serve_embed_ladder": _entry_serve_embed_ladder,
    "serve_quant_embed_ladder": _entry_serve_quant_embed_ladder,
    "serve_index_topk": _entry_serve_index_topk,
    "serve_pool_embed": _entry_serve_pool_embed,
    "serve_live_index": _entry_serve_live_index,
}


def run_trace_invariants(entries=None) -> list[CheckResult]:
    """Run the invariant checks; entries=None runs all registered ones.
    Builder exceptions become failing results, never crashes — the CLI
    must always finish its report."""
    results: list[CheckResult] = []
    for name in (entries or ENTRY_POINTS):
        try:
            results.extend(ENTRY_POINTS[name]())
        except Exception as exc:                    # pragma: no cover
            results.append(CheckResult(name, "build", False,
                                       f"{type(exc).__name__}: {exc}"))
    return results
