"""LINT.md emission for graftlint.

Follows the repo's report-header convention (tests/test_suite_hygiene.py):
every auto-written artifact opens by naming its generator — the header
string "(auto-written by scripts/graft_lint.py)" below is what the
hygiene lint pins.  The report's job is not just pass/fail: the
suppression table is the living registry of every audited hot-path
exception, with its reason, so "what syncs are we allowing and why" has
one answer.
"""

from __future__ import annotations

from milnce_tpu.analysis.astlint import Finding
from milnce_tpu.analysis.rules import RULES

HEADER = ("<!-- (auto-written by scripts/graft_lint.py — do not hand-edit; "
          "regenerate with `python scripts/graft_lint.py`) -->\n")


def render_report(findings: list[Finding], trace_results=None,
                  paths=None, lock_graph=None, mem_results=None,
                  numerics_results=None) -> str:
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    lines = [HEADER, "# graftlint report", ""]
    if paths:
        lines.append(f"Scope: `{'`, `'.join(paths)}`")
        lines.append("")

    lines.append("## Pass 1 + Pass 3 — AST lint (rules + concurrency)")
    lines.append("")
    lines.append(f"- findings: **{len(active)}**")
    lines.append(f"- audited suppressions in force: {len(suppressed)}")
    lines.append("")
    if active:
        lines.append("| where | rule | finding |")
        lines.append("|---|---|---|")
        for f in active:
            lines.append(f"| `{f.path}:{f.line}` | {f.rule.id} "
                         f"({f.rule.name}) | {f.message} |")
        lines.append("")
    if suppressed:
        lines.append("### Audited exceptions (inline suppressions)")
        lines.append("")
        lines.append("| where | rule | reason |")
        lines.append("|---|---|---|")
        for f in suppressed:
            lines.append(f"| `{f.path}:{f.line}` | {f.rule.id} "
                         f"({f.rule.name}) | {f.suppress_reason} |")
        lines.append("")

    lines.append("## Pass 3 — lock-order graph")
    lines.append("")
    if lock_graph is None:
        lines.append("(skipped — run without `--no-concurrency` for the "
                     "lock-discipline pass)")
    else:
        edges = sorted((u, v, site) for (u, v), site
                       in lock_graph.edges.items())
        lines.append(f"- locks in the acquisition graph: "
                     f"{len(lock_graph.locks)}; ordering edges: "
                     f"{len(edges)}; cycles fail as GL011 findings above")
        if edges:
            lines.append("")
            lines.append("| held | acquired | first site |")
            lines.append("|---|---|---|")
            for u, v, (path, line) in edges:
                lines.append(f"| `{u}` | `{v}` | `{path}:{line}` |")
    lines.append("")

    lines.append("## Pass 2 — trace invariants")
    lines.append("")
    if trace_results is None:
        lines.append("(skipped — run without `--no-trace` for jaxpr-level "
                     "checks)")
    else:
        bad = [r for r in trace_results if not r.ok]
        lines.append(f"- checks: {len(trace_results)}, failing: "
                     f"**{len(bad)}**")
        lines.append("")
        lines.append("| entry | check | status |")
        lines.append("|---|---|---|")
        for r in trace_results:
            status = "ok" if r.ok else f"**FAIL** — {r.detail}"
            lines.append(f"| {r.entry} | {r.check} | {status} |")
    lines.append("")

    lines.append("## Pass 4 — static HBM planner (GL013-GL015)")
    lines.append("")
    if mem_results is None:
        lines.append("(skipped — run without `--no-memplan`/`--no-trace` "
                     "for the per-entry peak-byte gates; full plan table: "
                     "MEMPLAN.md via `python scripts/mem_plan.py`)")
    else:
        bad = [r for r in mem_results if not r.ok]
        lines.append(f"- checks: {len(mem_results)}, failing: "
                     f"**{len(bad)}** (per-entry peak table + "
                     "contributors: MEMPLAN.md)")
        lines.append("")
        lines.append("| entry | check | status |")
        lines.append("|---|---|---|")
        for r in mem_results:
            status = "ok" if r.ok else f"**FAIL** — {r.detail}"
            lines.append(f"| {r.entry} | {r.check} | {status} |")
    lines.append("")

    lines.append("## Pass 5 — numerics (GL016-GL018)")
    lines.append("")
    if numerics_results is None:
        lines.append("(skipped — run without `--no-numerics`/`--no-trace` "
                     "for the dtype census / cast-inventory / "
                     "f32-residency gates; full per-entry tables: "
                     "NUMERICS.md via `python scripts/precision_audit.py`)")
    else:
        bad = [r for r in numerics_results if not r.ok]
        lines.append(f"- checks: {len(numerics_results)}, failing: "
                     f"**{len(bad)}** (per-entry census + cast table + "
                     "bf16 what-if: NUMERICS.md)")
        lines.append("")
        lines.append("| entry | check | status |")
        lines.append("|---|---|---|")
        for r in numerics_results:
            status = "ok" if r.ok else f"**FAIL** — {r.detail}"
            lines.append(f"| {r.entry} | {r.check} | {status} |")
    lines.append("")

    lines.append("## Rules")
    lines.append("")
    lines.append("| id | name | guards against |")
    lines.append("|---|---|---|")
    for rule in RULES.values():
        lines.append(f"| {rule.id} | {rule.name} | {rule.summary} |")
    lines.append("")
    lines.append("Full rationale, examples and the suppression syntax: "
                 "ANALYSIS.md.")
    lines.append("")
    return "\n".join(lines)
