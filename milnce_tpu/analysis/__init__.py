"""graftlint: JAX-aware static analysis + trace invariants for the hot path.

Two complementary passes guard the throughput story (PR 1 spent ~1.5k LoC
winning back stem MFU; nothing else stops a later change from silently
reintroducing per-step host syncs, f64 drift, recompilation storms or
undonated buffers):

- :mod:`milnce_tpu.analysis.astlint` — pure-AST lint (no jax import) with
  JAX-specific rules (:mod:`milnce_tpu.analysis.rules`) and an inline
  ``# graftlint: disable=RULE(reason)`` suppression syntax, so audited
  exceptions stay documented instead of silenced (stale suppressions are
  themselves findings);
- :mod:`milnce_tpu.analysis.trace_invariants` — traces the registered
  entry points (train step variants, soft-DTW ops, eval retrieval) under
  a CPU mesh and asserts jaxpr-level invariants: no float64 anywhere,
  the expected collective count per step, identical param treedefs
  across conv impls, and a double-call recompile detector;
- :mod:`milnce_tpu.analysis.concurrency` — Pass 3a: lock-discipline lint
  for the serving/obs thread mesh (GL010 unguarded shared state, GL011
  lock-order cycles, GL012 blocking under a lock), with ``# guarded-by:``
  annotations and an inferred per-class guard map (SERVING.md "Threading
  model");
- :mod:`milnce_tpu.analysis.lockrt` — Pass 3b: the runtime twin, an
  opt-in order-checking ``SanitizedLock`` (``MILNCE_LOCK_SANITIZE=1``)
  that raises on ABBA cycles, self-deadlocks and blown hold budgets.

CLI: ``scripts/graft_lint.py`` (writes LINT.md; ``--check`` exits
nonzero on findings; ``--no-concurrency`` skips Pass 3).  Rule
catalogue: ANALYSIS.md.
"""

from milnce_tpu.analysis.rules import RULES, Rule  # noqa: F401
