"""graftlint Pass 5: numerics — static precision-flow analysis.

The whole perf story runs on bf16 (BENCH_NOTES.md headline: batch 256,
bf16) and ROADMAP item 5 wants an int8 edge tier — but precision
placement in this repo was, until this pass, an emergent property:
Pass 4's GL015 named the f32 BatchNorm intermediates as the top HBM
contributor *on the bf16 model* and nobody could say whether that f32
residency was load-bearing or accidental.  Pass 5 makes dtype placement
a STATIC, pinned property, the same "pin it, then change it
consciously" treatment Passes 2/4 gave collectives and bytes:

- **dtype census**: every registered entry's closed jaxpr is walked and
  its buffer bytes are bucketed by dtype (entry args + every primitive
  output, per level; ``call``/``shard_map`` results are counted once at
  the level that materializes them).
- **cast inventory**: every ``convert_element_type`` is NAMED by its
  route and location — ``"f32->bf16 @ state/params/conv1/kernel"`` for
  a cast of an entry arg, ``"bf16->f32 @ dot_general"`` for a cast of
  an intermediate (by producing primitive, GL015-style).  An appearing
  or vanishing cast is a readable diff, not a mystery loss-curve
  divergence.
- **f32-residency set**: the labels that must stay f32 — BatchNorm
  statistics (``batch_stats``), optimizer moments (``mu``/``nu``) and
  the log-domain accumulators (``log``/``log1p`` operands, i.e. the
  logsumexp/loss chain) — audited against the traced program.

Three rules ride on the walk (catalogue: analysis/rules.py):

- **GL016 low-precision-accumulation**: an add-based reduction
  (``reduce_sum``), ``dot_general`` accumulation or cross-replica
  ``psum`` whose accumulator dtype is bf16/f16 at reduction extent
  >= ``GL016_MIN_EXTENT`` — the missing ``preferred_element_type=f32``
  detector.  ``psum`` fires at ANY extent: its true extent is the pod's
  replica count, which the jaxpr doesn't carry and which exceeds any
  sensible threshold at real scale.
- **GL017 unstabilized-exp-domain**: the jaxpr half — every ``exp``
  whose operand's producer chain (through shape/dtype/scale
  passthroughs) does not reach a subtraction or a bounded-domain op.
  The AST half lives in astlint (pattern over ``losses/``, inline-
  suppressible); HERE deliberately-unguarded sites are registered
  per entry in ``EXPECTED_UNGUARDED_EXP`` — entry-level discipline.
- **GL018 dtype-boundary-drift**: the census and the cast inventory are
  pinned per entry (``EXPECTED_DTYPE_CENSUS`` / ``EXPECTED_CASTS``)
  exactly like collective multisets; drift fails tier-1 with a named
  diff and the CLI prints the paste-ready re-pin dict.

Known approximations (documented in ANALYSIS.md): loop bodies are
censused once (a scan's per-iteration buffers are one program buffer);
``add``-chain accumulations inside scan carries are not GL016 sites
(the registered entries reduce via ``reduce_sum``/``psum``); guard
detection follows the FIRST operand through passthrough ops, so a
guard arriving via the second operand of a ``mul`` is conservatively
treated as present only if the chain bottoms out at a boundary.

Everything runs on the hermetic 8-virtual-CPU-device mesh; jax imports
live inside functions so astlint stays importable without jax.
``scripts/precision_audit.py`` is the CLI (NUMERICS.md, ``--check``,
``--what-if --dtype bf16``, and the quantization-readiness report over
an export artifact — the ROADMAP item 5 feed).
"""

from __future__ import annotations

import functools
import hashlib
import json
from dataclasses import dataclass, field

from milnce_tpu.analysis.trace_invariants import CheckResult

# GL016 reduction-extent floor: summing N same-sign bf16 terms loses
# ~log2(N) of the 8 mantissa bits, so 64 terms (6 bits) is where the
# fraction is mostly gone.  Below it, the finding costs more attention
# than the ulps cost accuracy.
GL016_MIN_EXTENT = 64

# accumulator dtypes GL016 objects to (short names, see _short)
LOW_PRECISION = ("bf16", "f16")

_DTYPE_SHORT = {
    "float64": "f64", "float32": "f32", "bfloat16": "bf16",
    "float16": "f16", "int64": "i64", "int32": "i32", "int16": "i16",
    "int8": "i8", "uint64": "u64", "uint32": "u32", "uint8": "u8",
    "bool": "bool",
}


def _short(dtype) -> str:
    s = str(dtype)
    return _DTYPE_SHORT.get(s, s)


@dataclass
class NumericsAudit:
    """Per-entry result of the dtype-flow walk."""
    entry: str = ""
    census: dict = field(default_factory=dict)   # short dtype -> bytes
    casts: dict = field(default_factory=dict)    # "src->dst @ loc" -> n
    gl016_sites: tuple = ()                      # low-precision accums
    exp_sites: tuple = ()                        # unguarded exp (jaxpr)
    f32_residency: tuple = ()                    # labels audited f32
    residency_violations: tuple = ()             # must-be-f32 that isn't
    mesh: str = ""

    def census_hash(self) -> str:
        """12-hex digest over (census, casts) — the bench-record /
        obs_report cross-precision identity (a dtype-structure change
        shows as a differing hash, like the sharding-map hash)."""
        blob = json.dumps({"census": self.census, "casts": self.casts},
                          sort_keys=True)
        return hashlib.sha256(blob.encode()).hexdigest()[:12]


# --------------------------------------------------------------------------
# the dtype-flow walk
# --------------------------------------------------------------------------

# ops through which a max-subtraction guard still reaches the exp:
# shape/dtype changes, sign/scale changes.  The chain follows the FIRST
# operand (documented approximation).
_GUARD_PASSTHROUGH = frozenset({
    "convert_element_type", "broadcast_in_dim", "reshape", "transpose",
    "squeeze", "expand_dims", "copy", "stop_gradient", "slice",
    "dynamic_slice", "neg", "abs", "mul", "div",
})

# producers whose output domain is bounded above — exp of these cannot
# overflow (clamp/min/logistic/tanh and the max-trick's own sub)
_GUARD_TERMINAL = frozenset({
    "sub", "min", "reduce_min", "clamp", "logistic", "tanh", "erf",
    "log", "log1p",
})


def _exp_guarded(v, producer, depth: int = 12) -> bool:
    """Does ``v``'s producer chain show a max-subtraction (or bounded
    domain) before ``depth`` hops?  Chains that bottom out at a jaxpr
    boundary (entry arg, nest invar, literal) are treated guarded —
    the guard may live one level up, and a boundary false-positive
    would punish every scan-carried accumulator."""
    from milnce_tpu.analysis.memplan import _is_literal

    for _ in range(depth):
        if _is_literal(v):
            return True
        eqn = producer.get(v)
        if eqn is None:
            return True
        name = eqn.primitive.name
        if name in _GUARD_TERMINAL:
            return True
        if name in _GUARD_PASSTHROUGH:
            v = eqn.invars[0]
            continue
        return False
    return False


def _gl016_eqn(eqn) -> list:
    """Low-precision-accumulation sites for one equation."""
    from milnce_tpu.analysis.memplan import _is_dropvar

    name = eqn.primitive.name
    sites = []
    if name == "reduce_sum":
        op = eqn.invars[0]
        if _short(op.aval.dtype) in LOW_PRECISION:
            extent = 1
            for a in eqn.params.get("axes", ()):
                extent *= int(op.aval.shape[a])
            if extent >= GL016_MIN_EXTENT:
                sites.append(
                    f"reduce_sum {op.aval.str_short()} extent {extent} — "
                    f"{_short(op.aval.dtype)} accumulator")
    elif name == "dot_general":
        out = eqn.outvars[0]
        if not _is_dropvar(out) and _short(out.aval.dtype) in LOW_PRECISION:
            (lhs_c, _rhs_c), _batch = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            extent = 1
            for d in lhs_c:
                extent *= int(lhs.shape[d])
            if extent >= GL016_MIN_EXTENT:
                sites.append(
                    f"dot_general {out.aval.str_short()} contraction "
                    f"{extent} — accumulates in {_short(out.aval.dtype)} "
                    "(preferred_element_type=f32 missing)")
    elif name == "psum":
        for op in eqn.invars:
            aval = getattr(op, "aval", None)
            if aval is not None and _short(aval.dtype) in LOW_PRECISION:
                sites.append(
                    f"psum {aval.str_short()} — low-precision "
                    "cross-replica accumulator (extent = replica count)")
    return sites


def _audit_level(jaxpr, lab, audit_state) -> None:
    """One jaxpr level of the walk.  ``lab`` maps this level's vars to
    names (entry-arg tree paths zipped through ``call``/``shard_map``
    boundaries); intermediates are named by producing primitive."""
    from milnce_tpu.analysis.memplan import (_is_dropvar, _is_literal,
                                             _nested, _open, aval_bytes)

    census, casts, gl016, exps, resid_bad = audit_state
    producer: dict = {}
    for eqn in jaxpr.eqns:
        kind, bodies = _nested(eqn)
        # census: primitive outputs materialize at this level; a call /
        # shard_map result IS its body's output buffer — count it once,
        # inside (loop/branch outputs are fresh stacked buffers: count)
        if kind not in ("call", "shard_map"):
            for v in eqn.outvars:
                if _is_dropvar(v):
                    continue
                key = _short(v.aval.dtype)
                census[key] = census.get(key, 0) + aval_bytes(v.aval)
        # cast inventory
        if eqn.primitive.name == "convert_element_type":
            src = eqn.invars[0]
            dst = _short(eqn.params.get("new_dtype",
                                        eqn.outvars[0].aval.dtype))
            if _is_literal(src):
                loc, sdt = "literal", _short(src.aval.dtype)
            elif src in lab:
                loc, sdt = lab[src], _short(src.aval.dtype)
            elif src in producer:
                loc = producer[src].primitive.name
                sdt = _short(src.aval.dtype)
            else:
                loc, sdt = "nest-boundary", _short(src.aval.dtype)
            key = f"{sdt}->{dst} @ {loc}"
            casts[key] = casts.get(key, 0) + 1
        # GL016 low-precision accumulation
        gl016.extend(_gl016_eqn(eqn))
        # GL017 jaxpr half: unguarded exp
        if eqn.primitive.name == "exp":
            op = eqn.invars[0]
            if not _exp_guarded(op, producer):
                via = (lab.get(op) or
                       (producer[op].primitive.name if op in producer
                        else "boundary"))
                exps.append(f"exp {op.aval.str_short()} of {via}")
        # f32-residency: the log-domain accumulators (logsumexp / loss
        # chain) must not run in a low-precision dtype
        if eqn.primitive.name in ("log", "log1p"):
            op = eqn.invars[0]
            aval = getattr(op, "aval", None)
            if aval is not None and _short(aval.dtype) in LOW_PRECISION:
                resid_bad.append(
                    f"{eqn.primitive.name} operand {aval.str_short()} — "
                    "log-domain accumulator demoted below f32")
        for v in eqn.outvars:
            if not _is_dropvar(v):
                producer[v] = eqn
        # recurse, threading labels through call-kind boundaries
        for body in bodies:
            bj = _open(body)
            sub_lab: dict = {}
            if (kind in ("call", "shard_map")
                    and len(bj.invars) == len(eqn.invars)):
                for bv, ov in zip(bj.invars, eqn.invars):
                    if not _is_literal(ov) and ov in lab:
                        sub_lab[bv] = lab[ov]
            _audit_level(bj, sub_lab, audit_state)


# arg-leaf label substrings whose buffers belong to the f32-residency
# set: BatchNorm statistics and Adam moments.  The paper's own recipe
# (and PERF.md's "Batch cliffs" finding) keeps these f32 on the bf16
# model — this audit is what makes that deliberate.
_RESIDENT_MARKERS = ("batch_stats", "/mu/", "/nu/")


def audit_jaxpr(closed_jaxpr, *, labels=None, entry="") -> NumericsAudit:
    """Dtype-flow walk of an entry's closed jaxpr -> NumericsAudit."""
    from milnce_tpu.analysis.memplan import _open, aval_bytes

    jaxpr = _open(closed_jaxpr)
    n = len(jaxpr.invars)
    labels = list(labels) if labels is not None else [f"arg{i}"
                                                      for i in range(n)]
    census: dict = {}
    casts: dict = {}
    gl016: list = []
    exps: list = []
    resid_bad: list = []
    resident: list = []
    lab = dict(zip(jaxpr.invars, labels))
    for v, label in zip(jaxpr.invars, labels):
        key = _short(v.aval.dtype)
        census[key] = census.get(key, 0) + aval_bytes(v.aval)
        if any(m in label for m in _RESIDENT_MARKERS):
            resident.append(label)
            if _short(v.aval.dtype) != "f32":
                resid_bad.append(
                    f"{label} is {_short(v.aval.dtype)} — BN stats and "
                    "optimizer moments must stay f32")
    for v in jaxpr.constvars:
        key = _short(v.aval.dtype)
        census[key] = census.get(key, 0) + aval_bytes(v.aval)
    _audit_level(jaxpr, lab, (census, casts, gl016, exps, resid_bad))
    return NumericsAudit(entry=entry, census=census, casts=casts,
                         gl016_sites=tuple(gl016), exp_sites=tuple(exps),
                         f32_residency=tuple(resident),
                         residency_violations=tuple(resid_bad))


def audit_fn(fn, args, *, argnames=None, entry="") -> NumericsAudit:
    """Trace ``fn(*args)`` and audit — the bench-record hook (every
    record carries ``dtype_census_hash``) and the planted-fixture path."""
    import jax

    from milnce_tpu.analysis.memplan import arg_leaf_labels

    closed = jax.make_jaxpr(fn)(*args)
    labels = (arg_leaf_labels(args, argnames) if argnames is not None
              else None)
    return audit_jaxpr(closed, labels=labels, entry=entry)


# --------------------------------------------------------------------------
# registered entries + pins (the Pass 5 gate)
# --------------------------------------------------------------------------

def entry_names() -> tuple:
    """Every audited entry: the Pass 4 registry (same traced programs,
    shared cache — zero extra tracing) plus the curriculum stage-2
    shape, which memplan doesn't price but whose dtype boundaries must
    match the stage-1 program's structurally."""
    from milnce_tpu.analysis.memplan import _entries

    return tuple(_entries()) + ("train_step_curriculum@s1",)


@functools.lru_cache(maxsize=None)
def _numerics_traced(name: str):
    """(closed_jaxpr, labels, mesh) for one audited entry."""
    from milnce_tpu.analysis.memplan import (_STEP_ARGNAMES, _entries,
                                             _traced_entry,
                                             arg_leaf_labels)

    if name == "train_step_curriculum@s1":
        import jax
        import numpy as np

        from milnce_tpu.analysis.trace_invariants import (_FRAMES, _SIZE,
                                                          _TINY, _WORDS,
                                                          _setup)
        from milnce_tpu.train.step import make_train_step

        model, opt, mesh, state, _batch = _setup()
        step = make_train_step(model, opt, mesh, donate=False)
        b = 2 * len(jax.devices())
        rng = np.random.default_rng(0)
        args = (state,
                rng.integers(0, 255, (b, 2 * _FRAMES, _SIZE, _SIZE, 3),
                             dtype=np.uint8),
                rng.integers(0, _TINY["vocab_size"],
                             (b, _WORDS)).astype(np.int32),
                np.zeros((b,), np.float32))
        return (jax.make_jaxpr(step)(*args),
                arg_leaf_labels(args, _STEP_ARGNAMES), "8x1 (data)")
    closed, labels, _donated = _traced_entry(name)
    return closed, labels, _entries()[name].mesh


def audit_entry(name: str) -> NumericsAudit:
    closed, labels, mesh = _numerics_traced(name)
    audit = audit_jaxpr(closed, labels=labels, entry=name)
    audit.mesh = mesh
    return audit


def check_entry_names(entries) -> None:
    """A typo'd entry filter must fail loudly, not audit zero entries
    and pass vacuously (the memplan/stage_probe scope discipline)."""
    if entries is None:
        return
    unknown = set(entries) - set(entry_names())
    if unknown:
        raise ValueError(
            f"unknown numerics entries: {sorted(unknown)} (registered: "
            f"{', '.join(entry_names())})")


def audit_all(entries=None) -> dict:
    """name -> NumericsAudit for the registered entries (or a subset)."""
    check_entry_names(entries)
    audits: dict = {}
    for name in entry_names():
        if entries is not None and name not in entries:
            continue
        audits[name] = audit_entry(name)
    return audits


# Registered low-precision accumulations (GL016): entry -> tuple of
# site labels that are DELIBERATE.  Empty on the f32 tree — the bf16
# what-if is where sites appear, and NUMERICS.md names them.
EXPECTED_GL016 = {}

# Registered unguarded-exp sites (GL017 jaxpr half): entry -> count.
# Absent entry = expected 0.  Each nonzero registration is an audited
# decision, same discipline as a re-pin.  Currently empty: every exp in
# the registered programs bottoms out at a subtraction or a bounded-
# domain producer — including sdtw_3's deliberately max-unguarded
# negative term (losses/dtw_losses.py), whose operand chain reaches the
# pairwise-distance subtraction and so reads as domain-bounded here;
# the AST half carries its audited inline suppression instead.
EXPECTED_UNGUARDED_EXP = {}

# Pinned per-entry dtype census (GL018): short dtype -> program buffer
# bytes.  Like EXPECTED_PEAK_BYTES: never changes SILENTLY — a
# deliberate precision change re-pins in the same commit.  Derived by
# ``python scripts/precision_audit.py`` (prints the re-pin dict on
# drift).  Reading the milnce step: everything numeric is f32 on the
# CPU entry config (the tiny entries trace the f32 model — bf16
# placement is the what-if axis), u8 is the raw video batch, bool the
# finite-guard / mask plumbing, i32 the token ids and step counters.
EXPECTED_DTYPE_CENSUS = {
    "train_step_milnce": {
        "i32": 592, "f32": 64258732, "u8": 196608, "bool": 216534},
    "train_step_milnce_guarded": {
        "i32": 608, "f32": 70595824, "u8": 196608, "bool": 744209},
    # 4-way elastic-resume layout: same program, 2 clips/chip — u8 video
    # doubles per chip, f32 shrinks (fewer psum partials), casts as 8-way
    "train_step_milnce@4way": {
        "i32": 432, "f32": 64253516, "u8": 98304, "bool": 216502},
    "train_step_sdtw3": {
        "i32": 1864, "f32": 67776548, "u8": 196608, "bool": 233142},
    "grad_cache_step_milnce": {
        "i32": 632, "f32": 64757228, "u8": 221184, "bool": 109366},
    "train_step_milnce_chunked": {
        "i32": 744, "f32": 64271036, "u8": 196608, "bool": 216556},
    "milnce_loss_dense": {"f32": 17633824, "i32": 2824, "bool": 329216},
    "milnce_loss_chunked": {"f32": 3516720, "i32": 6280, "bool": 84928},
    "train_step_milnce_2d": {
        "i32": 612, "f32": 49570220, "u8": 196608, "bool": 216534},
    "grad_cache_2d": {
        "i32": 652, "f32": 50068716, "u8": 221184, "bool": 109366},
    "serve_text_embed@b0": {"f32": 2120192, "i32": 220, "bool": 5},
    "serve_text_embed@b1": {"f32": 2121664, "i32": 440, "bool": 10},
    "serve_video_embed@b0": {"f32": 4646720, "u8": 98304},
    "serve_video_embed@b1": {"f32": 7143872, "u8": 196608},
    "serve_index_topk": {"f32": 3492, "i32": 1512, "bool": 51},
    "serve_index_topk@gen": {"f32": 4164, "i32": 1520, "bool": 60},
    "serve_pool_text_embed@b0": {"f32": 2121664, "i32": 160, "bool": 10},
    "serve_pool_video_embed@b1": {"f32": 12138176, "u8": 49152},
    # quantized edge engine (ISSUE 19): the i8 bucket IS the resident
    # weight tree (21 quantized leaves of the tiny model), f32 covers
    # the dequant copies + activations.  GL016-clean by construction:
    # int8 is a STORAGE dtype here — every dot_general runs on the
    # dequantized f32 operands, so no low-precision accumulator exists
    # for the rule to fire on (the ISSUE 19 w8/f32-accum contract)
    "serve_quant_text_embed@b1": {
        "f32": 2124308, "i8": 524992, "i32": 440, "bool": 10},
    "serve_quant_video_embed@b1": {
        "f32": 9241364, "i8": 524992, "u8": 196608},
    "train_step_curriculum@s1": {
        "i32": 592, "f32": 81928876, "u8": 393216, "bool": 430550},
}

# Pinned per-entry cast inventory (GL018): "src->dst @ location" -> n.
# An appearing cast is a new precision boundary (HBM + accuracy both
# care); a vanishing one is a silently demoted accumulator.  The
# recurring boundaries, named: ``u8->f32 @ video`` is the input
# normalization (the ONE place raw frames widen), ``bool->f32 @ eq``
# the masked-mean denominators, ``i32->f32 @ .../count`` the schedule
# step feeding the learning rate, ``f32->f32 @ max`` weak-type
# canonicalization at the loss clamps, and the ``@ nest-boundary``
# routes are casts whose source enters through a scan/grad-cache body
# invar (the microbatch slices in grad-cache entries).
EXPECTED_CASTS = {
    "train_step_milnce": {
        "u8->f32 @ video": 1, "bool->f32 @ eq": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2},
    "train_step_milnce_guarded": {
        "u8->f32 @ video": 1, "bool->f32 @ eq": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2, "bool->i32 @ not": 1},
    "train_step_milnce@4way": {
        "u8->f32 @ video": 1, "bool->f32 @ eq": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2},
    "train_step_sdtw3": {
        "u8->f32 @ video": 1, "bool->f32 @ eq": 4,
        "i32->i32 @ nest-boundary": 15, "f32->f32 @ nest-boundary": 18,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->f32 @ pjit": 2},
    "grad_cache_step_milnce": {
        "u8->f32 @ nest-boundary": 2, "bool->f32 @ eq": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2},
    "train_step_milnce_chunked": {
        "u8->f32 @ video": 1, "bool->f32 @ eq": 3,
        "i32->f32 @ nest-boundary": 4, "f32->f32 @ nest-boundary": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2},
    "milnce_loss_dense": {"bool->f32 @ eq": 3},
    "milnce_loss_chunked": {
        "f32->f32 @ nest-boundary": 4, "bool->f32 @ eq": 2},
    "train_step_milnce_2d": {
        "u8->f32 @ video": 1, "bool->f32 @ eq": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2},
    "grad_cache_2d": {
        "u8->f32 @ nest-boundary": 2, "bool->f32 @ eq": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2},
    "serve_text_embed@b0": {},
    "serve_text_embed@b1": {},
    "serve_video_embed@b0": {"u8->f32 @ video": 1},
    "serve_video_embed@b1": {"u8->f32 @ video": 1},
    "serve_index_topk": {"f32->f32 @ nest-boundary": 1},
    "serve_index_topk@gen": {"f32->f32 @ nest-boundary": 1},
    "serve_pool_text_embed@b0": {},
    "serve_pool_video_embed@b1": {"u8->f32 @ video": 1},
    # quant entries: exactly ONE named i8->f32 route per quantized leaf
    # — the dequant boundary inventory.  A vanished route is a weight
    # silently left f32 in the artifact; an extra one is a leaf the
    # readiness rule stopped protecting.  Both towers dequantize the
    # FULL tree (the jit entry binds the whole variables arg; XLA DCEs
    # the unused tower's convs post-trace, but the traced program —
    # what this pass audits — carries every route).
    "serve_quant_text_embed@b1": dict.fromkeys([
        f"i8->f32 @ variables/params/{k}" for k in (
            "conv1/conv/kernel", "conv_2b/conv/kernel",
            "conv_2c/conv_spatial/kernel", "conv_2c/conv_temporal/kernel",
            "fc/kernel", "gating/fc/kernel",
            "mixed_3b/conv_b0/conv/kernel",
            "mixed_3b/conv_b1_a/conv/kernel",
            "mixed_3b/conv_b1_b/conv_spatial/kernel",
            "mixed_3b/conv_b1_b/conv_temporal/kernel",
            "mixed_3b/conv_b2_a/conv/kernel",
            "mixed_3b/conv_b2_b/conv_spatial/kernel",
            "mixed_3b/conv_b2_b/conv_temporal/kernel",
            "mixed_3b/conv_b3_b/conv/kernel",
            "mixed_3b/gating_b0/fc/kernel",
            "mixed_3b/gating_b1/fc/kernel",
            "mixed_3b/gating_b2/fc/kernel",
            "mixed_3b/gating_b3/fc/kernel",
            "text_module/fc1/kernel", "text_module/fc2/kernel",
            "text_module/word_embd/embedding")], 1),
    "serve_quant_video_embed@b1": dict.fromkeys(["u8->f32 @ video"] + [
        f"i8->f32 @ variables/params/{k}" for k in (
            "conv1/conv/kernel", "conv_2b/conv/kernel",
            "conv_2c/conv_spatial/kernel", "conv_2c/conv_temporal/kernel",
            "fc/kernel", "gating/fc/kernel",
            "mixed_3b/conv_b0/conv/kernel",
            "mixed_3b/conv_b1_a/conv/kernel",
            "mixed_3b/conv_b1_b/conv_spatial/kernel",
            "mixed_3b/conv_b1_b/conv_temporal/kernel",
            "mixed_3b/conv_b2_a/conv/kernel",
            "mixed_3b/conv_b2_b/conv_spatial/kernel",
            "mixed_3b/conv_b2_b/conv_temporal/kernel",
            "mixed_3b/conv_b3_b/conv/kernel",
            "mixed_3b/gating_b0/fc/kernel",
            "mixed_3b/gating_b1/fc/kernel",
            "mixed_3b/gating_b2/fc/kernel",
            "mixed_3b/gating_b3/fc/kernel",
            "text_module/fc1/kernel", "text_module/fc2/kernel",
            "text_module/word_embd/embedding")], 1),
    "train_step_curriculum@s1": {
        "u8->f32 @ video": 1, "bool->f32 @ eq": 4,
        "i32->f32 @ state/opt_state/hyperparams_states/learning_rate/count": 1,
        "f32->f32 @ max": 2, "i32->i32 @ nest-boundary": 3,
        "i32->f32 @ pjit": 2},
}


def _check_gl016(name: str, audit: NumericsAudit) -> CheckResult:
    allowed = set(EXPECTED_GL016.get(name, ()))
    bad = [s for s in audit.gl016_sites if s not in allowed]
    return CheckResult(
        name, "GL016-low-precision-accum", not bad,
        "" if not bad else
        "; ".join(bad[:4]) + " — accumulate in f32 "
        "(preferred_element_type / astype) or register the site in "
        "EXPECTED_GL016")


def _check_gl017(name: str, audit: NumericsAudit) -> CheckResult:
    want = EXPECTED_UNGUARDED_EXP.get(name, 0)
    got = len(audit.exp_sites)
    ok = got == want
    return CheckResult(
        name, "GL017-exp-domain", ok,
        "" if ok else
        f"{got} unguarded exp site(s), {want} registered: "
        f"{'; '.join(audit.exp_sites[:4])} — subtract the max before "
        "exp, or register the audited count in EXPECTED_UNGUARDED_EXP")


def _check_gl018_census(name: str, audit: NumericsAudit) -> CheckResult:
    want = EXPECTED_DTYPE_CENSUS.get(name)
    if want is None:
        return CheckResult(name, "GL018-dtype-census", False,
                           f"entry unpinned — add EXPECTED_DTYPE_CENSUS"
                           f"[{name!r}] = {audit.census}")
    ok = audit.census == want
    if ok:
        return CheckResult(name, "GL018-dtype-census", True)
    diff = []
    for k in sorted(set(want) | set(audit.census)):
        if want.get(k) != audit.census.get(k):
            diff.append(f"{k}: pinned {want.get(k, 0)} B, traced "
                        f"{audit.census.get(k, 0)} B")
    return CheckResult(
        name, "GL018-dtype-census", False,
        "; ".join(diff) + " — precision placement moved; if intended, "
        "re-pin EXPECTED_DTYPE_CENSUS")


def _check_gl018_casts(name: str, audit: NumericsAudit) -> CheckResult:
    want = EXPECTED_CASTS.get(name)
    if want is None:
        return CheckResult(name, "GL018-cast-inventory", False,
                           f"entry unpinned — add EXPECTED_CASTS"
                           f"[{name!r}] = {audit.casts}")
    ok = audit.casts == want
    if ok:
        return CheckResult(name, "GL018-cast-inventory", True)
    diff = []
    for k in sorted(set(want) | set(audit.casts)):
        if want.get(k) != audit.casts.get(k):
            diff.append(f"`{k}`: pinned {want.get(k, 0)}, traced "
                        f"{audit.casts.get(k, 0)}")
    return CheckResult(
        name, "GL018-cast-inventory", False,
        "; ".join(diff[:6]) + " — a dtype boundary appeared or "
        "vanished; if intended, re-pin EXPECTED_CASTS")


def _check_residency(name: str, audit: NumericsAudit) -> CheckResult:
    bad = audit.residency_violations
    return CheckResult(
        name, "f32-residency", not bad,
        "" if not bad else "; ".join(bad[:4]))


def run_numerics_checks(entries=None, audits=None) -> list:
    """graftlint Pass 5: GL016 + GL017(jaxpr) + GL018 + the
    f32-residency audit over every registered entry.  Builder failures
    become failing results, like every other pass."""
    check_entry_names(entries)
    results: list = []
    if audits is None:
        audits = {}
    for name in entry_names():
        if entries is not None and name not in entries:
            continue
        try:
            if name not in audits:
                audits[name] = audit_entry(name)
            audit = audits[name]
            results.append(_check_gl016(name, audit))
            results.append(_check_gl017(name, audit))
            results.append(_check_gl018_census(name, audit))
            results.append(_check_gl018_casts(name, audit))
            results.append(_check_residency(name, audit))
        except Exception as exc:                     # pragma: no cover
            results.append(CheckResult(name, "numerics-build", False,
                                       f"{type(exc).__name__}: {exc}"))
    return results


# --------------------------------------------------------------------------
# what-if: the bf16 decision, statically
# --------------------------------------------------------------------------

def what_if_audit(**kw) -> NumericsAudit:
    """Audit the train step at a hypothetical operating point (sibling
    of memplan.what_if_step, same traced program): ``dtype='bfloat16'``
    answers "which reductions lose their f32 accumulator, which casts
    appear, does the loss chain stay f32" before anyone flips the model
    dtype on a chip."""
    from milnce_tpu.analysis.memplan import what_if_program

    closed, labels, _donated, entry, mesh = what_if_program(**kw)
    audit = audit_jaxpr(closed, labels=labels, entry=entry)
    audit.mesh = mesh
    return audit
