"""graftlint Pass 1: pure-AST lint with JAX-specific rules.

Deliberately imports no jax — ``scripts/graft_lint.py --check --no-trace``
must cost milliseconds from a cold interpreter so it can gate every test
run and pre-commit hook.  The analysis is intra-module and heuristic
(documented per rule in ANALYSIS.md); the design bias is *low false
negatives on the potholes that cost TPU throughput*, with the inline
suppression syntax absorbing the audited exceptions::

    lr = float(x)  # graftlint: disable=GL001(display-cadence, audited)

Scope heuristics this pass relies on:

- **traced scope** (GL002/GL006): a function is considered traced when it
  is decorated with (or passed by name to) a JAX tracing transform
  (``jit``/``shard_map``/``scan``/``grad``/...), plus every function
  nested inside one.  ``static_argnames``/``static_argnums`` of the
  jit/scan site are honored when tainting parameters.
- **hot region** (GL001): the body of any ``for`` loop iterating
  ``device_prefetch(...)`` — the canonical training hot loop — plus the
  transitive closure of same-module functions called (by bare name) from
  inside it.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field

from milnce_tpu.analysis.rules import RULES, Rule, resolve_rule

# Terminal callee names that put their function arguments under trace.
_TRACERS = {
    "jit", "pjit", "shard_map", "scan", "vmap", "pmap", "grad",
    "value_and_grad", "vjp", "jvp", "linearize", "checkpoint", "remat",
    "eval_shape", "make_jaxpr", "pallas_call", "fori_loop", "while_loop",
    "cond", "switch", "custom_vjp", "custom_jvp", "associative_scan",
}
# Roots an Attribute chain must start from for a terminal match to count
# (avoids flagging `csvreader.scan(...)`); bare names always count.
_TRACE_ROOTS = {"jax", "lax", "jnp", "pl", "pallas", "nn", "flax"}

# Attribute reads that turn a traced array into static Python data.
_TAINT_BREAKERS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                   "itemsize", "weak_type"}

# Host-sync call families (GL001); device_get and block_until_ready are
# matched inline in _check_hot_body (the latter in method form too).
_SYNC_BARE = {"float", "int", "bool", "complex"}
_SYNC_NP = {"asarray", "array"}

# Observability recording verbs (GL008): method calls on a registry
# metric / span recorder (milnce_tpu/obs/) — host I/O that must never
# sit under a trace.  Deliberately EXCLUDES ``set``: ``x.at[i].set(v)``
# is ubiquitous legitimate traced code.
_OBS_RECORDING = {"span", "event", "observe", "inc", "dec", "log_event"}

# Mesh-axis vocabulary GL009 always accepts: the repo's canonical axis
# names (ParallelConfig.data_axis default + the model/FSDP axis the 2-D
# mesh declares — config.py, parallel/mesh.py).  Axes declared by a
# Mesh(...) construction or an axis_name(s)= kwarg in the SAME module
# extend the set; anything else inside a with_sharding_constraint's
# PartitionSpec is a phantom axis GSPMD would silently replicate.
_CANONICAL_MESH_AXES = {"data", "model"}

_ARRAY_ROOTS = {"np", "numpy", "jnp"}
_FLOAT_DEFAULT_CTORS = {"zeros", "ones", "empty", "linspace", "eye"}
_VALUE_CTORS = {"array", "asarray", "full"}

# Entry-level (jaxpr) rules — the Pass 4 planner's GL013-GL015 and the
# Pass 5 numerics gates GL016/GL018 attach to registered trace entries,
# never to source lines, so an inline suppression can never match
# anything: writing one is itself a GL000 (the stale-suppression audit,
# extended to the rules that cannot fire here).  The sanctioned
# "suppression" is a conscious re-pin of the expectation tables in
# analysis/memplan.py or analysis/numerics.py, same commit.  GL017 is
# NOT here: its AST half fires on source lines in losses/ and takes a
# reasoned inline suppression like any Pass 1 rule.
_ENTRY_LEVEL_RULES = frozenset({"GL013", "GL014", "GL015",
                                "GL016", "GL018"})

_SUPPRESS_RE = re.compile(r"#\s*graftlint:\s*disable=(?P<body>.+)$")
_ITEM_RE = re.compile(r"\s*(?P<rule>[A-Za-z0-9_-]+)\s*(?:\((?P<reason>.*)\))?\s*$")


@dataclass
class Suppression:
    line: int
    rule_id: str            # normalized to the GLnnn id
    reason: str
    standalone: bool        # comment-only line: applies to the line below


@dataclass
class Finding:
    path: str
    line: int
    rule: Rule
    message: str
    suppressed: bool = False
    suppress_reason: str = ""

    def format(self) -> str:
        tag = f" [suppressed: {self.suppress_reason}]" if self.suppressed else ""
        return (f"{self.path}:{self.line}: {self.rule.id} "
                f"({self.rule.name}) {self.message}{tag}")


def _comment_tokens(src: str):
    """(lineno, comment_text, standalone) for every real COMMENT token —
    tokenizing (rather than regexing lines) keeps docstrings and strings
    that merely *mention* the suppression syntax from parsing as one."""
    import io
    import tokenize

    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except (tokenize.TokenError, SyntaxError):  # pragma: no cover
        return
    for tok in tokens:
        if tok.type == tokenize.COMMENT:
            line_prefix = tok.line[:tok.start[1]]
            yield tok.start[0], tok.string, line_prefix.strip() == ""


def parse_suppressions(src: str, path: str) -> tuple[list[Suppression],
                                                     list[Finding]]:
    """Collect ``# graftlint: disable=RULE(reason)[,...]`` comments.

    Malformed items (unknown rule, missing reason) become GL000 findings —
    a suppression that doesn't document itself suppresses nothing."""
    sups: list[Suppression] = []
    bad: list[Finding] = []
    for lineno, text, standalone in _comment_tokens(src):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        # split on commas OUTSIDE parens so reasons may contain commas
        body, items, depth, cur = m.group("body"), [], 0, ""
        for ch in body:
            depth += ch == "("
            depth -= ch == ")"
            if ch == "," and depth == 0:
                items.append(cur)
                cur = ""
            else:
                cur += ch
        items.append(cur)
        for item in items:
            im = _ITEM_RE.match(item)
            rule = resolve_rule(im.group("rule")) if im else None
            reason = (im.group("reason") or "").strip() if im else ""
            if rule is None:
                bad.append(Finding(path, lineno, RULES["GL000"],
                                   f"unknown rule in suppression: {item.strip()!r}"))
            elif rule.id in _ENTRY_LEVEL_RULES:
                bad.append(Finding(
                    path, lineno, RULES["GL000"],
                    f"suppression of {rule.id} ({rule.name}): entry-level "
                    "planner/numerics rules never fire on source lines — "
                    "re-pin the expectation table in analysis/memplan.py "
                    "or analysis/numerics.py instead"))
            elif not reason:
                bad.append(Finding(path, lineno, RULES["GL000"],
                                   f"suppression of {rule.id} carries no reason "
                                   "(write disable=RULE(reason))"))
            else:
                sups.append(Suppression(lineno, rule.id, reason, standalone))
    return sups, bad


def _terminal_and_root(node: ast.expr) -> tuple[str | None, str | None]:
    """('jit', 'jax') for jax.jit / jax.experimental.pjit.pjit; bare Name
    returns (name, name)."""
    if isinstance(node, ast.Name):
        return node.id, node.id
    if isinstance(node, ast.Attribute):
        terminal = node.attr
        cur = node.value
        while isinstance(cur, ast.Attribute):
            cur = cur.value
        return terminal, (cur.id if isinstance(cur, ast.Name) else None)
    return None, None


def _is_tracer_callee(func: ast.expr) -> bool:
    terminal, root = _terminal_and_root(func)
    if terminal is None:
        return False
    if isinstance(func, ast.Name):
        return terminal in _TRACERS
    return terminal in _TRACERS and root in _TRACE_ROOTS


def _static_names_from_call(call: ast.Call, fn: ast.FunctionDef | None) -> set:
    """Resolve static_argnames/static_argnums kwargs to parameter names."""
    out: set[str] = set()
    params = [a.arg for a in fn.args.args] if fn is not None else []
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
        elif kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if (isinstance(n, ast.Constant) and isinstance(n.value, int)
                        and 0 <= n.value < len(params)):
                    out.add(params[n.value])
    return out


class _TaintCheck(ast.NodeVisitor):
    """Does this expression's value depend on a tainted (traced) name?
    Descent stops at shape/dtype-like attribute reads and len()."""

    def __init__(self, tainted: set):
        self.tainted = tainted
        self.hit = False

    def visit_Name(self, node: ast.Name) -> None:
        if node.id in self.tainted:
            self.hit = True

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if node.attr in _TAINT_BREAKERS:
            return                      # x.shape is static under jit
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        terminal, _ = _terminal_and_root(node.func)
        if terminal in ("len", "isinstance", "type", "hasattr"):
            return                      # static under jit (shape-derived)
        self.generic_visit(node)


def _expr_tainted(node: ast.expr, tainted: set) -> bool:
    chk = _TaintCheck(tainted)
    chk.visit(node)
    return chk.hit


def _assigned_names(target: ast.expr):
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            yield n.id


class _ModuleLint:
    def __init__(self, src: str, path: str):
        self.src = src
        self.path = path
        self.tree = ast.parse(src)
        self.findings: list[Finding] = []
        self.imports_jax = bool(re.search(
            r"^\s*(import jax|from jax|import jax\.numpy)", src, re.M))
        # name -> ALL defs sharing that bare name (incl. nested): two
        # factories each defining `def local(...)` is the NORM in this
        # codebase (train/step.py), and keeping only the first would
        # silently exempt every later body from the traced-scope checks
        self.defs: dict[str, list] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.defs.setdefault(node.name, []).append(node)
        self.traced_roots: dict[str, set] = {}   # fn name -> static params
        self._discover_traced_roots()

    # ---- shared helpers -------------------------------------------------

    def _emit(self, rule_id: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(self.path, node.lineno,
                                     RULES[rule_id], message))

    # ---- traced-scope discovery ----------------------------------------

    def _discover_traced_roots(self) -> None:
        # decorators
        for name, fns in self.defs.items():
            for fn in fns:
                for deco in fn.decorator_list:
                    hit = any(_is_tracer_callee(n) for n in ast.walk(deco)
                              if isinstance(n, (ast.Name, ast.Attribute)))
                    if hit:
                        statics = (_static_names_from_call(deco, fn)
                                   if isinstance(deco, ast.Call) else set())
                        self.traced_roots.setdefault(name,
                                                     set()).update(statics)
        # call sites: jax.jit(f, ...), lax.scan(body, ...), shard_map(f, ...)
        # — a bare name marks EVERY def sharing it (conservative: name
        # resolution without scope analysis)
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and _is_tracer_callee(node.func)):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id in self.defs:
                    self.traced_roots.setdefault(arg.id, set())
                    for fn in self.defs[arg.id]:
                        self.traced_roots[arg.id].update(
                            _static_names_from_call(node, fn))

    # ---- GL002 / GL006: traced-scope body checks ------------------------

    def check_traced_scopes(self) -> None:
        for name, statics in self.traced_roots.items():
            for fn in self.defs[name]:
                params = {a.arg for a in fn.args.args
                          + fn.args.posonlyargs + fn.args.kwonlyargs}
                self._check_traced_fn(fn, params - statics)

    def _check_traced_fn(self, fn, inherited: set) -> None:
        tainted = set(inherited)
        for stmt in fn.body:
            self._walk_traced_stmt(stmt, tainted)

    def _walk_traced_stmt(self, stmt: ast.stmt, tainted: set) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def runs under the same trace; closure taint flows in
            params = {a.arg for a in stmt.args.args
                      + stmt.args.posonlyargs + stmt.args.kwonlyargs}
            self._check_traced_fn(stmt, tainted | params)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            value = stmt.value
            if value is not None and _expr_tainted(value, tainted):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for t in targets:
                    tainted.update(_assigned_names(t))
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            # iter_child_nodes yields ast.withitem (neither stmt nor
            # expr), so `with REC.span(...):` would slip the expression
            # walk — check the context managers explicitly (GL008's
            # canonical spelling is exactly a with-statement)
            for item in stmt.items:
                self._check_traced_exprs(item.context_expr, tainted)
        if isinstance(stmt, ast.If) and _expr_tainted(stmt.test, tainted):
            self._emit("GL002", stmt,
                       "Python `if` on a traced value — use lax.cond / "
                       "jnp.where, or hoist to build time")
        elif isinstance(stmt, ast.While) and _expr_tainted(stmt.test, tainted):
            self._emit("GL002", stmt,
                       "Python `while` on a traced value — use "
                       "lax.while_loop")
        elif isinstance(stmt, ast.For) and _expr_tainted(stmt.iter, tainted):
            self._emit("GL002", stmt,
                       "Python `for` over a traced value — use lax.scan / "
                       "lax.fori_loop")
        for node in ast.iter_child_nodes(stmt):
            if isinstance(node, ast.stmt):
                self._walk_traced_stmt(node, tainted)
            elif isinstance(node, ast.expr):
                self._check_traced_exprs(node, tainted)
        # statements nested in expressions (rare) are not walked further

    def _check_traced_exprs(self, node: ast.expr, tainted: set) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.IfExp) and _expr_tainted(sub.test, tainted):
                self._emit("GL002", sub,
                           "conditional expression on a traced value — use "
                           "jnp.where / lax.select")
            elif (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "print"):
                self._emit("GL006", sub,
                           "print() under trace fires once with tracers — "
                           "use jax.debug.print")
            elif (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _OBS_RECORDING):
                self._emit("GL008", sub,
                           f".{sub.func.attr}() under trace is host I/O "
                           "that fires once with tracers — record outside "
                           "the traced function (display cadence / "
                           "dispatch site)")

    # ---- GL001: hot-region host syncs -----------------------------------

    def check_hot_regions(self) -> None:
        hot_bodies: list[list[ast.stmt]] = []
        for node in ast.walk(self.tree):
            if (isinstance(node, ast.For)
                    and "device_prefetch" in ast.unparse(node.iter)):
                hot_bodies.append(node.body)
        if not hot_bodies:
            return
        # transitive closure over same-module functions called by bare name
        seen: set[str] = set()
        queue = list(hot_bodies)
        while queue:
            body = queue.pop()
            for call in self._calls_in(body):
                if isinstance(call.func, ast.Name):
                    callee = call.func.id
                    if callee in self.defs and callee not in seen:
                        seen.add(callee)
                        queue.extend(fn.body for fn in self.defs[callee])
            self._check_hot_body(body)

    def _calls_in(self, body: list[ast.stmt]):
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    yield node

    def _check_hot_body(self, body: list[ast.stmt]) -> None:
        for call in self._calls_in(body):
            terminal, root = _terminal_and_root(call.func)
            if (isinstance(call.func, ast.Name)
                    and terminal in _SYNC_BARE
                    and call.args
                    and not isinstance(call.args[0], ast.Constant)):
                self._emit("GL001", call,
                           f"{terminal}() on a (possibly device) value in "
                           "the hot loop blocks the host")
            elif terminal == "item" and not call.args:
                self._emit("GL001", call,
                           ".item() in the hot loop blocks the host")
            elif terminal == "block_until_ready":
                # function form (jax.block_until_ready(x)) AND the
                # idiomatic method form (x.block_until_ready()) — both
                # stall the dispatch pipeline per step
                self._emit("GL001", call,
                           "block_until_ready() in the hot loop stalls "
                           "the dispatch pipeline")
            elif terminal == "device_get" and root == "jax":
                self._emit("GL001", call,
                           "jax.device_get() in the hot loop blocks the "
                           "host")
            elif (terminal in _SYNC_NP and root in ("np", "numpy")):
                self._emit("GL001", call,
                           f"{root}.{terminal}() on a device value in the "
                           "hot loop forces a synchronous D2H copy")

    # ---- GL003: jit without donation ------------------------------------

    _STEPISH = re.compile(r"(^|_)(train_)?(step|loop)\b|(^|_)step(_|$)")
    _FACTORY = re.compile(r"make_\w*step")

    def check_donation(self) -> None:
        # call form: jax.jit(fn, ...)
        parents = _parent_functions(self.tree)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            terminal, root = _terminal_and_root(node.func)
            if terminal != "jit" or root not in ("jax", "jit"):
                continue
            if not node.args or not isinstance(node.args[0], ast.Name):
                continue
            arg_name = node.args[0].id
            encl = parents.get(id(node), "")
            stepish = (self._STEPISH.search(arg_name)
                       or self._FACTORY.search(encl))
            has_donate = any(kw.arg in ("donate_argnums", "donate_argnames")
                             for kw in node.keywords)
            if stepish and not has_donate:
                self._emit("GL003", node,
                           f"jax.jit({arg_name}) looks train-step-shaped "
                           "but donates no buffers — pass donate_argnums "
                           "for the consumed state")
        # decorator form: @jax.jit on def *step*
        for name, fns in self.defs.items():
            if not self._STEPISH.search(name):
                continue
            for fn in fns:
                self._check_decorated_donation(name, fn)

    def _check_decorated_donation(self, name: str, fn) -> None:
        for deco in fn.decorator_list:
            terminal, _root = _terminal_and_root(
                deco.func if isinstance(deco, ast.Call) else deco)
            if terminal != "jit":
                continue
            has_donate = (isinstance(deco, ast.Call) and any(
                kw.arg in ("donate_argnums", "donate_argnames")
                for kw in deco.keywords))
            if not has_donate:
                self._emit("GL003", fn,
                           f"@jit on {name}() donates no buffers — "
                           "pass donate_argnums for the consumed state")

    # ---- GL004: f64 drift ------------------------------------------------

    @staticmethod
    def _has_dtype_arg(node: ast.Call) -> bool:
        """dtype given as keyword OR positionally (np.zeros(shape, f32)):
        any positional arg that reads like a dtype counts."""
        if any(kw.arg == "dtype" for kw in node.keywords):
            return True
        for arg in node.args:
            for sub in ast.walk(arg):
                name = (sub.attr if isinstance(sub, ast.Attribute)
                        else sub.id if isinstance(sub, ast.Name)
                        else sub.value if (isinstance(sub, ast.Constant)
                                           and isinstance(sub.value, str))
                        else "")
                if isinstance(name, str) and (
                        name.startswith(("float", "int", "uint", "bfloat",
                                         "complex", "bool_"))
                        or "dtype" in name):
                    return True
        return False

    def check_f64(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Attribute) and node.attr == "float64":
                _, root = _terminal_and_root(node)
                if root in _ARRAY_ROOTS or root == "jax":
                    self._emit("GL004", node,
                               f"explicit float64 dtype ({root}.float64) — "
                               "f64 operands upcast everything downstream")
            if not isinstance(node, ast.Call):
                continue
            terminal, root = _terminal_and_root(node.func)
            if root not in _ARRAY_ROOTS:
                continue
            if self._has_dtype_arg(node):
                continue
            if terminal in _FLOAT_DEFAULT_CTORS:
                self._emit("GL004", node,
                           f"{root}.{terminal}() without dtype= defaults to "
                           "float64 (numpy always, jax under x64)")
            elif terminal in _VALUE_CTORS and any(
                    isinstance(a, ast.Constant) and isinstance(a.value, float)
                    for arg in node.args for a in ast.walk(arg)):
                self._emit("GL004", node,
                           f"{root}.{terminal}() of a float literal without "
                           "dtype= upcasts to float64 under x64")

    # ---- GL005: unsynced wall-clock timing -------------------------------

    @staticmethod
    def _own_nodes(fn):
        """Descendants of ``fn`` excluding nested function bodies (those
        are audited as their own timing scopes)."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def check_timing(self) -> None:
        if not self.imports_jax:
            return
        for name, fns in self.defs.items():
          for fn in fns:
            clock_calls = []
            has_block = False
            for node in self._own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                terminal, root = _terminal_and_root(node.func)
                if root == "time" and terminal in ("time", "perf_counter",
                                                   "monotonic"):
                    clock_calls.append(node)
                if terminal == "block_until_ready":
                    has_block = True
            if len(clock_calls) >= 2 and not has_block:
                clock_calls.sort(key=lambda n: n.lineno)
                self._emit("GL005", clock_calls[0],
                           f"{name}() reads the wall clock {len(clock_calls)}x "
                           "with no block_until_ready — async dispatch makes "
                           "the delta measure enqueue, not device work")

    # ---- GL007: swallowed broad except -----------------------------------

    _BROAD_EXC = {"Exception", "BaseException"}

    @classmethod
    def _is_broad_handler(cls, handler: ast.ExceptHandler) -> bool:
        if handler.type is None:                    # bare `except:`
            return True
        for node in ast.walk(handler.type):
            name = (node.id if isinstance(node, ast.Name)
                    else node.attr if isinstance(node, ast.Attribute)
                    else None)
            if name in cls._BROAD_EXC:
                return True
        return False

    def check_broad_except(self) -> None:
        """A broad handler must visibly DO something with the failure:
        re-raise, reference the bound exception (log/record/wrap it), or
        pass ``exc_info`` to a logging call.  Anything else is a silent
        swallow — exactly the class of 'handling' that turns a broken
        dataset or flaky store into a green-looking run."""
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.ExceptHandler)
                    and self._is_broad_handler(node)):
                continue
            body_nodes = [n for stmt in node.body for n in ast.walk(stmt)]
            reraises = any(isinstance(n, ast.Raise) for n in body_nodes)
            uses_exc = node.name is not None and any(
                isinstance(n, ast.Name) and n.id == node.name
                for n in body_nodes)
            logs_exc_info = any(
                isinstance(n, ast.Call)
                and any(kw.arg == "exc_info" for kw in n.keywords)
                for n in body_nodes)
            if not (reraises or uses_exc or logs_exc_info):
                self._emit("GL007", node,
                           "broad except swallows the error — re-raise, "
                           "record the bound exception, or add a reasoned "
                           "suppression")

    # ---- GL009: phantom mesh axis in sharding constraints ----------------

    def _declared_axes(self) -> set:
        """Axis names this module legitimizes: the canonical set plus
        string literals in ``Mesh(...)`` axis tuples and ``axis_name=``/
        ``axis_names=``/``data_axis=``/``model_axis=`` kwargs — so a
        module building its own exotic mesh lints clean against it."""
        axes = set(_CANONICAL_MESH_AXES)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            terminal, _root = _terminal_and_root(node.func)
            if terminal == "Mesh" and len(node.args) >= 2:
                for sub in ast.walk(node.args[1]):
                    if (isinstance(sub, ast.Constant)
                            and isinstance(sub.value, str)):
                        axes.add(sub.value)
            for kw in node.keywords:
                if kw.arg in ("axis_name", "axis_names", "data_axis",
                              "model_axis"):
                    for sub in ast.walk(kw.value):
                        if (isinstance(sub, ast.Constant)
                                and isinstance(sub.value, str)):
                            axes.add(sub.value)
        return axes

    def check_sharding_axes(self) -> None:
        axes = None                       # computed lazily: most modules
        for node in ast.walk(self.tree):  # never constrain a sharding
            if not isinstance(node, ast.Call):
                continue
            terminal, _root = _terminal_and_root(node.func)
            if terminal != "with_sharding_constraint":
                continue
            if axes is None:
                axes = self._declared_axes()
            phantoms = []
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if not isinstance(sub, ast.Call):
                        continue
                    t, _r = _terminal_and_root(sub.func)
                    if t not in ("P", "PartitionSpec"):
                        continue
                    for leaf in ast.walk(sub):
                        if (isinstance(leaf, ast.Constant)
                                and isinstance(leaf.value, str)
                                and leaf.value not in axes):
                            phantoms.append(leaf.value)
            if phantoms:
                self._emit("GL009", node,
                           f"with_sharding_constraint names axes "
                           f"{sorted(set(phantoms))} that no mesh in scope "
                           "declares — GSPMD silently replicates a phantom "
                           "axis instead of erroring")

    # ---- GL017: unstabilized exp domain (losses/ only) -------------------

    # calls whose result is a legitimate exp guard (the max-subtraction
    # trick and its bounded-domain relatives)
    _GUARD_CALLS = {"max", "maximum", "amax", "min", "minimum", "clip",
                    "logsumexp", "stop_gradient"}

    def _guard_names(self) -> set:
        """Names carrying a max/lse-derived bound, by fixed point: a
        name assigned from a guard call, from an expression referencing
        another guard name, or lexically guard-like (``row_lse``,
        ``m_new``-style running maxima are the losses' house idiom) —
        so ``rls = row_lse[:, None]; jnp.exp(x - rls)`` reads guarded."""
        def lexical(name: str) -> bool:
            return "max" in name or "lse" in name

        assigns = []
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign):
                names = [t.id for t in node.targets
                         if isinstance(t, ast.Name)]
                if names:
                    assigns.append((names, node.value))
        guards: set = set()
        changed = True
        while changed:
            changed = False
            for names, value in assigns:
                if all(n in guards for n in names):
                    continue
                hit = False
                for sub in ast.walk(value):
                    if isinstance(sub, ast.Call):
                        t, _r = _terminal_and_root(sub.func)
                        if t in self._GUARD_CALLS:
                            hit = True
                            break
                    elif isinstance(sub, ast.Name) and (
                            sub.id in guards or lexical(sub.id)):
                        hit = True
                        break
                if hit:
                    for n in names:
                        if n not in guards:
                            guards.add(n)
                            changed = True
        return guards

    def check_exp_stability(self) -> None:
        """GL017, AST half — scoped to ``losses/`` modules (the jaxpr
        half in analysis/numerics.py confirms guards survive tracing on
        every registered entry): ``exp`` whose argument shows no
        subtraction of a guard, and divisions whose denominator IS a
        bare reduced sum (no eps / maximum floor)."""
        parts = self.path.replace("\\", "/").split("/")
        if "losses" not in parts:
            return
        guards = self._guard_names()

        def guard_ref(node: ast.AST) -> bool:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Name) and (
                        sub.id in guards or "max" in sub.id
                        or "lse" in sub.id):
                    return True
                if isinstance(sub, ast.Call):
                    t, _r = _terminal_and_root(sub.func)
                    if t in self._GUARD_CALLS:
                        return True
            return False

        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                terminal, _root = _terminal_and_root(node.func)
                if terminal == "exp" and node.args:
                    arg = node.args[0]
                    guarded = (isinstance(arg, ast.Name)
                               and arg.id in guards)
                    if not guarded:
                        for sub in ast.walk(arg):
                            if (isinstance(sub, ast.BinOp)
                                    and isinstance(sub.op, ast.Sub)
                                    and guard_ref(sub.right)):
                                guarded = True
                                break
                    if not guarded:
                        self._emit("GL017", node,
                                   "exp without a max-subtraction guard "
                                   "— overflows f32 at x>88 (subtract "
                                   "the row max or reuse the "
                                   "logsumexp/online-softmax bound)")
            elif (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Div)
                    and isinstance(node.right, ast.Call)):
                t, _r = _terminal_and_root(node.right.func)
                if t in ("sum", "reduce_sum"):
                    self._emit("GL017", node,
                               "division by a reduced sum without an "
                               "eps/maximum floor — an all-masked row "
                               "divides by zero")

    # ---- driver ----------------------------------------------------------

    def run(self) -> list[Finding]:
        self.check_traced_scopes()
        self.check_hot_regions()
        self.check_donation()
        self.check_f64()
        self.check_timing()
        self.check_broad_except()
        self.check_sharding_axes()
        self.check_exp_stability()
        return self.findings


def _parent_functions(tree: ast.Module) -> dict:
    """id(node) -> name of the nearest enclosing function."""
    out: dict[int, str] = {}

    def walk(node, current):
        for child in ast.iter_child_nodes(node):
            name = (child.name
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))
                    else current)
            out[id(child)] = current
            walk(child, name)

    walk(tree, "")
    return out


# Rules whose findings a suppression can legitimately absorb, per pass.
# Staleness (GL000) is only judged against rules that actually RAN: a
# GL010 suppression is not "stale" under --no-concurrency, it is simply
# unevaluated this invocation.  GL011 is NEVER staleness-judged: a
# cycle's partner edge may live in a module outside the current lint
# scope (a `graft_lint milnce_tpu/serving` narrowed run must not call a
# cross-module cycle's audited suppression stale).
_PASS1_RULES = frozenset({"GL001", "GL002", "GL003", "GL004", "GL005",
                          "GL006", "GL007", "GL008", "GL009", "GL017"})
_PASS3_STALE_RULES = frozenset({"GL010", "GL012"})


def _finalize(findings: list[Finding], sups: list[Suppression],
              bad: list[Finding], path: str,
              evaluated: frozenset) -> list[Finding]:
    """Apply suppressions to one file's findings, then turn every
    well-formed suppression that matched NOTHING (for a rule that was
    evaluated) into a GL000 stale-suppression finding — the audited-
    exceptions table in LINT.md must never claim exceptions that no
    longer exist."""
    by_line: dict[tuple[int, str], Suppression] = {}
    for s in sups:
        target = s.line + 1 if s.standalone else s.line
        by_line[(target, s.rule_id)] = s
    matched: set = set()
    for f in findings:
        s = by_line.get((f.line, f.rule.id))
        if s is not None:
            f.suppressed = True
            f.suppress_reason = s.reason
            matched.add((f.line, f.rule.id))
    for (line, rule_id), s in by_line.items():
        if (line, rule_id) in matched or rule_id not in evaluated:
            continue
        findings.append(Finding(
            path, s.line, RULES["GL000"],
            f"stale suppression: {rule_id} no longer fires on this line "
            "— delete it (or re-audit why you expected it to fire)"))
    findings.extend(bad)
    findings.sort(key=lambda f: (f.path, f.line, f.rule.id))
    return findings


def _lint_one(src: str, path: str, concurrency: bool):
    """One file's raw findings + suppressions + (optional) lock graph,
    BEFORE suppression matching (GL011 needs the graphs of every file
    in scope merged first)."""
    sups, bad = parse_suppressions(src, path)
    findings = _ModuleLint(src, path).run()
    graph = None
    if concurrency:
        from milnce_tpu.analysis.concurrency import lint_concurrency_source

        cfindings, graph, _reports = lint_concurrency_source(src, path)
        findings.extend(cfindings)
    return findings, sups, bad, graph


def lint_source(src: str, path: str = "<string>", *,
                concurrency: bool = True) -> list[Finding]:
    """All findings for one module, suppressions applied (suppressed
    findings are RETURNED with .suppressed=True so reports can list the
    audited exceptions; callers gate on the unsuppressed subset).
    ``concurrency=False`` skips Pass 3 (GL010-GL012)."""
    findings, sups, bad, graph = _lint_one(src, path, concurrency)
    if graph is not None:
        findings.extend(graph.cycle_findings())
    evaluated = _PASS1_RULES | (_PASS3_STALE_RULES if concurrency else frozenset())
    return _finalize(findings, sups, bad, path, evaluated)


def _discover_files(paths: list[str]) -> list[str]:
    """Every .py under the given files/directories, sorted.

    A path that matches no Python files raises instead of being
    silently dropped — a typo'd scope argument must fail the gate
    loudly, not let it pass green while checking nothing."""
    files: list[str] = []
    for p in paths:
        found: list[str] = []
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                if "__pycache__" in root:
                    continue
                found.extend(os.path.join(root, n) for n in names
                             if n.endswith(".py"))
        elif os.path.isfile(p) and p.endswith(".py"):
            found.append(p)
        if not found:
            raise FileNotFoundError(
                f"lint scope {p!r} matches no Python files — typo'd path? "
                "(a silently empty scope would pass the gate vacuously)")
        files.extend(found)
    return sorted(files)


def lint_paths_full(paths: list[str], *, concurrency: bool = True):
    """Lint every .py under the given files/directories.

    Returns ``(findings, lock_graph)`` where ``lock_graph`` is the
    MERGED cross-module lock-order graph (None when ``concurrency``
    is off) — GL011 cycles split across files (A->B in one module,
    B->A in another, joined by an imported lock) only exist in the
    union."""
    per_file = []
    merged = None
    for fname in _discover_files(paths):
        with open(fname) as fh:
            findings, sups, bad, graph = _lint_one(fh.read(), fname,
                                                   concurrency)
        per_file.append((fname, findings, sups, bad))
        if graph is not None:
            if merged is None:
                from milnce_tpu.analysis.concurrency import LockGraph

                merged = LockGraph()
            merged.merge(graph)
    cycle_by_path: dict[str, list] = {}
    if merged is not None:
        for f in merged.cycle_findings():
            cycle_by_path.setdefault(f.path, []).append(f)
    evaluated = _PASS1_RULES | (_PASS3_STALE_RULES if concurrency else frozenset())
    out: list[Finding] = []
    for fname, findings, sups, bad in per_file:
        findings.extend(cycle_by_path.pop(fname, []))
        out.extend(_finalize(findings, sups, bad, fname, evaluated))
    # cycles anchored outside the scanned files (can't happen today —
    # anchors are always edge sites in scope — but never drop findings)
    for leftovers in cycle_by_path.values():
        out.extend(leftovers)
    out.sort(key=lambda f: (f.path, f.line, f.rule.id))
    return out, merged


def lint_paths(paths: list[str], *,
               concurrency: bool = True) -> list[Finding]:
    """:func:`lint_paths_full` without the graph (the common caller)."""
    findings, _graph = lint_paths_full(paths, concurrency=concurrency)
    return findings
