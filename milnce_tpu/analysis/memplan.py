"""graftlint Pass 4: static HBM planner — jaxpr live-range memory analysis.

The binding constraint of the original MIL-NCE run was fitting the
32-frame S3D step into TPU v3 HBM, and this repo's own PERF.md records a
>10% throughput cliff at batch 192 whose diagnosis cost a chip session.
This pass makes per-chip peak device bytes a STATIC property, checked on
the hermetic CPU mesh like every other trace invariant: every registered
entry's closed jaxpr is walked with buffer live-range analysis and the
result is pinned, so a memory regression (a rematerialized activation, a
donation that silently stopped taking effect, an optimizer state that
doubled) lands as a failing tier-1 check — not as an OOM three weeks
later at batch 192 on a v5e.

The model (known approximations are documented in ANALYSIS.md):

- **liveness**: a buffer is live from the equation that defines it to
  its last use; entry arguments live for the whole program unless
  donated (donated inputs free at their last use — XLA's buffer
  donation, modeled); outputs live to the end.
- **peak**: for each equation, bytes live while it executes = live set
  + the equation's own transient (outputs being materialized for plain
  primitives; the recursive peak of the body for scan/cond/while; the
  body peak minus the already-counted operands for pjit / shard_map /
  custom_vjp nests, so a buffer crossing a nest boundary is counted
  once).
- **sharding-aware**: a value sharded over mesh axes contributes
  ``bytes / prod(axis sizes)`` per chip.  Inside ``shard_map`` bodies
  shapes are already per-shard; at the jit level the divisors are read
  off the shard_map equation's ``in_names``/``out_names`` — i.e. from
  the entry's committed PartitionSpecs, the same specs the sharding-map
  hash in bench records is built from.
- **donation-aware**: donated argument leaves free at last use, and a
  donated leaf with no same-shape/dtype output to alias (or one the
  program keeps live to the end) is a GL014 finding — donation that
  cannot take effect.

Three rules ride on the planner (rule catalogue: analysis/rules.py):

- **GL013 peak-budget-regression**: per-entry per-chip peak bytes are
  pinned in ``EXPECTED_PEAK_BYTES`` within ``PEAK_TOLERANCE``, exactly
  like pinned collective counts — a deliberate change re-pins the
  number in the same commit.
- **GL014 ineffective-or-missing-donation**: a large aliasable arg not
  donated on a grad-bearing entry, or a donated leaf whose buffer
  cannot be reused; findings name the buffer and its bytes.  The audit
  honors the CPU donation gate (parallel/compat.py) and verifies the
  TPU path still REQUESTS donation via
  :func:`~milnce_tpu.parallel.compat.donation_argnums_for_backend`.
- **GL015 top-contributor-drift**: the top-3 peak contributors per
  entry are pinned BY NAME (``EXPECTED_TOP_CONTRIBUTORS``) so a
  silently rematerialized activation shows up as a named diff, not a
  mystery byte delta.

Everything runs under ``JAX_PLATFORMS=cpu`` on the same 8-virtual-device
mesh as Pass 2; jax imports live inside functions so astlint stays
importable without jax.  ``scripts/mem_plan.py`` is the CLI (MEMPLAN.md,
``--check``, ``--what-if`` operating-point prediction).
"""

from __future__ import annotations

import functools
import math
from dataclasses import dataclass, field

from milnce_tpu.analysis.trace_invariants import CheckResult

# Relative tolerance for the GL013 peak pin: wide enough to absorb
# jaxpr-level drift across jax point releases (a fused primitive more or
# less), far tighter than the >10% batch-cliff class it exists to catch.
PEAK_TOLERANCE = 0.10

# GL014 "large" floor: an aliasable-but-undonated arg smaller than this
# costs less than the finding costs attention.  64 KiB mirrors the FSDP
# threshold's reasoning (sharding_map.DEFAULT_FSDP_MIN_SIZE in elements).
GL014_MIN_BYTES = 64 * 1024


# --------------------------------------------------------------------------
# live-range analysis over a (possibly nested) jaxpr
# --------------------------------------------------------------------------

@dataclass
class MemPlan:
    """Per-entry result of the live-range walk (all byte counts are
    PER-CHIP: sharded values divided by their mesh-axis extents)."""
    entry: str
    peak_bytes: int
    arg_bytes: int                       # entry args resident per chip
    out_bytes: int                       # entry outputs per chip
    contributors: list = field(default_factory=list)  # [(label, bytes)] desc
    donated: tuple = ()                  # labels of donated arg leaves
    mesh: str = ""

    def top(self, k: int = 3) -> tuple:
        return tuple(label for label, _ in self.contributors[:k])


def aval_bytes(aval) -> int:
    """Device bytes of one (unsharded) abstract value."""
    import numpy as np

    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    return int(size) * int(np.dtype(dtype).itemsize)


def _is_literal(v) -> bool:
    import jax

    return isinstance(v, jax.core.Literal)


def _is_dropvar(v) -> bool:
    return type(v).__name__ == "DropVar"


def _names_divisor(names_entry, axis_sizes: dict) -> int:
    """shard_map ``in_names``/``out_names`` entry ({dim: axes}) -> the
    per-chip divisor prod(axis sizes).  Trailing-None-normalized specs
    (sharding_map._dim_spec) and un-normalized ones land on the same
    divisor here — the names map only carries sharded dims."""
    d = 1
    for axes in (names_entry or {}).values():
        axes = axes if isinstance(axes, (tuple, list)) else (axes,)
        for a in axes:
            d *= int(axis_sizes.get(a, 1))
    return d


def _nested(eqn):
    """(kind, [sub-jaxprs]) for equations that carry a body.

    ``call`` bodies run once with the operands (pjit / custom_vjp /
    remat): their peak overlaps the operands already live outside.
    ``loop`` bodies run repeatedly over fresh slices (scan / while);
    ``branch`` picks one of several (cond)."""
    p, prm = eqn.primitive.name, eqn.params
    if p == "pjit":
        return "call", [prm["jaxpr"]]
    if p in ("closed_call", "core_call", "remat", "remat2", "checkpoint"):
        j = prm.get("jaxpr") or prm.get("call_jaxpr")
        return "call", [j] if j is not None else []
    if p in ("custom_vjp_call", "custom_jvp_call", "custom_vjp_call_jaxpr",
             "custom_lin"):
        j = prm.get("call_jaxpr") or prm.get("fun_jaxpr")
        return "call", [j] if j is not None else []
    if p == "shard_map":
        return "shard_map", [prm["jaxpr"]]
    if p == "scan":
        return "loop", [prm["jaxpr"]]
    if p == "while":
        return "loop", [prm["cond_jaxpr"], prm["body_jaxpr"]]
    if p == "cond":
        return "branch", list(prm["branches"])
    return "", []


def _open(j):
    return j.jaxpr if hasattr(j, "jaxpr") else j


def _div_prepass(jaxpr, invar_div):
    """Per-chip divisor map for one jaxpr level, BEFORE liveness runs —
    the initial live set (args + consts) must already be counted at
    per-chip size or an 8-way-sharded batch would inflate the entry
    peak 8x at step zero.  Divisors come from shard_map
    ``in_names``/``out_names`` (the committed specs) and propagate
    through ``call``-kind bodies in BOTH directions: a jit-level state
    arg that only a nested shard_map shards (jit(shard_map(step)) — the
    entry shape) still counts per-chip at the jit level.  Returns
    ``(div_map, outvar_divs, invar_divs)``."""
    jaxpr = _open(jaxpr)
    div: dict = {}
    for i, v in enumerate(jaxpr.invars):
        div[v] = invar_div[i] if invar_div else 1
    for eqn in jaxpr.eqns:
        kind, bodies = _nested(eqn)
        if kind == "shard_map":
            sizes = dict(getattr(eqn.params["mesh"], "shape", {}) or {})
            for v, names in zip(eqn.invars, eqn.params["in_names"]):
                if not _is_literal(v):
                    div[v] = max(div.get(v, 1), _names_divisor(names, sizes))
            for v, names in zip(eqn.outvars, eqn.params["out_names"]):
                div[v] = _names_divisor(names, sizes)
        elif kind == "call" and bodies:
            sub = [1 if _is_literal(v) else div.get(v, 1)
                   for v in eqn.invars]
            _, out_divs, in_divs = _div_prepass(bodies[0], sub)
            for v, d in zip(eqn.invars, in_divs):
                if not _is_literal(v):
                    div[v] = max(div.get(v, 1), d)
            for v, d in zip(eqn.outvars, out_divs):
                div[v] = d
    return (div, [div.get(v, 1) for v in jaxpr.outvars],
            [div.get(v, 1) for v in jaxpr.invars])


def analyze_jaxpr(closed_jaxpr, *, donated=None, labels=None) -> MemPlan:
    """Live-range walk of an entry's closed jaxpr -> :class:`MemPlan`.

    ``donated``: bool per flattened invar (True = freeable at last use);
    ``labels``: name per flattened invar (tree paths — the contributor
    attribution GL015 pins).  Intermediates are labeled
    ``"<primitive> <aval>"`` so a rematerialized activation is namable.
    """
    jaxpr = _open(closed_jaxpr)
    n = len(jaxpr.invars)
    donated = list(donated) if donated is not None else [False] * n
    labels = list(labels) if labels is not None else [f"arg{i}"
                                                     for i in range(n)]
    pinned = [not d for d in donated]
    peak, snap, arg_b, out_b = _walk(jaxpr, None, pinned, labels)
    agg: dict[str, int] = {}
    for label, nbytes in snap:
        agg[label] = agg.get(label, 0) + nbytes
    contributors = sorted(agg.items(), key=lambda kv: (-kv[1], kv[0]))
    return MemPlan(entry="", peak_bytes=peak, arg_bytes=arg_b,
                   out_bytes=out_b, contributors=contributors,
                   donated=tuple(l for l, d in zip(labels, donated) if d))


def _walk(jaxpr, invar_div, pinned, labels):
    """One level of the analysis.  Returns ``(peak, snapshot, arg_bytes,
    out_bytes)`` — snapshot is the flat [(label, bytes)] of everything
    live at the peak instant, across nest levels."""
    jaxpr = _open(jaxpr)
    div, out_divs, _in_divs = _div_prepass(jaxpr, invar_div)
    lab: dict = {}
    for v, name in zip(jaxpr.invars, labels or []):
        lab[v] = name
    for v in jaxpr.constvars:
        div.setdefault(v, 1)
        lab[v] = f"const {v.aval.str_short()}"

    def per_chip(v) -> int:
        return -(-aval_bytes(v.aval) // div.get(v, 1))

    last: dict = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last[v] = i
    outset = {v for v in jaxpr.outvars if not _is_literal(v)}
    for v in outset:
        last[v] = len(jaxpr.eqns)
    pinset = {v for v, p in zip(jaxpr.invars, pinned or []) if p}

    live: dict = {}
    for v in list(jaxpr.invars) + list(jaxpr.constvars):
        live[v] = per_chip(v)
    arg_bytes = sum(live[v] for v in jaxpr.invars)
    peak = sum(live.values())
    snap = [(lab.get(v, "?"), b) for v, b in live.items()]

    for i, eqn in enumerate(jaxpr.eqns):
        kind, bodies = _nested(eqn)
        out_bytes_eqn = sum(per_chip(v) for v in eqn.outvars
                            if not _is_dropvar(v))
        if not bodies:
            # in-place reuse: an operand DYING at this equation whose
            # shape/dtype matches an output lends it its buffer — what
            # XLA's buffer assignment does for any dead intermediate,
            # and what donation extends to entry args (a donated state
            # updating in place is exactly this rule firing at the
            # optimizer's add)
            pool: dict = {}
            for v in {x for x in eqn.invars if not _is_literal(x)}:
                if last.get(v) == i and v not in pinset and v in live:
                    key = (tuple(v.aval.shape), str(v.aval.dtype))
                    pool[key] = pool.get(key, 0) + 1
            reuse = 0
            for v in eqn.outvars:
                if _is_dropvar(v):
                    continue
                key = (tuple(v.aval.shape), str(v.aval.dtype))
                if pool.get(key, 0) > 0:
                    pool[key] -= 1
                    reuse += per_chip(v)
            out_bytes_eqn = max(0, out_bytes_eqn - reuse)
        transient, inner_snap = out_bytes_eqn, []
        if bodies:
            sub_labels = [("lit" if _is_literal(v)
                           else lab.get(v, f"{eqn.primitive.name} operand"))
                          for v in eqn.invars]
            sub_pin = [(not _is_literal(v)) and v in pinset
                       for v in eqn.invars]
            if kind in ("call", "shard_map"):
                # body peak counts the operands again (they ARE the body
                # invars — same buffers); subtract the overlap so a
                # value crossing the nest boundary is counted once.  The
                # body's in-flight outputs stand in for the eqn outputs,
                # which only join the outer live set at completion.
                sub_div = None
                if kind == "call":
                    sub_div = [1 if _is_literal(v) else div.get(v, 1)
                               for v in eqn.invars]
                p2, s2, _a, _o = _walk(bodies[0], sub_div, sub_pin,
                                       sub_labels)
                overlap = sum(live.get(v, 0) for v in
                              {x for x in eqn.invars if not _is_literal(x)}
                              & set(live))
                transient, inner_snap = max(0, p2 - overlap), s2
            else:   # loop / branch: body runs over fresh slices; stacked
                    # eqn outputs fill DURING execution, so they stay in
                    # the transient alongside the body peak
                best, best_snap = 0, []
                for body in bodies:
                    binv = _open(body).invars
                    body_labels = [
                        f"{eqn.primitive.name} body {v.aval.str_short()}"
                        for v in binv]
                    p2, s2, _a, _o = _walk(body, None, [False] * len(binv),
                                           body_labels)
                    if p2 >= best:
                        best, best_snap = p2, s2
                # consts AND the carry overlap the body's view of them:
                # the carry is ONE buffer threaded through iterations
                # (scan reuses it in place), never a per-iteration copy
                n_over = int(eqn.params.get("num_consts", 0)) + int(
                    eqn.params.get("num_carry", 0))
                overlap = sum(live.get(v, 0)
                              for v in eqn.invars[:n_over]
                              if not _is_literal(v) and v in live)
                transient = out_bytes_eqn + max(0, best - overlap)
                inner_snap = best_snap

        cur = sum(live.values()) + transient
        if cur > peak:
            peak = cur
            snap = [(lab.get(v, "?"), b) for v, b in live.items()]
            if bodies:
                snap += inner_snap
            else:
                snap += [(f"{eqn.primitive.name} {v.aval.str_short()}",
                          per_chip(v)) for v in eqn.outvars
                         if not _is_dropvar(v)]

        # completion: outputs join the live set, dead operands free
        for v in eqn.outvars:
            if _is_dropvar(v):
                continue
            live[v] = per_chip(v)
            lab[v] = f"{eqn.primitive.name} {v.aval.str_short()}"
        for v in {x for x in eqn.invars if not _is_literal(x)}:
            if last.get(v) == i and v not in pinset and v in live:
                del live[v]
        for v in eqn.outvars:
            if (not _is_dropvar(v) and last.get(v, -1) <= i
                    and v not in outset and v in live):
                del live[v]          # dead output (DCE'd downstream)
        cur = sum(live.values())
        if cur > peak:
            peak = cur
            snap = [(lab.get(v, "?"), b) for v, b in live.items()]

    out_bytes = sum(-(-aval_bytes(v.aval) // d)
                    for v, d in zip(jaxpr.outvars, out_divs)
                    if not _is_literal(v))
    return peak, snap, arg_bytes, out_bytes


# --------------------------------------------------------------------------
# entry planning
# --------------------------------------------------------------------------

def arg_leaf_labels(args, argnames) -> list:
    """Flattened-leaf labels for an entry's positional args — the tree
    paths GL015 pins (``state/params/conv1/kernel``, ``video``, ...)."""
    import jax

    from milnce_tpu.parallel.sharding_map import _path_str

    flat, _ = jax.tree_util.tree_flatten_with_path(tuple(args))
    out = []
    for path, _leaf in flat:
        idx = getattr(path[0], "idx", 0)
        rest = _path_str(path[1:])
        out.append(argnames[idx] + ("/" + rest if rest else ""))
    return out


def donated_leaf_flags(args, donate_argnums) -> list:
    """bool per flattened leaf: does its top-level positional arg sit in
    ``donate_argnums`` (the entry's TPU donation intent)?"""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tuple(args))
    want = set(donate_argnums or ())
    return [getattr(path[0], "idx", 0) in want for path, _leaf in flat]


def plan_fn(fn, args, *, argnames, donate_argnums=(), entry="",
            mesh="") -> "MemPlan":
    """Trace ``fn(*args)`` and run the live-range walk with the entry's
    donation intent applied (the TPU path's donation, even when the
    entry itself was built donate=False for the CPU gate)."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    plan = analyze_jaxpr(
        closed,
        donated=donated_leaf_flags(args, donate_argnums),
        labels=arg_leaf_labels(args, argnames))
    plan.entry = entry
    plan.mesh = mesh
    return plan


def donation_findings(fn, args, *, argnames, donate_argnums, grad_bearing,
                      min_bytes: int = GL014_MIN_BYTES) -> list:
    """GL014: (a) donated leaves that cannot alias any output
    (no same-shape/dtype output left to claim, or the input is itself
    kept live to the end), (b) large aliasable args NOT donated on a
    grad-bearing entry.  Each finding names the buffer and its bytes."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    return _donation_findings_jaxpr(
        closed, arg_leaf_labels(args, argnames),
        donated_leaf_flags(args, donate_argnums), grad_bearing,
        min_bytes=min_bytes)


def _donation_findings_jaxpr(closed, labels, donated, grad_bearing,
                             min_bytes: int = GL014_MIN_BYTES) -> list:
    jaxpr = _open(closed)
    # multiset of output (shape, dtype) available for aliasing
    pool: dict = {}
    for v in jaxpr.outvars:
        if _is_literal(v):
            continue
        key = (tuple(v.aval.shape), str(v.aval.dtype))
        pool[key] = pool.get(key, 0) + 1
    passthrough = {v for v in jaxpr.outvars if not _is_literal(v)}
    findings = []
    for v, label, don in zip(jaxpr.invars, labels, donated):
        key = (tuple(v.aval.shape), str(v.aval.dtype))
        nbytes = aval_bytes(v.aval)
        if don:
            if v in passthrough:
                findings.append(
                    f"donated `{label}` ({nbytes} B) is returned "
                    "unchanged — its buffer stays live to the end, the "
                    "donation cannot take effect")
            elif pool.get(key, 0) > 0:
                pool[key] -= 1
            else:
                findings.append(
                    f"donated `{label}` ({nbytes} B, {key[1]}"
                    f"{list(key[0])}) matches no program output — XLA "
                    "cannot reuse the buffer, the donation is dead "
                    "weight")
        elif (grad_bearing and nbytes >= min_bytes
                and v not in passthrough      # returned unchanged: donating
                and pool.get(key, 0) > 0):    # it could never take effect
            findings.append(
                f"`{label}` ({nbytes} B) aliases an output "
                f"shape/dtype but is not donated — at scale that is "
                "two copies of the buffer across the update")
    return findings


# --------------------------------------------------------------------------
# registered entries + pins (the Pass 4 gate)
# --------------------------------------------------------------------------

_STEP_ARGNAMES = ("state", "video", "text", "start")


@dataclass(frozen=True)
class MemEntry:
    name: str
    build: object                      # () -> (fn, args)
    argnames: tuple = _STEP_ARGNAMES
    donate_argnums: tuple = ()         # the TPU path's donation intent
    grad_bearing: bool = False
    mesh: str = "8x1 (data)"


def _e_train(loss: str = "milnce", guard: bool = False):
    def build(donate: bool = False):
        from milnce_tpu.analysis.trace_invariants import _setup
        from milnce_tpu.config import LossConfig
        from milnce_tpu.train.step import make_train_step

        model, opt, mesh, state, batch = _setup()
        loss_cfg = (None if loss == "milnce"
                    else LossConfig(name=loss, sdtw_backend="scan"))
        step = make_train_step(model, opt, mesh, donate=donate,
                               loss_cfg=loss_cfg, finite_guard=guard)
        return step, (state,) + batch()
    return build


def _e_grad_cache():
    def build(donate: bool = False):
        from milnce_tpu.analysis.trace_invariants import _setup
        from milnce_tpu.config import LossConfig
        from milnce_tpu.train.step import make_grad_cache_step

        model, opt, mesh, state, batch = _setup()
        step = make_grad_cache_step(model, opt, mesh, 2, donate=donate,
                                    loss_cfg=LossConfig(name="milnce"))
        return step, (state,) + batch()
    return build


def _e_train_2d(grad_cache: bool = False):
    def build(donate: bool = False):
        from milnce_tpu.analysis.trace_invariants import _setup_2d
        from milnce_tpu.config import LossConfig
        from milnce_tpu.train.step import (make_grad_cache_step,
                                           make_train_step)

        model, opt, mesh, specs, state, batch = _setup_2d()
        if grad_cache:
            step = make_grad_cache_step(model, opt, mesh, 2, donate=donate,
                                        loss_cfg=LossConfig(name="milnce"),
                                        state_specs=specs,
                                        model_axis="model")
        else:
            step = make_train_step(model, opt, mesh, donate=donate,
                                   state_specs=specs, model_axis="model")
        return step, (state,) + batch()
    return build


def _e_train_4way():
    def build(donate: bool = False):
        from milnce_tpu.analysis.trace_invariants import _setup_4way
        from milnce_tpu.train.step import make_train_step

        model, opt, mesh, state, batch = _setup_4way()
        step = make_train_step(model, opt, mesh, donate=donate)
        return step, (state,) + batch()
    return build


def _e_train_chunked():
    def build(donate: bool = False):
        from milnce_tpu.analysis.trace_invariants import (_chunked_loss_cfg,
                                                          _setup)
        from milnce_tpu.train.step import make_train_step

        model, opt, mesh, state, batch = _setup()
        step = make_train_step(model, opt, mesh, donate=donate,
                               loss_cfg=_chunked_loss_cfg())
        return step, (state,) + batch()
    return build


# Loss-only entries (ISSUE 12): the dense cube vs the chunked stream at
# a shape where the LOSS side dominates the plan — b_local=64, Bg=512,
# K=5, D=16 on the 8-way mesh, so one (B_local, Bg, K) f32 cube is
# 640 KiB/chip against ~200 KiB of gathered embeddings.  The pins prove
# the tentpole's scaling claim structurally: dense peaks at the cubes +
# their AD twins (O(B_local * Bg * K)); chunked peaks at one streamed
# block (O(B_local * chunk)) — GL013 numbers + the GL015 contributor
# names say which buffers those are.
_MILNCE_LOSS_SHAPE = dict(b_global=512, k=5, d=16, chunk=64)


def milnce_loss_plan_program(impl: str, b_global: int, k: int, d: int,
                             chunk: int, backend: str = "scan"):
    """The ONE sharded value-and-grad loss program both the GL013
    entries and scripts/milnce_loss_bench.py's memory column plan —
    shared so the committed BENCH_MILNCE_LOSS.md peaks can never drift
    from the pinned entries' program.  Returns ``(fn, args)`` for
    :func:`plan_fn` (args are abstract — nothing allocates)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from milnce_tpu.analysis.trace_invariants import _setup
    from milnce_tpu.losses.milnce import milnce_loss
    from milnce_tpu.losses.milnce_chunked import milnce_loss_chunked
    from milnce_tpu.parallel.compat import shard_map

    _model, _opt, mesh, _state, _batch = _setup()

    def local(v, t):
        if impl == "chunked":
            return milnce_loss_chunked(v, t, axis_name="data",
                                       chunk=chunk, backend=backend)
        return milnce_loss(v, t, axis_name="data")

    def value_and_grads(v, t):
        return jax.value_and_grad(local, argnums=(0, 1))(v, t)

    fn = jax.jit(shard_map(
        value_and_grads, mesh=mesh,
        in_specs=(P("data"), P("data")),
        out_specs=(P(), (P("data"), P("data"))), check_vma=False))
    args = (jax.ShapeDtypeStruct((b_global, d), jnp.float32),
            jax.ShapeDtypeStruct((b_global * k, d), jnp.float32))
    return fn, args


def _e_milnce_loss(impl: str):
    def build():
        return milnce_loss_plan_program(impl, **_MILNCE_LOSS_SHAPE)
    return build


@functools.lru_cache(maxsize=1)
def _serve_engine():
    """Cold engine (precompile=False — planning only needs the traced
    programs, not warmed executables) over the shared tiny setup."""
    import jax

    from milnce_tpu.analysis.trace_invariants import (_FRAMES, _SIZE,
                                                      _WORDS, _setup)
    from milnce_tpu.serving.engine import InferenceEngine

    model, _opt, mesh, state, _batch = _setup()
    varz = {"params": state.params, "batch_stats": state.batch_stats}
    ndev = len(jax.devices())
    engine = InferenceEngine(model, varz, mesh, text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=2 * ndev, precompile=False)
    return engine, varz


def _e_serve(entry: str, bucket_idx: int):
    def build():
        import numpy as np

        from milnce_tpu.analysis.trace_invariants import _FRAMES, _SIZE, _WORDS

        engine, varz = _serve_engine()
        fn = engine.jit_entries()[entry]
        b = engine.buckets[bucket_idx]
        x = (np.zeros((b, _WORDS), np.int32) if entry == "text"
             else np.zeros((b, _FRAMES, _SIZE, _SIZE, 3), np.uint8))
        return fn, (varz, x)
    return build


@functools.lru_cache(maxsize=1)
def _serve_pool_engine():
    """Cold SINGLE-DEVICE replica engine — the pool's CPU test shape
    (serving/pool.py: one replica per device group, single-device groups
    on the CPU backend).  The per-chip plan of a replica entry must
    charge exactly ONE replica's footprint: params are per-replica
    copies but each lives on its own device group, so N replicas never
    stack bytes on a chip (a divisor-of-1 shard_map on the replica's own
    mesh, NOT the full test mesh's 8-way division)."""
    import jax
    import numpy as np
    from jax.sharding import Mesh

    from milnce_tpu.analysis.lockrt import make_lock
    from milnce_tpu.analysis.trace_invariants import (_FRAMES, _SIZE,
                                                      _WORDS, _setup)
    from milnce_tpu.serving.engine import InferenceEngine

    model, _opt, _mesh, state, _batch = _setup()
    varz = {"params": state.params, "batch_stats": state.batch_stats}
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("data",))
    engine = InferenceEngine(
        model, varz, mesh, text_words=_WORDS,
        video_shape=(_FRAMES, _SIZE, _SIZE, 3), max_batch=4, min_bucket=2,
        precompile=False,
        dispatch_lock=make_lock("serving.replica0.dispatch"))
    return engine, varz


def _e_pool_serve(entry: str, bucket_idx: int):
    def build():
        import numpy as np

        from milnce_tpu.analysis.trace_invariants import _FRAMES, _SIZE, _WORDS

        engine, varz = _serve_pool_engine()
        fn = engine.jit_entries()[entry]
        b = engine.buckets[bucket_idx]
        x = (np.zeros((b, _WORDS), np.int32) if entry == "text"
             else np.zeros((b, _FRAMES, _SIZE, _SIZE, 3), np.uint8))
        return fn, (varz, x)
    return build


@functools.lru_cache(maxsize=1)
def _serve_quant_engine():
    """Cold QUANTIZED engine (ISSUE 19): the tiny model's weights int8
    per the readiness rule, behind the same ladder.  The plan prices
    what the edge tier buys — int8 param residency (4x smaller leaves)
    against the in-jit dequantize's transient f32 copies; the pins keep
    that trade visible, so a dequant that started materializing the
    whole f32 tree at once shows up as GL013/GL015 drift."""
    import jax

    from milnce_tpu.analysis.trace_invariants import (_FRAMES, _SIZE,
                                                      _WORDS, _setup)
    from milnce_tpu.quant.quantize import (QuantizedModel,
                                           quantize_variables)
    from milnce_tpu.serving.engine import InferenceEngine

    model, _opt, mesh, state, _batch = _setup()
    varz = {"params": state.params, "batch_stats": state.batch_stats}
    qvarz = quantize_variables(varz)
    ndev = len(jax.devices())
    engine = InferenceEngine(QuantizedModel(model), qvarz, mesh,
                             text_words=_WORDS,
                             video_shape=(_FRAMES, _SIZE, _SIZE, 3),
                             max_batch=2 * ndev, precompile=False)
    return engine, qvarz


def _e_quant_serve(entry: str, bucket_idx: int):
    def build():
        import numpy as np

        from milnce_tpu.analysis.trace_invariants import _FRAMES, _SIZE, _WORDS

        engine, qvarz = _serve_quant_engine()
        fn = engine.jit_entries()[entry]
        b = engine.buckets[bucket_idx]
        x = (np.zeros((b, _WORDS), np.int32) if entry == "text"
             else np.zeros((b, _FRAMES, _SIZE, _SIZE, 3), np.uint8))
        return fn, (qvarz, x)
    return build


def _e_index_topk():
    def build():
        import jax
        import numpy as np

        from milnce_tpu.analysis.trace_invariants import _TINY, _setup
        from milnce_tpu.serving.index import DeviceRetrievalIndex

        _model, _opt, mesh, _state, _batch = _setup()
        ndev = len(jax.devices())
        rng = np.random.default_rng(0)
        corpus = rng.standard_normal(
            (3 * ndev - 2, _TINY["embedding_dim"])).astype(np.float32)
        index = DeviceRetrievalIndex(mesh, corpus, k=3,
                                     query_buckets=(ndev,))
        q = rng.standard_normal((ndev, index.dim)).astype(np.float32)
        fn, operands = index.topk_program()
        return fn, operands + (q,)
    return build


def _e_live_index_topk():
    def build():
        import jax
        import numpy as np

        from milnce_tpu.analysis.trace_invariants import _TINY, _setup
        from milnce_tpu.serving.live_index import LiveRetrievalIndex

        _model, _opt, mesh, _state, _batch = _setup()
        ndev = len(jax.devices())
        rng = np.random.default_rng(0)
        corpus = rng.standard_normal(
            (3 * ndev - 2, _TINY["embedding_dim"])).astype(np.float32)
        # same boot corpus as serve_index_topk, but the LIVE index pads
        # every shard to its capacity RUNG (power of two >= k) — the
        # footprint the planner prices is the rung's, i.e. what a
        # generation costs for the whole life of that rung
        index = LiveRetrievalIndex(mesh, corpus, k=3, query_buckets=(ndev,),
                                   precompile=False)
        try:
            q = rng.standard_normal((ndev, index.dim)).astype(np.float32)
            fn, operands = index.topk_program()
            return fn, operands + (q,)
        finally:
            index.close()
    return build


def _entries() -> dict:
    from milnce_tpu.train.step import STATE_DONATION_ARGNUMS as DON

    return {e.name: e for e in (
        MemEntry("train_step_milnce", _e_train(), donate_argnums=DON,
                 grad_bearing=True),
        MemEntry("train_step_milnce_guarded", _e_train(guard=True),
                 donate_argnums=DON, grad_bearing=True),
        MemEntry("train_step_sdtw3", _e_train(loss="sdtw_3"),
                 donate_argnums=DON, grad_bearing=True),
        MemEntry("grad_cache_step_milnce", _e_grad_cache(),
                 donate_argnums=DON, grad_bearing=True),
        MemEntry("train_step_milnce_chunked", _e_train_chunked(),
                 donate_argnums=DON, grad_bearing=True),
        MemEntry("milnce_loss_dense", _e_milnce_loss("dense"),
                 argnames=("video", "text")),
        MemEntry("milnce_loss_chunked", _e_milnce_loss("chunked"),
                 argnames=("video", "text")),
        MemEntry("train_step_milnce@4way", _e_train_4way(),
                 donate_argnums=DON, grad_bearing=True,
                 mesh="4x1 (data)"),
        MemEntry("train_step_milnce_2d", _e_train_2d(),
                 donate_argnums=DON, grad_bearing=True,
                 mesh="4x2 (data,model)"),
        MemEntry("grad_cache_2d", _e_train_2d(grad_cache=True),
                 donate_argnums=DON, grad_bearing=True,
                 mesh="4x2 (data,model)"),
        MemEntry("serve_text_embed@b0", _e_serve("text", 0),
                 argnames=("variables", "tokens")),
        MemEntry("serve_text_embed@b1", _e_serve("text", 1),
                 argnames=("variables", "tokens")),
        MemEntry("serve_video_embed@b0", _e_serve("video", 0),
                 argnames=("variables", "video")),
        MemEntry("serve_video_embed@b1", _e_serve("video", 1),
                 argnames=("variables", "video")),
        MemEntry("serve_index_topk", _e_index_topk(),
                 argnames=("corpus", "valid", "queries")),
        MemEntry("serve_index_topk@gen", _e_live_index_topk(),
                 argnames=("corpus", "valid", "queries")),
        MemEntry("serve_pool_text_embed@b0", _e_pool_serve("text", 0),
                 argnames=("variables", "tokens"), mesh="1x1 replica"),
        MemEntry("serve_pool_video_embed@b1", _e_pool_serve("video", 1),
                 argnames=("variables", "video"), mesh="1x1 replica"),
        MemEntry("serve_quant_text_embed@b1", _e_quant_serve("text", 1),
                 argnames=("variables", "tokens")),
        MemEntry("serve_quant_video_embed@b1", _e_quant_serve("video", 1),
                 argnames=("variables", "video")),
    )}


# Pinned per-chip peak bytes (GL013) for the tiny entry configs on the
# hermetic CPU meshes.  Like EXPECTED_COLLECTIVES: the invariant is that
# they never change SILENTLY — a deliberate model/step/layout change
# re-pins the number in the same commit.  Derived by
# ``python scripts/mem_plan.py`` (which prints the re-pin dict on drift).
EXPECTED_PEAK_BYTES = {
    "train_step_milnce": 10612424,
    "train_step_milnce_guarded": 16917340,
    "train_step_sdtw3": 10612424,
    "grad_cache_step_milnce": 12448688,
    # chunked MIL-NCE (ISSUE 12): the full chunked step pins IDENTICAL
    # to train_step_milnce — at the tiny entry scale the optimizer
    # moments dominate both, which is itself the no-regression pin (the
    # stream must never ADD memory).  The loss-only pair below isolates
    # the loss side at a shape where the cube dominates: dense peaks at
    # the (B_local, Bg, K) cubes + AD twins (the GL015 names are the
    # [64, 2560] = (B_local, Bg*K) cube ops), chunked at one
    # (B_local, chunk*K) streamed block — O(B_local*Bg*K) ->
    # O(B_local*chunk), 4.1x less per chip at this shape, and the gap
    # widens linearly in Bg/chunk (tests/test_memplan.py pins the
    # strict inequality; PERF.md "Memory-efficient loss" has the
    # Bg=8192 what-if numbers).
    "train_step_milnce_chunked": 10612424,
    "milnce_loss_dense": 2863940,
    "milnce_loss_chunked": 703276,
    # elastic 4-way layout (ISSUE 20): pinned IDENTICAL to the 8-way
    # step — per-chip peak is a function of clips PER CHIP (2 at both
    # layouts: b = 2*ndev shards evenly), so downsizing the mesh halves
    # the global batch, never the per-chip footprint.  That equality is
    # the elastic memory contract: a resume onto fewer chips fits
    # wherever the full mesh fit.
    "train_step_milnce@4way": 10612424,
    "train_step_milnce_2d": 8652104,
    "grad_cache_2d": 11399984,
    "serve_text_embed@b0": 2119092,
    "serve_text_embed@b1": 2119592,
    "serve_video_embed@b0": 2311104,
    "serve_video_embed@b1": 2503616,
    "serve_index_topk": 2436,
    # live index (ISSUE 14): same program, shard rows padded to the
    # capacity RUNG (pow2 >= k: 3 rows/shard -> 4) — the 64-byte delta
    # vs the frozen entry is the rung headroom, i.e. what pre-provisioned
    # growth costs per chip at the tiny scale
    "serve_index_topk@gen": 2500,
    # replica-pool entries (ISSUE 10): per-chip bytes on a replica's OWN
    # single-device mesh.  The pin is the no-double-count property: a
    # pool puts ONE replica per device (group), so a replica's per-chip
    # footprint equals the single-engine entry at the same rows-per-chip
    # (text@b0 here is 2 rows on 1 chip == serve_text_embed@b1's 16 rows
    # over 8 chips — byte-identical), never N-replicas-times-anything
    "serve_pool_text_embed@b0": 2119592,
    "serve_pool_video_embed@b1": 2888640,
    # quantized edge engine (ISSUE 19): int8 residency vs dequant
    # transients, both legible in the numbers.  The text entry drops to
    # ~0.5x the f32 engine's peak (params live as int8; only the text
    # tower's few kernels dequantize, transiently).  The video entry
    # pays ~1.2x: the conv kernels' f32 dequant copies (the GL015 `mul`
    # names) overlap the activation peak — the expected trade (the edge
    # class buys HBM residency and PCIe bytes, not peak-transient)
    "serve_quant_text_embed@b1": 986108,
    "serve_quant_video_embed@b1": 3026132,
}

# Pinned top-3 peak contributors per entry (GL015), by aggregated label:
# args by tree path, intermediates by "primitive aval".  A silently
# rematerialized activation / doubled optimizer moment shows up HERE as
# a named diff even when the byte delta hides inside the GL013
# tolerance.  Re-pin consciously, same commit, like the counts above.
EXPECTED_TOP_CONTRIBUTORS = {
    "train_step_milnce": (
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_spatial/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_temporal/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "train_step_milnce_guarded": (
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_spatial/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_temporal/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    # 4-way elastic-resume layout: per-chip hot set identical to the
    # 8-way entry — replicated optimizer moments dominate at both
    "train_step_milnce@4way": (
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_spatial/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_temporal/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "train_step_sdtw3": (
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_spatial/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_temporal/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "grad_cache_step_milnce": (
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_spatial/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_temporal/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "train_step_milnce_chunked": (
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_spatial/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_temporal/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    # the loss-only pair: dense's top contributors ARE the similarity
    # cube ([64, 2560] = (B_local, Bg*K) softmax intermediates + the
    # lse-transpose scatter over the (B_local, Bg, K) cube); chunked's
    # are one (B_local, chunk*K) = [64, 320] streamed block — the
    # tentpole's scaling claim, pinned by name
    "milnce_loss_dense": (
        "exp float32[64,2560]",
        "broadcast_in_dim float32[64,2560]",
        "scatter-add float32[64,512,5]"),
    "milnce_loss_chunked": (
        "exp float32[64,320]",
        "reshape float32[8,320,16]",
        "broadcast_in_dim float32[64,320]"),
    "train_step_milnce_2d": (
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_spatial/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/conv_2c/conv_temporal/kernel",
        "state/opt_state/inner_state/inner_state/0/mu/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "grad_cache_2d": (
        "scan body float32[1,3,3,64,192]",
        "scan body float32[1,3,3,96,128]",
        "scan body float32[3,1,1,192,192]"),
    "serve_text_embed@b0": (
        "variables/params/conv_2c/conv_spatial/kernel",
        "variables/params/conv_2c/conv_temporal/kernel",
        "variables/params/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "serve_text_embed@b1": (
        "variables/params/conv_2c/conv_spatial/kernel",
        "variables/params/conv_2c/conv_temporal/kernel",
        "variables/params/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "serve_video_embed@b0": (
        "variables/params/conv_2c/conv_spatial/kernel",
        "variables/params/conv_2c/conv_temporal/kernel",
        "variables/params/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "serve_video_embed@b1": (
        "variables/params/conv_2c/conv_spatial/kernel",
        "variables/params/conv_2c/conv_temporal/kernel",
        "variables/params/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "serve_index_topk": (
        "queries",
        "all_gather float32[8,24]",
        "all_gather int32[8,24]"),
    "serve_index_topk@gen": (
        "queries",
        "all_gather float32[8,24]",
        "all_gather int32[8,24]"),
    "serve_pool_text_embed@b0": (
        "variables/params/conv_2c/conv_spatial/kernel",
        "variables/params/conv_2c/conv_temporal/kernel",
        "variables/params/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    "serve_pool_video_embed@b1": (
        "variables/params/conv_2c/conv_spatial/kernel",
        "variables/params/conv_2c/conv_temporal/kernel",
        "variables/params/mixed_3b/conv_b1_b/conv_spatial/kernel"),
    # quant entries: the top contributors ARE the dequant story — the
    # text peak sits at one kernel's i8->f32 convert beside the int8
    # residents; the video peak at the three largest kernels' scale
    # `mul` outputs (the f32 copies that feed the convs)
    "serve_quant_text_embed@b1": (
        "convert_element_type float32[1,3,3,64,192]",
        "variables/params/conv_2c/conv_spatial/kernel",
        "variables/params/conv_2c/conv_temporal/kernel"),
    "serve_quant_video_embed@b1": (
        "mul float32[1,3,3,64,192]",
        "mul float32[1,3,3,96,128]",
        "mul float32[3,1,1,192,192]"),
}


@functools.lru_cache(maxsize=None)
def _traced_entry(name: str):
    """(closed_jaxpr, labels, donated_flags) for one registered entry —
    cached per process: tracing the step is the expensive half of
    Pass 4, and the GL013/GL015 plan and the GL014 audit walk the SAME
    program."""
    import jax

    spec = _entries()[name]
    fn, args = spec.build()
    return (jax.make_jaxpr(fn)(*args),
            arg_leaf_labels(args, spec.argnames),
            donated_leaf_flags(args, spec.donate_argnums))


def _plan_entry(name: str) -> MemPlan:
    spec = _entries()[name]
    closed, labels, donated = _traced_entry(name)
    plan = analyze_jaxpr(closed, donated=donated, labels=labels)
    plan.entry = name
    plan.mesh = spec.mesh
    return plan


def check_entry_names(entries) -> None:
    """A typo'd entry filter must fail loudly, not plan zero entries
    and pass the gate vacuously (the stage_probe --stages /
    lint-scope discipline)."""
    if entries is None:
        return
    unknown = set(entries) - set(_entries())
    if unknown:
        raise ValueError(
            f"unknown memplan entries: {sorted(unknown)} (registered: "
            f"{', '.join(_entries())})")


def plan_all(entries=None) -> dict:
    """name -> MemPlan for the registered entries (or a subset)."""
    check_entry_names(entries)
    plans: dict = {}
    for name in _entries():
        if entries is not None and name not in entries:
            continue
        plans[name] = _plan_entry(name)
    return plans


def _check_gl013(name: str, plan: MemPlan) -> CheckResult:
    want = EXPECTED_PEAK_BYTES.get(name)
    if want is None:
        return CheckResult(name, "GL013-peak-budget", False,
                           f"entry unpinned — add EXPECTED_PEAK_BYTES"
                           f"[{name!r}] = {plan.peak_bytes}")
    drift = (plan.peak_bytes - want) / want
    ok = abs(drift) <= PEAK_TOLERANCE
    return CheckResult(
        name, "GL013-peak-budget", ok,
        "" if ok else
        f"per-chip peak {plan.peak_bytes} B vs pinned {want} B "
        f"({drift:+.1%}, tolerance ±{PEAK_TOLERANCE:.0%}) — memory "
        "structure changed; if intended, re-pin EXPECTED_PEAK_BYTES")


def _check_gl015(name: str, plan: MemPlan) -> CheckResult:
    want = EXPECTED_TOP_CONTRIBUTORS.get(name)
    if want is None:
        return CheckResult(name, "GL015-top-contributors", False,
                           f"entry unpinned — add EXPECTED_TOP_CONTRIBUTORS"
                           f"[{name!r}] = {plan.top()}")
    got = plan.top(len(want))
    ok = got == tuple(want)
    return CheckResult(
        name, "GL015-top-contributors", ok,
        "" if ok else
        f"top contributors drifted: expected {tuple(want)}, planned "
        f"{got} — a renamed entry here is a re-materialized or "
        "re-shaped peak buffer; if intended, re-pin "
        "EXPECTED_TOP_CONTRIBUTORS")


def traced_donated_invar_count(fn, args) -> int:
    """Flattened invars the traced program actually marks donated —
    read off the top-level pjit equation's ``donated_invars``, i.e.
    what the factory REALLY passed to ``jax.jit``, not what a registry
    claims it passes."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    total = 0
    for eqn in _open(closed).eqns:
        if eqn.primitive.name == "pjit":
            total += sum(bool(d) for d in
                         eqn.params.get("donated_invars", ()))
    return total


@functools.lru_cache(maxsize=None)
def _tpu_donation_wired(name: str):
    """(donated_invars_traced, donated_leaves_expected) for a
    grad-bearing entry's PRODUCTION build (donate=True) under a
    forced-TPU donation gate.

    This is the half of GL014 the registry cannot vouch for: the
    entry's factory must actually WIRE the donation intent into
    ``jax.jit`` on accelerator backends.  We swap the factory's
    ``donation_argnums`` binding for the pure TPU-keyed rule
    (parallel/compat.donation_argnums_for_backend), build with
    ``donate=True``, and count ``donated_invars`` in the traced pjit —
    a factory that dropped its ``donate_argnums=`` plumbing traces
    zero donated invars here and fails the check, while the plain
    registry round-trip would have stayed green."""
    from milnce_tpu.parallel.compat import donation_argnums_for_backend
    from milnce_tpu.train import step as step_mod

    spec = _entries()[name]
    real = step_mod.donation_argnums
    step_mod.donation_argnums = functools.partial(
        donation_argnums_for_backend, "tpu")
    try:
        fn, args = spec.build(donate=True)
        traced = traced_donated_invar_count(fn, args)
    finally:
        step_mod.donation_argnums = real
    expected = sum(donated_leaf_flags(args, spec.donate_argnums))
    return traced, expected


def _check_gl014(name: str, spec: MemEntry) -> list:
    """The donation audit: jaxpr-level effectiveness findings plus the
    backend-gate half — the CPU build legitimately drops donation
    (parallel/compat.py), but every grad-bearing entry's factory must
    still wire the request into ``jax.jit`` on the TPU path (verified
    against the TRACED program, not the registry's claim)."""
    out = []
    closed, labels, donated = _traced_entry(name)
    found = _donation_findings_jaxpr(closed, labels, donated,
                                     spec.grad_bearing)
    out.append(CheckResult(
        name, "GL014-donation", not found,
        "; ".join(found[:4]) if found else ""))
    if spec.grad_bearing:
        traced, expected = _tpu_donation_wired(name)
        ok = expected > 0 and traced == expected
        out.append(CheckResult(
            name, "GL014-tpu-donation-requested", bool(ok),
            "" if ok else
            f"production build (donate=True) under the TPU donation "
            f"gate traces {traced} donated invars, expected {expected} "
            f"(the {spec.donate_argnums} state tree) — the factory "
            "dropped its donate_argnums plumbing, or the CPU gate "
            "leaked into the TPU program"))
    return out


def run_memplan_checks(entries=None, plans=None) -> list:
    """graftlint Pass 4: GL013 + GL014 + GL015 over every registered
    entry, plus the instrumented-step identity (the obs span wrapper
    must not change the memory plan any more than it may change the
    collectives).  Builder failures become failing results."""
    check_entry_names(entries)
    results: list = []
    specs = _entries()
    if plans is None:
        plans = {}
    for name, spec in specs.items():
        if entries is not None and name not in entries:
            continue
        try:
            if name not in plans:
                plans[name] = _plan_entry(name)
            plan = plans[name]
            results.append(_check_gl013(name, plan))
            results.append(_check_gl015(name, plan))
            results.extend(_check_gl014(name, spec))
        except Exception as exc:                     # pragma: no cover
            results.append(CheckResult(name, "memplan-build", False,
                                       f"{type(exc).__name__}: {exc}"))
    if (entries is None and "train_step_milnce" in plans):
        # the instrumented step is the SAME program behind a host-side
        # span — its plan must be byte-identical to the plain step's
        try:
            from milnce_tpu.analysis.trace_invariants import _setup
            from milnce_tpu.obs import spans as obs_spans
            from milnce_tpu.train.step import make_train_step

            model, opt, mesh, state, batch = _setup()
            step = make_train_step(model, opt, mesh, donate=False)
            rec = obs_spans.SpanRecorder()

            def instrumented(s, video, text, start):
                with rec.span("step"):
                    return step(s, video, text, start)

            from milnce_tpu.train.step import STATE_DONATION_ARGNUMS
            iplan = plan_fn(instrumented, (state,) + batch(),
                            argnames=_STEP_ARGNAMES,
                            donate_argnums=STATE_DONATION_ARGNUMS,
                            entry="train_step_milnce_instrumented")
            same = iplan.peak_bytes == plans["train_step_milnce"].peak_bytes
            results.append(CheckResult(
                "train_step_milnce_instrumented", "GL013-identical-plan",
                same, "" if same else
                f"instrumented peak {iplan.peak_bytes} B != plain "
                f"{plans['train_step_milnce'].peak_bytes} B — the span "
                "wrapper changed the traced program"))
        except Exception as exc:                     # pragma: no cover
            results.append(CheckResult(
                "train_step_milnce_instrumented", "memplan-build", False,
                f"{type(exc).__name__}: {exc}"))
    return results


# --------------------------------------------------------------------------
# what-if prediction (operating points the CPU can only trace, not run)
# --------------------------------------------------------------------------

def what_if_program(*, batch: int, frames: int, size: int, words: int = 20,
                    k: int = 5, dtype: str = "bfloat16",
                    grad_accum: int = 1, mesh_axes=None,
                    preset: str = "full", fsdp_min_size=None,
                    loss_impl: str = "dense",
                    milnce_chunk: int = 0) -> tuple:
    """Trace the train step at a (possibly TPU-scale) operating point
    on the CPU: the model is built at the requested config, the state
    comes from ``jax.eval_shape`` (no bytes allocated), and
    ``make_jaxpr`` over ShapeDtypeStructs gives the exact program the
    operating point would compile — tracing is abstract, so a
    batch-256 32f@224 program costs seconds of host time and zero
    device memory.  ``mesh_axes`` like ``{'data': 4, 'model': 2}``
    needs ``prod(sizes)`` visible devices (scripts/mem_plan.py forces
    the virtual-CPU count to match).

    Returns ``(closed_jaxpr, labels, donated, entry_desc, mesh_desc)``
    — the shared what-if substrate: Pass 4 (what_if_step) runs the
    live-range walk over it, Pass 5 (numerics.what_if_audit) the
    dtype-flow walk, over the SAME traced program."""
    import jax
    import jax.numpy as jnp

    from milnce_tpu.config import full_preset, tiny_preset
    from milnce_tpu.models.build import build_model
    from milnce_tpu.parallel.mesh import build_mesh
    from milnce_tpu.train.schedule import build_schedule
    from milnce_tpu.train.state import build_optimizer, create_train_state
    from milnce_tpu.train.step import (STATE_DONATION_ARGNUMS,
                                       make_grad_cache_step,
                                       make_train_step)

    cfg = full_preset() if preset == "full" else tiny_preset()
    cfg.model.dtype = dtype
    # loss-impl axis (ISSUE 12): predict the SAME operating point under
    # the dense cube vs the chunked stream — the dense-vs-chunked
    # crossover at the Bg=8192 recipe is a what-if verdict pair, no chip
    # needed (PERF.md "Memory-efficient loss", BENCH_MILNCE_LOSS.md)
    cfg.loss.milnce_impl = loss_impl
    cfg.loss.milnce_chunk = int(milnce_chunk)
    if loss_impl == "dense" and milnce_chunk:
        raise ValueError(
            "--milnce-chunk only shapes the chunked/auto impls — pass "
            "--loss-impl chunked (a dense what-if never reads it)")
    loss_cfg = cfg.loss if loss_impl != "dense" else None
    mesh_axes = dict(mesh_axes or {"data": len(jax.devices())})
    model_axis = None
    for ax, n in mesh_axes.items():
        if ax == "data":
            continue
        model_axis = ax
        cfg.parallel.model_axis = ax
        cfg.parallel.model_parallel_size = int(n)
    need = math.prod(mesh_axes.values())
    have = len(jax.devices())
    if need != have:
        # EXACT match, not <=: build_mesh folds every visible device
        # into the grid, so 8 devices under a requested data=2,model=2
        # would silently become a 4x2 mesh — divisors doubled, per-chip
        # peak halved, and the refusal gate waving through a config
        # that OOMs on the real 2x2 topology
        raise ValueError(
            f"what-if mesh {mesh_axes} needs exactly {need} visible "
            f"devices, got {have} — scripts/mem_plan.py sets "
            "xla_force_host_platform_device_count to match; in-process "
            "callers must request a mesh whose product equals the "
            "device count")
    model = build_model(cfg.model)
    optimizer = build_optimizer(cfg.optim, build_schedule(cfg.optim, 1000))
    mesh = build_mesh(cfg.parallel)

    def init_fn(key):
        variables = model.init(
            key, jnp.zeros((2, frames, size, size, 3), jnp.float32),
            jnp.zeros((2 * k, words), jnp.int32))
        return create_train_state(variables, optimizer)

    state = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    state_specs = None
    if model_axis:
        from milnce_tpu.parallel.sharding_map import state_partition_specs

        kw = {} if fsdp_min_size is None else {"min_size": fsdp_min_size}
        state_specs = state_partition_specs(state, mesh, model_axis, **kw)
    if grad_accum > 1:
        step = make_grad_cache_step(model, optimizer, mesh, grad_accum,
                                    donate=False, loss_cfg=loss_cfg,
                                    state_specs=state_specs,
                                    model_axis=model_axis)
    else:
        step = make_train_step(model, optimizer, mesh, donate=False,
                               loss_cfg=loss_cfg, state_specs=state_specs,
                               model_axis=model_axis)
    args = (state,
            jax.ShapeDtypeStruct((batch, frames, size, size, 3), jnp.uint8),
            jax.ShapeDtypeStruct((batch * k, words), jnp.int32),
            jax.ShapeDtypeStruct((batch,), jnp.float32))
    mesh_desc = "x".join(f"{n}" for n in mesh_axes.values()) + (
        f" ({','.join(mesh_axes)})")
    impl_tag = "" if loss_impl == "dense" else f", loss={loss_impl}"
    entry_desc = (f"what_if(batch={batch}, {frames}f@{size}, "
                  f"{dtype}, ga={grad_accum}{impl_tag})")
    return (jax.make_jaxpr(step)(*args),
            arg_leaf_labels(args, _STEP_ARGNAMES),
            donated_leaf_flags(args, STATE_DONATION_ARGNUMS),
            entry_desc, mesh_desc)


def what_if_step(*, batch: int, frames: int, size: int, **kw) -> MemPlan:
    """Predict the per-chip peak of the train step at an operating
    point — the live-range walk over :func:`what_if_program`'s trace
    (flags documented there; scripts/mem_plan.py is the CLI)."""
    closed, labels, donated, entry_desc, mesh_desc = what_if_program(
        batch=batch, frames=frames, size=size, **kw)
    plan = analyze_jaxpr(closed, donated=donated, labels=labels)
    plan.entry = entry_desc
    plan.mesh = mesh_desc
    return plan


def budget_verdict(plan: MemPlan, hbm_gib: float) -> tuple:
    """(fits, message) against a per-chip HBM budget; the refusal names
    the top-3 contributors so the fix is actionable without a chip."""
    budget = int(hbm_gib * 2 ** 30)
    fits = plan.peak_bytes <= budget
    top = ", ".join(f"{label} ({b / 2**20:.1f} MiB)"
                    for label, b in plan.contributors[:3])
    msg = (f"{plan.entry} on {plan.mesh}: predicted per-chip peak "
           f"{plan.peak_bytes / 2**30:.3f} GiB "
           f"{'fits' if fits else 'EXCEEDS'} the {hbm_gib:g} GiB budget"
           f"; top contributors: {top}")
    return fits, msg


def preflight_fn_peak(fn, *args) -> int:
    """Per-chip predicted peak of an arbitrary jitted/traceable callable
    — the stage_probe autotune pre-flight (no donation, no sharding
    assumptions beyond what the program carries)."""
    import jax

    closed = jax.make_jaxpr(fn)(*args)
    return analyze_jaxpr(closed).peak_bytes
