"""graftlint rule catalogue.

Every rule exists because the corresponding pothole has already cost (or
would silently cost) real TPU throughput in this codebase; ANALYSIS.md
carries the long-form rationale and a worked example per rule.  Rules are
addressed by ID (``GL001``) or name (``host-sync-hot-loop``) — both work
in the suppression syntax::

    x = float(loss)  # graftlint: disable=GL001(display-cadence fetch)

A suppression must carry a non-empty reason; a bare ``disable=GL001`` is
itself a finding (GL000) so exceptions stay *documented*, never silent.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    summary: str
    rationale: str
    example: str
    fix: str


_RULE_LIST = (
    Rule(
        id="GL000",
        name="bad-suppression",
        summary="malformed or stale graftlint suppression / annotation "
                "comment",
        rationale="A suppression without a reason (or naming an unknown "
                  "rule) silences findings without documenting why; the "
                  "whole point of the inline syntax is that every audited "
                  "exception carries its audit.  A STALE suppression — one "
                  "whose rule no longer fires on that line — is the same "
                  "rot in reverse: the audited-exceptions table in LINT.md "
                  "claims an exception that no longer exists, and a later "
                  "real finding on that line would be silently absorbed.  "
                  "Ditto a `# guarded-by:` annotation naming a lock the "
                  "module doesn't declare.",
        example="x = float(loss)  # graftlint: disable=GL001",
        fix="write `# graftlint: disable=GL001(<why this sync is safe>)`; "
            "delete suppressions whose rule stopped firing (or re-audit "
            "why you expected it to); fix typo'd guarded-by lock names",
    ),
    Rule(
        id="GL001",
        name="host-sync-hot-loop",
        summary="host-blocking call reachable from the training hot loop",
        rationale="float()/int()/.item()/np.asarray()/jax.device_get() on "
                  "a device value blocks the host until the device "
                  "catches up, defeating the async dispatch pipeline "
                  "device_prefetch exists to enable — the reference loses "
                  "throughput to exactly this (loss.item() per batch).",
        example="loss_val = float(loss)  # inside the per-batch loop",
        fix="accumulate on device; transfer only at n_display cadence "
            "(and suppress that audited fetch with a reason)",
    ),
    Rule(
        id="GL002",
        name="traced-python-flow",
        summary="Python if/for/while on a traced value inside jitted code",
        rationale="Branching on a tracer either crashes at trace time "
                  "(ConcretizationTypeError) or — via `static_argnums` "
                  "promotion or weak-type coincidence — silently builds a "
                  "new XLA program per value: a recompilation storm.",
        example="if x > 0:  # x is a traced array",
        fix="use lax.cond/lax.select/jnp.where, or hoist the decision to "
            "build time (shapes and config are static)",
    ),
    Rule(
        id="GL003",
        name="jit-missing-donate",
        summary="jax.jit of a train-step-shaped function without "
                "donate_argnums",
        rationale="A train step that updates a TrainState without "
                  "donating it keeps TWO copies of params+opt_state live "
                  "across the update — at real scale that is the "
                  "difference between fitting the batch and OOM, and XLA "
                  "cannot reuse the input buffers in place.",
        example="step = jax.jit(train_step)",
        fix="jax.jit(train_step, donate_argnums=(0,)) — donate the state "
            "argument that the step consumes and returns",
    ),
    Rule(
        id="GL004",
        name="f64-literal-drift",
        summary="array construction that lands in float64 under x64 "
                "(or anywhere)",
        rationale="np.zeros()/jnp.asarray(0.5) without an explicit dtype "
                  "default to float64 (numpy always; jax under "
                  "jax_enable_x64).  An f64 operand silently upcasts "
                  "every downstream op — 2x HBM traffic and off the MXU "
                  "fast path — and H2D transfers double in size.",
        example="pad = jnp.asarray(0.5)  # f64 under x64",
        fix="pass dtype= explicitly (np.float32, or the model's compute "
            "dtype)",
    ),
    Rule(
        id="GL005",
        name="unsynced-walltime",
        summary="wall-clock timing without a device sync",
        rationale="JAX dispatch is async: time.time() deltas around a "
                  "jitted call measure enqueue latency, not device work. "
                  "Every headline number in BENCH_NOTES.md exists because "
                  "naive timing once reported 11.5 ms for a 5 us kernel.",
        example="t0 = time.time(); f(x); dt = time.time() - t0",
        fix="jax.block_until_ready(result) before reading the clock (or "
            "materialize the value on host, utils/timing.py protocol)",
    ),
    Rule(
        id="GL006",
        name="print-under-trace",
        summary="print() inside jit-traced code",
        rationale="print in traced code fires once at trace time (showing "
                  "tracers, not values) and never again — it reads like "
                  "per-step logging but is neither per-step nor values; "
                  "with impure callbacks it can also pin a host sync.",
        example="print('loss', loss)  # inside the jitted step",
        fix="jax.debug.print for traced values; host-side logging belongs "
            "outside the step at display cadence",
    ),
    Rule(
        id="GL007",
        name="swallowed-broad-except",
        summary="broad `except` that drops the error on the floor",
        rationale="A bare/`except Exception:` handler that neither "
                  "re-raises nor records the caught exception (uses the "
                  "bound name, logs with exc_info) swallows failures "
                  "silently — at pod scale that is how a 90%-corrupt "
                  "dataset 'trains' green and how a flaky checkpoint "
                  "store loses an epoch without a log line.",
        example="except Exception:\n    pass",
        fix="re-raise, bind and use the exception (log/record it), pass "
            "exc_info to a logging call, or suppress with a reason: "
            "# graftlint: disable=GL007(<why swallowing is correct here>)",
    ),
    Rule(
        id="GL008",
        name="obs-under-trace",
        summary="metrics/span recording reachable inside jit-traced code",
        rationale="Registry counters and span recorders are HOST I/O "
                  "(locks, ring appends, line-buffered file writes — "
                  "milnce_tpu/obs/).  Under jit they fire exactly once at "
                  "trace time with tracer values: what reads like per-step "
                  "telemetry records garbage once and then never again, "
                  "and routing it through a callback instead pins a host "
                  "sync into the step.  Recording belongs OUTSIDE the "
                  "traced function, at the existing host boundary "
                  "(display cadence / the dispatch site).",
        example="with REC.span('inner'):  # inside the jitted step body\n"
                "    loss = loss_fn(params)\n"
                "METRICS.inc()             # ditto",
        fix="move the .inc()/.observe()/.span()/.event() call outside the "
            "traced function (train/loop.py feeds the registry from the "
            "display-cadence fetch); genuinely trace-time-only setup gets "
            "# graftlint: disable=GL008(<why this is trace-time setup>)",
    ),
    Rule(
        id="GL009",
        name="phantom-mesh-axis",
        summary="with_sharding_constraint naming an axis absent from "
                "the mesh",
        rationale="A PartitionSpec axis name that no mesh declares does "
                  "not error — GSPMD just treats the dimension as "
                  "unconstrained and REPLICATES it.  A typo'd "
                  "`P('modle')` in a traced step therefore traces, "
                  "compiles, and runs... with every 'sharded' tensor "
                  "silently full-size on every chip: the exact failure "
                  "the 2-D FSDP path exists to avoid, invisible until "
                  "someone reads an HBM profile.  (The runtime twin of "
                  "this check is sharding_map.build_param_specs, which "
                  "raises on a phantom model_axis.)",
        example="x = jax.lax.with_sharding_constraint(x, P('modle'))",
        fix="name only axes the mesh declares (this repo's canonical "
            "axes are 'data' and 'model' — ParallelConfig; the lint "
            "also accepts axes named by a Mesh(...) construction or an "
            "axis_name= kwarg in the same module); a deliberate "
            "foreign-mesh constraint gets "
            "# graftlint: disable=GL009(<which mesh declares it>)",
    ),
    Rule(
        id="GL010",
        name="unguarded-shared-state",
        summary="shared mutable attribute accessed outside its lock in a "
                "thread-shared class",
        rationale="The serving/obs layers are a thread mesh: batcher "
                  "worker, HTTP request threads, data readers and the "
                  "train loop share per-class state behind ad-hoc locks. "
                  "A write outside the attribute's guard (or with no "
                  "guard at all) is a data race — lost counter "
                  "increments, dict-changed-size crashes mid-/healthz, "
                  "the exact bugs three of the last four PRs fixed by "
                  "hand after review.  Lock-free READS of a guarded "
                  "attribute are equally racy unless the attribute is "
                  "write-once in __init__ (the audited tokenizer "
                  "pattern: publish-then-read-only is safe under the "
                  "GIL's reference semantics).",
        example="self._calls[key] = self._calls.get(key, 0) + 1  "
                "# no lock; called from worker AND request threads",
        fix="take the guard (`with self._lock:`) around every access; "
            "declare the guard explicitly with `# guarded-by: _lock` on "
            "the __init__ assignment when inference can't see it; a "
            "deliberate lock-free read of a write-once attribute is "
            "already exempt — anything else needs a reasoned "
            "suppression",
    ),
    Rule(
        id="GL011",
        name="lock-order-cycle",
        summary="cycle in the static lock-acquisition order graph",
        rationale="If thread 1 takes A then B while thread 2 takes B "
                  "then A, some interleaving deadlocks — whether or not "
                  "today's tests hit it.  The lint builds the "
                  "acquisition graph (lock held -> lock acquired, "
                  "through same-module calls and across modules via "
                  "imported module-level locks like "
                  "DEVICE_DISPATCH_LOCK) and fails on any cycle, so a "
                  "deadlock-shaped ordering is a tier-1 failure at "
                  "review time, not a wedged pod at 3am.  The runtime "
                  "twin (analysis/lockrt.SanitizedLock) enforces the "
                  "same discipline on live threads.",
        example="# thread 1: with A: with B: ...\n"
                "# thread 2: with B: with A: ...",
        fix="pick ONE global order for the locks involved and acquire "
            "in that order everywhere (narrow critical sections until "
            "nesting disappears is even better); a provably-safe "
            "ordering the analysis can't see gets "
            "# graftlint: disable=GL011(<why no interleaving deadlocks>)",
    ),
    Rule(
        id="GL012",
        name="blocking-under-lock",
        summary="blocking call (future.result/join/wait/open/sleep or "
                "device dispatch) while holding a lock",
        rationale="A lock held across a blocking call stalls EVERY "
                  "contender for the full wait: request threads pile up "
                  "behind one file open, one future, one device "
                  "dispatch.  Worse, blocking on work that needs another "
                  "lock-holder to finish (future.result under a lock "
                  "the worker also takes) is a deadlock with extra "
                  "steps.  Device dispatch is exempt ONLY under locks "
                  "whose name contains 'dispatch' — serializing device "
                  "work is DEVICE_DISPATCH_LOCK's entire job; anything "
                  "else blocking under it still fires.",
        example="with self._lock:\n    row = fut.result()",
        fix="move the blocking work outside the critical section (copy "
            "state under the lock, block after release — the "
            "kill_inflight_decoders pattern); a deliberate "
            "block-under-lock gets "
            "# graftlint: disable=GL012(<why contenders may wait>)",
    ),
    Rule(
        id="GL013",
        name="peak-budget-regression",
        summary="per-entry per-chip peak device bytes drifted from the "
                "pinned budget (static HBM planner)",
        rationale="Fitting the 32-frame step into HBM was the original "
                  "run's binding constraint, and our own PERF.md records "
                  "a >10% batch cliff whose diagnosis cost a chip "
                  "session.  The Pass 4 planner (analysis/memplan.py) "
                  "computes each entry's per-chip peak bytes from jaxpr "
                  "live ranges — sharding- and donation-aware — and pins "
                  "it like a collective count: a rematerialized "
                  "activation, a doubled optimizer moment or a lost "
                  "donation lands as a failing tier-1 check, not as an "
                  "OOM weeks later on the chip.",
        example="EXPECTED_PEAK_BYTES['train_step_milnce'] drifts +30%",
        fix="find the buffer in the GL015 contributor diff / MEMPLAN.md; "
            "if the growth is intended, re-pin EXPECTED_PEAK_BYTES in "
            "the same commit (entry-level rule — inline suppressions "
            "don't apply)",
    ),
    Rule(
        id="GL014",
        name="ineffective-or-missing-donation",
        summary="donated buffer that cannot be reused, or a large "
                "aliasable arg left undonated on a grad-bearing entry",
        rationale="donate_argnums is the difference between one and two "
                  "copies of params+opt_state across the update — at "
                  "real scale, the difference between fitting the batch "
                  "and OOM (GL003's rationale, enforced at the jaxpr "
                  "level where it is checkable).  A donation whose "
                  "buffer matches no program output (or is returned "
                  "unchanged) is dead weight that reads like a "
                  "protection; an undonated large aliasable arg is the "
                  "regression GL003 cannot see once jit sites hide "
                  "behind factories.  The audit honors the CPU gate "
                  "(parallel/compat.donation_argnums buys nothing on "
                  "CPU and double-frees on old jax) while verifying the "
                  "TPU path still REQUESTS donation.",
        example="jax.jit(step, donate_argnums=(1,))  # arg 1 is returned "
                "unchanged",
        fix="donate the consumed state (train/step.py "
            "STATE_DONATION_ARGNUMS is the declared intent), or drop a "
            "donation that cannot take effect; entry-level rule — "
            "re-register the intent in analysis/memplan.py, inline "
            "suppressions don't apply",
    ),
    Rule(
        id="GL015",
        name="top-contributor-drift",
        summary="an entry's top-3 peak-memory contributors changed "
                "identity (pinned by name)",
        rationale="A peak regression inside the GL013 tolerance can "
                  "still change WHAT occupies the peak — a silently "
                  "rematerialized activation, an f32 upcast of a bf16 "
                  "buffer, an optimizer moment that stopped sharding.  "
                  "Pinning the top-3 contributor NAMES (arg tree paths "
                  "/ 'primitive aval' labels) turns that into a "
                  "readable diff instead of a mystery byte delta — the "
                  "same reasoning as pinning collective multisets "
                  "rather than just their sum.",
        example="'conv_general_dilated f32[...]' replaces "
                "'state/params/conv_2c/...' at the peak",
        fix="explain the new occupant (MEMPLAN.md names its bytes); if "
            "intended, re-pin EXPECTED_TOP_CONTRIBUTORS in the same "
            "commit (entry-level rule — inline suppressions don't "
            "apply)",
    ),
    Rule(
        id="GL016",
        name="low-precision-accumulation",
        summary="add-based reduction / dot_general accumulation / psum "
                "whose accumulator dtype is bf16/f16 at reduction "
                "extent >= threshold",
        rationale="bf16 has an 8-bit mantissa: summing N same-sign "
                  "terms loses ~log2(N) of it, so a 256-term reduction "
                  "keeps EFFECTIVELY zero fractional bits.  The MXU "
                  "accumulates f32 natively — a bf16 accumulator is "
                  "never a speed win, only a missing "
                  "preferred_element_type=f32 (or an upcast dropped "
                  "from a loss/psum chain).  Pass 5 "
                  "(analysis/numerics.py) walks each entry's jaxpr and "
                  "fires on every low-precision accumulation whose "
                  "reduced extent crosses the threshold, so the bf16 "
                  "what-if shows exactly which reductions must keep an "
                  "f32 accumulator before anyone flips the model dtype.",
        example="jnp.sum(x_bf16, axis=0)  # extent 4096, bf16 "
                "accumulator",
        fix="accumulate in f32: preferred_element_type=jnp.float32 on "
            "the dot, or .astype(jnp.float32) before the sum/psum "
            "(entry-level rule — a deliberate low-precision "
            "accumulation is re-registered in analysis/numerics.py, "
            "inline suppressions don't apply)",
    ),
    Rule(
        id="GL017",
        name="unstabilized-exp-domain",
        summary="exp without a max-subtraction guard, or a reduce-sum "
                "division without eps, in a loss module",
        rationale="exp overflows f32 at x>88 and bf16 at x>88 with far "
                  "coarser spacing; every softmax/logsumexp in the "
                  "losses must subtract a running or global max before "
                  "exponentiating (the online-softmax identity keeps "
                  "this free), and every normalization that divides by "
                  "a reduced sum needs an eps or max() floor.  The "
                  "AST half of Pass 5 pattern-matches exp/division "
                  "sites in losses/; the jaxpr half confirms the "
                  "subtraction actually reaches the exp operand.  A "
                  "deliberately-unguarded site (e.g. reference parity "
                  "with the paper's unstabilized sum) carries an "
                  "audited reason.",
        example="neg = jnp.exp(pairwise).sum(axis=1)",
        fix="subtract the row max (or reuse the logsumexp/online-"
            "softmax guard) before exp; floor sum denominators with "
            "eps or jnp.maximum; a deliberate site gets "
            "# graftlint: disable=GL017(<why the domain is bounded>)",
    ),
    Rule(
        id="GL018",
        name="dtype-boundary-drift",
        summary="an entry's dtype census (buffer bytes by dtype) or "
                "cast inventory (named convert_element_type sites) "
                "drifted from the pin",
        rationale="Mixed precision only stays correct if every "
                  "f32<->bf16 boundary is deliberate: an appearing "
                  "cast is a new upconversion eating HBM (GL015's f32 "
                  "BatchNorm finding), a vanishing cast is a loss "
                  "accumulator silently demoted.  Pass 5 pins each "
                  "entry's census and cast inventory the way Pass 2 "
                  "pins collective multisets — drift lands as a "
                  "readable named diff in tier-1, not as a loss curve "
                  "divergence three days into a run.",
        example="'f32->bf16 @ convert_element_type(state/params/...)' "
                "vanishes from train_step_milnce",
        fix="explain the moved boundary (NUMERICS.md names every "
            "cast); if intended, re-pin EXPECTED_DTYPE_CENSUS / "
            "EXPECTED_CASTS in the same commit (entry-level rule — "
            "inline suppressions don't apply)",
    ),
)

RULES: dict[str, Rule] = {r.id: r for r in _RULE_LIST}
RULES_BY_NAME: dict[str, Rule] = {r.name: r for r in _RULE_LIST}


def resolve_rule(token: str) -> Rule | None:
    """Accept either a rule ID ('GL001') or name ('host-sync-hot-loop')."""
    return RULES.get(token) or RULES_BY_NAME.get(token)
