"""graftlint Pass 3b: the runtime lock sanitizer (the dynamic twin of
the static lock-discipline lint in :mod:`analysis.concurrency`).

Static analysis sees the lock-acquisition *sites*; it cannot see
orderings assembled dynamically (callbacks, injected ``run_batch``
callables, locks reached through an attribute the AST can't resolve).
:class:`SanitizedLock` closes that gap the way the kernel's lockdep
does: every acquisition records an ordering edge *held -> acquired*
into a process-wide graph keyed by lock **name** (lock classes, not
instances — two batchers' children locks share one discipline), and an
acquisition that would close a cycle raises :class:`LockOrderError`
immediately — at the inversion site, on the first run that exhibits the
ordering, *without* needing the actual interleaving that deadlocks.

What it catches:

- **ABBA inversions** — thread 1 takes A then B, thread 2 takes B then
  A: the second ordering raises even if the threads never actually
  interleave into the deadlock;
- **self-deadlock** — re-acquiring a non-reentrant lock on the same
  thread (the ``stats()`` calling ``recompiles()`` under the same lock
  class of bug) raises instead of hanging;
- **hold-time pathologies** — an optional per-lock budget raises
  :class:`LockHoldBudgetExceeded` on release when a critical section
  ran long (device work or file I/O smuggled under a lock request
  threads contend on — the runtime face of GL012).

Opt-in wiring: every lock in the serving/obs/data/utils thread mesh is
created through :func:`make_lock`, which returns a plain
``threading.Lock`` unless ``MILNCE_LOCK_SANITIZE=1`` is set in the
environment **at construction time** (module-level locks therefore need
the variable set before import — the concurrency hammer test drives the
real serving stack in a subprocess exactly so).
``MILNCE_LOCK_HOLD_BUDGET_MS`` sets a global hold budget for
``make_lock`` locks; unset means no budget.

Pure stdlib, no jax — importable from anywhere (including the obs
metrics registry, which must stay jax/numpy-free).

Limitations (documented, deliberate):

- edges are keyed by lock *name*: two instances sharing a name share an
  order class (that is the point — per-instance orders that are safe by
  construction should use distinct names);
- acquire/release are assumed to happen on the same thread (true for
  every ``with`` use; a cross-thread release leaves a stale held-stack
  entry on the acquiring thread);
- the graph only grows — a deliberately re-ordered lock hierarchy needs
  :func:`reset_global_graph` (tests) or a process restart (production).
"""

from __future__ import annotations

import os
import sys
import threading
import time

ENV_SANITIZE = "MILNCE_LOCK_SANITIZE"
ENV_HOLD_BUDGET_MS = "MILNCE_LOCK_HOLD_BUDGET_MS"


class LockOrderError(RuntimeError):
    """Acquisition would close a cycle in the lock-order graph (a
    latent ABBA deadlock), or re-acquire a non-reentrant lock on the
    holding thread (a certain deadlock)."""


class LockHoldBudgetExceeded(RuntimeError):
    """A critical section outlived its configured hold budget."""


def _caller_site() -> str:
    """file:line of the first frame outside this module (the
    acquisition site recorded on order-graph edges)."""
    frame = sys._getframe(1)
    here = __file__
    while frame is not None and frame.f_code.co_filename == here:
        frame = frame.f_back
    if frame is None:  # pragma: no cover - only under exotic embedding
        return "<unknown>"
    return f"{os.path.basename(frame.f_code.co_filename)}:{frame.f_lineno}"


class LockOrderGraph:
    """Process-wide ordering graph: edge u -> v means "v was acquired
    while u was held".  A cycle means some interleaving deadlocks."""

    def __init__(self):
        self._meta = threading.Lock()      # guards _edges/_sites; never
        self._edges: dict[str, set] = {}   # sanitized (it IS the sanitizer)
        self._sites: dict[tuple, str] = {}

    def _path(self, src: str, dst: str) -> list | None:
        """Edge-path src ->* dst, or None (iterative DFS; called with
        the meta lock held)."""
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in sorted(self._edges.get(node, ())):
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def check_acquire(self, held: list, name: str, site: str) -> None:
        """Record held->name edges; raise :class:`LockOrderError` if any
        edge would close a cycle (checked BEFORE blocking on the lock,
        so the violation surfaces even when no deadlock materializes)."""
        with self._meta:
            for h in held:
                if h == name:
                    continue
                cycle = self._path(name, h)
                if cycle is not None:
                    chain = " -> ".join(cycle + [name])
                    sites = "; ".join(
                        f"{u}->{v} @ {self._sites.get((u, v), '?')}"
                        for u, v in zip(cycle, cycle[1:]))
                    raise LockOrderError(
                        f"lock-order cycle: acquiring {name!r} at {site} "
                        f"while holding {h!r} inverts the established "
                        f"order {chain} (established: {sites}) — some "
                        "interleaving of these threads deadlocks")
            for h in held:
                if h != name and name not in self._edges.setdefault(h,
                                                                    set()):
                    self._edges[h].add(name)
                    self._sites[(h, name)] = site

    def snapshot(self) -> dict:
        """{'edges': [[u, v, first-site], ...]} sorted — for tests and
        the hammer's "sanitizer actually engaged" assertion."""
        with self._meta:
            return {"edges": sorted(
                [u, v, self._sites.get((u, v), "?")]
                for u, vs in self._edges.items() for v in vs)}

    def reset(self) -> None:
        with self._meta:
            self._edges.clear()
            self._sites.clear()


GLOBAL_GRAPH = LockOrderGraph()


def reset_global_graph() -> None:
    """Clear the process-wide order graph (test isolation)."""
    GLOBAL_GRAPH.reset()


_tls = threading.local()


def _held_stack() -> list:
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


class SanitizedLock:
    """Drop-in for ``threading.Lock`` that records per-thread
    acquisition stacks into the process-wide order graph and raises on
    a would-be cycle, a same-thread re-acquire, or (optionally) a
    blown hold-time budget.

    - ``name``: the lock's order *class* (defaults to the creation
      site) — instances sharing a name share ordering discipline;
    - ``hold_budget_s``: max seconds a holder may keep the lock;
      exceeded -> :class:`LockHoldBudgetExceeded` raised on release
      (after the lock is actually released — never wedges others);
    - ``graph``: injectable order graph (tests); default process-wide.
    """

    _REENTRANT = False

    def __init__(self, name: str | None = None, *,
                 hold_budget_s: float | None = None,
                 graph: LockOrderGraph | None = None):
        self._inner = threading.RLock() if self._REENTRANT \
            else threading.Lock()
        self.name = name if name else _caller_site()
        self.hold_budget_s = hold_budget_s
        self._graph = graph if graph is not None else GLOBAL_GRAPH

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        held = _held_stack()
        depth = sum(1 for entry in held if entry[0] is self)
        if depth and not self._REENTRANT and blocking:
            # a blocking re-acquire deadlocks for certain; a trylock on
            # a self-held lock legally returns False (stdlib semantics)
            raise LockOrderError(
                f"self-deadlock: thread {threading.current_thread().name!r} "
                f"re-acquiring non-reentrant lock {self.name!r} it already "
                "holds")
        if not depth and blocking:
            # trylocks are exempt from ordering (lockdep parity): a
            # failed non-blocking acquire can never participate in a
            # deadlock, and recording its edge would poison the graph
            # for the avoid-deadlock-by-trylock pattern
            self._graph.check_acquire(
                [entry[1] for entry in held], self.name, _caller_site())
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            held.append((self, self.name, time.monotonic()))
        return ok

    def release(self) -> None:
        held = _held_stack()
        t0 = None
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] is self:
                t0 = held.pop(i)[2]
                break
        self._inner.release()
        if (t0 is not None and self.hold_budget_s is not None):
            dt = time.monotonic() - t0
            if dt > self.hold_budget_s:
                raise LockHoldBudgetExceeded(
                    f"{self.name!r} held {dt * 1e3:.1f} ms > budget "
                    f"{self.hold_budget_s * 1e3:.1f} ms — move the blocking "
                    "work outside the critical section (GL012)")

    def __enter__(self) -> "SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        try:
            self.release()
        except LockHoldBudgetExceeded:
            # an exception already unwinding through the with-block is
            # the root cause — the budget report must not replace it
            if exc_type is None:
                raise

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False


class SanitizedRLock(SanitizedLock):
    """Reentrant variant: same-thread re-acquires are legal and do not
    re-check ordering (only the outermost acquire orders)."""

    _REENTRANT = True


def sanitizing_enabled() -> bool:
    return os.environ.get(ENV_SANITIZE, "") == "1"


def make_lock(name: str):
    """THE lock factory of the serving/obs/data/utils thread mesh.

    Plain ``threading.Lock`` by default (zero overhead in production);
    a :class:`SanitizedLock` carrying ``name`` when
    ``MILNCE_LOCK_SANITIZE=1`` is set at construction time.  Naming is
    what makes the order graph readable — pick stable dotted roles
    (``serving.device_dispatch``, ``obs.metrics.counter``)."""
    if not sanitizing_enabled():
        return threading.Lock()
    budget_ms = float(os.environ.get(ENV_HOLD_BUDGET_MS, "") or 0.0)
    # <= 0 (incl. an explicit "0") disables the budget — a 0.0-second
    # budget would raise on essentially every release
    return SanitizedLock(
        name, hold_budget_s=budget_ms / 1e3 if budget_ms > 0 else None)
