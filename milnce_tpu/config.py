"""Typed configuration for the whole framework.

Replaces the reference's two near-duplicate argparse files (args.py:3-52,
args_small.py:3-52) with one dataclass tree + presets.  Every knob of the
reference CLI has a typed home here; nothing is hardcoded in library code
(the reference leaked node IPs into train.py:48 and checkpoint paths into
eval scripts — see SURVEY.md §2.4).
"""

from __future__ import annotations

import argparse
import dataclasses
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class DataConfig:
    """Input-pipeline knobs (reference: args.py:5-7,14,16,22-26,29,32)."""

    train_csv: str = ""                 # manifest csv with a `video_path` column
    video_root: str = ""
    caption_root: str = ""
    eval_video_root: str = ""
    eval_csv: str = "csv/hmdb51.csv"    # in-training eval manifest
    fps: int = 10
    num_frames: int = 32
    video_size: int = 224
    crop_only: bool = True
    center_crop: bool = False
    random_flip: bool = True
    min_time: float = 5.0
    max_words: int = 20                 # training caption length
    eval_max_words: int = 30            # eval caption length (youcook/msrvtt
                                        # loaders, youcook_loader.py:28)
    num_candidates: int = 5             # MIL candidate captions per clip
    num_reader_threads: int = 20        # host-side decode workers per process
    use_native_reader: bool = False     # C++ ReaderPool pipe pump for ffmpeg
                                        # decode (native/milnce_native.cpp)
    decoder_backend: str = "auto"       # auto | ffmpeg | cv2 (auto prefers
                                        # the ffmpeg binary, falls back to
                                        # in-process cv2 decode)
    prefetch_depth: int = 2             # device prefetch buffer (batches)
    decode_lookahead: int = 2           # extra batches of decode futures kept
                                        # in flight across batch boundaries
    sample_timeout: float = 120.0       # decode watchdog: per-sample timeout
                                        # (s), doubling per retry; a wedged
                                        # decode escalates to the black-frame
                                        # fallback instead of stalling the
                                        # pod's next collective.  0 disables.
    sample_timeout_retries: int = 2     # fresh decode attempts per sample
                                        # before the watchdog escalates
    max_failure_rate: float = 0.5       # abort the run (DataHealthError) when
                                        # the decode-failure fraction exceeds
                                        # this — a mostly-corrupt dataset must
                                        # not silently train on black frames.
                                        # 1.0 disables.
    synthetic: bool = False             # hermetic in-memory source (no ffmpeg)
    synthetic_num_samples: int = 256


@dataclass
class ModelConfig:
    """S3D-G + sentence tower (reference: s3dg.py:207-263)."""

    embedding_dim: int = 512            # args.py `--num_class`
    gating: bool = True
    space_to_depth: bool = False
    inception_blocks: int = 9           # trunk depth (9 = full S3D-G;
                                        # smaller for dryruns/ablations)
    weight_init: str = "uniform"        # 'uniform' (framework default) | 'kaiming_normal'
    vocab_size: int = 66250             # s3dg.py:152
    word_embedding_dim: int = 300
    text_hidden_dim: int = 2048
    text_max_words: int = 16            # s3dg.py:155 (train loader uses DataConfig.max_words)
    word2vec_path: str = ""             # .npy/.npz table; '' = trainable-from-scratch table
    token_dict_path: str = ""           # dict.npy vocab for the tokenizer
    sync_batchnorm: bool = False        # cross-replica BN (original TPU run); False = local
                                        # BN for parity with the GPU reference (README.md:13)
    dtype: str = "float32"              # activation dtype ('bfloat16' for MXU speed)
    conv_impl: str = "native"           # 'native' 3D convs | 'fold2d' (same
                                        # math as 2D convs — layout XLA:TPU's
                                        # conv emitter is tuned for) |
                                        # 'im2col' (patches + one dot_general;
                                        # see models/conv3d.py, identical
                                        # params under all three)
    conv_impl_map: str = ""             # PER-STAGE impl override on top of
                                        # conv_impl: inline
                                        # 'conv1=im2col,mixed_3b=fold2d' or a
                                        # path to the autotune artifact
                                        # scripts/stage_probe.py --autotune
                                        # writes (JSON with an 'impl_map'
                                        # key); stages not named fall back to
                                        # conv_impl.  '' = uniform conv_impl.
    remat: bool = False                 # rematerialize Inception blocks
                                        # (jax.checkpoint) to fit big batches


CONV_IMPLS = ("native", "fold2d", "im2col")        # models/conv3d.py
# Stage names an impl map may address — the granularity the stage probe
# measures at (scripts/stage_probe.py; mirrors models/s3dg.py setup).
CONV_STAGES = ("conv1", "conv_2b", "conv_2c",
               "mixed_3b", "mixed_3c", "mixed_4b", "mixed_4c", "mixed_4d",
               "mixed_4e", "mixed_4f", "mixed_5b", "mixed_5c")


def parse_conv_impl_map(spec: str) -> dict:
    """ModelConfig.conv_impl_map -> {stage: impl}.

    Accepts '' (empty map), an inline 'stage=impl[,stage=impl...]' spec,
    or a path to a JSON file — either a raw map or the autotune artifact
    (``scripts/stage_probe.py --autotune``), whose map lives under the
    'impl_map' key.  Unknown stages or impls raise ValueError so a typo
    fails at config time, not as a silently-ignored key."""
    if not spec:
        return {}
    if "=" in spec:
        items = [item for item in spec.split(",") if item]
        bad = [item for item in items if "=" not in item]
        if bad:
            raise ValueError(f"impl map items missing '=': {bad} "
                             "(inline form is 'stage=impl[,stage=impl...]')")
        mapping = dict(item.split("=", 1) for item in items)
    else:
        import json

        with open(spec) as fh:
            payload = json.load(fh)
        mapping = payload.get("impl_map", payload)
    for stage, impl in mapping.items():
        if stage not in CONV_STAGES:
            raise ValueError(f"impl map names unknown stage {stage!r} "
                             f"(stages: {', '.join(CONV_STAGES)})")
        if impl not in CONV_IMPLS:
            raise ValueError(f"impl map stage {stage!r} names unknown impl "
                             f"{impl!r} (impls: {', '.join(CONV_IMPLS)})")
    return dict(mapping)


@dataclass
class LossConfig:
    """Loss selection + hyperparams (reference: loss.py)."""

    name: str = "milnce"                # milnce | cdtw | sdtw_cidm | sdtw_negative | sdtw_3
    milnce_impl: str = "dense"          # dense | chunked | auto: 'dense'
                                        # materializes the two
                                        # (B_local, Bg, K) similarity cubes
                                        # (losses/milnce.py — fewest matmul
                                        # passes, fine while the cubes are
                                        # small); 'chunked' streams negative
                                        # chunks with running logsumexps and
                                        # a recompute-in-backward custom VJP
                                        # (losses/milnce_chunked.py — the
                                        # Bg=8192 recipe's loss); 'auto'
                                        # switches to chunked once the cubes
                                        # + AD twins pass the 64 MiB budget
                                        # (prefers_chunked).  PERF.md
                                        # "Memory-efficient loss".
    milnce_chunk: int = 0               # global samples per streamed chunk
                                        # (0 = the milnce_default_chunk
                                        # rule, ~2 MiB of row logits per
                                        # block); Bg % chunk != 0 is padded
                                        # + masked
    milnce_backend: str = "auto"        # chunked impl inner backend: auto |
                                        # scan | pallas (auto = the
                                        # prefers_pallas VMEM/lane shape
                                        # rule, ops/milnce_pallas.py)
    sdtw_backend: str = "auto"          # auto | scan | pallas; auto picks the
                                        # TPU wavefront kernel wherever a
                                        # measured-winning layout applies
                                        # (batch-on-lanes or one-block), scan
                                        # otherwise (BENCH_SOFTDTW.md;
                                        # reference always ran CUDA,
                                        # loss.py:26-97)
    sdtw_gamma: Optional[float] = None  # None = each loss's reference
                                        # default: 1e-5 for cdtw (loss.py:
                                        # 26), 0.1 for the sdtw_* family
                                        # (loss.py:38,74,97)
    sdtw_dist: str = ""                 # '' = each loss's reference default
                                        # (cosine for cdtw/cidm/negative,
                                        # negative_dot for sdtw_3 — loss.py:
                                        # 26,38,74,97); override with any of
                                        # cosine | negative_dot |
                                        # negative_cosine | euclidean
    sdtw_bandwidth: int = 0             # Sakoe-Chiba band; 0 = off
    sdtw_pair_chunk: int = 0            # sdtw_3 only: stream each NCE
                                        # term's B x B pair logsumexp in
                                        # anchor-row chunks of this size
                                        # (jax.checkpoint'd scan — peak
                                        # pair batch O(B*chunk) instead
                                        # of the B^2 broadcast); 0 = the
                                        # dense all-pairs form
    cidm_sigma: float = 10.0            # loss.py:58
    cidm_lambda: float = 1.0            # loss.py:57


@dataclass
class OptimConfig:
    """Optimizer + schedule (reference: args.py:12,20,28,34,36-37; utils.py:26-38)."""

    name: str = "adam"                  # adam | sgd
    lr: float = 1e-3
    momentum: float = 0.9
    warmup_steps: int = 50_000
    epochs: int = 300
    num_cycles: float = 0.5


@dataclass
class ParallelConfig:
    """Mesh layout. Replaces NCCL/TCP rendezvous + mp.spawn (main_distributed.py:50-75)
    with `jax.distributed.initialize` + one GSPMD program over a named mesh."""

    data_axis: str = "data"             # batch-sharded axis (DP + global negatives)
    model_axis: Optional[str] = None    # FSDP/model axis: set (with
                                        # model_parallel_size > 1) to train
                                        # on a 2-D (data, model) mesh with
                                        # large params sharded per the
                                        # sharding map (parallel/
                                        # sharding_map.py, PERF.md)
    model_parallel_size: int = 1
    fsdp_min_size: int = 65536          # FSDP threshold (ELEMENTS): params
                                        # with >= this many elements shard
                                        # over model_axis on their largest
                                        # divisible dim; smaller ones
                                        # replicate (gather latency beats
                                        # the storage win below it)
    sharding_map: str = ""              # per-param overrides on top of the
                                        # size rule: inline 'glob=dim[,...]'
                                        # ('-' = force-replicate) or a JSON
                                        # artifact path, mirroring
                                        # model.conv_impl_map.  '' = pure
                                        # automatic rule.
    overlap_grad_reduce: bool = True    # 2-D mesh only: reduce grads
                                        # per-leaf (XLA can overlap each
                                        # reduction with the rest of the
                                        # backward) instead of one fused
                                        # terminal psum; the 1-D step keeps
                                        # its pinned fused reduction
    coordinator_address: Optional[str] = None   # multi-host bootstrap (None = single host)
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    platform: str = ""                  # force a jax backend ('cpu' for
                                        # hermetic runs on accelerator
                                        # hosts; '' = jax default).  Env
                                        # vars alone don't suffice —
                                        # accelerator plugins override
                                        # JAX_PLATFORMS, so this applies
                                        # jax.config before backend init.
    num_devices: int = 0                # build the mesh over the FIRST N
                                        # local devices only (0 = all) —
                                        # how an elastic resume boots a
                                        # SMALLER mesh on the same host
                                        # (8-way -> 4-way; MIGRATING.md
                                        # "Checkpoint resharding") and how
                                        # tests shape-change in one
                                        # process.  Multi-host capacity
                                        # changes use num_processes
                                        # instead; both reshard through
                                        # the same restore-template path.


@dataclass
class TrainConfig:
    batch_size: int = 128               # GLOBAL batch (reference splits per GPU at
                                        # main_distributed.py:88; we shard over the mesh)
    batch_size_val: int = 32
    seed: int = 1
    n_display: int = 400
    checkpoint_root: str = "checkpoint"
    checkpoint_dir: str = ""
    checkpoint_keep: int = 10           # sliding retention (main_distributed.py:289-294)
    log_root: str = "log"
    resume: bool = False
    pretrain_ckpt: str = ""             # load converted weights before training
    evaluate: bool = False
    eval_task: str = "hmdb"             # hmdb | youcook | msrvtt (in-training)
    num_windows_test: int = 4
    verbose: bool = True
    trace_dir: str = ""                 # jax.profiler trace output ('' = off)
    obs_dir: str = ""                   # span/event stream: RUN_EVENTS.jsonl
                                        # is appended under this dir ('' =
                                        # log_root; written only when the
                                        # run logger is enabled).  Recording
                                        # is host-side only — obs/,
                                        # OBSERVABILITY.md
    obs_profiler_bridge: bool = False   # wrap spans in jax.profiler.
                                        # TraceAnnotation so they land in
                                        # real TPU traces (pairs with
                                        # trace_dir)
    run_id: str = ""                    # run identity stamped on every
                                        # RUN_EVENTS.jsonl line + obs
                                        # snapshot ('' = auto: process 0
                                        # generates one and broadcasts it
                                        # cluster-wide).  Pod aggregation
                                        # and obs_report split on it.
    anomaly_detect: bool = True         # EWMA step-time spike detector at
                                        # display cadence (host-side only:
                                        # fed from the window timing the
                                        # display already computes); emits
                                        # 'anomaly' events and arms the
                                        # profiler capture when configured
    anomaly_ratio: float = 2.0          # spike = window step time > ratio
                                        # x EWMA (and > 4 sigma; obs/
                                        # anomaly.py)
    anomaly_warmup: int = 3             # display windows before the
                                        # detector may fire (compile +
                                        # cache-cold windows)
    anomaly_cooldown_s: float = 300.0   # suppression window between
                                        # anomaly events
    capture_dir: str = ""               # anomaly-triggered bounded one-
                                        # shot jax.profiler capture root
                                        # ('' = no capture; also armable
                                        # via SIGUSR1)
    capture_ms: float = 2000.0          # capture stops itself after this
    capture_max: int = 1                # captures per run (a bad run
                                        # captures once, not forever)
    halt_on_nan: bool = True            # checkpoint + halt when the windowed
                                        # loss goes non-finite (divergence guard)
    max_steps: Optional[int] = None     # stop (with a checkpoint) after N
                                        # optimizer steps — bounded smoke /
                                        # bench runs; None = run all epochs
    finite_guard: bool = True           # fold a per-step all-finite gradient
                                        # check into the jitted step: a
                                        # non-finite update is SKIPPED (params
                                        # kept, jnp.where select — no host
                                        # sync, no new collectives) and
                                        # counted; surfaced at display cadence
    skip_rollback_after: int = 25       # circuit breaker: after K CONSECUTIVE
                                        # skipped updates, restore the last
                                        # rotation checkpoint and resume past
                                        # the poisoned data window instead of
                                        # halting.  Checked at display cadence
                                        # (the existing sync point), so keep
                                        # K <= n_display.  0 disables.
    faults: str = ""                    # fault-injection spec (chaos tests /
                                        # drills), e.g. 'decode.raise@1,2;
                                        # grad.nonfinite@3' — grammar and site
                                        # catalogue in resilience/faults.py;
                                        # also armable via MILNCE_FAULTS env
    checkpoint_save_retries: int = 2    # transient-I/O retries (exponential
                                        # backoff) before a checkpoint save
                                        # gives up — a SIGTERM save must not
                                        # race one flaky write for the whole
                                        # partial epoch
    grad_accum: int = 1                 # microbatches per optimizer step
                                        # (two-pass embedding-cache MIL-NCE:
                                        # FULL global-batch negatives at 1/M
                                        # activation memory — how the
                                        # reference's 8192-batch recipe runs
                                        # on a small mesh; train/step.py)
    preempt_sync_steps: int = 25        # multi-process runs all-reduce the
                                        # SIGTERM flag every N steps so ONE
                                        # preempted worker triggers a
                                        # cluster-wide cooperative checkpoint
                                        # (a unilateral exit would wedge the
                                        # others in their next collective);
                                        # the check costs one tiny collective
                                        # + host sync per N steps.  Single
                                        # process: checked locally every step.
                                        # TUNE to step time: a SIGTERM is only
                                        # acted on at the next boundary, so the
                                        # worst-case delay before checkpointing
                                        # begins is N*step_time — keep that
                                        # well inside the preemption grace
                                        # window (e.g. 300ms steps + 30s grace
                                        # -> N<=50; multi-second steps -> N<=5).
    drain_signal_file: str = ""         # drain trigger for orchestrators
                                        # that can't deliver SIGTERM: the
                                        # loop polls for this path once per
                                        # step and starts a cooperative
                                        # drain (checkpoint + ELASTIC_STAMP
                                        # + drained exit status) when it
                                        # appears ('' = SIGTERM/fault-site
                                        # only; milnce_tpu/elastic/)
    straggler_ratio: float = 1.25       # live straggler rule: a host whose
                                        # window step-time p50 exceeds
                                        # ratio x the fastest host's is
                                        # flagged (same rule obs_report
                                        # --merge applies post-hoc;
                                        # elastic/straggler.py)
    straggler_window: int = 3           # consecutive flagged display
                                        # windows before the host is
                                        # DEMOTED in the goodput ledger
                                        # (one slow window is noise; a
                                        # streak is a bad host)
    straggler_resize: bool = False      # on demotion, also emit a
                                        # straggler.resize_recommended
                                        # event (drain + resume without
                                        # the slow host) — recommendation
                                        # only: training can't evict a
                                        # host mid-collective
    curriculum: str = ""                # staged (frames, resolution, batch)
                                        # training schedule — ordered
                                        # 'num_frames=4,resolution=64,
                                        # until_step=1000;...' stages (or a
                                        # JSON artifact path); final stage
                                        # open-ended.  '' = flat run.
                                        # Grammar, plan semantics and the
                                        # per-stage mem_plan pre-flight:
                                        # train/curriculum.py + PERF.md
                                        # "Curriculum training"


@dataclass
class ServeConfig:
    """Online-serving knobs (milnce_tpu/serving/, SERVING.md).

    The three SLO levers: ``max_batch`` trades per-request latency for
    device efficiency (taller ladder = fuller MXU at high load),
    ``max_delay_ms`` bounds how long a lone request waits for batch
    company, ``default_timeout_ms`` bounds total queue wait before a
    request errors (DeadlineExpired) instead of silently aging.

    Resilience tier (serving/pool.py, ROBUSTNESS.md "Serving request
    path"): ``replicas`` > 1 serves through a ReplicaPool — per-replica
    dispatch locks, bounded queues, health-gated routing, quarantine +
    probe recovery, hedged dispatch — and ``max_inflight`` arms the
    admission controller's bounded global queue + deadline-feasibility
    load shedding (HTTP 429)."""

    max_batch: int = 64                 # top of the bucket ladder
    min_bucket: int = 0                 # smallest bucket (0 = mesh size)
    max_delay_ms: float = 5.0           # batcher flush-on-delay bound
    default_timeout_ms: float = 0.0     # per-request queue deadline (0 = none)
    cache_capacity: int = 4096          # LRU text-embedding cache entries
                                        # (<= 0 disables)
    topk: int = 10                      # retrieval depth (static in the
                                        # traced top-k program)
    dtype: str = ""                     # serve-time cast ('bfloat16' for
                                        # MXU-rate inference; '' = exported)
    host: str = "127.0.0.1"
    port: int = 8000
    export_dir: str = ""                # milnce-export artifact to serve
    corpus_npz: str = ""                # (N, D) f32 corpus embeddings to
                                        # index ('' = embed-only service)
    token_dict_path: str = ""           # dict.npy vocab for serve-time
                                        # sentence tokenization ('' = the
                                        # path recorded in the export's
                                        # metadata; without either, only
                                        # token_ids requests work)
    capture_dir: str = ""               # profiler-capture root for the
                                        # serving process ('' = POST
                                        # /obs/capture answers 404);
                                        # flush-latency anomalies arm it
                                        # too when set
    capture_ms: float = 2000.0          # bounded capture duration
    capture_max: int = 1                # captures per process
    anomaly_ratio: float = 3.0          # flush-latency spike ratio for
                                        # the serving EWMA detector
                                        # (queueing makes latency noisier
                                        # than step time — wider than the
                                        # train default)
    replicas: int = 1                   # engine replica pool size (1 = the
                                        # single-engine path; >1 = one
                                        # engine per device group, single-
                                        # device groups on the CPU backend
                                        # — serving/pool.py)
    replica_queue_depth: int = 16       # bounded per-replica work queue;
                                        # all queues full = HTTP 429
    error_threshold: int = 3            # consecutive dispatch errors
                                        # before a replica QUARANTINES
                                        # (ReplicaDead quarantines at once)
    slo_ms: float = 0.0                 # per-dispatch latency SLO driving
                                        # the DEGRADED breaker (0 = off)
    slo_breaches: int = 5               # consecutive SLO breaches before
                                        # SERVING -> DEGRADED (and the
                                        # in-SLO streak to recover)
    probe_interval_s: float = 1.0       # quarantined replicas re-probed
                                        # (synthetic embed at the smallest
                                        # bucket) at this cadence
    hedge_quantile: float = 0.0         # hedge a dispatch still pending
                                        # past this latency quantile to a
                                        # second healthy replica (first
                                        # result wins; 0 = off)
    hedge_min_ms: float = 20.0          # hedge threshold floor — never
                                        # hedge sooner than this
    max_requeues: int = 1               # dispatch errors retried on
                                        # another replica before the
                                        # caller sees the failure
    max_inflight: int = 0               # admission controller: bounded
                                        # global in-flight rows; past it
                                        # requests shed with HTTP 429 +
                                        # Retry-After (0 = unbounded).
                                        # /healthz and /metrics never shed.
    continuous_batching: bool = False   # admit requests into partially-
                                        # filled bucket slots: flush the
                                        # instant a dispatch lane is free,
                                        # accumulate while lanes are busy
                                        # (vLLM-style slot reuse on the
                                        # fixed ladder; max_delay_ms is
                                        # then ignored — serving/batcher.py)
    tiers: str = ""                     # per-tenant SLO classes on the
                                        # admission controller: priority-
                                        # ordered 'name:share[,...]' (e.g.
                                        # 'interactive:1.0,batch:0.5' —
                                        # each tier may hold at most
                                        # share*max_inflight rows, so a
                                        # batch backfill cannot starve
                                        # interactive traffic).  Requests
                                        # pick a class via the 'tier'
                                        # field; '' = untiered.
    live_index: bool = False            # serve a generation-swapped LIVE
                                        # index (serving/live_index.py):
                                        # POST /v1/index/add ingests while
                                        # serving; swaps are atomic and
                                        # recompile-free within a corpus
                                        # rung.  False = the frozen
                                        # DeviceRetrievalIndex.
    index_snapshot_dir: str = ""        # live-index corpus checkpoint dir
                                        # (corpus.npz + index_meta.json):
                                        # restored at boot when present,
                                        # written at shutdown ('' = no
                                        # snapshotting)
    index_min_shard_rows: int = 0       # live-index per-shard capacity
                                        # rung floor (0 = sized by k and
                                        # the boot corpus; raise it to
                                        # pre-provision headroom so early
                                        # growth never crosses a rung).
                                        # HowTo100M-scale default: 524288
                                        # (= 2**19; ~1.2M corpus rows /
                                        # 8-way data axis x 2 headroom —
                                        # recommended_min_shard_rows() in
                                        # serving/live_index.py computes
                                        # the rung for other corpora)
    edge_export_dir: str = ""           # quantized/student artifact the
                                        # edge replica class serves
                                        # (SERVING.md "Edge tier");
                                        # '' = no edge tier
    edge_replicas: int = 0              # edge-class replicas added to the
                                        # pool beside the f32 replicas;
                                        # requests pin a class via the
                                        # 'replica_class' field


@dataclass
class Config:
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    loss: LossConfig = field(default_factory=LossConfig)
    optim: OptimConfig = field(default_factory=OptimConfig)
    parallel: ParallelConfig = field(default_factory=ParallelConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)


def full_preset() -> Config:
    """Defaults of the reference full run (args.py)."""
    return Config()


def small_preset() -> Config:
    """Scaled-down run: EXACTLY the args_small.py deltas over args.py
    (batch 12 :17, n_display 100 :21, warmup 1000 :28, 100 epochs :34)
    made actually runnable — the reference's train_small.py is
    import-broken (SURVEY.md §2.4).  Input shapes stay the full run's
    (32f@224, K=5), as args_small keeps them."""
    cfg = Config()
    cfg.train.batch_size = 12
    cfg.train.n_display = 100
    cfg.optim.warmup_steps = 1000
    cfg.optim.epochs = 100
    return cfg


def tiny_preset() -> Config:
    """Hermetic CPU/CI preset: synthetic data, tiny shapes, no external files."""
    cfg = small_preset()
    cfg.data.synthetic = True
    cfg.data.num_frames = 4
    cfg.data.video_size = 32
    cfg.data.max_words = 6
    cfg.data.num_candidates = 1
    cfg.train.batch_size = 4
    cfg.model.vocab_size = 128
    cfg.optim.warmup_steps = 2
    cfg.optim.epochs = 1
    cfg.train.n_display = 1
    return cfg


PRESETS = {"full": full_preset, "small": small_preset, "tiny": tiny_preset}


def _add_dataclass_args(parser: argparse.ArgumentParser, prefix: str, dc) -> None:
    import typing

    hints = typing.get_type_hints(type(dc))
    for f in dataclasses.fields(dc):
        typ = hints[f.name]
        if typing.get_origin(typ) is typing.Union:   # Optional[T] -> T
            typ = next(a for a in typing.get_args(typ) if a is not type(None))
        name = f"--{prefix}{f.name}"
        if typ is bool:
            parser.add_argument(name, type=lambda s: s.lower() in ("1", "true", "yes"),
                                default=None, metavar="BOOL")
        elif typ in (int, float, str):
            parser.add_argument(name, type=typ, default=None)


def parse_cli(argv: Optional[list[str]] = None, description: str = "milnce-tpu") -> Config:
    """CLI front-end: `--preset {full,small,tiny}` then per-field overrides
    like `--train.batch_size 256` / `--optim.lr 1e-3`."""
    parser = argparse.ArgumentParser(description=description)
    parser.add_argument("--preset", choices=sorted(PRESETS), default="full")
    base = Config()
    for section in dataclasses.fields(base):
        _add_dataclass_args(parser, f"{section.name}.", getattr(base, section.name))
    ns = parser.parse_args(argv)
    cfg = PRESETS[ns.preset]()
    for key, val in vars(ns).items():
        if key == "preset" or val is None:
            continue
        section, _, fname = key.partition(".")
        setattr(getattr(cfg, section), fname, val)
    return cfg
