"""Engine replica pool: health-gated routing, hedged dispatch, bounded
per-replica queues — the serving path's failure-isolation substrate.

Before this module every request funneled through ONE
:class:`~milnce_tpu.serving.engine.InferenceEngine` behind ONE dispatch
lock: a single wedged dispatch, poisoned jit entry or slow replica
stalled the entire service.  The pool owns N engines — one per device
group on a real mesh; N independent **single-device** engines on the
CPU test backend (the multi-device XLA:CPU client deadlocks under
concurrent multi-device dispatch, so single-device groups are the only
shape that may dispatch concurrently there) — each with its OWN
dispatch lock, its own bounded work queue and worker thread, and a
per-replica health state machine:

::

                 consecutive latency-SLO breaches
        SERVING ─────────────────────────────────> DEGRADED
           ^  ^                                      │   │
           │  │   SLO-ok streak                      │   │
           │  └──────────────────────────────────────┘   │
           │                 consecutive dispatch errors │
           │                 (from EITHER state), or     │
           │                 ReplicaDead instantly       v
           └──────────────────────────────────── QUARANTINED
             background synthetic probe succeeds
             (smallest bucket rung, every probe_interval_s)

- **SERVING**: routable, preferred.
- **DEGRADED**: routable only when no SERVING replica exists; entered
  after ``slo_breaches`` consecutive dispatches slower than ``slo_ms``;
  leaves back to SERVING after the same streak of in-SLO dispatches.
- **QUARANTINED**: never routed.  Entered after ``error_threshold``
  consecutive dispatch errors (immediately on
  :class:`~milnce_tpu.serving.engine.ReplicaDead`).  A background probe
  thread re-runs a synthetic embed at the smallest bucket rung every
  ``probe_interval_s``; one success returns the replica to SERVING
  (a force-killed replica's probes keep failing — it stays quarantined
  for the life of the process).

Request flow (``submit_text``/``submit_video`` → Future):

1. **route**: least-outstanding SERVING replica (DEGRADED only as
   fallback); every routable replica's queue full →
   :class:`PoolSaturated` (the admission controller's 429).  No
   routable replica at all → :class:`PoolUnavailable` (the degradation
   ladder's 503 — service.py answers cache hits and sheds misses).
2. **execute**: the replica worker pops the dispatch and runs it on its
   own engine (own dispatch lock — a sibling's hang is not our hang).
3. **requeue**: a dispatch that ERRORS on a replica is re-submitted to
   a different healthy replica up to ``max_requeues`` times before the
   caller sees the error — one flaky replica does not fail requests
   while healthy capacity remains.
4. **hedge**: a dispatch still unresolved past a configurable latency
   quantile (``hedge_quantile`` over the pool's recent dispatch
   latencies, floored at ``hedge_min_ms``) is re-submitted to a second
   healthy replica; the FIRST result wins and the loser's queue slot is
   reclaimed unexecuted (a queued hedge loser is skipped the moment its
   worker sees the future already resolved).

Everything observable lands on the obs metrics registry (per-replica
state/outstanding/probe-age gauges, quarantine/recovery/requeue/hedge
counters — OBSERVABILITY.md) and the span recorder (``pool.quarantine``
/ ``pool.recover`` / ``pool.hedge`` events); ``pool_stats()`` feeds the
``/healthz`` ``pool`` section.

Thread mesh (SERVING.md "Threading model"): N replica workers, one
probe thread, one hedge monitor, plus every submitting thread (batcher
worker, warmup callers).  All mutable pool/replica health state is
guarded by ``_state_lock``; engine dispatch happens under NO pool lock
(each engine takes its own dispatch lock); metric/recorder calls happen
outside ``_state_lock`` (lock-order hygiene, GL011/GL012).
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Callable, Optional, Sequence

import numpy as np

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.obs import spans as obs_spans
from milnce_tpu.serving.engine import InferenceEngine, ReplicaDead

SERVING = "SERVING"
DEGRADED = "DEGRADED"
QUARANTINED = "QUARANTINED"
STATE_NUM = {SERVING: 0, DEGRADED: 1, QUARANTINED: 2}

# Replica classes (heterogeneous pools, ROADMAP item 5): the default
# full-precision tier and the edge tier (int8-quantized or distilled-
# student engines built from a quantized/student export).  Class names
# are plain strings — these two are the conventions the service and
# serve_bench speak.
F32_CLASS = "f32"
EDGE_CLASS = "edge"

# Worker idle poll (bounds close() latency) and the hedge monitor's
# minimum resolution; latency samples kept for the hedge quantile.
_IDLE_POLL_S = 0.05
_LATENCY_WINDOW = 256
_MIN_HEDGE_SAMPLES = 16


class PoolUnavailable(RuntimeError):
    """No replica can take traffic (all quarantined/dead).  The
    degradation ladder's trigger: the service answers cache hits and
    turns misses into structured 503s (SERVING.md "HTTP error
    contract")."""

    def __init__(self, msg: str, reason: str = "no_healthy_replicas"):
        super().__init__(msg)
        self.reason = reason


class PoolSaturated(RuntimeError):
    """Every routable replica's bounded work queue is full — overload,
    not failure.  Surfaced as HTTP 429 with ``retry_after_ms``."""

    def __init__(self, msg: str, retry_after_ms: float = 0.0):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)


class _Dispatch:
    """One logical batch dispatch: routed to a replica, possibly
    requeued after an error or hedged onto a second replica.  The future
    resolves exactly once (first result wins).  ``attempts``/``hedged``
    are guarded by the pool's ``_state_lock``."""

    __slots__ = ("entry", "rows", "future", "t0", "attempts", "hedged",
                 "primary_rid", "cls")

    def __init__(self, entry: str, rows: np.ndarray,
                 cls: Optional[str] = None):
        self.entry = entry
        self.rows = rows
        self.cls = cls              # replica-class pin (None = any)
        self.future: Future = Future()
        self.t0 = time.monotonic()
        # attempts/hedged/primary_rid are only touched under the owning
        # pool's _state_lock (the pool, not this record, is the
        # thread-shared object)
        self.attempts = 0
        self.hedged = False
        self.primary_rid = -1


class Replica:
    """One engine + bounded queue + health bookkeeping.  Every mutable
    field is guarded by the OWNING pool's ``_state_lock`` (the replica
    itself holds no lock — state transitions and routing must see one
    consistent snapshot across all replicas)."""

    def __init__(self, rid: int, engine, queue_depth: int,
                 cls: str = F32_CLASS):
        self.rid = rid
        self.engine = engine
        self.cls = cls              # replica class, immutable after build
        self.queue: queue.Queue[_Dispatch] = queue.Queue(maxsize=queue_depth)
        # ---- everything below: guarded-by the pool's _state_lock ----
        self.state = SERVING
        self.consecutive_errors = 0
        self.slo_breach_streak = 0
        self.slo_ok_streak = 0
        self.outstanding = 0        # queued + executing dispatches
        self.dispatches = 0
        self.errors = 0
        self.last_probe: Optional[float] = None   # monotonic


class ReplicaPool:
    """N engine replicas behind health-gated, load-aware routing.

    Duck-types the single-engine surface the service/batcher consume
    (``embed_text`` / ``embed_video`` / ``bucket_for`` / ``buckets`` /
    ``max_batch`` / ``text_words`` / ``embed_dim`` / ``recompiles`` /
    ``stats``), plus the Future-returning ``submit_text`` /
    ``submit_video`` the batcher's pipelined mode uses.

    ``engines`` may be real :class:`InferenceEngine` replicas
    (:meth:`build` / :meth:`from_export` construct them over device
    groups) or engine-shaped test doubles — the pool only needs the
    embed/bucket surface, which keeps its chaos unit tests jax-free.
    """

    def __init__(self, engines: Sequence, *, queue_depth: int = 16,
                 error_threshold: int = 3, slo_ms: float = 0.0,
                 slo_breaches: int = 5, probe_interval_s: float = 1.0,
                 hedge_quantile: float = 0.0, hedge_min_ms: float = 20.0,
                 max_requeues: int = 1, classes: Optional[Sequence] = None,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 recorder: Optional[obs_spans.SpanRecorder] = None,
                 on_latency: Optional[Callable[[float, int], None]] = None):
        engines = list(engines)
        if not engines:
            raise ValueError("a replica pool needs at least one engine")
        # Heterogeneous pools: ``classes`` labels each engine with its
        # replica class ('f32' full-precision, 'edge' int8/student, or
        # any caller-defined string).  Routing, requeue and hedging all
        # stay WITHIN a dispatch's requested class; the ladder must
        # still be uniform across classes — a class switch must never
        # change which bucket a batch pads to.
        classes = ([F32_CLASS] * len(engines) if classes is None
                   else [str(c) for c in classes])
        if len(classes) != len(engines):
            raise ValueError(f"{len(classes)} classes for "
                             f"{len(engines)} engines")
        ladders = {tuple(e.buckets) for e in engines}
        if len(ladders) != 1:
            raise ValueError(f"replica bucket ladders diverge: {ladders} — "
                             "every replica must serve the same ladder")
        self.buckets = engines[0].buckets
        self.max_batch = engines[0].max_batch
        self.text_words = engines[0].text_words
        self.error_threshold = int(error_threshold)
        self.slo_ms = float(slo_ms)
        self.slo_breaches = int(slo_breaches)
        self.probe_interval_s = float(probe_interval_s)
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_ms = float(hedge_min_ms)
        self.max_requeues = int(max_requeues)
        self.replicas = [Replica(i, e, queue_depth, cls=c)
                         for i, (e, c) in enumerate(zip(engines, classes))]
        self.classes = tuple(classes)
        self._state_lock = make_lock("serving.pool.state")
        self._latencies: deque = deque(maxlen=_LATENCY_WINDOW)  # guarded-by: _state_lock
        self._inflight: set = set()                             # guarded-by: _state_lock
        self._rr = 0                                            # guarded-by: _state_lock
        self._on_latency = on_latency                           # guarded-by: _state_lock
        self._closed = threading.Event()
        self._recorder = recorder
        reg = registry if registry is not None \
            else obs_metrics.MetricsRegistry()
        self.registry = reg
        self._f_state = reg.gauge(
            "milnce_serve_replica_state",
            "per-replica health state (0=SERVING 1=DEGRADED 2=QUARANTINED)",
            ("replica",))
        self._f_outstanding = reg.gauge(
            "milnce_serve_replica_outstanding",
            "dispatches queued or executing per replica", ("replica",))
        self._f_probe_age = reg.gauge(
            "milnce_serve_replica_last_probe_age_seconds",
            "seconds since the replica's last synthetic probe "
            "(-1 = never probed)", ("replica",))
        self._f_quarantined = reg.counter(
            "milnce_serve_pool_quarantined_total",
            "replica transitions into QUARANTINED", ("replica",))
        self._f_recovered = reg.counter(
            "milnce_serve_pool_recovered_total",
            "replica recoveries (probe success -> SERVING)", ("replica",))
        self._f_probes = reg.counter(
            "milnce_serve_pool_probes_total",
            "synthetic probes against quarantined replicas", ("result",))
        self._m_requeued = reg.counter(
            "milnce_serve_pool_requeued_total",
            "dispatches re-submitted to another replica after an error")
        self._m_hedged = reg.counter(
            "milnce_serve_pool_hedged_total",
            "dispatches re-submitted to a second replica past the "
            "hedge latency quantile")
        self._f_hedge_wins = reg.counter(
            "milnce_serve_pool_hedge_wins_total",
            "hedged dispatches by which copy resolved first", ("winner",))
        self._m_saturated = reg.counter(
            "milnce_serve_pool_saturated_total",
            "submissions refused because every routable replica's "
            "queue was full")
        self._m_reclaimed = reg.counter(
            "milnce_serve_pool_reclaimed_total",
            "queue slots reclaimed unexecuted (hedge/requeue loser "
            "already resolved)")
        for r in self.replicas:
            self._f_state.labels(replica=str(r.rid)).bind(
                lambda r=r: float(STATE_NUM[self._replica_state(r)]))
            self._f_outstanding.labels(replica=str(r.rid)).bind(
                lambda r=r: float(self._replica_outstanding(r)))
            self._f_probe_age.labels(replica=str(r.rid)).bind(
                lambda r=r: self._probe_age(r))
        self._workers = [
            threading.Thread(target=self._worker, args=(r,), daemon=True,
                             name=f"pool-replica{r.rid}")
            for r in self.replicas]
        for t in self._workers:
            t.start()
        self._prober = threading.Thread(target=self._probe_loop, daemon=True,
                                        name="pool-prober")
        self._prober.start()
        self._hedger = None
        if self.hedge_quantile > 0.0:
            self._hedger = threading.Thread(target=self._hedge_loop,
                                            daemon=True, name="pool-hedger")
            self._hedger.start()

    # ---- engine-compatible surface ---------------------------------------

    @property
    def embed_dim(self) -> Optional[int]:
        for r in self.replicas:
            if r.engine.embed_dim is not None:
                return r.engine.embed_dim
        return None

    def bucket_for(self, n: int) -> int:
        return self.replicas[0].engine.bucket_for(n)

    def embed_text(self, token_ids: np.ndarray,
                   cls: Optional[str] = None) -> np.ndarray:
        return self.submit_text(token_ids, cls=cls).result()

    def embed_video(self, video_u8: np.ndarray,
                    cls: Optional[str] = None) -> np.ndarray:
        return self.submit_video(video_u8, cls=cls).result()

    def submit_text(self, token_ids: np.ndarray,
                    cls: Optional[str] = None) -> Future:
        return self._submit("text", token_ids, cls=cls)

    def submit_video(self, video_u8: np.ndarray,
                     cls: Optional[str] = None) -> Future:
        return self._submit("video", video_u8, cls=cls)

    def recompiles(self) -> int:
        """Jit-cache growth since warmup summed over SURVIVING (non-dead)
        replicas; -1 when no surviving replica has cache introspection."""
        counts = [r.engine.recompiles() for r in self.replicas
                  if not getattr(r.engine, "dead", False)]
        known = [c for c in counts if c >= 0]
        return sum(known) if known else -1

    def stats(self) -> dict:
        """Engine-shaped aggregate (the ``/healthz`` ``engine`` section
        keeps its keys when a pool replaces the single engine): calls
        merged across replicas, recompiles summed over survivors."""
        calls: dict[str, int] = {}
        for r in self.replicas:
            for key, n in r.engine.stats().get("calls", {}).items():
                calls[key] = calls.get(key, 0) + n
        counts: dict[str, int] = {}
        for c in self.classes:
            counts[c] = counts.get(c, 0) + 1
        return {
            "buckets": list(self.buckets),
            "max_batch": self.max_batch,
            "recompiles": self.recompiles(),
            "replicas": len(self.replicas),
            "classes": dict(sorted(counts.items())),
            "calls": dict(sorted(calls.items())),
        }

    # ---- submission / routing --------------------------------------------

    def _submit(self, entry: str, rows: np.ndarray,
                cls: Optional[str] = None) -> Future:
        if self._closed.is_set():
            raise RuntimeError("replica pool is closed")
        if cls is not None and cls not in self.classes:
            raise ValueError(f"no {cls!r} replica class in this pool "
                             f"(classes: {sorted(set(self.classes))})")
        d = _Dispatch(entry, np.asarray(rows), cls=cls)
        targets = self._route(cls=cls)
        rid = self._enqueue(d, targets, primary=True)
        if rid < 0:
            self._m_saturated.inc()
            raise PoolSaturated(
                f"every routable replica's work queue is full "
                f"({len(targets)} routable of {len(self.replicas)})",
                retry_after_ms=self._mean_latency_ms())
        if self._closed.is_set():
            # close() raced the enqueue above: the workers may already
            # have drained and exited, so this dispatch would hang
            # forever — sweep every queue from here (idempotent: the
            # resolve path tolerates double resolution), same defense
            # as DynamicBatcher.submit
            for r in self.replicas:
                self._drain_closed(r)
        return d.future

    def _route(self, exclude: tuple = (),
               cls: Optional[str] = None) -> list:
        """Routable replicas, best-first: SERVING by least outstanding,
        then DEGRADED by least outstanding.  ``cls`` restricts routing
        to one replica class — STRICT: a class-pinned dispatch with no
        routable replica of that class fails PoolUnavailable even if
        another class has capacity (a caller asking for the edge tier
        asked for its precision/latency contract, not any answer).
        Raises PoolUnavailable when nothing is routable."""
        with self._state_lock:
            pool = [r for r in self.replicas
                    if cls is None or r.cls == cls]
            serving = [r for r in pool
                       if r.state == SERVING and r.rid not in exclude]
            degraded = [r for r in pool
                        if r.state == DEGRADED and r.rid not in exclude]
            # least-outstanding first; equal depths rotate round-robin
            # (a fixed tie-break would starve every replica but one at
            # low load, making hedges and probes the only traffic they
            # ever see)
            self._rr += 1
            rr, n = self._rr, len(self.replicas)
            key = lambda r: (r.outstanding, (r.rid - rr) % n)  # noqa: E731
            serving.sort(key=key)
            degraded.sort(key=key)
        if not serving and not degraded:
            scope = (f"class {cls!r} ({len(pool)} replicas)"
                     if cls is not None else f"pool of {len(self.replicas)}")
            raise PoolUnavailable(
                "no SERVING or DEGRADED replica left "
                f"({scope}, exclude={list(exclude)})")
        return serving + degraded

    def _enqueue(self, d: _Dispatch, targets: list,
                 primary: bool = False) -> int:
        """Queue ``d`` on the first target with a free slot; returns the
        replica id, or -1 when every target's bounded queue is full.

        Bookkeeping is registered BEFORE the put (and rolled back on a
        full queue): the instant the worker can see the dispatch, its
        outstanding count, primary marker and in-flight registration
        already exist — registering after the put raced a fast worker
        into resolving (and discarding from ``_inflight``) a dispatch
        the submitter then added back, leaking it there forever."""
        for r in targets:
            with self._state_lock:
                r.outstanding += 1
                if primary:
                    d.primary_rid = r.rid
                    self._inflight.add(d)
            try:
                r.queue.put_nowait(d)
            except queue.Full:
                with self._state_lock:
                    r.outstanding -= 1
                    if primary:
                        self._inflight.discard(d)
                        d.primary_rid = -1
                continue
            return r.rid
        return -1

    # ---- replica workers --------------------------------------------------

    def _worker(self, replica: Replica) -> None:
        while not self._closed.is_set():
            try:
                d = replica.queue.get(timeout=_IDLE_POLL_S)
            except queue.Empty:
                continue
            try:
                self._execute(replica, d)
            except Exception as exc:
                # _execute guards the dispatch itself; this barrier is
                # for the BOOKKEEPING around it (metrics, recorder, the
                # injected on_latency callback).  A raising callback
                # must never kill the lane — a dead worker would strand
                # every queued dispatch while the replica still reads
                # SERVING (the exact failure DynamicBatcher._flush
                # defends against).  Resolve the caller (no-op if the
                # dispatch already resolved) and keep draining.
                self._resolve(d, exc=exc)
        self._drain_closed(replica)

    def _execute(self, replica: Replica, d: _Dispatch) -> None:
        if d.future.done():
            # hedge/requeue loser still queued: reclaim the slot without
            # touching the device
            with self._state_lock:
                replica.outstanding -= 1
            self._m_reclaimed.inc()
            return
        t0 = time.monotonic()  # graftlint: disable=GL005(the dispatch IS host-blocking — engine._run device_gets the result before returning, so this delta measures real replica latency, and it feeds the latency-SLO breaker + hedge quantile)
        try:
            fn = (replica.engine.embed_text if d.entry == "text"
                  else replica.engine.embed_video)
            out = fn(d.rows)
        except Exception as exc:
            with self._state_lock:
                replica.outstanding -= 1
            self._record_error(replica, exc)
            self._handle_failure(d, replica, exc)
            return
        dur_s = time.monotonic() - t0
        with self._state_lock:
            replica.outstanding -= 1
            on_latency = self._on_latency
        self._record_success(replica, dur_s)
        won = self._resolve(d, result=out)
        if won and d.hedged:
            winner = "primary" if replica.rid == d.primary_rid else "hedge"
            self._f_hedge_wins.labels(winner=winner).inc()
        if on_latency is not None:
            on_latency(dur_s * 1e3, int(d.rows.shape[0]))

    def _resolve(self, d: _Dispatch, *, result=None, exc=None) -> bool:
        try:
            if exc is not None:
                d.future.set_exception(exc)
            else:
                d.future.set_result(result)
            won = True
        except InvalidStateError:
            won = False                 # the other copy got there first
        with self._state_lock:
            self._inflight.discard(d)
        return won

    def _handle_failure(self, d: _Dispatch, replica: Replica,
                        exc: Exception) -> None:
        """Requeue the dispatch on another healthy replica (bounded),
        else fail the caller with the LAST error — bounded, structured,
        never a hang."""
        with self._state_lock:
            d.attempts += 1
            attempts = d.attempts
        if attempts <= self.max_requeues:
            try:
                targets = self._route(exclude=(replica.rid,), cls=d.cls)
            except PoolUnavailable as unavailable:
                # nobody left to retry on: the caller-facing error is
                # the DEGRADATION signal (the service's cache-only /
                # full-503 ladder keys on it), with the dispatch error
                # chained as the cause
                unavailable.__cause__ = exc
                self._resolve(d, exc=unavailable)
                return
            rid = self._enqueue(d, targets)
            if rid >= 0:
                with self._state_lock:
                    # the requeued copy is a FRESH attempt: restart the
                    # hedge clock and move the primary marker, else the
                    # hedge monitor sees a stale t0 and can immediately
                    # "hedge" onto the very replica now executing it
                    d.t0 = time.monotonic()
                    d.primary_rid = rid
                self._m_requeued.inc()
                self._recorder_event("pool.requeue", replica=replica.rid,
                                     attempts=attempts,
                                     error=type(exc).__name__)
                return
        self._resolve(d, exc=exc)

    def _drain_closed(self, replica: Replica) -> None:
        while True:
            try:
                d = replica.queue.get_nowait()
            except queue.Empty:
                return
            self._resolve(d, exc=RuntimeError("replica pool closed"))

    # ---- health state machine --------------------------------------------

    def _record_success(self, replica: Replica, dur_s: float) -> None:
        transition = None
        with self._state_lock:
            replica.dispatches += 1
            replica.consecutive_errors = 0
            self._latencies.append(dur_s)
            if self.slo_ms > 0:
                if dur_s * 1e3 > self.slo_ms:
                    replica.slo_breach_streak += 1
                    replica.slo_ok_streak = 0
                    if (replica.state == SERVING and
                            replica.slo_breach_streak >= self.slo_breaches):
                        replica.state = DEGRADED
                        replica.slo_breach_streak = 0
                        transition = DEGRADED
                else:
                    replica.slo_ok_streak += 1
                    replica.slo_breach_streak = 0
                    if (replica.state == DEGRADED and
                            replica.slo_ok_streak >= self.slo_breaches):
                        replica.state = SERVING
                        replica.slo_ok_streak = 0
                        transition = SERVING
        if transition == DEGRADED:
            self._recorder_event("pool.degrade", replica=replica.rid,
                                 slo_ms=self.slo_ms)
        elif transition == SERVING:
            self._recorder_event("pool.undegrade", replica=replica.rid)

    def _record_error(self, replica: Replica, exc: Exception) -> None:
        quarantined = False
        with self._state_lock:
            replica.dispatches += 1
            replica.errors += 1
            replica.consecutive_errors += 1
            if replica.state != QUARANTINED and (
                    isinstance(exc, ReplicaDead) or
                    replica.consecutive_errors >= self.error_threshold):
                replica.state = QUARANTINED
                quarantined = True
        if quarantined:
            self._f_quarantined.labels(replica=str(replica.rid)).inc()
            self._recorder_event("pool.quarantine", replica=replica.rid,
                                 error=type(exc).__name__)

    # ---- background probe (quarantine recovery) ---------------------------

    def _probe_loop(self) -> None:
        while not self._closed.wait(self.probe_interval_s):
            for r in self.replicas:
                if self._replica_state(r) == QUARANTINED:
                    self._probe(r)

    def _probe(self, replica: Replica) -> None:
        """Synthetic embed at the smallest bucket rung, through the
        replica's own engine (and its fault sites — an armed
        ``serve.dispatch_raise`` can fail a probe, which just means the
        replica stays quarantined until a clean probe)."""
        try:
            replica.engine.embed_text(
                np.zeros((self.buckets[0], self.text_words), np.int32))
            ok, err = True, ""
        except Exception as exc:
            ok, err = False, type(exc).__name__
        recovered = False
        with self._state_lock:
            replica.last_probe = time.monotonic()
            if ok and replica.state == QUARANTINED:
                replica.state = SERVING
                replica.consecutive_errors = 0
                replica.slo_breach_streak = 0
                replica.slo_ok_streak = 0
                recovered = True
        self._f_probes.labels(result="ok" if ok else "fail").inc()
        if recovered:
            self._f_recovered.labels(replica=str(replica.rid)).inc()
            self._recorder_event("pool.recover", replica=replica.rid)
        elif not ok:
            self._recorder_event("pool.probe_fail", replica=replica.rid,
                                 error=err)

    # ---- hedged dispatch --------------------------------------------------

    def _hedge_threshold_s(self) -> Optional[float]:
        with self._state_lock:
            if len(self._latencies) < _MIN_HEDGE_SAMPLES:
                return None
            lats = sorted(self._latencies)
        q = lats[min(len(lats) - 1,
                     int(self.hedge_quantile * len(lats)))]
        return max(q, self.hedge_min_ms / 1e3)

    def _hedge_loop(self) -> None:
        poll = max(self.hedge_min_ms / 4e3, 0.002)
        while not self._closed.wait(poll):
            thr = self._hedge_threshold_s()
            if thr is None:
                continue
            now = time.monotonic()
            with self._state_lock:
                stale = [d for d in self._inflight
                         if not d.hedged and now - d.t0 > thr
                         and not d.future.done()]
                for d in stale:
                    d.hedged = True    # one hedge attempt per dispatch
            for d in stale:
                self._hedge(d)

    def _hedge(self, d: _Dispatch) -> None:
        try:
            targets = self._route(exclude=(d.primary_rid,), cls=d.cls)
        except PoolUnavailable:
            return                      # nobody to hedge onto
        if self._enqueue(d, targets) >= 0:
            self._m_hedged.inc()
            self._recorder_event("pool.hedge", replica=d.primary_rid,
                                 age_ms=round((time.monotonic() - d.t0) * 1e3,
                                              2))

    # ---- observability / lifecycle ---------------------------------------

    def _replica_state(self, r: Replica) -> str:
        with self._state_lock:
            return r.state

    def _replica_outstanding(self, r: Replica) -> int:
        with self._state_lock:
            return r.outstanding

    def _probe_age(self, r: Replica) -> float:
        with self._state_lock:
            last = r.last_probe
        return -1.0 if last is None else round(time.monotonic() - last, 3)

    def _mean_latency_ms(self) -> float:
        with self._state_lock:
            lats = list(self._latencies)
        return round(sum(lats) / len(lats) * 1e3, 2) if lats else 50.0

    def _recorder_event(self, name: str, **attrs) -> None:
        rec = self._recorder if self._recorder is not None \
            else obs_spans.get_recorder()
        rec.event(name, **attrs)

    def set_on_latency(self, cb: Optional[Callable[[float, int], None]]
                       ) -> None:
        """Per-dispatch latency observer ``(dur_ms, rows)`` — the service
        wires its EWMA flush-latency spike detector here so pool
        dispatches feed the anomaly→capture path like batcher flushes."""
        with self._state_lock:
            self._on_latency = cb

    def counts(self) -> dict:
        """The pool's resilience counters as plain ints (single source:
        the registry metrics) — serve_bench's chaos record reads these."""
        def _fam_total(fam) -> int:
            return int(sum(child.value for _, child in fam.items()))

        return {
            "requeued": int(self._m_requeued.value),
            "hedged": int(self._m_hedged.value),
            "hedge_wins": _fam_total(self._f_hedge_wins),
            "saturated": int(self._m_saturated.value),
            "reclaimed": int(self._m_reclaimed.value),
            "quarantines": _fam_total(self._f_quarantined),
            "recoveries": _fam_total(self._f_recovered),
            "probes": _fam_total(self._f_probes),
        }

    def pool_stats(self) -> dict:
        """The ``/healthz`` ``pool`` section: per-replica state,
        outstanding depth, probe age, error/dispatch counts, plus the
        pool-level resilience counters."""
        now = time.monotonic()
        with self._state_lock:
            reps = [{
                "id": r.rid,
                "class": r.cls,
                "state": r.state,
                "outstanding": r.outstanding,
                "consecutive_errors": r.consecutive_errors,
                "dispatches": r.dispatches,
                "errors": r.errors,
                "last_probe_age_s": (round(now - r.last_probe, 3)
                                     if r.last_probe is not None else None),
            } for r in self.replicas]
        for rep, r in zip(reps, self.replicas):
            rep["dead"] = bool(getattr(r.engine, "dead", False))
            rep["recompiles"] = r.engine.recompiles()
        out = {"replicas": reps}
        out.update(self.counts())
        return out

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        for t in self._workers:
            t.join(timeout)
        self._prober.join(timeout)
        if self._hedger is not None:
            self._hedger.join(timeout)
        for r in self.replicas:
            self._drain_closed(r)

    # ---- construction over device groups ---------------------------------

    @staticmethod
    def partition_devices(devices: Sequence, n_replicas: int) -> list:
        """Device groups for ``n_replicas`` engines.  On the CPU backend
        every group is a SINGLE device (concurrent multi-device dispatch
        deadlocks the XLA:CPU client — engine.py's dispatch-lock note;
        single-device executions from several threads are safe, verified
        by the serving chaos suite); on real hardware the devices split
        into ``n_replicas`` even contiguous groups."""
        import jax

        devices = list(devices)
        if n_replicas < 1:
            raise ValueError(f"n_replicas={n_replicas} < 1")
        if n_replicas > len(devices):
            raise ValueError(f"{n_replicas} replicas > {len(devices)} "
                             "devices — a replica needs at least one chip")
        if jax.default_backend() == "cpu":
            return [[devices[i]] for i in range(n_replicas)]
        if len(devices) % n_replicas:
            raise ValueError(
                f"{len(devices)} devices do not split evenly into "
                f"{n_replicas} replica groups")
        size = len(devices) // n_replicas
        return [devices[i * size:(i + 1) * size] for i in range(n_replicas)]

    @classmethod
    def build(cls, model, variables, n_replicas: int, *, text_words: int,
              video_shape: Sequence[int], max_batch: int = 64,
              min_bucket: int = 0, data_axis: str = "data",
              cast_dtype: Optional[str] = None, devices=None,
              precompile: bool = True, **pool_kwargs) -> "ReplicaPool":
        """Partition the visible devices and build one engine per group,
        each with its OWN dispatch lock (named ``serving.replica<i>.
        dispatch`` — the name keeps GL012's dispatch exemption and gives
        the runtime sanitizer distinct order classes)."""
        import jax
        from jax.sharding import Mesh

        devs = list(devices if devices is not None else jax.devices())
        groups = cls.partition_devices(devs, n_replicas)
        # every replica must expose the same ladder: the smallest group
        # sets the floor so 'mesh-size' buckets cannot diverge per group
        floor = max(min_bucket, max(len(g) for g in groups))
        engines = []
        for i, group in enumerate(groups):
            mesh = Mesh(np.asarray(group), (data_axis,))
            engines.append(InferenceEngine(
                model, variables, mesh, text_words=text_words,
                video_shape=video_shape, max_batch=max_batch,
                min_bucket=floor, data_axis=data_axis,
                cast_dtype=cast_dtype, precompile=precompile,
                dispatch_lock=make_lock(f"serving.replica{i}.dispatch")))
        return cls(engines, **pool_kwargs)

    @classmethod
    def from_export(cls, export_dir: str, n_replicas: int, *,
                    dtype: str = "", max_batch: int = 64,
                    min_bucket: int = 0, data_axis: str = "data",
                    devices=None, precompile: bool = True,
                    edge_export_dir: str = "", edge_replicas: int = 0,
                    edge_class: str = EDGE_CLASS,
                    **pool_kwargs) -> "ReplicaPool":
        """Pooled twin of ``InferenceEngine.from_export``: one frozen
        export served by ``n_replicas`` engines.

        ``edge_export_dir``/``edge_replicas`` add a heterogeneous edge
        tier: that many extra replicas built from a SECOND artifact
        (int8-quantized or distilled-student export — any format the
        engine's loader detects), registered under ``edge_class``.
        Both artifacts must agree on the serving contract (tokenizer
        max_words, video shape — same embedding space is the exporter's
        responsibility); every replica serves the same bucket ladder,
        so a class switch never changes batch padding."""
        import jax
        from jax.sharding import Mesh

        from milnce_tpu.serving.engine import load_serving_model

        model, variables, meta = load_serving_model(export_dir, dtype)
        specs = [(model, variables, (dtype or None), F32_CLASS)
                 ] * n_replicas
        if edge_export_dir and edge_replicas:
            emodel, evars, emeta = load_serving_model(edge_export_dir)
            if (emeta["tokenizer"]["max_words"]
                    != meta["tokenizer"]["max_words"]
                    or list(emeta["video_shape"])
                    != list(meta["video_shape"])):
                raise ValueError(
                    "edge export disagrees with the f32 export on the "
                    "serving contract: max_words "
                    f"{emeta['tokenizer']['max_words']} vs "
                    f"{meta['tokenizer']['max_words']}, video_shape "
                    f"{emeta['video_shape']} vs {meta['video_shape']}")
            specs += [(emodel, evars, None, edge_class)] * edge_replicas
        devs = list(devices if devices is not None else jax.devices())
        groups = cls.partition_devices(devs, len(specs))
        floor = max(min_bucket, max(len(g) for g in groups))
        engines, classes = [], []
        for i, (group, (m, v, cast, rep_cls)) in enumerate(
                zip(groups, specs)):
            mesh = Mesh(np.asarray(group), (data_axis,))
            engines.append(InferenceEngine(
                m, v, mesh, text_words=meta["tokenizer"]["max_words"],
                video_shape=meta["video_shape"], max_batch=max_batch,
                min_bucket=floor, data_axis=data_axis, cast_dtype=cast,
                precompile=precompile,
                dispatch_lock=make_lock(f"serving.replica{i}.dispatch")))
            classes.append(rep_cls)
        return cls(engines, classes=classes, **pool_kwargs)
