"""Online text->video retrieval serving (SERVING.md).

The training side of this repo produces frozen parameters; this package
turns them into a production inference path the ROADMAP's north star
demands: a bucketed, pre-traced, transfer-guarded embedding engine
(`engine`), a dynamic micro-batcher with per-request deadlines
(`batcher`), an LRU text-embedding cache (`cache`), a device-resident
sharded retrieval index (`index`), an engine replica pool with
health-gated routing, hedged dispatch and quarantine/probe recovery
(`pool`), a stdlib HTTP/JSON front with admission control and a
degradation ladder (`service`), and the params-only export that feeds
it (`export`).

Import discipline: `batcher` and `cache` are numpy-only (usable, and
testable, without jax); `engine`/`index` own every device interaction
and keep the steady state free of implicit transfers and recompiles —
the serve entries are pinned by `analysis/trace_invariants.py`.
"""

from milnce_tpu.serving.batcher import DeadlineExpired, DynamicBatcher
from milnce_tpu.serving.cache import EmbeddingLRUCache

__all__ = [
    "DeadlineExpired",
    "DynamicBatcher",
    "EmbeddingLRUCache",
]
