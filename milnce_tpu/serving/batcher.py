"""Dynamic micro-batcher: request queue -> padded bucket -> per-request
results.

The serving engine (engine.py) only executes fixed, pre-traced batch
shapes (the bucket ladder); individual requests arrive one row at a
time.  This module is the shim between the two worlds: a worker thread
drains a queue, groups rows into a batch, pads the batch to the
smallest bucket that fits, runs it, and scatters per-row results back
to the callers' futures.

Flush policy (both bounds are SLO knobs, SERVING.md):

- **size**: a batch flushes as soon as ``max_batch`` rows are waiting —
  never pads past the top bucket;
- **delay**: a batch flushes at most ``max_delay_ms`` after its FIRST
  row arrived — a lone request never waits longer than the delay bound
  for company.

**Continuous batching** (``continuous=True`` — the vLLM slot-reuse idea
adapted to the fixed bucket ladder, SERVING.md "Continuous batching"):
instead of flush-and-wait, the worker flushes the moment a dispatch
LANE is free — a lone request never pays ``max_delay_ms`` for company
that isn't coming — and while every lane is busy, arrivals accumulate
into the forming batch, filling bucket slots for free (occupancy rises
exactly when the device is the bottleneck).  ``lanes`` is the number of
concurrently-dispatchable batches (1 for a single engine; the replica
count for a pool in pipelined mode); a semaphore bounds in-flight
batches to it.  Deadlines stay prompt: the lane-wait loop expires aged
requests at the same ~2 ms resolution the deadline wake gives the
flush-and-wait path.

Deadline semantics (the request-path analogue of the training side's
decode watchdog, ROBUSTNESS.md): a request may carry a deadline that
bounds its QUEUE WAIT.  A request whose deadline passes before its
batch runs completes with :class:`DeadlineExpired` — an error the
caller sees, never a silent drop — and the worker wakes early at the
nearest pending deadline so expiry is prompt, not discovered at the
next size/delay flush.  A deadline does NOT abort device work already
in flight: once a batch is submitted its rows get their results.

numpy-only on purpose: payloads and results are host arrays; every
device interaction lives behind the injected ``run_batch`` callable.
Thread safety: ``submit`` may be called from any number of threads;
one worker thread owns the flush path; every counter lives on the obs
metrics registry (lock-guarded there — OBSERVABILITY.md), so request
threads and the worker can no longer race an unlocked dict.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.obs import spans as obs_spans

# The worker wakes this soon after the nearest deadline so an expired
# request fails promptly (bounded staleness of the expiry verdict).
_DEADLINE_SLACK_S = 0.002
# Idle poll period: how often the worker re-checks the closed flag when
# the queue is empty (bounds close() latency, costs nothing hot).
_IDLE_POLL_S = 0.05
# Continuous mode's lane-wait tick: bounds both deadline-expiry
# staleness and close() latency while every dispatch lane is busy.
_LANE_POLL_S = 0.002


class DeadlineExpired(RuntimeError):
    """The request's deadline passed while it was still queued.

    ``retry_after_ms`` is the server's retry hint (a fresh, lone request's
    expected queue wait) — the HTTP front surfaces it as a real
    ``Retry-After`` header plus a ``retry_after_ms`` JSON body field
    (SERVING.md "HTTP error contract")."""

    def __init__(self, msg: str, retry_after_ms: float = 0.0):
        super().__init__(msg)
        self.retry_after_ms = float(retry_after_ms)


def pad_rows(rows: np.ndarray, bucket: int) -> np.ndarray:
    """Zero-pad ``(n, ...)`` rows up to ``(bucket, ...)`` on axis 0
    (no-op when already at the bucket).  THE pad rule of the serve path
    — batcher, engine and index all share it so it cannot diverge."""
    n = rows.shape[0]
    if bucket <= n:
        return rows
    pad = np.zeros((bucket - n,) + rows.shape[1:], dtype=rows.dtype)
    return np.concatenate([rows, pad], axis=0)


@dataclass
class _Request:
    payload: np.ndarray
    future: Future
    deadline: Optional[float]        # absolute time.monotonic() seconds


class DynamicBatcher:
    """Queue + worker thread turning single-row submits into bucket-padded
    batch executions.

    - ``run_batch(padded (bucket, ...)) -> (bucket, D)``: the batch
      executor (e.g. ``InferenceEngine.embed_text``).  Row ``i`` of the
      output must correspond to row ``i`` of the input — the pad/unpad
      identity the batcher relies on (pinned by tests).
    - ``bucket_for(n) -> bucket >= n``: the engine's ladder lookup.
    - ``max_batch``: size-flush threshold (== the top bucket).
    - ``max_delay_ms``: delay-flush bound.
    - ``default_timeout_ms``: deadline applied to submits that don't pass
      their own; 0 disables.
    - ``registry``: obs metrics registry the counters/occupancy histogram
      land on (None = a private one, so standalone batchers stay
      isolated; the service passes its registry down so ``GET /metrics``
      sees the request path).
    - ``buckets``: the engine's ladder, used as the occupancy histogram's
      fixed edges (None = powers of two up to ``max_batch``).
    - ``run_batch_async``: optional Future-returning batch executor (e.g.
      ``ReplicaPool.submit_text``).  When set, the worker SUBMITS each
      padded batch and moves on — results scatter to the callers' futures
      from a completion callback — so several batches can be in flight
      across pool replicas at once and one wedged replica never blocks
      the flush loop.  ``run_batch`` is ignored when this is set.
    - ``continuous``: continuous batching (module docstring) — flush the
      instant a lane is free, accumulate while lanes are busy;
      ``max_delay_ms`` is ignored (a lone request never waits for
      company that isn't coming).
    - ``lanes``: concurrently-in-flight batch bound in continuous mode
      (the pool's replica count in pipelined mode, else 1).
    """

    def __init__(self, run_batch: Callable[[np.ndarray], np.ndarray],
                 bucket_for: Callable[[int], int], *, max_batch: int,
                 max_delay_ms: float = 5.0, default_timeout_ms: float = 0.0,
                 name: str = "batcher",
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 buckets: Optional[tuple] = None,
                 recorder: Optional[obs_spans.SpanRecorder] = None,
                 on_flush: Optional[Callable[[float, int], None]] = None,
                 run_batch_async: Optional[Callable[[np.ndarray],
                                                    Future]] = None,
                 continuous: bool = False, lanes: int = 1):
        assert max_batch >= 1
        self._run_batch = run_batch
        self._run_batch_async = run_batch_async
        self.continuous = bool(continuous)
        # in-flight batch bound for continuous mode: acquired by the
        # worker before each flush, released when the flush resolves
        # (sync: after run_batch; async: in the completion callback)
        self._lane_sem = (threading.Semaphore(max(1, int(lanes)))
                          if continuous else None)
        # flush-latency observer ``(dur_ms, live_rows) -> None``: the
        # service feeds its EWMA spike detector here (anomaly-triggered
        # profiler capture).  Invoked on the worker thread AFTER the
        # flush resolves, outside every batcher lock (GL012 discipline:
        # the callee takes its own locks)
        self._on_flush = on_flush
        # flush spans go to the injected recorder when the owner (the
        # service) isolates one; None = the process default, resolved at
        # flush time so a later spans.install() is honored
        self._recorder = recorder
        self._bucket_for = bucket_for
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.default_timeout_ms = float(default_timeout_ms)
        self.name = name
        self._q: queue.Queue[_Request] = queue.Queue()
        self._closed = threading.Event()
        self.registry = registry if registry is not None \
            else obs_metrics.MetricsRegistry()
        if buckets is None:
            buckets, b = [], 1
            while b < self.max_batch:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_batch)
        lbl = {"batcher": name}
        reg = self.registry
        self._m_requests = reg.counter(
            "milnce_serve_requests_total",
            "rows submitted to the batcher", ("batcher",)).labels(**lbl)
        self._m_flushes = reg.counter(
            "milnce_serve_flushes_total",
            "batches executed", ("batcher",)).labels(**lbl)
        self._m_expired = reg.counter(
            "milnce_serve_deadline_expired_total",
            "requests failed with DeadlineExpired while queued",
            ("batcher",)).labels(**lbl)
        self._m_batch_errors = reg.counter(
            "milnce_serve_batch_errors_total",
            "batch executions that failed (propagated to every caller)",
            ("batcher",)).labels(**lbl)
        self._m_occupancy = reg.histogram(
            "milnce_serve_batch_occupancy",
            "live rows per executed batch (bucket edges = the ladder)",
            buckets=tuple(buckets), labels=("batcher",)).labels(**lbl)
        self._f_bucket_flushes = reg.counter(
            "milnce_serve_bucket_flushes_total",
            "batches executed per padded bucket size",
            ("batcher", "bucket"))
        self._f_bucket_rows = reg.counter(
            "milnce_serve_bucket_rows_total",
            "live rows executed per padded bucket size",
            ("batcher", "bucket"))
        # cached per-bucket child handles (resolved once per bucket on
        # the worker thread).  Children are keyed by label values, so
        # two batchers sharing a registry AND a name read combined
        # totals — isolation is a private registry (the default) or a
        # distinct name, not this cache.  Lock-guarded: the worker
        # inserts on a bucket's first flush while request threads
        # iterate it in stats() (/healthz) — EVERY access, including the
        # worker's own lookup (graftlint GL010: single-writer does not
        # make a lock-free read of a guarded dict safe)
        self._bucket_children: dict[int, tuple] = {}
        self._children_lock = make_lock("serving.batcher.children")
        # rows the continuous worker has dequeued into its FORMING batch
        # (left _q, not yet flushed): depth() must count them or the
        # admission feasibility floor undercounts by up to max_batch
        # while the worker parks on busy lanes
        self._forming = 0                     # guarded-by: _forming_lock
        self._forming_lock = make_lock("serving.batcher.forming")
        self._worker = threading.Thread(target=self._run, daemon=True,
                                        name=f"{name}-worker")
        self._worker.start()

    # ---- client side ----------------------------------------------------

    def submit(self, payload: np.ndarray,
               timeout_ms: Optional[float] = None) -> Future:
        """Enqueue one row; returns a Future resolving to its result row.

        ``timeout_ms``: deadline for THIS request (None = the batcher
        default; <= 0 = no deadline)."""
        if self._closed.is_set():
            raise RuntimeError("batcher is closed")
        t_ms = self.default_timeout_ms if timeout_ms is None else timeout_ms
        deadline = (time.monotonic() + t_ms / 1000.0) if t_ms > 0 else None
        fut: Future = Future()
        self._m_requests.inc()
        self._q.put(_Request(np.asarray(payload), fut, deadline))
        if self._closed.is_set():
            # close() raced the put above: the worker may already have
            # drained and exited, so this request would hang forever —
            # sweep the queue from here (idempotent, InvalidStateError-
            # safe) so the future resolves either way
            self._drain_closed()
        return fut

    # ---- worker side ----------------------------------------------------

    def _run(self) -> None:
        if self.continuous:
            self._run_continuous()
            return
        while not self._closed.is_set():
            try:
                first = self._q.get(timeout=_IDLE_POLL_S)
            except queue.Empty:
                continue
            batch = [first]
            flush_at = time.monotonic() + self.max_delay_s
            while len(batch) < self.max_batch:
                wake = flush_at
                for r in batch:
                    if r.deadline is not None:
                        wake = min(wake, r.deadline + _DEADLINE_SLACK_S)
                remaining = wake - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break        # woke at flush_at or a pending deadline
            self._flush(batch)
        self._drain_closed()

    def _run_continuous(self) -> None:
        """Continuous batching: flush as soon as a lane is free, fill
        bucket slots from new arrivals while every lane is busy."""
        while not self._closed.is_set():
            try:
                first = self._q.get(timeout=_IDLE_POLL_S)
            except queue.Empty:
                continue
            batch = [first]
            self._drain_into(batch)
            self._set_forming(len(batch))
            got_lane = self._lane_sem.acquire(timeout=_LANE_POLL_S)
            while not got_lane and not self._closed.is_set():
                # parked on busy lanes: expire aged requests promptly
                # and keep topping the forming batch up to the bucket
                batch = self._expire(batch)
                self._drain_into(batch)
                self._set_forming(len(batch))
                got_lane = self._lane_sem.acquire(timeout=_LANE_POLL_S)
            self._set_forming(0)
            if not got_lane:        # closing: fail the collected batch
                for r in batch:
                    self._fail_closed(r)
                break
            self._flush(batch)      # the flush resolution frees the lane
        self._drain_closed()

    def _set_forming(self, n: int) -> None:
        with self._forming_lock:
            self._forming = n

    def _drain_into(self, batch: list) -> None:
        """Move whatever is queued RIGHT NOW into ``batch`` (up to the
        top bucket) without waiting — the continuous-mode accumulator."""
        while len(batch) < self.max_batch:
            try:
                batch.append(self._q.get_nowait())
            except queue.Empty:
                return

    def _release_lane(self) -> None:
        if self._lane_sem is not None:
            self._lane_sem.release()

    def _expire(self, batch: list) -> list:
        """Fail (promptly) every request in ``batch`` whose deadline has
        passed; returns the survivors."""
        now = time.monotonic()
        live, expired = [], 0
        for r in batch:
            if r.deadline is not None and r.deadline < now:
                r.future.set_exception(DeadlineExpired(
                    f"deadline exceeded by {self._past_ms(r, now):.1f} ms "
                    "while queued (request was never batched)",
                    retry_after_ms=self.max_delay_s * 1e3))
                expired += 1
            else:
                live.append(r)
        if expired:
            self._m_expired.inc(expired)
        return live

    def _flush(self, batch: list[_Request]) -> None:
        live = self._expire(batch)
        if not live:
            self._release_lane()
            return
        n = len(live)
        try:
            # the whole batch computation is inside the try: a bad
            # payload (mixed row shapes -> np.stack raises) must fail
            # THIS batch's futures, never kill the worker thread — a
            # dead worker would strand every later submit forever
            bucket = self._bucket_for(n)
            rows = pad_rows(np.stack([r.payload for r in live]), bucket)
            if self._run_batch_async is not None:
                # pipelined mode: submit and move on — the pool resolves
                # the batch on its own worker and the completion callback
                # scatters results, so the NEXT batch can flush (to
                # another replica) while this one is still in flight
                t0 = time.monotonic()
                fut = self._run_batch_async(rows)
                fut.add_done_callback(
                    lambda f: self._complete(f, live, bucket, n, t0))
                return
            rec = self._recorder if self._recorder is not None \
                else obs_spans.get_recorder()
            with rec.span("batcher.flush", batcher=self.name,
                          bucket=bucket, rows=n) as flush_span:
                out = np.asarray(self._run_batch(rows))
        except Exception as exc:
            # batch failure -> every caller sees the error (never a hang)
            self._release_lane()
            for r in live:
                r.future.set_exception(exc)
            self._m_batch_errors.inc()
            return
        self._release_lane()
        for i, r in enumerate(live):
            r.future.set_result(out[i])
        self._account_flush(bucket, n, flush_span["dur_ms"])

    def _complete(self, f: Future, live: list[_Request], bucket: int,
                  n: int, t0: float) -> None:
        """Async-flush completion (runs on the pool's worker thread):
        scatter per-row results / the batch error, then the same
        accounting as a synchronous flush.  The timed record is an
        ``event`` with ``dur_ms`` (a span cannot straddle threads)."""
        self._release_lane()            # frees the lane for the NEXT
        try:                            # batch before scattering results
            out = np.asarray(f.result())
        except Exception as exc:
            for r in live:
                r.future.set_exception(exc)
            self._m_batch_errors.inc()
            return
        for i, r in enumerate(live):
            r.future.set_result(out[i])
        dur_ms = round((time.monotonic() - t0) * 1e3, 4)
        rec = self._recorder if self._recorder is not None \
            else obs_spans.get_recorder()
        rec.event("batcher.flush", batcher=self.name, bucket=bucket,
                  rows=n, dur_ms=dur_ms)
        self._account_flush(bucket, n, dur_ms)

    def _account_flush(self, bucket: int, n: int, dur_ms: float) -> None:
        self._m_flushes.inc()
        self._m_occupancy.observe(n)
        with self._children_lock:
            children = self._bucket_children.get(bucket)
        if children is None:
            # insert: flush path only (worker thread, or the pool worker
            # resolving an async flush).  The label resolution happens
            # OUTSIDE the children lock so it never nests over the
            # registry family lock (lock-order hygiene, GL011); a racing
            # double-insert writes the same label children twice, which
            # is idempotent.
            children = (
                self._f_bucket_flushes.labels(batcher=self.name,
                                              bucket=bucket),
                self._f_bucket_rows.labels(batcher=self.name, bucket=bucket))
            with self._children_lock:
                self._bucket_children[bucket] = children
        children[0].inc()
        children[1].inc(n)
        if self._on_flush is not None:
            self._on_flush(dur_ms, n)

    @staticmethod
    def _past_ms(r: _Request, now: float) -> float:
        return max(0.0, (now - r.deadline) * 1000.0) if r.deadline else 0.0

    @staticmethod
    def _fail_closed(r: _Request) -> None:
        from concurrent.futures import InvalidStateError

        try:
            r.future.set_exception(RuntimeError("batcher closed"))
        except InvalidStateError:
            pass                        # the other drainer got it first

    def _drain_closed(self) -> None:
        """Fail (never drop) anything still queued when the batcher
        closes.  Callable from both the exiting worker and a racing
        ``submit`` thread — double-resolution is tolerated."""
        while True:
            try:
                r = self._q.get_nowait()
            except queue.Empty:
                return
            self._fail_closed(r)

    # ---- lifecycle / observability --------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        self._worker.join(timeout)

    def depth(self) -> int:
        """Requests currently queued (approximate — stdlib qsize) plus
        any rows the continuous worker holds in its forming batch.  The
        admission controller's feasibility input (service.py)."""
        with self._forming_lock:
            forming = self._forming
        return self._q.qsize() + forming

    def stats(self) -> dict:
        """Counters + the batch-occupancy histogram (bucket -> how full
        batches ran) — the number that tells you whether max_delay_ms is
        tuned right for the offered load.  Keys are the pre-registry
        ``/healthz`` contract; the values now READ the registry metrics
        (one source of truth — SERVING.md observability section)."""
        occupancy = {}
        with self._children_lock:
            children = sorted(self._bucket_children.items())
        for b, (fc, rc) in children:
            f, rows = int(fc.value), int(rc.value)
            occupancy[str(b)] = {
                "flushes": f, "rows": rows,
                "mean_fill": (rows / (f * b)) if f else 0.0}
        # flushes read BEFORE requests: each read is atomic but the PAIR
        # is only monotonically consistent in this order (a reader
        # preempted between the two reads then sees requests >= the
        # causal floor of the flush count, never flushes > requests)
        flushes = int(self._m_flushes.value)
        return {
            "requests": int(self._m_requests.value),
            "flushes": flushes,
            "deadline_expired": int(self._m_expired.value),
            "batch_errors": int(self._m_batch_errors.value),
            "occupancy": occupancy,
        }
