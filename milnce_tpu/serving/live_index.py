"""Live retrieval index: online ingest with generation-swapped corpus
shards.

``DeviceRetrievalIndex`` (serving/index.py) freezes its corpus at boot —
fine for serving an offline extraction, useless for the paper's end
state, where fresh clips must go live while the service runs.  This
module is the double-buffered twin:

- **ingest** (:meth:`LiveRetrievalIndex.add`) appends embedding rows to
  a host-side pending buffer and returns immediately — no device work,
  no lock shared with the query path beyond a pointer read;
- a **background builder thread** drains the buffer, concatenates the
  grown corpus on host, pads/shards it on-device under the dispatch
  discipline (``DEVICE_DISPATCH_LOCK`` + ``transfer_guard``), then
  performs an **atomic generation swap** — one reference assignment
  under ``_state_lock``.  Queries capture the generation reference once
  per call, so every query is answered by exactly ONE generation (old
  or new, never a torn mix), and the old generation's arrays are freed
  by GC once the last in-flight query drops them;
- **zero recompiles across swaps**: per-shard row capacity rides the
  same power-of-two rung rule as the engine's bucket ladder
  (:func:`shard_rung`), so a swap re-uses the compiled top-k executable
  until the corpus actually outgrows its rung.  Crossing a rung is a
  BUILDER event: the new shape is compiled and warmed on the builder
  thread *before* the swap publishes, and the recompile baseline is
  re-snapshotted there — the query path never compiles
  (:meth:`recompiles` stays 0; ``builder_compiles`` counts the
  boot-equivalent rung compiles honestly).

Failure discipline (ROBUSTNESS.md "Live index"): a build/swap failure
(the ``index.swap_raise`` fault site fires just before publication)
leaves the OLD generation serving, re-queues the drained rows at the
front of the pending buffer (ingest order preserved, nothing lost), and
the builder thread survives to retry — first on the next ingest/flush
signal, else on a bounded idle backoff.  ``index.ingest_hang`` wedges
an ``add`` caller without touching the query path.

Snapshot/restore ties into the ``milnce-export`` artifact family
(serving/export.py): :meth:`snapshot` writes the live generation's
corpus as ``corpus.npz`` (the exact array ``--serve.corpus_npz``
accepts) + ``index_meta.json``; :meth:`restore` boots a new index from
one, generation counter preserved — the round trip is bit-exact.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

import numpy as np

import jax

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.obs import spans as obs_spans
from milnce_tpu.parallel.mesh import batch_sharding, replicated
from milnce_tpu.resilience import faults
from milnce_tpu.serving.batcher import pad_rows
from milnce_tpu.serving.engine import DEVICE_DISPATCH_LOCK
from milnce_tpu.serving.export import (export_corpus_snapshot,
                                       load_corpus_snapshot)
from milnce_tpu.serving.index import make_topk_fn, shard_corpus

# Builder idle poll (bounds close() latency) and the backoff before a
# FAILED build is retried without a fresh ingest/flush signal.
_IDLE_POLL_S = 0.05
_RETRY_BACKOFF_S = 0.25


def shard_rung(size: int, n_data: int, k: int, floor: int = 0) -> int:
    """Per-shard row capacity for a ``size``-row corpus: the smallest
    power of two >= max(ceil(size / n_data), k, floor, 1).

    The serving twin of ``engine.bucket_ladder``'s rung rule: corpus
    growth within a rung swaps generations at IDENTICAL padded shapes
    (same executable, zero recompiles); only crossing a rung — a
    doubling, so O(log corpus) times ever — builds a new shape."""
    need = max(-(-size // n_data) if size else 1, k, int(floor), 1)
    rung = 1
    while rung < need:
        rung *= 2
    return rung


def recommended_min_shard_rows(corpus_rows: int, n_data: int,
                               headroom: int = 2) -> int:
    """``--serve.index_min_shard_rows`` sizing rule for a corpus that is
    expected to GROW to ~``corpus_rows``: the rung that fits
    ``headroom`` x the per-device share, so ingest reaches the target
    size (and then some) without ever crossing a rung — zero index
    recompiles over the corpus's whole planned life.

    HowTo100M scale: ~1.2M videos over an 8-way data axis with the
    default 2x headroom lands on 524288 (= 2**19) rows/shard — 4M rows
    of pre-provisioned capacity, ~2 GiB/device of f32 corpus at
    D=512."""
    if corpus_rows <= 0:
        raise ValueError("corpus_rows must be positive")
    if n_data <= 0:
        raise ValueError("n_data must be positive")
    if headroom < 1:
        raise ValueError("headroom must be >= 1")
    return shard_rung(int(corpus_rows) * int(headroom), n_data, 1)


class _Generation:
    """One immutable published corpus generation.  Everything here is
    written once by the builder (or ``__init__``) before publication and
    only ever read afterwards — the atomic-swap contract."""

    __slots__ = ("gen", "host", "size", "rows", "corpus", "valid",
                 "built_mono")

    def __init__(self, gen: int, host: np.ndarray, rows: int,
                 corpus, valid):
        self.gen = int(gen)
        self.host = host                 # (size, D) f32 — snapshot/rebuild
        self.size = int(host.shape[0])
        self.rows = int(rows)            # per-shard capacity (the rung)
        self.corpus = corpus             # device, (rows * n_data, D)
        self.valid = valid               # device, (n_data,) int32
        self.built_mono = time.monotonic()


class LiveRetrievalIndex:
    """Generation-swapped sharded corpus + fixed-k jitted top-k.

    Query surface is a superset of :class:`DeviceRetrievalIndex`
    (``topk`` / ``bucket_for`` / ``stats`` / ``recompiles`` /
    ``topk_program`` / ``query_sharding``), plus the live surface:
    ``add`` / ``flush`` / ``topk_with_gen`` / ``snapshot`` /
    ``restore``.  ``embeddings=None`` boots an EMPTY index (``dim``
    required); queries refuse until the corpus holds at least ``k``
    rows, but ingest works from the first second.
    """

    def __init__(self, mesh, embeddings: Optional[np.ndarray] = None, *,
                 k: int = 10, query_buckets: Sequence[int] = (8,),
                 data_axis: str = "data", dim: Optional[int] = None,
                 min_shard_rows: int = 0, generation: int = 0,
                 precompile: bool = True,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 recorder: Optional[obs_spans.SpanRecorder] = None):
        if embeddings is None:
            if dim is None:
                raise ValueError("an empty live index needs dim= (the "
                                 "embedding width ingest rows will have)")
            emb = np.zeros((0, int(dim)), np.float32)
        else:
            emb = np.ascontiguousarray(embeddings, dtype=np.float32)
            if emb.ndim != 2:
                raise ValueError(f"expected (N, D) embeddings, "
                                 f"got {emb.shape}")
        self.dim = int(emb.shape[1])
        self.k = int(k)
        if self.k < 1:
            raise ValueError(f"k={k} < 1")
        self.query_buckets = tuple(sorted(int(b) for b in query_buckets))
        self.data_axis = data_axis
        # geometry follows the DATA axis extent (index.py's 2-D mesh
        # rule: P(data) shards rows over data, replicates over model)
        self._n_data = int(mesh.shape[data_axis])
        self._min_shard_rows = int(min_shard_rows)
        self._query_sh = replicated(mesh)
        self._corpus_sh = batch_sharding(mesh, data_axis)
        self._fn = make_topk_fn(mesh, data_axis, self.k)
        self._recorder = recorder
        reg = registry if registry is not None \
            else obs_metrics.MetricsRegistry()
        self._m_ingested = reg.counter(
            "milnce_serve_index_ingested_rows_total",
            "embedding rows accepted into the live-index pending buffer")
        self._m_swaps = reg.counter(
            "milnce_serve_index_swaps_total",
            "generation swaps published (the corpus grew atomically)")
        self._m_swap_failures = reg.counter(
            "milnce_serve_index_swap_failures_total",
            "builds/swaps that failed (old generation kept serving, "
            "rows re-queued)")
        self._m_builder_compiles = reg.counter(
            "milnce_serve_index_builder_compiles_total",
            "rung-crossing compiles performed on the builder thread "
            "(boot-equivalent; the query path never compiles)")
        reg.gauge("milnce_serve_index_generation",
                  "live-index generation counter",
                  fn=lambda: float(self.stats()["generation"]))
        reg.gauge("milnce_serve_index_pending_rows",
                  "ingested rows not yet swapped live",
                  fn=lambda: float(self.stats()["pending_rows"]))
        reg.gauge("milnce_serve_index_last_swap_age_seconds",
                  "seconds since the last generation swap",
                  fn=lambda: float(self.stats()["last_swap_age_s"]))
        # One lock for all mutable host state: generation pointer,
        # pending buffer, call/compile accounting.  NEVER held across
        # device work, sleeps, or metric calls — the builder and the
        # query path each take it for pointer/bookkeeping flips only.
        self._state_lock = make_lock("serving.live_index.state")
        self._pending: list[np.ndarray] = []   # guarded-by: _state_lock
        self._pending_rows = 0                 # guarded-by: _state_lock
        self._ingested_total = 0               # guarded-by: _state_lock
        self._calls = 0                        # guarded-by: _state_lock
        self._baseline_cache = None            # guarded-by: _state_lock
        self._swaps = 0                        # guarded-by: _state_lock
        self._swap_failures = 0                # guarded-by: _state_lock
        self._last_attempt = 0.0               # guarded-by: _state_lock
        self._warmed_rungs: set = set()        # guarded-by: _state_lock
        self._warming_recompiles = None        # guarded-by: _state_lock
        # the published generation: written only under _state_lock (one
        # reference assignment — the atomic swap); readers take the lock
        # for the pointer read and hold the REFERENCE, not the lock,
        # through device work
        self._gen = self._make_generation(     # guarded-by: _state_lock
            int(generation), emb)
        self._boot_size = self._gen.size
        self._work = threading.Event()
        self._closed = threading.Event()
        self._builder = threading.Thread(target=self._builder_loop,
                                         daemon=True,
                                         name="live-index-builder")
        if precompile:
            self.warmup()
        self._builder.start()

    # ---- geometry / program construction ---------------------------------

    def _make_generation(self, gen: int, host: np.ndarray) -> _Generation:
        """Pad + shard ``host`` onto the devices at its rung.  Device
        transfers run under the dispatch discipline — the same lock and
        transfer guard as every other serving device interaction."""
        rows = shard_rung(host.shape[0], self._n_data, self.k,
                          self._min_shard_rows)
        corpus, valid = shard_corpus(host, self._n_data, rows)
        with DEVICE_DISPATCH_LOCK, jax.transfer_guard("disallow"):
            corpus_d = jax.device_put(corpus, self._corpus_sh)
            valid_d = jax.device_put(valid, self._corpus_sh)
        return _Generation(gen, host, rows, corpus_d, valid_d)

    def _dispatch(self, g: _Generation, q_padded: np.ndarray):
        with DEVICE_DISPATCH_LOCK, jax.transfer_guard("disallow"):
            qd = jax.device_put(q_padded, self._query_sh)
            scores, idx = jax.device_get(self._fn(g.corpus, g.valid, qd))
        return np.asarray(scores), np.asarray(idx)

    def _warm_rung(self, g: _Generation) -> None:
        """Compile + execute the top-k program for every query bucket at
        ``g``'s shape, then re-snapshot the recompile baseline: rung
        compiles are boot-equivalent builder work, never a query-path
        recompile (they are counted separately for honesty).

        While the warm is in flight the jit cache grows BEFORE the
        baseline catches up, so :meth:`recompiles` answers with the
        pre-warm value for the duration — a /healthz poll landing inside
        a multi-second rung compile must not read the builder's own
        compiles as query-path recompiles."""
        with self._state_lock:
            warmed = g.rows in self._warmed_rungs
        if warmed:
            return
        pre = self.recompiles()
        with self._state_lock:
            self._warming_recompiles = pre
        try:
            for b in self.query_buckets:
                self._dispatch(g, np.zeros((b, self.dim), np.float32))
            self._m_builder_compiles.inc()
            size = getattr(self._fn, "_cache_size", None)
            baseline = int(size()) if size is not None else None
            with self._state_lock:
                self._warmed_rungs.add(g.rows)
                self._baseline_cache = baseline
        finally:
            with self._state_lock:
                self._warming_recompiles = None

    # ---- query path ------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        for b in self.query_buckets:
            if n <= b:
                return b
        raise ValueError(f"{n} queries exceeds the top query bucket "
                         f"{self.query_buckets[-1]}")

    def topk_with_gen(self, queries: np.ndarray
                      ) -> tuple[np.ndarray, np.ndarray, int]:
        """(n, D) query embeddings -> ((n, k) scores, (n, k) corpus row
        indices, generation).  The generation reference is captured ONCE
        — a swap completing mid-query cannot tear the answer, and the
        returned generation is exactly the corpus the ranking is over."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(f"expected (n, {self.dim}) queries, "
                             f"got {q.shape}")
        with self._state_lock:
            g = self._gen
        if g.size < self.k:
            raise ValueError(f"corpus holds {g.size} rows < k={self.k} — "
                             "ingest more before querying")
        n = q.shape[0]
        scores, idx = self._dispatch(g, pad_rows(q, self.bucket_for(n)))
        with self._state_lock:
            self._calls += 1
        return scores[:n], idx[:n], g.gen

    def topk(self, queries: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """DeviceRetrievalIndex-compatible surface (no generation)."""
        scores, idx, _ = self.topk_with_gen(queries)
        return scores, idx

    def topk_program(self) -> tuple:
        """``(jitted_fn, (corpus, valid))`` of the LIVE generation — the
        analysis surface (trace invariants, Pass 4 planner), same
        contract as ``DeviceRetrievalIndex.topk_program``."""
        with self._state_lock:
            g = self._gen
        return self._fn, (g.corpus, g.valid)

    @property
    def query_sharding(self):
        return self._query_sh

    @property
    def size(self) -> int:
        """LIVE corpus rows (pending ingest not yet included)."""
        with self._state_lock:
            return self._gen.size

    @property
    def generation(self) -> int:
        with self._state_lock:
            return self._gen.gen

    # ---- ingest path -----------------------------------------------------

    def add(self, embeddings: np.ndarray) -> dict:
        """Queue (n, D) embedding rows for the next generation; returns
        ``{"pending_rows", "generation", "target_rows"}`` where
        ``target_rows`` is the corpus size once everything queued so far
        is live (the :meth:`flush` wait target).  Host-only — the
        builder does the device work."""
        rows = np.ascontiguousarray(embeddings, dtype=np.float32)
        if rows.ndim != 2 or rows.shape[1] != self.dim:
            raise ValueError(f"expected (n, {self.dim}) embeddings, "
                             f"got {rows.shape}")
        if rows.shape[0] < 1:
            raise ValueError("empty ingest batch")
        if self._closed.is_set():
            raise RuntimeError("live index is closed")
        # fault site: a wedged ingest caller (slow storage, stuck embed
        # upstream) — must never touch the query path's locks
        faults.maybe_hang("index.ingest_hang")
        n = rows.shape[0]
        with self._state_lock:
            self._pending.append(rows)
            self._pending_rows += n
            self._ingested_total += n
            out = {"pending_rows": self._pending_rows,
                   "generation": self._gen.gen,
                   "target_rows": self._boot_size + self._ingested_total}
        self._m_ingested.inc(n)
        self._work.set()
        return out

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until every row ingested BEFORE this call is live (a
        generation containing them has been published), or ``timeout``
        expires — False means rows are still pending (e.g. the builder
        is riding out injected swap failures), never an exception."""
        with self._state_lock:
            target = self._boot_size + self._ingested_total
        self._work.set()
        deadline = time.monotonic() + timeout  # graftlint: disable=GL005(host-side timeout bookkeeping for the flush wait loop — deliberately wall time, not a device-timing delta; nothing here is dispatched)
        while time.monotonic() < deadline:
            with self._state_lock:
                live = self._gen.size
            if live >= target:
                return True
            if self._closed.is_set():
                return False
            time.sleep(0.005)
        return False

    # ---- builder thread --------------------------------------------------

    def _builder_loop(self) -> None:
        while not self._closed.is_set():
            signaled = self._work.wait(timeout=_IDLE_POLL_S)
            if self._closed.is_set():
                return
            if signaled:
                self._work.clear()
            else:
                # idle tick: retry a previously-failed build, backed off
                with self._state_lock:
                    retry = (self._pending_rows > 0 and
                             time.monotonic() - self._last_attempt
                             > _RETRY_BACKOFF_S)
                if not retry:
                    continue
            self._build_once()

    def _build_once(self) -> None:
        with self._state_lock:
            if not self._pending:
                return
            pending, self._pending = self._pending, []
            moved = self._pending_rows
            self._pending_rows = 0
            base = self._gen
            self._last_attempt = time.monotonic()
        rec = self._recorder if self._recorder is not None \
            else obs_spans.get_recorder()
        try:
            with rec.span("index.build", rows=moved, base_gen=base.gen):
                host = np.concatenate([base.host] + pending) \
                    if base.size else np.concatenate(pending)
                g = self._make_generation(base.gen + 1, host)
                self._warm_rung(g)
                # fault site: the publication step itself fails (a bad
                # device transfer, poisoned executable) — must leave the
                # old generation serving and the builder alive
                faults.maybe_raise("index.swap_raise")
                with self._state_lock:
                    self._gen = g                 # THE atomic swap
                    self._swaps += 1
            self._m_swaps.inc()
            rec.event("index.swap", generation=g.gen, size=g.size,
                      shard_rows=g.rows)
        except Exception as exc:
            # failed build/swap: re-queue the drained rows at the FRONT
            # (ingest order preserved for the retry); the old generation
            # keeps serving and this thread keeps running
            with self._state_lock:
                self._pending = pending + self._pending
                self._pending_rows += moved
                self._swap_failures += 1
            self._m_swap_failures.inc()
            rec.event("index.swap_fail", base_gen=base.gen, rows=moved,
                      error=type(exc).__name__)

    # ---- warmup + recompile accounting -----------------------------------

    def warmup(self) -> None:
        with self._state_lock:
            g = self._gen
        self._warm_rung(g)

    def recompiles(self) -> int:
        """Query-path jit-cache growth since the last builder/boot
        warmup — 0 in a healthy steady state ACROSS generation swaps
        (rung compiles re-baseline on the builder thread and count on
        ``builder_compiles`` instead).  -1 without cache introspection.
        While a rung warm is in flight, answers the pre-warm value (the
        builder's boot-equivalent compiles are not query recompiles)."""
        with self._state_lock:
            if self._warming_recompiles is not None:
                return self._warming_recompiles
            baseline = self._baseline_cache
        if baseline is None:
            return -1
        size = getattr(self._fn, "_cache_size", None)
        if size is None:
            return -1
        return max(0, int(size()) - baseline)

    # ---- snapshot / restore ----------------------------------------------

    def snapshot(self, out_dir: str) -> str:
        """Write the LIVE generation's corpus as a ``milnce-export``
        family artifact (corpus.npz + index_meta.json).  Pending ingest
        rows are not included — :meth:`flush` first to capture them."""
        with self._state_lock:
            g = self._gen
        return export_corpus_snapshot(out_dir, g.host, generation=g.gen,
                                      k=self.k, source="live_index")

    @classmethod
    def restore(cls, snap_dir: str, mesh, **kwargs) -> "LiveRetrievalIndex":
        """Boot a live index from a :meth:`snapshot` directory —
        generation counter preserved, corpus bit-exact."""
        meta, emb = load_corpus_snapshot(snap_dir)
        kwargs.setdefault("k", meta["k"])
        kwargs.setdefault("generation", meta["generation"])
        return cls(mesh, emb, **kwargs)

    # ---- lifecycle / observability ---------------------------------------

    def close(self, timeout: float = 5.0) -> None:
        self._closed.set()
        self._work.set()
        self._builder.join(timeout)

    def stats(self) -> dict:
        """Superset of ``DeviceRetrievalIndex.stats()`` — every frozen
        key byte-compatible, the live keys additive (the ``/healthz``
        ``index`` section contract)."""
        now = time.monotonic()
        with self._state_lock:
            g = self._gen
            out = {
                "size": g.size, "dim": self.dim, "k": self.k,
                "query_buckets": list(self.query_buckets),
                "calls": self._calls,
                "generation": g.gen,
                "pending_rows": self._pending_rows,
                "ingested_rows": self._ingested_total,
                "swaps": self._swaps,
                "swap_failures": self._swap_failures,
                "shard_rows": g.rows,
                "capacity": g.rows * self._n_data,
                "last_swap_age_s": round(now - g.built_mono, 3),
            }
        out["recompiles"] = self.recompiles()
        out["builder_alive"] = self._builder.is_alive()
        return out
