"""Device-resident retrieval index: corpus embeddings sharded over the
mesh data axis, jitted dot-product + ``lax.top_k`` retrieval.

Offline eval materializes the full T x V similarity matrix on host
(eval/retrieval.py) — fine for a 1k-video benchmark, hopeless for a
served corpus: at production scale the corpus embedding table is the
largest tensor in the system and must live ON the devices, sharded,
with only (Q, k) winners ever crossing back to host.

The retrieval program (one jitted shard_map, fixed shapes, pinned
collectives — see the ``serve_index_topk`` trace invariant):

1. each shard scores the replicated query block against its local
   corpus rows (one (Q, R_local) matmul — MXU work, embarrassingly
   parallel);
2. pad rows are masked to -inf and each shard takes a LOCAL top-k,
   shifting to global row indices via ``axis_index`` — this is the
   communication win: per shard only (Q, k) survives, not (Q, R_local);
3. the per-shard candidate lists ride ONE all_gather each for scores
   and indices (2 total, pinned), and a final top-k over the
   ``n_dev * k`` candidates is exact — every true global winner is
   necessarily some shard's local winner.

Query batches are padded to a fixed bucket ladder exactly like the
embed entries (pad queries produce garbage rows that are dropped on
unpad; they never affect real rows), so the whole serve path —
embed + retrieve — runs zero recompiles after boot.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.parallel.compat import shard_map
from milnce_tpu.parallel.mesh import batch_sharding, replicated
from milnce_tpu.serving.batcher import pad_rows
from milnce_tpu.serving.engine import DEVICE_DISPATCH_LOCK


def make_topk_fn(mesh: Mesh, data_axis: str, k: int):
    """The jitted sharded top-k program (the ``serve_index_topk`` trace
    invariant's subject): each data shard scores the replicated query
    block against its local corpus rows, takes a LOCAL top-k, and the
    per-shard (Q, k) candidate lists ride ONE all_gather each for scores
    and indices before an exact global top-k.  Shared by the frozen
    :class:`DeviceRetrievalIndex` and the generation-swapped
    :class:`~milnce_tpu.serving.live_index.LiveRetrievalIndex` — one
    program, one set of pinned collectives, however the corpus is
    managed."""

    def local_topk(corpus_l, valid_l, queries):
        scores = queries @ corpus_l.T                    # (Q, R_local)
        col = lax.iota(jnp.int32, corpus_l.shape[0])
        scores = jnp.where(col[None, :] < valid_l[0], scores, -jnp.inf)
        s, i = lax.top_k(scores, k)                      # local winners
        gidx = i + lax.axis_index(data_axis) * corpus_l.shape[0]
        s_all = lax.all_gather(s, data_axis, axis=1, tiled=True)
        i_all = lax.all_gather(gidx, data_axis, axis=1, tiled=True)
        s_top, j = lax.top_k(s_all, k)                   # exact global
        return s_top, jnp.take_along_axis(i_all, j, axis=1)

    return jax.jit(shard_map(
        local_topk, mesh=mesh,
        in_specs=(P(data_axis), P(data_axis), P()),
        out_specs=(P(), P()), check_vma=False))


def shard_corpus(emb: np.ndarray, n_data: int, rows: int
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Pad ``(size, D)`` embeddings to ``rows`` rows per data shard ->
    (``(rows * n_data, D)`` padded corpus, ``(n_data,)`` int32 per-shard
    valid-row counts).  Pad rows are zeros and masked to -inf inside the
    top-k program, so they can never be retrieved."""
    size, dim = emb.shape
    corpus = np.zeros((rows * n_data, dim), np.float32)
    corpus[:size] = emb
    valid = np.asarray(
        [max(0, min(size, (s + 1) * rows) - s * rows)
         for s in range(n_data)], np.int32)
    return corpus, valid


class DeviceRetrievalIndex:
    """Immutable sharded corpus + fixed-k jitted top-k retrieval.

    - ``embeddings``: (N, D) float32 video-corpus embeddings (built from
      ``InferenceEngine.embed_video`` or an offline extraction);
    - ``k``: retrieval depth, static in the traced program;
    - ``query_buckets``: the query-batch ladder to pre-trace (share the
      engine's so batcher output feeds straight through).
    """

    def __init__(self, mesh: Mesh, embeddings: np.ndarray, *, k: int = 10,
                 query_buckets: Sequence[int] = (8,), data_axis: str = "data",
                 precompile: bool = True):
        emb = np.ascontiguousarray(embeddings, dtype=np.float32)
        if emb.ndim != 2:
            raise ValueError(f"expected (N, D) embeddings, got {emb.shape}")
        self.size, self.dim = emb.shape
        self.k = int(k)
        if not 1 <= self.k <= self.size:
            raise ValueError(f"k={k} outside [1, corpus size {self.size}]")
        self.query_buckets = tuple(sorted(int(b) for b in query_buckets))
        self.data_axis = data_axis
        # geometry follows the DATA axis extent, not the total device
        # count: P(data) shards rows over data and replicates over any
        # model axis, so each data shard holds rows (not rows/model) —
        # sizing by the product would mis-mask most of the corpus on a
        # (data, model) mesh
        n_data = int(mesh.shape[data_axis])

        # Pad the corpus so rows split evenly AND every shard holds at
        # least k rows (lax.top_k needs k <= local extent).
        rows = max(-(-self.size // n_data), self.k)
        corpus, valid = shard_corpus(emb, n_data, rows)

        sh_rows = batch_sharding(mesh, data_axis)
        self._corpus = jax.device_put(corpus, sh_rows)       # device-resident
        self._valid = jax.device_put(valid, sh_rows)
        self._query_sh = replicated(mesh)
        self._fn = make_topk_fn(mesh, data_axis, self.k)
        # call accounting is hit straight off concurrent request threads
        # — its own lock, never the dispatch lock (graftlint GL010: the
        # bare `_calls += 1` here lost increments under contention)
        self._stats_lock = make_lock("serving.index.stats")
        self._calls = 0
        self._baseline_cache = None
        if precompile:
            self.warmup()

    # ---- query path ------------------------------------------------------

    def bucket_for(self, n: int) -> int:
        for b in self.query_buckets:
            if n <= b:
                return b
        raise ValueError(f"{n} queries exceeds the top query bucket "
                         f"{self.query_buckets[-1]}")

    def topk(self, queries: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(n, D) query embeddings -> ((n, k) scores, (n, k) corpus row
        indices), ranked best-first.  Ties broken by lower index, the
        same order ``np.argsort(-sim)`` yields on distinct scores."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        if q.ndim != 2 or q.shape[1] != self.dim:
            raise ValueError(f"expected (n, {self.dim}) queries, got "
                             f"{q.shape}")
        n = q.shape[0]
        q = pad_rows(q, self.bucket_for(n))
        # serialized dispatch: see DEVICE_DISPATCH_LOCK in engine.py —
        # index queries come straight off request threads
        with DEVICE_DISPATCH_LOCK, jax.transfer_guard("disallow"):
            qd = jax.device_put(q, self._query_sh)
            scores, idx = jax.device_get(self._fn(self._corpus, self._valid,
                                                  qd))
        with self._stats_lock:
            self._calls += 1
        return np.asarray(scores)[:n], np.asarray(idx)[:n]

    def topk_program(self) -> tuple:
        """``(jitted_fn, (corpus, valid))`` — the compiled retrieval
        program plus its committed operand arrays, the supported surface
        for the analysis passes (trace invariants pin its collectives,
        the Pass 4 planner walks its jaxpr) instead of reaching into
        ``_fn``/``_corpus``/``_valid``.  Callers append a query batch
        committed to :attr:`query_sharding`."""
        return self._fn, (self._corpus, self._valid)

    @property
    def query_sharding(self):
        """The replicated sharding query batches must be committed to
        before calling the program from :meth:`topk_program` directly
        (an uncommitted host array would key a separate jit-cache
        entry)."""
        return self._query_sh

    # ---- warmup + observability -----------------------------------------

    def warmup(self) -> None:
        for b in self.query_buckets:
            self.topk(np.zeros((b, self.dim), np.float32))
        size = getattr(self._fn, "_cache_size", None)
        baseline = int(size()) if size is not None else None
        with self._stats_lock:
            self._baseline_cache = baseline

    def recompiles(self) -> int:
        with self._stats_lock:
            baseline = self._baseline_cache
        if baseline is None:
            return -1
        size = getattr(self._fn, "_cache_size", None)
        if size is None:
            return -1
        return max(0, int(size()) - baseline)

    def stats(self) -> dict:
        with self._stats_lock:
            calls = self._calls
        return {"size": self.size, "dim": self.dim, "k": self.k,
                "query_buckets": list(self.query_buckets),
                "calls": calls, "recompiles": self.recompiles()}
