"""Frozen-param inference engine: pre-traced bucket ladder, transfer-
guarded steady state.

What JAX/XLA rewards at serve time is exactly what Neodragon and
On-device Sora (PAPERS.md) report for video-model serving: fixed-shape
pre-traced execution and aggressive reuse — never a runtime recompile,
never an accidental host round-trip.  This engine packages the repo's
existing embed towers (train/step.py ``make_text_embed_fn`` /
``make_video_embed_fn`` — the same jitted shard_map programs offline
eval uses, so served numbers ARE eval numbers) behind that discipline:

- **bucket ladder**: batch entries exist only at a power-of-two ladder
  of batch sizes (each a multiple of the mesh's data-axis extent, so
  every bucket shards).  Requests are padded UP to the smallest bucket
  that fits; the jit cache therefore holds exactly
  ``len(buckets) x 2`` executables forever.
- **pre-trace at startup**: every (entry, bucket) pair is compiled and
  executed once in ``__init__`` — first-request latency is steady-state
  latency, and a compile storm can only happen where it belongs: at
  boot, visibly.
- **steady state under ``jax.transfer_guard("disallow")``**: inputs go
  up via explicit ``device_put`` against the batch sharding, results
  come back via explicit ``device_get``; anything else — a smuggled
  implicit H2D in a future edit — raises instead of silently stalling
  the dispatch pipeline (same contract as the train loop,
  tests/test_transfer_guard.py).
- **recompile accounting**: jit cache sizes are snapshotted after the
  warmup sweep; :meth:`recompiles` must stay 0 for the life of the
  process (pinned by the ``serve_embed_ladder`` trace invariant and
  surfaced by the service health endpoint).

Frozen params: the engine holds ``{'params', 'batch_stats'}`` only (no
optimizer state — see serving/export.py), replicated onto the mesh once
at construction, optionally cast to bf16 for MXU-rate inference.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import spans as obs_spans
from milnce_tpu.parallel.mesh import batch_sharding, replicated
from milnce_tpu.resilience import faults
from milnce_tpu.serving.batcher import pad_rows
from milnce_tpu.train.step import make_text_embed_fn, make_video_embed_fn


class ReplicaDead(RuntimeError):
    """The engine has been force-killed (``serve.replica_dead`` fault or
    :meth:`InferenceEngine.kill`) — every dispatch fails instantly until
    the process restarts.  The replica pool treats this as a permanent
    condition: the replica quarantines and its probes keep failing."""

# One device-dispatch queue per process, shared by every serving
# component that executes on the mesh (engine entries AND index.topk).
# Two reasons, one per backend: the multi-device XLA:CPU client
# DEADLOCKS when multi-device executions + transfers are issued
# concurrently from several host threads (observed: N request threads
# wedged in device_get while the batcher worker wedges in execute); and
# on TPU, concurrent host threads racing enqueues just interleave into
# the single per-device execution queue anyway — serialized dispatch is
# the semantics the hardware gives you, made explicit and deadlock-free.
# Request-level concurrency belongs ABOVE this lock, in the batcher.
# Created through make_lock so MILNCE_LOCK_SANITIZE=1 (set before
# import) swaps in the order-checking SanitizedLock; the "dispatch" in
# its name is what exempts device work under it from graftlint GL012.
DEVICE_DISPATCH_LOCK = make_lock("serving.device_dispatch")


def bucket_ladder(n_dev: int, min_bucket: int, max_batch: int) -> tuple:
    """Power-of-two batch buckets, each divisible by the mesh size.

    Starts at the smallest power of two >= max(min_bucket, n_dev) and
    doubles up to ``max_batch`` inclusive.  On a power-of-two mesh (the
    only kind this repo runs) every rung then shards evenly."""
    start = max(int(min_bucket) or n_dev, n_dev)
    b = 1
    while b < start:
        b *= 2
    if b % n_dev:
        raise ValueError(
            f"bucket {b} is not divisible by the {n_dev}-way data axis — "
            "pick min_bucket as a multiple of the mesh size")
    if b > max_batch:
        raise ValueError(f"max_batch={max_batch} is below the smallest "
                         f"shardable bucket {b} on a {n_dev}-device mesh")
    out = []
    while b <= max_batch:
        out.append(b)
        b *= 2
    return tuple(out)


def cast_floats(tree, dtype):
    """Cast floating leaves of a pytree (params/batch_stats) to ``dtype``;
    integer leaves (e.g. embedding ids baked into stats) pass through."""
    dt = jnp.dtype(dtype)

    def cast(x):
        x = jnp.asarray(x)
        return x.astype(dt) if jnp.issubdtype(x.dtype, jnp.floating) else x

    return jax.tree_util.tree_map(cast, tree)


def load_serving_model(export_dir: str, dtype: str = ""):
    """Load any ``milnce-export``-family artifact -> ``(model,
    variables, metadata)`` ready for an :class:`InferenceEngine`.

    Format detection is metadata-driven: a quantized edge-tier
    artifact (export.QUANT_FORMAT_VERSION) loads through
    ``load_quantized_checkpoint`` and returns a
    :class:`~milnce_tpu.quant.quantize.QuantizedModel` wrapper — int8
    weights resident, dequantize inside the jitted entries, f32
    accumulation.  ``dtype`` overrides are refused for quantized
    artifacts (the stored precision IS the artifact's contract)."""
    from milnce_tpu.config import ModelConfig
    from milnce_tpu.models.build import build_model
    from milnce_tpu.serving.export import (QUANT_FORMAT_VERSION,
                                           load_inference_checkpoint,
                                           load_quantized_checkpoint,
                                           read_export_metadata)

    quantized = (read_export_metadata(export_dir).get("format_version")
                 == QUANT_FORMAT_VERSION)
    if quantized:
        if dtype:
            raise ValueError(
                "dtype override is not supported for quantized exports "
                "— int8 weights + f32 scales are the artifact's "
                "precision contract")
        meta, variables = load_quantized_checkpoint(export_dir)
    else:
        meta, variables = load_inference_checkpoint(export_dir)
    model_cfg = ModelConfig(**meta["model"])
    if dtype:
        model_cfg.dtype = dtype
    model = build_model(model_cfg)
    if quantized:
        from milnce_tpu.quant.quantize import QuantizedModel

        model = QuantizedModel(model)
    return model, variables, meta


class InferenceEngine:
    """Bucketed, pre-traced, transfer-guarded embed entries over frozen
    params.

    - ``variables``: ``{'params': ..., 'batch_stats': ...}`` (params-only
      inference checkpoint — serving/export.py round-trips one).
    - ``text_words`` / ``video_shape``: the fixed per-row input shapes
      ((W,) token ids / (T, H, W, 3) uint8 frames) the entries are traced
      at; requests with any other trailing shape are rejected, they would
      otherwise silently compile a new program.
    - ``cast_dtype``: optional float dtype ('bfloat16') the frozen params
      are cast to at load — the model itself must be built with the
      matching compute dtype (``InferenceEngine.from_export`` wires both).
    - ``dispatch_lock``: the lock serializing this engine's device work.
      Default is the process-wide :data:`DEVICE_DISPATCH_LOCK`; the
      replica pool (serving/pool.py) passes each replica its OWN lock so
      one wedged replica cannot stall the others' dispatch queues (the
      lock's name must contain "dispatch" — the GL012 exemption).
    """

    def __init__(self, model, variables, mesh: Mesh, *, text_words: int,
                 video_shape: Sequence[int], max_batch: int = 64,
                 min_bucket: int = 0, data_axis: str = "data",
                 cast_dtype: Optional[str] = None, precompile: bool = True,
                 dispatch_lock=None):
        self.mesh = mesh
        self.data_axis = data_axis
        self._dispatch_lock = (dispatch_lock if dispatch_lock is not None
                               else DEVICE_DISPATCH_LOCK)
        # batch divisibility is governed by the DATA axis extent alone:
        # on a (data, model) mesh the embed programs shard rows over
        # data and replicate over model (P(data) in/out specs)
        n_dev = int(mesh.shape[data_axis])
        self.buckets = bucket_ladder(n_dev, min_bucket, max_batch)
        self.max_batch = self.buckets[-1]
        self.text_words = int(text_words)
        self.video_shape = tuple(int(d) for d in video_shape)
        if cast_dtype:
            variables = cast_floats(variables, cast_dtype)
        # one explicit replication at boot; steady state never moves params
        self._variables = jax.device_put(variables, replicated(mesh))
        self._batch_sh = batch_sharding(mesh, data_axis)
        self._text_fn = make_text_embed_fn(model, mesh, data_axis)
        self._video_fn = make_video_embed_fn(model, mesh, data_axis)
        # Bookkeeping shared by the batcher worker, request threads
        # (video/index paths) and /healthz readers — guarded by its own
        # tiny lock, NEVER the dispatch lock (stats reads must not
        # contend with device work).  The unlocked dict update here was
        # a real lost-increment race (graftlint GL010, ISSUE 7).
        self._stats_lock = make_lock("serving.engine.stats")
        self._calls: dict[tuple, int] = {}     # (entry, bucket) -> calls
        self._baseline_cache: Optional[dict] = None
        self.embed_dim: Optional[int] = None   # known after the first call
        self._dead = False                     # guarded-by: _stats_lock
        if precompile:
            self.warmup()

    # ---- bucket ladder ---------------------------------------------------

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that fits ``n`` rows."""
        if n < 1:
            raise ValueError(f"batch of {n} rows")
        for b in self.buckets:
            if n <= b:
                return b
        raise ValueError(f"{n} rows exceeds max_batch={self.max_batch} "
                         "(split upstream, or rebuild with a taller ladder)")

    # ---- entries ---------------------------------------------------------

    def embed_text(self, token_ids: np.ndarray) -> np.ndarray:
        """(n, W) int32 token ids -> (n, D) float embeddings; n is padded
        to the bucket internally and unpadded on return."""
        rows = np.ascontiguousarray(token_ids, dtype=np.int32)
        if rows.ndim != 2 or rows.shape[1] != self.text_words:
            raise ValueError(f"expected (n, {self.text_words}) token ids, "
                             f"got {rows.shape}")
        return self._run("text", self._text_fn, rows)

    def embed_video(self, video_u8: np.ndarray) -> np.ndarray:
        """(n, T, H, W, 3) uint8 frames -> (n, D) float embeddings."""
        clips = np.ascontiguousarray(video_u8, dtype=np.uint8)
        if clips.shape[1:] != self.video_shape:
            raise ValueError(f"expected (n,) + {self.video_shape} uint8 "
                             f"video, got {clips.shape}")
        return self._run("video", self._video_fn, clips)

    def _run(self, entry: str, fn, rows: np.ndarray) -> np.ndarray:
        n = rows.shape[0]
        bucket = self.bucket_for(n)
        rows = pad_rows(rows, bucket)
        # Serving-path fault sites (resilience/faults.py; chaos tests
        # kill/hang/flake individual replicas through here).  Checked
        # BEFORE the dispatch lock: a dead replica fails instantly and a
        # hang wedges only this engine's callers, never the lock queue
        # of a pool sibling.
        if self.dead:
            raise ReplicaDead("replica is dead (serve.replica_dead / "
                              "kill()) — restart the process to revive it")
        faults.maybe_raise("serve.dispatch_raise")
        faults.maybe_hang("serve.dispatch_hang")
        if faults.fire_site("serve.replica_dead"):
            self.kill()
            raise ReplicaDead("injected fault at serve.replica_dead — "
                              "this replica is now permanently dead")
        # Steady state: implicit transfers are bugs (they stall the async
        # dispatch pipeline); both legs of the request are explicit.
        with self._dispatch_lock, jax.transfer_guard("disallow"):
            x = jax.device_put(rows, self._batch_sh)
            out = jax.device_get(fn(self._variables, x))
        out = np.asarray(out)
        with self._stats_lock:
            self._calls[(entry, bucket)] = \
                self._calls.get((entry, bucket), 0) + 1
            self.embed_dim = int(out.shape[-1])
        return out[:n]

    def jit_entries(self) -> dict:
        """The engine's jitted programs by entry name — the supported
        surface for the analysis passes (trace invariants pin their
        collectives; the graftlint Pass 4 planner walks their jaxprs at
        every ladder rung) instead of reaching into ``_text_fn``/
        ``_video_fn``.  Tracing these does NOT require a warmed engine:
        build with ``precompile=False`` for planning-only use."""
        return {"text": self._text_fn, "video": self._video_fn}

    # ---- warmup + recompile accounting -----------------------------------

    def warmup(self) -> None:
        """Sweep BOTH entries over the full bucket ladder so every
        executable the engine will ever run exists before the first
        request, then snapshot the jit cache sizes — any later growth is
        a recompile (:meth:`recompiles`)."""
        with obs_spans.get_recorder().span("ladder.warmup",
                                           buckets=list(self.buckets)):
            for b in self.buckets:
                self.embed_text(np.zeros((b, self.text_words), np.int32))
                self.embed_video(np.zeros((b,) + self.video_shape, np.uint8))
        baseline = self._cache_sizes()
        with self._stats_lock:
            self._baseline_cache = baseline

    def _cache_sizes(self) -> dict:
        out = {}
        for name, fn in (("text", self._text_fn), ("video", self._video_fn)):
            size = getattr(fn, "_cache_size", None)
            out[name] = int(size()) if size is not None else -1
        return out

    def recompiles(self) -> int:
        """Jit-cache entries created SINCE the warmup sweep — 0 in a
        healthy steady state (pinned by the serve_embed_ladder trace
        invariant).  -1 when this jax build has no cache introspection."""
        with self._stats_lock:
            baseline = self._baseline_cache
        if baseline is None:
            return -1
        now = self._cache_sizes()
        if -1 in now.values() or -1 in baseline.values():
            return -1
        return sum(max(0, now[k] - baseline[k]) for k in now)

    # ---- liveness (pool failure isolation) -------------------------------

    @property
    def dead(self) -> bool:
        with self._stats_lock:
            return self._dead

    def kill(self) -> None:
        """Force-kill this engine: every subsequent dispatch raises
        :class:`ReplicaDead` instantly.  The ``serve.replica_dead`` fault
        site and chaos drills use this to simulate a replica whose
        device/process is gone; there is no un-kill — recovery is a
        process restart (the pool keeps it QUARANTINED forever)."""
        with self._stats_lock:
            self._dead = True

    def stats(self) -> dict:
        with self._stats_lock:
            calls = dict(self._calls)
            dead = self._dead
        return {
            "buckets": list(self.buckets),
            "max_batch": self.max_batch,
            "recompiles": self.recompiles(),
            "dead": dead,
            "calls": {f"{entry}@{bucket}": n
                      for (entry, bucket), n in sorted(calls.items())},
        }

    # ---- construction from a frozen export -------------------------------

    @classmethod
    def from_export(cls, export_dir: str, mesh: Mesh, *, dtype: str = "",
                    max_batch: int = 64, min_bucket: int = 0,
                    data_axis: str = "data", precompile: bool = True
                    ) -> "InferenceEngine":
        """Build model + engine from a ``milnce-export`` directory.

        ``dtype`` overrides the exported compute dtype ('bfloat16' casts
        the frozen params AND builds the model at bf16 — the MXU-rate
        deployment mode; '' keeps the exported dtype).

        Format detection is metadata-driven: a quantized edge-tier
        artifact (export.QUANT_FORMAT_VERSION) loads through
        ``load_quantized_checkpoint`` and serves behind a
        :class:`~milnce_tpu.quant.quantize.QuantizedModel` wrapper —
        int8 weights resident, dequantize inside the jitted entries,
        f32 accumulation; same ladder, same recompiles=0 contract.
        ``dtype`` overrides are refused for quantized artifacts (the
        stored precision IS the artifact's contract)."""
        model, variables, meta = load_serving_model(export_dir, dtype)
        return cls(model, variables, mesh,
                   text_words=meta["tokenizer"]["max_words"],
                   video_shape=meta["video_shape"],
                   max_batch=max_batch, min_bucket=min_bucket,
                   data_axis=data_axis,
                   cast_dtype=(dtype or None), precompile=precompile)
