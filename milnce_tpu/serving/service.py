"""Serving front: programmatic retrieval API + stdlib threaded HTTP/JSON.

Request flow for a text query (the full tentpole path)::

    sentence --tokenizer--> token row --cache?--> hit: cached embedding
                                      \\--miss--> DynamicBatcher (pad to
                                      bucket) --> InferenceEngine.embed_text
    embedding --> DeviceRetrievalIndex.topk --> (scores, corpus indices)

Everything device-side is pre-traced and transfer-guarded (engine.py /
index.py); everything host-side is stdlib + numpy.  The HTTP front is
``http.server.ThreadingHTTPServer`` on purpose: zero new dependencies,
one thread per connection, and the real concurrency story lives in the
batcher anyway — handler threads just block on futures.

Endpoints (JSON in/out):

- ``POST /v1/query``       {"token_ids": [[...]] | "sentences": [...],
                            "k": int?, "timeout_ms": float?, "tier": str?,
                            "replica_class": str?}  ("f32"/"edge" pins the
                           request to one pool replica class — SERVING.md
                           "Edge tier"; omitted = any class)
                           -> {"results": [{"indices": [...],
                                            "scores": [...]}, ...],
                               "index_generation": int?}  (live index
                           only — the freshness stamp)
- ``POST /v1/embed_text``  same inputs -> {"embeddings": [[...], ...]}
- ``POST /v1/index/add``   {"embeddings": [[...]] | "clips": [[...]],
                            "wait": bool?} — live-index ingest: raw
                           clips route through the video embed tower,
                           precomputed embeddings go straight to the
                           pending buffer; ``wait`` blocks until the
                           generation swap publishes the rows
                           (serving/live_index.py; 400 on a frozen
                           index).
- ``GET  /healthz``        resilience-style counters: uptime, request /
                           error / deadline-expired totals, engine
                           recompile count, batch-occupancy histogram,
                           cache hit rate, index size.
- ``GET  /metrics``        Prometheus text exposition of the service's
                           obs registry (request counters, batcher
                           occupancy histogram, cache hit rate,
                           recompile gauge — OBSERVABILITY.md).
- ``GET  /obs/events``     the span recorder's in-memory ring as JSON
                           (``?n=`` limits to the most recent N;
                           ``?since=<mono>`` returns only records
                           appended after that cursor, so pollers stop
                           re-downloading the whole ring).
- ``POST /obs/capture``    arm the bounded one-shot profiler capture
                           (obs/capture.py; 404 without
                           ``--serve.capture_dir``, refusal reasons as
                           JSON — the capture enforces its own
                           one-in-flight/cooldown/budget discipline).

Deadline semantics: ``timeout_ms`` bounds a request's QUEUE wait in the
batcher (ROBUSTNESS.md "Serving request path").  An expired request
fails with HTTP 504 / :class:`~milnce_tpu.serving.batcher.DeadlineExpired`
— never a silent drop.

HTTP error contract (SERVING.md "HTTP error contract"): every refusal
is a STRUCTURED JSON body — ``{"error", "kind", "reason"?,
"retry_after_ms"?}`` — and 429/503/504 responses carry a real
``Retry-After`` header.  504 = this request aged out (DeadlineExpired);
429 = shed at admission (bounded global queue full, deadline provably
infeasible, or every replica queue full — try again later); 503 =
degraded service (no healthy replica; cache hits still answered, misses
refused).  ``/healthz`` and ``/metrics`` NEVER shed — an overloaded
service must stay observable.
"""

from __future__ import annotations

import contextlib
import json
import logging
import math
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from milnce_tpu.analysis.lockrt import make_lock
from milnce_tpu.obs import export as obs_export
from milnce_tpu.obs import metrics as obs_metrics
from milnce_tpu.obs import spans as obs_spans
from milnce_tpu.obs.anomaly import EwmaSpikeDetector
from milnce_tpu.serving.batcher import DeadlineExpired, DynamicBatcher
from milnce_tpu.serving.cache import EmbeddingLRUCache, token_key
from milnce_tpu.serving.pool import PoolSaturated, PoolUnavailable

log = logging.getLogger(__name__)

# Safety margin on future waits past the request deadline: covers device
# execution of an already-submitted batch (a deadline bounds queue wait,
# not in-flight compute), so a wedged device surfaces as an error instead
# of a hung handler thread.
_RESULT_WAIT_SLACK_S = 30.0


class ShedError(RuntimeError):
    """Request refused at ADMISSION (HTTP 429): the bounded global
    queue is full or the deadline is provably infeasible.  Nothing was
    queued — retrying after ``retry_after_ms`` is safe and cheap."""

    def __init__(self, msg: str, reason: str, retry_after_ms: float):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


class DegradedError(RuntimeError):
    """Request refused because the service is DEGRADED (HTTP 503): no
    healthy replica can embed.  ``reason`` is machine-readable —
    ``cache_only`` (hits still answered, this request missed) or
    ``no_healthy_replicas`` (cache disabled/cold: full 503)."""

    def __init__(self, msg: str, reason: str, retry_after_ms: float = 1000.0):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


def parse_tier_spec(spec: str) -> dict:
    """``serve.tiers`` grammar: ``name:share[,name:share...]`` ->
    ordered ``{name: share}`` — PRIORITY order (first = highest; a
    request naming no tier gets the first one).  ``share`` in (0, 1] is
    the fraction of ``max_inflight`` that tier may occupy: the
    per-tenant SLO-class mechanism — a ``batch:0.5`` backfill tier can
    never hold more than half the admission budget, so the
    ``interactive:1.0`` tier always has headroom (it can't be starved).
    Malformed items and out-of-range shares raise ValueError at config
    time, not as a silently-ignored tier."""
    out: dict[str, float] = {}
    for item in filter(None, (c.strip() for c in spec.split(","))):
        if ":" not in item:
            raise ValueError(f"tier item {item!r} missing ':share' "
                             "(grammar: name:share[,name:share...])")
        name, _, share = item.partition(":")
        name = name.strip()
        if not name or name in out:
            raise ValueError(f"bad/duplicate tier name in {item!r}")
        share_f = float(share)
        if not 0.0 < share_f <= 1.0:
            raise ValueError(f"tier {name!r} share {share_f} outside "
                             "(0, 1]")
        out[name] = share_f
    return out


class AdmissionController:
    """Bounded global queue + deadline-feasibility load shedding.

    Sits in FRONT of the batcher (`embed_text_ids` / `query_ids` admit
    through here; `/healthz` and `/metrics` never do).  Two refusal
    conditions, both HTTP 429 with ``Retry-After``:

    - **overload**: admitted-but-unresolved rows would exceed
      ``max_inflight`` (the bounded global queue; 0 disables);
    - **deadline infeasibility**: the request carries a deadline, and a
      PROVABLE lower bound on its queue wait already exceeds it.  The
      bound is conservative: (batches provably ahead in the queue,
      spread across the pool's dispatch lanes) x the FASTEST dispatch
      ever observed — when it sheds, the request could not have met its
      deadline even on the service's best day, so failing it now (with
      nothing queued) beats failing it later with a 504 after it
      consumed queue space.

    Both refusals require the controller to be ARMED
    (``max_inflight`` > 0 — the config.py contract), and feasibility
    additionally needs latency samples; until the first dispatch
    completes it never sheds on deadline (the bound is unknown, so the
    controller stays conservative in the other direction).  The floor
    must be fed PURE dispatch time: the single-engine service feeds
    batcher flush durations (flush == dispatch there), the pooled
    service feeds the pool's per-dispatch latencies — an async flush's
    submit-to-resolution time includes replica queue wait and would
    inflate the "provable" floor into false 429s.

    **Per-tenant SLO classes** (``tiers`` — :func:`parse_tier_spec`):
    each tier may occupy at most ``share x max_inflight`` admitted rows;
    past it, THAT tier sheds (``tier_overload``, HTTP 429) while
    higher-priority tiers keep admitting into their own headroom — a
    batch backfill job cannot starve interactive traffic.  A request
    naming no tier rides the FIRST (highest-priority) tier; an unknown
    tier is a loud ValueError (HTTP 400), never a silent default."""

    def __init__(self, max_inflight: int, *, max_batch: int, lanes: int = 1,
                 depth_fn=None,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 tiers=None):
        self.max_inflight = int(max_inflight)
        self.max_batch = max(1, int(max_batch))
        self.lanes = max(1, int(lanes))
        self._depth_fn = depth_fn           # batcher queue depth (rows)
        self.tiers = (parse_tier_spec(tiers) if isinstance(tiers, str)
                      else dict(tiers or {}))
        self.default_tier = next(iter(self.tiers), None)
        self._lock = make_lock("serving.admission")
        self._inflight = 0                  # guarded-by: _lock
        self._tier_inflight = {t: 0 for t in self.tiers}  # guarded-by: _lock
        self._flush_floor_ms: Optional[float] = None  # guarded-by: _lock
        self._flush_mean_ms: Optional[float] = None   # guarded-by: _lock
        reg = registry if registry is not None \
            else obs_metrics.MetricsRegistry()
        self._f_shed = reg.counter(
            "milnce_serve_shed_total",
            "requests refused at admission (HTTP 429)", ("reason",))
        reg.gauge("milnce_serve_admission_inflight",
                  "rows admitted and not yet resolved",
                  fn=lambda: float(self.inflight))
        self._f_tier_shed = None
        if self.tiers:
            self._f_tier_shed = reg.counter(
                "milnce_serve_tier_shed_total",
                "admission refusals per SLO tier (HTTP 429)",
                ("tier", "reason"))
            g = reg.gauge("milnce_serve_tier_inflight",
                          "rows admitted and unresolved per SLO tier",
                          ("tier",))
            for name in self.tiers:
                g.labels(tier=name).bind(
                    lambda n=name: float(self.tier_inflight(n)))

    @property
    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def tier_inflight(self, tier: str) -> int:
        with self._lock:
            return self._tier_inflight.get(tier, 0)

    def tier_cap(self, tier: str) -> int:
        """Rows tier ``tier`` may hold: ``ceil(share * max_inflight)``
        (unbounded while the controller is unarmed)."""
        if self.max_inflight <= 0:
            return 0
        return max(1, math.ceil(self.tiers[tier] * self.max_inflight))

    def observe_flush(self, dur_ms: float, rows: int) -> None:
        """Fed from the batcher's ``on_flush`` hook: tracks the fastest
        flush (the provable floor) and an EWMA (the Retry-After hint)."""
        with self._lock:
            self._flush_floor_ms = dur_ms if self._flush_floor_ms is None \
                else min(self._flush_floor_ms, dur_ms)
            self._flush_mean_ms = dur_ms if self._flush_mean_ms is None \
                else 0.8 * self._flush_mean_ms + 0.2 * dur_ms

    def _shed(self, reason: str, msg: str, retry_after_ms: float,
              tier: Optional[str] = None):
        self._f_shed.labels(reason=reason).inc()
        if tier is not None and self._f_tier_shed is not None:
            self._f_tier_shed.labels(tier=tier, reason=reason).inc()
        raise ShedError(msg, reason, retry_after_ms)

    def resolve_tier(self, tier: Optional[str]) -> Optional[str]:
        """None -> the highest-priority tier; unknown names are a loud
        error (HTTP 400), never a silent default tier."""
        if not self.tiers:
            return None
        if tier is None:
            return self.default_tier
        if tier not in self.tiers:
            raise ValueError(f"unknown SLO tier {tier!r} "
                             f"(tiers: {', '.join(self.tiers)})")
        return tier

    @contextlib.contextmanager
    def admit(self, rows: int, timeout_ms: Optional[float],
              tier: Optional[str] = None):
        """Reserve ``rows`` slots for the duration of the request, or
        refuse with :class:`ShedError` — the refusal happens BEFORE
        anything is queued, so a shed request costs nothing downstream
        and can never hang."""
        rows = int(rows)
        tier = self.resolve_tier(tier)
        shed = None
        with self._lock:
            if (self.max_inflight > 0
                    and self._inflight + rows > self.max_inflight):
                hint = self._flush_mean_ms or 100.0
                shed = ("overload",
                        f"{self._inflight} rows in flight + {rows} would "
                        f"exceed max_inflight={self.max_inflight}", hint)
            elif (tier is not None and self.max_inflight > 0
                    and self._tier_inflight[tier] + rows
                    > self.tier_cap(tier)):
                hint = self._flush_mean_ms or 100.0
                shed = ("tier_overload",
                        f"tier {tier!r} holds "
                        f"{self._tier_inflight[tier]} rows + {rows} would "
                        f"exceed its share cap {self.tier_cap(tier)} "
                        f"(share {self.tiers[tier]:g} of "
                        f"max_inflight={self.max_inflight})", hint)
            elif self.max_inflight > 0 and timeout_ms and timeout_ms > 0 \
                    and self._flush_floor_ms is not None \
                    and self._depth_fn is not None:
                batches_ahead = math.ceil(self._depth_fn() / self.max_batch)
                floor_ms = (batches_ahead / self.lanes) \
                    * self._flush_floor_ms
                if floor_ms > float(timeout_ms):
                    shed = ("deadline_infeasible",
                            f"deadline {timeout_ms:.0f} ms < provable "
                            f"queue-wait floor {floor_ms:.0f} ms "
                            f"({batches_ahead} batches ahead)", floor_ms)
            if shed is None:
                self._inflight += rows
                if tier is not None:
                    self._tier_inflight[tier] += rows
        if shed is not None:
            self._shed(*shed, tier=tier)
        try:
            yield
        finally:
            with self._lock:
                self._inflight -= rows
                if tier is not None:
                    self._tier_inflight[tier] -= rows

    def stats(self) -> dict:
        with self._lock:
            inflight = self._inflight
            tier_inflight = dict(self._tier_inflight)
            floor = self._flush_floor_ms
        out = {
            "max_inflight": self.max_inflight,
            "inflight": inflight,
            "flush_floor_ms": floor,
            "shed": {str(labels[0]): int(child.value)
                     for labels, child in self._f_shed.items()},
        }
        if self.tiers:
            tier_shed: dict[str, dict] = {t: {} for t in self.tiers}
            for labels, child in self._f_tier_shed.items():
                tier_shed.setdefault(str(labels[0]), {})[
                    str(labels[1])] = int(child.value)
            out["tiers"] = {
                t: {"share": share,
                    "cap": self.tier_cap(t) if self.max_inflight > 0
                    else None,
                    "inflight": tier_inflight[t],
                    "shed": tier_shed.get(t, {})}
                for t, share in self.tiers.items()}
        return out


class RetrievalService:
    """Programmatic API over engine + batcher + cache + index."""

    def __init__(self, engine, index=None, *, tokenizer=None,
                 cache: Optional[EmbeddingLRUCache] = None,
                 max_delay_ms: float = 5.0, default_timeout_ms: float = 0.0,
                 registry: Optional[obs_metrics.MetricsRegistry] = None,
                 recorder: Optional[obs_spans.SpanRecorder] = None,
                 capture=None, anomaly_ratio: float = 3.0,
                 max_inflight: int = 0, tiers="", continuous: bool = False):
        self.engine = engine
        self.index = index
        self.tokenizer = tokenizer
        self.cache = cache if cache is not None else EmbeddingLRUCache(0)
        # engine may be a single InferenceEngine or a ReplicaPool —
        # the pool adds the Future-returning submit surface (pipelined
        # batcher flushes) and per-replica health (serving/pool.py)
        self._pool = engine if hasattr(engine, "pool_stats") else None
        # Anomaly-triggered profiler capture (obs/anomaly.py + obs/
        # capture.py): an EWMA detector watches per-flush latency (fed
        # by the batcher worker) and — when a ProfilerCapture is
        # injected — arms ONE bounded capture on a spike; POST
        # /obs/capture arms it manually.  None = events only / 404.
        self.capture = capture
        self._flush_detector = EwmaSpikeDetector(
            "serve.flush_ms", ratio=anomaly_ratio, recorder=recorder,
            on_anomaly=((lambda v, e: capture.arm(reason="flush_spike"))
                        if capture is not None else None))
        # Every counter on the request path lives on ONE obs registry
        # (the old per-component dicts raced request threads against the
        # batcher worker; registry metrics are lock-guarded).  None = a
        # private registry, so multiple services in one process stay
        # isolated; the milnce-serve CLI passes the process-wide
        # ``obs.metrics.registry()``.
        self.registry = registry if registry is not None \
            else obs_metrics.MetricsRegistry()
        # None = the process-default recorder, resolved PER USE (not
        # captured here): a later ``spans.install()`` — e.g. a train run
        # in the same process — must divert this service's spans and the
        # ``/obs/events`` ring together, never split them
        self._recorder = recorder
        # admission controller (the bounded global queue + feasibility
        # shed): max_inflight=0 keeps the overload bound off but the
        # controller still meters in-flight rows for /healthz
        self._admission = AdmissionController(
            max_inflight, max_batch=engine.max_batch,
            lanes=(len(self._pool.replicas) if self._pool is not None else 1),
            depth_fn=lambda: self._batcher.depth(),
            registry=self.registry, tiers=tiers)

        def _on_flush(dur_ms: float, rows: int) -> None:
            # one hook, two consumers: the EWMA spike detector (anomaly
            # -> profiler capture) and — single-engine mode only — the
            # admission feasibility floor (a sync flush IS the dispatch;
            # a pooled async flush spans replica queue wait too, so the
            # pooled floor feeds from the pool's dispatch latencies
            # below instead)
            self._flush_detector.observe(dur_ms, rows=rows)
            if self._pool is None:
                self._admission.observe_flush(dur_ms, rows)

        self._batcher = DynamicBatcher(
            engine.embed_text, engine.bucket_for, max_batch=engine.max_batch,
            max_delay_ms=max_delay_ms, default_timeout_ms=default_timeout_ms,
            name="text", registry=self.registry, buckets=engine.buckets,
            recorder=recorder, on_flush=_on_flush,
            # pooled: submit-and-move-on so batches pipeline across
            # replicas and one wedged replica never blocks the flush loop
            run_batch_async=(self._pool.submit_text
                             if self._pool is not None else None),
            # continuous batching (SERVING.md): one dispatch lane per
            # pool replica; the single-engine path has exactly one
            continuous=continuous,
            lanes=(len(self._pool.replicas)
                   if self._pool is not None else 1))
        if self._pool is not None:
            # the pool's per-dispatch latencies feed the same spike
            # detector (the anomaly->capture path sees replica-level
            # slowness even when batcher queueing hides it) AND the
            # admission feasibility floor (pure execution time — the
            # honest "fastest the service has ever dispatched")
            def _on_dispatch(dur_ms: float, rows: int) -> None:
                self._flush_detector.observe(dur_ms, rows=rows)
                self._admission.observe_flush(dur_ms, rows)

            self._pool.set_on_latency(_on_dispatch)
        self._default_timeout_ms = float(default_timeout_ms)
        self._m_degraded = self.registry.counter(
            "milnce_serve_degraded_total",
            "requests refused in degraded mode (HTTP 503)", ("reason",))
        self._started = time.time()  # graftlint: disable=GL005(wall-clock uptime bookkeeping for /healthz + the uptime gauge — deliberate wall time, not a device-timing delta; audited when main()'s jax import put this file in GL005 scope)
        reg = self.registry
        self._m_queries = reg.counter(
            "milnce_serve_queries_total", "retrieval queries received")
        self._m_errors = reg.counter(
            "milnce_serve_query_errors_total", "retrieval queries failed")
        # collect-time gauges: values owned by other components, read at
        # scrape/snapshot — never cached stale, never double-counted
        reg.gauge("milnce_serve_uptime_seconds", "seconds since boot",
                  fn=lambda: time.time() - self._started)
        reg.gauge("milnce_serve_engine_recompiles",
                  "jit-cache entries created since the warmup sweep "
                  "(must stay 0; -1 = no introspection on this jax)",
                  fn=engine.recompiles)
        reg.gauge("milnce_serve_cache_hits",
                  "text-embedding cache hits",
                  fn=lambda: self.cache.stats()["hits"])
        reg.gauge("milnce_serve_cache_misses",
                  "text-embedding cache misses",
                  fn=lambda: self.cache.stats()["misses"])
        reg.gauge("milnce_serve_cache_hit_rate",
                  "hits / (hits + misses), 0 before traffic",
                  fn=lambda: self.cache.stats()["hit_rate"])
        if index is not None:
            reg.gauge("milnce_serve_index_size", "corpus rows indexed",
                      fn=lambda: self.index.stats()["size"])

    # ---- embedding path --------------------------------------------------

    def embed_text_ids(self, token_ids: np.ndarray,
                       timeout_ms: Optional[float] = None,
                       tier: Optional[str] = None,
                       replica_class: Optional[str] = None) -> np.ndarray:
        """(n, W) int32 -> (n, D): cache hits answered on host, misses
        batched through the engine; results land back in the cache.

        Admission runs FIRST (a shed request touches neither cache nor
        queue); a miss that fails because no replica is healthy becomes
        :class:`DegradedError` — the degradation ladder's cache-only
        tier (an all-hit request still succeeds because it never reaches
        the batcher).  ``tier`` names the request's SLO class when the
        controller has tiers configured (None = highest priority).

        ``replica_class`` pins the request to one pool replica class
        ('f32' / 'edge' — SERVING.md "Edge tier").  Class-pinned
        requests bypass the batcher AND the embedding cache: the
        batcher's queue is class-blind, and cached rows carry no class
        stamp — an edge-tier int8 embedding silently answering a later
        full-precision request would mix tiers.  None (the default)
        batches across every class as usual."""
        rows = np.ascontiguousarray(token_ids, dtype=np.int32)
        if rows.ndim != 2:
            raise ValueError(f"expected (n, W) token ids, got {rows.shape}")
        if replica_class is not None and self._pool is None:
            raise ValueError("replica_class requires a pooled service "
                             "(--serve.replicas > 1 or an edge tier)")
        # admission judges the EFFECTIVE deadline (the batcher applies
        # default_timeout_ms to a None request deadline, so feasibility
        # must see the same number — a raw None would silently disable
        # the check for every default-deadline client)
        eff_timeout_ms = (self._default_timeout_ms if timeout_ms is None
                          else float(timeout_ms))
        with self._admission.admit(rows.shape[0], eff_timeout_ms, tier):
            if replica_class is not None:
                return self._embed_class_pinned(rows, replica_class)
            keys = [token_key(r) for r in rows]
            out: list[Optional[np.ndarray]] = [self.cache.get(k)
                                               for k in keys]
            pending = [(i, self._batcher.submit(rows[i], timeout_ms))
                       for i, hit in enumerate(out) if hit is None]
            wait = self._result_wait_s(timeout_ms)
            for i, fut in pending:
                try:
                    row = fut.result(timeout=wait)
                except PoolUnavailable as exc:
                    reason = ("cache_only" if self.cache.capacity > 0
                              else exc.reason)
                    self._m_degraded.labels(reason=reason).inc()
                    raise DegradedError(
                        f"no healthy replica to embed this request "
                        f"({exc}); cache hits are still served",
                        reason) from exc
                self.cache.put(keys[i], row)
                out[i] = row
            return np.stack(out) if out else np.zeros(
                (0, self.engine.embed_dim or 0), np.float32)

    def _embed_class_pinned(self, rows: np.ndarray,
                            replica_class: str) -> np.ndarray:
        """Direct class-pinned dispatch (no batcher, no cache): the pool
        pads each chunk to its bucket; chunks stay within max_batch."""
        top = self.engine.max_batch
        if rows.shape[0] == 0:
            return np.zeros((0, self.engine.embed_dim or 0), np.float32)
        try:
            return np.concatenate(
                [self._pool.embed_text(rows[lo:lo + top],
                                       cls=replica_class)
                 for lo in range(0, rows.shape[0], top)])
        except PoolUnavailable as exc:
            self._m_degraded.labels(reason=exc.reason).inc()
            raise DegradedError(
                f"no healthy {replica_class!r} replica to embed this "
                f"request ({exc})", exc.reason) from exc

    def _result_wait_s(self, timeout_ms: Optional[float]) -> Optional[float]:
        t_ms = (self._default_timeout_ms if timeout_ms is None
                else float(timeout_ms))
        return (t_ms / 1000.0 + _RESULT_WAIT_SLACK_S) if t_ms > 0 else None

    def _encode(self, sentences) -> np.ndarray:
        if self.tokenizer is None:
            raise ValueError("service built without a tokenizer — send "
                             "token_ids instead of sentences")
        return self.tokenizer.encode_batch(sentences,
                                           self.engine.text_words)

    # ---- query path ------------------------------------------------------

    def query_ids_with_gen(self, token_ids: np.ndarray,
                           k: Optional[int] = None,
                           timeout_ms: Optional[float] = None,
                           tier: Optional[str] = None,
                           replica_class: Optional[str] = None
                           ) -> tuple[np.ndarray, np.ndarray,
                                      Optional[int]]:
        """(n, W) token ids -> ((n, k) scores, (n, k) corpus indices,
        index generation).  The generation is the freshness stamp a
        live index answers with (``/v1/query`` surfaces it as
        ``index_generation`` so clients can detect a stale read); a
        frozen index answers None."""
        if self.index is None:
            raise ValueError("service built without a retrieval index")
        k = self.index.k if k is None else int(k)
        if not 1 <= k <= self.index.k:
            raise ValueError(f"k={k} outside [1, index k={self.index.k}]")
        self._m_queries.inc(len(token_ids))
        try:
            emb = self.embed_text_ids(token_ids, timeout_ms, tier,
                                      replica_class)
            if hasattr(self.index, "topk_with_gen"):
                scores, idx, gen = self.index.topk_with_gen(emb)
            else:
                scores, idx = self.index.topk(emb)
                gen = None
        except (ShedError, DegradedError, PoolSaturated, PoolUnavailable):
            raise        # refusals, not failures: counted on their own
        except Exception:
            self._m_errors.inc(len(token_ids))
            raise
        return scores[:, :k], idx[:, :k], gen

    def query_ids(self, token_ids: np.ndarray, k: Optional[int] = None,
                  timeout_ms: Optional[float] = None,
                  tier: Optional[str] = None,
                  replica_class: Optional[str] = None
                  ) -> tuple[np.ndarray, np.ndarray]:
        """(n, W) token ids -> ((n, k) scores, (n, k) corpus indices)."""
        scores, idx, _ = self.query_ids_with_gen(token_ids, k, timeout_ms,
                                                 tier, replica_class)
        return scores, idx

    def query_sentences_with_gen(self, sentences, k: Optional[int] = None,
                                 timeout_ms: Optional[float] = None,
                                 tier: Optional[str] = None,
                                 replica_class: Optional[str] = None):
        return self.query_ids_with_gen(self._encode(sentences), k,
                                       timeout_ms, tier, replica_class)

    def query_sentences(self, sentences, k: Optional[int] = None,
                        timeout_ms: Optional[float] = None,
                        tier: Optional[str] = None,
                        replica_class: Optional[str] = None
                        ) -> tuple[np.ndarray, np.ndarray]:
        return self.query_ids(self._encode(sentences), k, timeout_ms, tier,
                              replica_class)

    # ---- write path (live index ingest) ----------------------------------

    def index_add(self, embeddings=None, clips=None, *, wait: bool = False,
                  timeout_s: float = 30.0) -> dict:
        """Ingest corpus rows into a LIVE index: either precomputed
        ``(n, D)`` embeddings, or raw ``(n, T, H, W, 3)`` uint8 clips
        routed through the SAME video embed tower serving uses (pooled
        when the service is pooled) — served numbers stay eval numbers
        for ingested rows too.  ``wait=True`` blocks until the rows are
        swapped live and reports the published generation."""
        if self.index is None or not hasattr(self.index, "add"):
            raise ValueError("service index is not a live index — boot "
                             "with serving/live_index.py (or "
                             "--serve.live_index) to ingest online")
        if (embeddings is None) == (clips is None):
            raise ValueError("exactly one of 'embeddings' (n, D floats) "
                             "or 'clips' (n, T, H, W, 3 uint8) required")
        if clips is not None:
            rows = np.ascontiguousarray(clips, dtype=np.uint8)
            top = self.engine.max_batch
            emb = np.concatenate(
                [self.engine.embed_video(rows[lo:lo + top])
                 for lo in range(0, rows.shape[0], top)])
        else:
            emb = np.ascontiguousarray(embeddings, dtype=np.float32)
        out = self.index.add(emb)
        out["rows"] = int(emb.shape[0])
        if wait:
            out["live"] = self.index.flush(timeout_s)
            out["generation"] = self.index.generation
            out["size"] = self.index.size
        return out

    # ---- lifecycle / observability --------------------------------------

    def health(self) -> dict:
        """The pre-registry ``/healthz`` contract, keys unchanged —
        every value now reads the obs registry (or a component stats()
        that itself reads the registry)."""
        out = {
            "status": "ok",
            "uptime_s": time.time() - self._started,
            "queries": int(self._m_queries.value),
            "query_errors": int(self._m_errors.value),
            "engine": self.engine.stats(),
            "batcher": self._batcher.stats(),
            "cache": self.cache.stats(),
            "index": self.index.stats() if self.index is not None else None,
            "admission": self._admission.stats(),
        }
        if self._pool is not None:
            # per-replica state / outstanding / last-probe age + the
            # pool resilience counters (additive key — every
            # pre-existing /healthz key above is byte-compatible)
            out["pool"] = self._pool.pool_stats()
        return out

    def metrics_text(self) -> str:
        """Prometheus text exposition of the service registry."""
        return obs_export.to_prometheus(self.registry)

    @property
    def recorder(self) -> obs_spans.SpanRecorder:
        """The recorder ``/obs/events`` serves: the injected one, else
        whatever is CURRENTLY installed as the process default."""
        return self._recorder if self._recorder is not None \
            else obs_spans.get_recorder()

    def close(self) -> None:
        self._batcher.close()


# ---------------------------------------------------------------------------
# HTTP front
# ---------------------------------------------------------------------------

class _Handler(BaseHTTPRequestHandler):
    # set per-server in serve_http
    service: RetrievalService = None        # type: ignore[assignment]

    def log_message(self, fmt, *args):       # route access logs to logging
        log.debug("%s " + fmt, self.address_string(), *args)

    def _reply(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self._reply_raw(code, body, "application/json")

    def _reply_raw(self, code: int, body: bytes, content_type: str,
                   retry_after_ms: Optional[float] = None) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        if retry_after_ms is not None:
            # Retry-After is whole seconds (RFC 9110); round UP so the
            # client never retries before the hinted wait
            self.send_header("Retry-After",
                             str(max(1, math.ceil(retry_after_ms / 1000.0))))
        self.end_headers()
        self.wfile.write(body)

    def _refuse(self, code: int, kind: str, exc: Exception,
                reason: Optional[str] = None) -> None:
        """The structured refusal contract (SERVING.md): JSON body with
        ``error``/``kind``/``reason``/``retry_after_ms`` + a real
        ``Retry-After`` header — machine-actionable, never a bare
        string or a socket hang."""
        retry_ms = float(getattr(exc, "retry_after_ms", 1000.0)) or 1000.0
        payload = {"error": str(exc), "kind": kind,
                   "retry_after_ms": round(retry_ms, 1)}
        if reason is not None:
            payload["reason"] = reason
        body = json.dumps(payload).encode()
        self._reply_raw(code, body, "application/json",
                        retry_after_ms=retry_ms)

    def do_GET(self) -> None:
        from urllib.parse import parse_qs, urlparse

        url = urlparse(self.path)
        route = url.path.rstrip("/")
        if route in ("/healthz", "/health"):
            self._reply(200, self.service.health())
        elif route == "/metrics":
            self._reply_raw(200, self.service.metrics_text().encode(),
                            obs_export.PROMETHEUS_CONTENT_TYPE)
        elif route == "/obs/events":
            qs = parse_qs(url.query)
            n = qs.get("n", [None])[0]
            try:
                n = int(n) if n else None
            except ValueError:
                self._reply(400, {"error": f"n must be an integer, "
                                           f"got {n!r}"})
                return
            # ?since=<mono>: only records appended after that cursor
            # (the `mono` stamp each record carries) — pollers pass
            # their last-seen value back instead of re-downloading the
            # whole ring
            since = qs.get("since", [None])[0]
            try:
                since = float(since) if since else None
            except ValueError:
                self._reply(400, {"error": f"since must be a number "
                                           f"(a record's mono stamp), "
                                           f"got {since!r}"})
                return
            self._reply(200, {"events":
                              self.service.recorder.tail(n, since=since)})
        else:
            self._reply(404, {"error": f"no route {self.path!r}"})

    def do_POST(self) -> None:
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            if self.path == "/v1/query":
                scores, idx, gen = self._dispatch(
                    self.service.query_ids_with_gen,
                    self.service.query_sentences_with_gen, req)
                payload = {"results": [
                    {"indices": row_i.tolist(), "scores": row_s.tolist()}
                    for row_s, row_i in zip(scores, idx)]}
                if gen is not None:
                    # freshness stamp: the live-index generation this
                    # ranking was answered from (SERVING.md "Live index")
                    payload["index_generation"] = int(gen)
                self._reply(200, payload)
            elif self.path == "/v1/embed_text":
                rows = self._token_rows(req)
                emb = self.service.embed_text_ids(
                    rows, req.get("timeout_ms"), req.get("tier"),
                    req.get("replica_class"))
                self._reply(200, {"embeddings": emb.tolist()})
            elif self.path == "/v1/index/add":
                out = self.service.index_add(
                    embeddings=req.get("embeddings"),
                    clips=req.get("clips"),
                    wait=bool(req.get("wait", False)))
                self._reply(200, out)
            elif self.path == "/obs/capture":
                # manual profiler-capture arm; the capture object
                # enforces the one-shot/cooldown budget and reports a
                # refusal reason instead of silently double-capturing
                if self.service.capture is None:
                    self._reply(404, {"error": "no profiler capture "
                                               "configured "
                                               "(--serve.capture_dir)"})
                else:
                    self._reply(200, self.service.capture.arm(
                        reason=str(req.get("reason", "http"))))
            else:
                self._reply(404, {"error": f"no route {self.path!r}"})
        except DeadlineExpired as exc:
            self._refuse(504, "deadline_expired", exc)
        except ShedError as exc:
            self._refuse(429, "shed", exc, reason=exc.reason)
        except PoolSaturated as exc:
            self._refuse(429, "shed", exc, reason="replica_queues_full")
        except DegradedError as exc:
            self._refuse(503, "degraded", exc, reason=exc.reason)
        except PoolUnavailable as exc:
            self._refuse(503, "degraded", exc, reason=exc.reason)
        except (ValueError, KeyError, json.JSONDecodeError) as exc:
            self._reply(400, {"error": str(exc)})
        except Exception as exc:
            log.exception("serving request failed")
            self._reply(500, {"error": f"{type(exc).__name__}: {exc}"})

    def _token_rows(self, req: dict) -> np.ndarray:
        if "token_ids" in req:
            return np.asarray(req["token_ids"], np.int32)
        return self.service._encode(req["sentences"])

    def _dispatch(self, by_ids, by_sentences, req: dict):
        k, t, tier = req.get("k"), req.get("timeout_ms"), req.get("tier")
        cls = req.get("replica_class")
        if "token_ids" in req:
            return by_ids(np.asarray(req["token_ids"], np.int32), k, t,
                          tier, cls)
        if "sentences" in req:
            return by_sentences(req["sentences"], k, t, tier, cls)
        raise ValueError("request needs 'token_ids' or 'sentences'")


def serve_http(service: RetrievalService, host: str = "127.0.0.1",
               port: int = 0) -> ThreadingHTTPServer:
    """Bind a threaded HTTP server (port 0 = ephemeral, for tests); the
    caller owns ``serve_forever`` / ``shutdown``."""
    handler = type("BoundHandler", (_Handler,), {"service": service})
    server = ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    return server


def main(argv=None) -> None:
    """``milnce-serve``: HTTP retrieval service over a frozen export.

    Same CLI grammar as the trainer (``--preset`` + ``--serve.*`` /
    ``--parallel.*`` overrides — config.py).  The corpus comes from
    ``--serve.corpus_npz`` (a (N, D) float32 embedding matrix, e.g. an
    offline eval extraction); without it the service starts embed-only
    (query requests 400 until an index exists)."""
    import os

    import jax

    from milnce_tpu.config import parse_cli
    from milnce_tpu.data.tokenizer import Tokenizer
    from milnce_tpu.obs import runctx as obs_runctx
    from milnce_tpu.obs.capture import ProfilerCapture
    from milnce_tpu.parallel.mesh import build_mesh, initialize_distributed
    from milnce_tpu.serving.engine import InferenceEngine
    from milnce_tpu.serving.export import METADATA_FILE
    from milnce_tpu.serving.index import DeviceRetrievalIndex

    cfg = parse_cli(argv, description="milnce-tpu serving front")
    s = cfg.serve
    if not s.export_dir:
        raise SystemExit("--serve.export_dir is required (a milnce-export "
                         "artifact directory)")
    initialize_distributed(cfg.parallel)
    mesh = build_mesh(cfg.parallel)
    edge = bool(s.edge_export_dir) and s.edge_replicas > 0
    if s.edge_replicas > 0 and not s.edge_export_dir:
        raise SystemExit("--serve.edge_replicas needs "
                         "--serve.edge_export_dir (the quantized/student "
                         "artifact the edge class serves)")
    if s.replicas > 1 or edge:
        from milnce_tpu.serving.pool import ReplicaPool

        engine = ReplicaPool.from_export(
            s.export_dir, s.replicas, dtype=s.dtype,
            max_batch=s.max_batch, min_bucket=s.min_bucket,
            data_axis=cfg.parallel.data_axis,
            queue_depth=s.replica_queue_depth,
            error_threshold=s.error_threshold, slo_ms=s.slo_ms,
            slo_breaches=s.slo_breaches,
            probe_interval_s=s.probe_interval_s,
            hedge_quantile=s.hedge_quantile, hedge_min_ms=s.hedge_min_ms,
            max_requeues=s.max_requeues,
            edge_export_dir=s.edge_export_dir,
            edge_replicas=s.edge_replicas,
            registry=obs_metrics.registry())
    else:
        engine = InferenceEngine.from_export(
            s.export_dir, mesh, dtype=s.dtype, max_batch=s.max_batch,
            min_bucket=s.min_bucket, data_axis=cfg.parallel.data_axis)
    # sentence requests need a vocab: --serve.token_dict_path wins, else
    # the path the export recorded; with neither, token_ids-only (400s
    # on "sentences" explain themselves)
    with open(os.path.join(s.export_dir, METADATA_FILE)) as fh:
        meta = json.load(fh)
    tok_meta = meta.get("tokenizer", {})
    tokenizer = None
    if s.token_dict_path:
        if not os.path.exists(s.token_dict_path):
            # an explicit operator path must fail loudly at boot — the
            # export-recorded fallback below is the only silent degrade
            raise SystemExit(f"--serve.token_dict_path "
                             f"{s.token_dict_path!r} does not exist")
        tokenizer = Tokenizer.from_npy(s.token_dict_path,
                                       max_words=engine.text_words)
    else:
        recorded = tok_meta.get("token_dict_path", "")
        if recorded and os.path.exists(recorded):
            tokenizer = Tokenizer.from_npy(recorded,
                                           max_words=engine.text_words)
    corpus = None
    if s.corpus_npz:
        with np.load(s.corpus_npz) as z:
            if "emb" in z.files:            # the documented contract
                corpus = z["emb"]
            elif len(z.files) == 1:
                corpus = z[z.files[0]]
            else:
                raise SystemExit(
                    f"--serve.corpus_npz {s.corpus_npz!r} holds "
                    f"{z.files} — store the corpus under the 'emb' key "
                    "(np.savez(..., emb=embeddings)) so the index can't "
                    "silently build over the wrong array")
    index = None
    if s.live_index:
        from milnce_tpu.serving.export import INDEX_METADATA_FILE
        from milnce_tpu.serving.live_index import LiveRetrievalIndex

        live_kwargs = dict(query_buckets=engine.buckets,
                           data_axis=cfg.parallel.data_axis,
                           min_shard_rows=s.index_min_shard_rows,
                           registry=obs_metrics.registry())
        snap = s.index_snapshot_dir
        if snap and os.path.exists(os.path.join(snap,
                                                INDEX_METADATA_FILE)):
            # a snapshot resumes the ingesting service where it left
            # off (generation counter included); --serve.corpus_npz is
            # ignored in that case — the snapshot IS the corpus
            index = LiveRetrievalIndex.restore(snap, mesh, k=s.topk,
                                               **live_kwargs)
        else:
            index = LiveRetrievalIndex(mesh, corpus, k=s.topk,
                                       dim=engine.embed_dim,
                                       **live_kwargs)
    elif corpus is not None:
        index = DeviceRetrievalIndex(mesh, corpus, k=s.topk,
                                     query_buckets=engine.buckets,
                                     data_axis=cfg.parallel.data_axis)
    # run identity for every snapshot/event this process emits
    # (obs/runctx.py — pod aggregation + obs_report split on it)
    obs_runctx.set_run_context(obs_runctx.auto_run_id("serve-"),
                               jax.process_index())
    capture = None
    if s.capture_dir:
        capture = ProfilerCapture(s.capture_dir,
                                  duration_s=s.capture_ms / 1e3,
                                  max_captures=s.capture_max)
    service = RetrievalService(
        engine, index, tokenizer=tokenizer,
        cache=EmbeddingLRUCache(s.cache_capacity),
        max_delay_ms=s.max_delay_ms, default_timeout_ms=s.default_timeout_ms,
        # the live process has ONE registry: /metrics on this server
        # also exposes anything other subsystems record process-wide
        registry=obs_metrics.registry(),
        capture=capture, anomaly_ratio=s.anomaly_ratio,
        max_inflight=s.max_inflight, tiers=s.tiers,
        continuous=s.continuous_batching)
    server = serve_http(service, s.host, s.port)

    # graceful shutdown: SIGTERM/SIGINT must unwind through the finally
    # below (live-index snapshot, batcher/pool close) instead of killing
    # the process mid-write.  shutdown() blocks until serve_forever
    # returns, so it must run OFF the main thread (the handler interrupts
    # serve_forever's own poll loop).
    import signal
    import threading

    def _graceful(signum, frame):
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _graceful)
    signal.signal(signal.SIGINT, _graceful)
    # flush: operators poll a redirected log for this readiness line
    print(f"milnce-serve: listening on http://{s.host}:"
          f"{server.server_address[1]} (buckets {engine.buckets}, "
          f"replicas={s.replicas}"
          + (f"+{s.edge_replicas} edge" if edge else "") + ", "
          f"index={'none' if index is None else index.size}, "
          f"tokenizer={'yes' if tokenizer else 'token_ids-only'}; "
          f"Prometheus scrape: /metrics)",
          flush=True)
    try:
        server.serve_forever()
    finally:
        server.server_close()
        service.close()
        if s.live_index and index is not None:
            if s.index_snapshot_dir:
                # checkpoint the grown corpus so the next boot resumes
                # the generation instead of re-ingesting from scratch
                if not index.flush(timeout=30.0):
                    # acknowledged-but-unpublished rows exist and could
                    # not be swapped in time (wedged/failing builder) —
                    # the snapshot below is the LIVE generation only;
                    # dropping ingest silently would betray the 200s
                    # those adds already returned
                    st = index.stats()
                    print(f"milnce-serve: WARNING — shutdown flush timed "
                          f"out with {st['pending_rows']} ingested rows "
                          f"unpublished ({st['swap_failures']} swap "
                          f"failures); snapshot covers generation "
                          f"{st['generation']} only", flush=True)
                index.snapshot(s.index_snapshot_dir)
            index.close()
        if s.replicas > 1:
            engine.close()


if __name__ == "__main__":
    main()
