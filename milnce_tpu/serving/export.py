"""Params-only frozen export: training checkpoint -> inference artifact.

A training checkpoint (Orbax, train/checkpoint.py) carries the full
``TrainState`` — params, BatchNorm stats, AND the optimizer moments,
which for Adam are 2x the params and pure dead weight at serve time.
This module writes the inference subset in a deliberately boring
format: one ``arrays.npz`` (flattened ``params`` + ``batch_stats``
leaves, '/'-joined tree paths as keys) plus one ``metadata.json``
(model config, tokenizer contract, per-clip video shape) — loadable on
any host with numpy, no Orbax, no original mesh, no model code at read
time.

Arrays are stored float32; casting to bf16 is a LOAD-time decision
(``InferenceEngine.from_export(dtype='bfloat16')``) so one artifact
serves both precision modes ("bf16-castable", not bf16-committed).

CLI (console script ``milnce-export`` /
``python -m milnce_tpu.serving.export``)::

    milnce-export --checkpoint_dir checkpoint/run1 --out export/run1 \\
        --preset small [--epoch 7] [--model.embedding_dim 512 ...]

The model/data flags mirror the trainer CLI: the checkpoint stores only
arrays, so the exporter must be told the same model config the run was
trained with (preset + overrides), and bakes it into the artifact —
the serving host never guesses shapes again.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
from typing import Optional

import numpy as np

ARRAYS_FILE = "arrays.npz"
METADATA_FILE = "metadata.json"
FORMAT_VERSION = 1

# Quantized edge-tier artifact (milnce_tpu/quant/): SAME two files, but
# quantized params ship int8 with their f32 scales under the
# 'quant_scales/' key prefix, the array_dtypes manifest records 'int8'
# entries, and metadata carries a 'quant' block (scheme + calibration
# summary).  A separate format version so the v1 loader rejects it
# LOUDLY instead of serving int8 bits as weights.
QUANT_FORMAT_VERSION = 2
SCALES_PREFIX = "quant_scales"

# Live-index corpus snapshot (serving/live_index.py): the SAME boring
# two-file shape as the params export — one npz (the corpus under the
# 'emb' key, the exact array ``--serve.corpus_npz`` accepts) plus one
# versioned metadata json — so an ingesting service can checkpoint its
# grown corpus and a restore (or a cold boot off the npz alone) is
# bit-exact.
INDEX_ARRAYS_FILE = "corpus.npz"
INDEX_METADATA_FILE = "index_meta.json"
INDEX_FORMAT_VERSION = 1


def export_corpus_snapshot(out_dir: str, embeddings: np.ndarray, *,
                           generation: int, k: int,
                           source: str = "") -> str:
    """Write a live-index corpus snapshot; returns ``out_dir``.

    ``embeddings`` is the LIVE generation's (N, D) float32 host corpus
    (pending ingest rows are the caller's business — flush first)."""
    emb = np.ascontiguousarray(embeddings, dtype=np.float32)
    if emb.ndim != 2:
        raise ValueError(f"expected (N, D) embeddings, got {emb.shape}")
    os.makedirs(out_dir, exist_ok=True)
    meta = {
        "format_version": INDEX_FORMAT_VERSION,
        "generator": "milnce_tpu/serving/export.py (corpus snapshot)",
        "generation": int(generation),
        "k": int(k),
        "size": int(emb.shape[0]),
        "dim": int(emb.shape[1]),
        "source": source,
    }
    # tmp-write + atomic rename, corpus first: an ingesting service
    # snapshots into the SAME directory every shutdown, so an in-place
    # write killed mid-stream would destroy the previous good snapshot
    # (the exact crash-window class train/checkpoint.py defends
    # against).  Worst case after a crash between the two renames is a
    # NEW corpus beside the OLD metadata — load_corpus_snapshot's
    # shape-vs-metadata check turns a size-changing tear into a loud
    # boot error instead of silently serving a mixed snapshot.
    arrays_path = os.path.join(out_dir, INDEX_ARRAYS_FILE)
    meta_path = os.path.join(out_dir, INDEX_METADATA_FILE)
    # np.savez force-appends '.npz' to names missing it — keep the tmp
    # name's suffix so the path savez writes IS the path we rename
    tmp_arrays = os.path.join(out_dir, f".tmp-{os.getpid()}-corpus.npz")
    tmp_meta = meta_path + f".tmp-{os.getpid()}"
    try:
        np.savez(tmp_arrays, emb=emb)
        with open(tmp_meta, "w") as fh:
            json.dump(meta, fh, indent=2, sort_keys=True)
        os.replace(tmp_arrays, arrays_path)
        os.replace(tmp_meta, meta_path)
    finally:
        for leftover in (tmp_arrays, tmp_meta):
            if os.path.exists(leftover):
                os.unlink(leftover)
    return out_dir


def load_corpus_snapshot(snap_dir: str) -> tuple[dict, np.ndarray]:
    """Read a corpus snapshot -> (metadata dict, (N, D) f32 corpus)."""
    with open(os.path.join(snap_dir, INDEX_METADATA_FILE)) as fh:
        meta = json.load(fh)
    if meta.get("format_version") != INDEX_FORMAT_VERSION:
        raise ValueError(
            f"corpus snapshot format {meta.get('format_version')!r} "
            f"unsupported (this build reads {INDEX_FORMAT_VERSION})")
    with np.load(os.path.join(snap_dir, INDEX_ARRAYS_FILE)) as z:
        emb = np.ascontiguousarray(z["emb"], dtype=np.float32)
    if emb.shape != (meta["size"], meta["dim"]):
        raise ValueError(f"snapshot corpus shape {emb.shape} disagrees "
                         f"with its metadata ({meta['size']}, "
                         f"{meta['dim']}) — truncated or mixed artifact")
    return meta, emb


def _key_name(k) -> str:
    for attr in ("key", "name", "idx"):
        if hasattr(k, attr):
            return str(getattr(k, attr))
    return str(k)


def _flatten(tree, prefix: str) -> dict[str, np.ndarray]:
    """Pytree -> {'prefix/path/to/leaf': np.ndarray}."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join([prefix] + [_key_name(p) for p in path])
        out[key] = np.asarray(leaf)
    return out


def _unflatten(arrays: dict[str, np.ndarray], prefix: str) -> dict:
    """Inverse of :func:`_flatten` for dict-shaped trees (flax params /
    batch_stats are nested string-keyed dicts)."""
    root: dict = {}
    for key, value in arrays.items():
        parts = key.split("/")
        if parts[0] != prefix:
            continue
        node = root
        for p in parts[1:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


def _artifact_metadata(model_cfg, *, max_words: int, video_shape,
                       step: int, source: str, arrays: dict,
                       format_version: int) -> dict:
    """Shared metadata assembly for the f32 and quantized formats:
    sanitized model config, tokenizer contract, video shape and the
    per-array dtype manifest."""
    from milnce_tpu.config import parse_conv_impl_map

    model_meta = dataclasses.asdict(model_cfg)
    model_meta["word2vec_path"] = ""        # table already lives in params
    impl_map = parse_conv_impl_map(model_meta.get("conv_impl_map", ""))
    model_meta["conv_impl_map"] = ",".join(  # resolve file specs inline
        f"{s}={i}" for s, i in sorted(impl_map.items()))
    token_dict = model_meta.pop("token_dict_path", "")
    return {
        "format_version": int(format_version),
        "generator": "milnce-export (milnce_tpu/serving/export.py)",
        "step": int(step),
        "source_checkpoint": source,
        "model": model_meta,
        "tokenizer": {"max_words": int(max_words),
                      "vocab_size": int(model_meta["vocab_size"]),
                      "token_dict_path": token_dict},
        "video_shape": [int(d) for d in video_shape],
        "param_bytes": int(sum(v.nbytes for v in arrays.values())),
        # per-array dtype manifest: the on-disk precision contract a
        # loader (and scripts/precision_audit.py's quant-readiness
        # report) can audit without opening the npz — float leaves are
        # f32 (or int8, in the quantized format) by construction,
        # everything else ships as stored
        "array_dtypes": {k: str(v.dtype) for k, v in arrays.items()},
    }


def export_inference_checkpoint(out_dir: str, params, batch_stats,
                                model_cfg, *, max_words: int,
                                video_shape, step: int = 0,
                                source: str = "") -> str:
    """Write the frozen artifact; returns ``out_dir``.

    ``model_cfg`` is a ``milnce_tpu.config.ModelConfig``; host-specific
    fields (word2vec/token-dict paths, impl-map file paths) are
    sanitized so the artifact is self-contained."""
    os.makedirs(out_dir, exist_ok=True)
    arrays = _flatten(params, "params")
    arrays.update(_flatten(batch_stats, "batch_stats"))
    # float leaves stored f32 (bf16 is a load-time cast; f64 never ships)
    arrays = {k: (v.astype(np.float32)
                  if np.issubdtype(v.dtype, np.floating) else v)
              for k, v in arrays.items()}
    np.savez(os.path.join(out_dir, ARRAYS_FILE), **arrays)
    meta = _artifact_metadata(model_cfg, max_words=max_words,
                              video_shape=video_shape, step=step,
                              source=source, arrays=arrays,
                              format_version=FORMAT_VERSION)
    with open(os.path.join(out_dir, METADATA_FILE), "w") as fh:
        json.dump(meta, fh, indent=2, sort_keys=True)
    return out_dir


def export_quantized_checkpoint(out_dir: str, qvariables, model_cfg, *,
                                max_words: int, video_shape,
                                step: int = 0, source: str = "",
                                calibration: dict | None = None) -> str:
    """Write a quantized edge-tier artifact; returns ``out_dir``.

    ``qvariables`` is ``quant.quantize_variables`` output:
    ``{'params': <int8 where quantized>, 'batch_stats': <f32>,
    'quant_scales': {'params/<path>': f32 scale}}``.  int8 leaves ship
    bit-exact (pinned by the round-trip test); float leaves coerce to
    f32 exactly like the v1 format.  ``calibration`` is the JSON-safe
    block ``quant.calibrate.calibrate_and_quantize`` returns."""
    os.makedirs(out_dir, exist_ok=True)
    arrays = _flatten(qvariables["params"], "params")
    arrays.update(_flatten(qvariables["batch_stats"], "batch_stats"))
    arrays = {k: (v if v.dtype == np.int8 else
                  (v.astype(np.float32)
                   if np.issubdtype(v.dtype, np.floating) else v))
              for k, v in arrays.items()}
    scales = qvariables.get("quant_scales", {})
    for key, scale in scales.items():
        arrays[f"{SCALES_PREFIX}/{key}"] = np.asarray(scale, np.float32)
    np.savez(os.path.join(out_dir, ARRAYS_FILE), **arrays)
    meta = _artifact_metadata(model_cfg, max_words=max_words,
                              video_shape=video_shape, step=step,
                              source=source, arrays=arrays,
                              format_version=QUANT_FORMAT_VERSION)
    meta["quant"] = {
        "scheme": "symmetric-int8",
        "n_quantized": len(scales),
        "per_channel": sorted(
            k for k, s in scales.items() if np.asarray(s).ndim),
        "calibration": calibration or {},
    }
    with open(os.path.join(out_dir, METADATA_FILE), "w") as fh:
        json.dump(meta, fh, indent=2, sort_keys=True)
    return out_dir


def read_export_metadata(export_dir: str) -> dict:
    """Metadata alone (no arrays): how a loader decides which format
    family an artifact is before touching the npz."""
    with open(os.path.join(export_dir, METADATA_FILE)) as fh:
        return json.load(fh)


def load_quantized_checkpoint(export_dir: str) -> tuple[dict, dict]:
    """Read a quantized export -> (metadata, ``{'params',
    'batch_stats', 'quant_scales'}`` variables tree).  Every array is
    checked against the on-disk ``array_dtypes`` manifest — the
    bit-exactness contract is only as good as the dtype it round-trips
    at."""
    meta = read_export_metadata(export_dir)
    if meta.get("format_version") != QUANT_FORMAT_VERSION:
        raise ValueError(
            f"quantized export format {meta.get('format_version')!r} "
            f"unsupported (this build reads {QUANT_FORMAT_VERSION})")
    meta["model"].pop("token_dict_path", None)
    with np.load(os.path.join(export_dir, ARRAYS_FILE)) as z:
        arrays = {k: z[k] for k in z.files}
    manifest = meta.get("array_dtypes", {})
    for key, value in arrays.items():
        want = manifest.get(key)
        if want is not None and str(value.dtype) != want:
            raise ValueError(f"array {key!r} is {value.dtype}, manifest "
                             f"says {want} — corrupt or rewritten npz")
    prefix = SCALES_PREFIX + "/"
    scales = {k[len(prefix):]: v for k, v in arrays.items()
              if k.startswith(prefix)}
    return meta, {"params": _unflatten(arrays, "params"),
                  "batch_stats": _unflatten(arrays, "batch_stats"),
                  "quant_scales": scales}


def load_inference_checkpoint(export_dir: str) -> tuple[dict, dict]:
    """Read an export -> (metadata dict, ``{'params', 'batch_stats'}``
    variables tree of host numpy arrays)."""
    with open(os.path.join(export_dir, METADATA_FILE)) as fh:
        meta = json.load(fh)
    if meta.get("format_version") != FORMAT_VERSION:
        hint = (" — a quantized artifact; load with "
                "load_quantized_checkpoint"
                if meta.get("format_version") == QUANT_FORMAT_VERSION
                else "")
        raise ValueError(f"export format {meta.get('format_version')!r} "
                         f"unsupported (this build reads {FORMAT_VERSION}"
                         f"){hint}")
    # ModelConfig round-trips through JSON minus the serve-sanitized field
    meta["model"].pop("token_dict_path", None)
    with np.load(os.path.join(export_dir, ARRAYS_FILE)) as z:
        arrays = {k: z[k] for k in z.files}
    return meta, {"params": _unflatten(arrays, "params"),
                  "batch_stats": _unflatten(arrays, "batch_stats")}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def _restore_inference_subset(checkpoint_dir: str,
                              epoch: Optional[int]) -> tuple[int, dict]:
    """(step, {'params', 'batch_stats'}) from a training run directory —
    metadata-templated restore, so no model build and no optimizer I/O."""
    from milnce_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(checkpoint_dir, create=False)
    try:
        label, raw = mgr.restore_raw(epoch,
                                     subtrees={"step", "params",
                                               "batch_stats"})
    finally:
        mgr.close()
    if not isinstance(raw, dict):           # TrainState restored as object
        raw = {"step": raw.step, "params": raw.params,
               "batch_stats": raw.batch_stats}
    step = int(np.asarray(raw["step"])) if "step" in raw else int(label)
    return step, {"params": raw["params"],
                  "batch_stats": raw.get("batch_stats", {})}


def main(argv=None) -> None:
    from milnce_tpu.config import PRESETS, _add_dataclass_args

    ap = argparse.ArgumentParser(
        description="Export a params-only inference checkpoint "
                    "(milnce_tpu/serving/export.py)")
    ap.add_argument("--checkpoint_dir", required=True,
                    help="training run directory (Orbax)")
    ap.add_argument("--out", required=True, help="export directory to write")
    ap.add_argument("--epoch", type=int, default=None,
                    help="checkpoint label to export (default: latest)")
    ap.add_argument("--preset", choices=sorted(PRESETS), default="full",
                    help="model/data config the run was trained with")
    base = PRESETS["full"]()
    _add_dataclass_args(ap, "model.", base.model)
    _add_dataclass_args(ap, "data.", base.data)
    ns = ap.parse_args(argv)

    cfg = PRESETS[ns.preset]()
    for key, val in vars(ns).items():
        if "." in key and val is not None:
            section, _, fname = key.partition(".")
            setattr(getattr(cfg, section), fname, val)

    step, tree = _restore_inference_subset(ns.checkpoint_dir, ns.epoch)
    video_shape = (cfg.data.num_frames, cfg.data.video_size,
                   cfg.data.video_size, 3)
    out = export_inference_checkpoint(
        ns.out, tree["params"], tree["batch_stats"], cfg.model,
        max_words=cfg.data.max_words, video_shape=video_shape, step=step,
        source=os.path.abspath(ns.checkpoint_dir))
    meta_path = os.path.join(out, METADATA_FILE)
    print(f"exported step {step} -> {out} ({meta_path})")


if __name__ == "__main__":
    main()
