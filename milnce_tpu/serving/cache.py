"""LRU text-embedding cache keyed by the exact token-id tuple.

Production query streams are heavy-tailed — the same captions and
search phrases recur constantly — and a text-tower forward costs a
device dispatch per miss.  Caching at the *token-id* level (not the raw
string) means the key is exactly what determines the embedding: two
strings that tokenize identically share an entry, and tokenizer config
changes can never serve a stale vector for a new id sequence.

numpy-only on purpose: the cache sits on the request path *in front of*
the batcher, so a hit never touches jax at all — no dispatch, no
transfer, no bucket slot consumed.

Thread safety: every public method takes the internal lock; stored
arrays are marked read-only so a caller mutating a returned row cannot
poison later hits.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from milnce_tpu.analysis.lockrt import make_lock


def token_key(row: np.ndarray) -> tuple:
    """(W,) int row -> hashable cache key.  The FULL padded row is the
    key (pad ids included): the text tower consumes the padded row, so
    the row is the complete input signature."""
    return tuple(int(t) for t in row)


class EmbeddingLRUCache:
    """Bounded LRU map: token-id tuple -> (D,) embedding row.

    ``capacity <= 0`` disables the cache (get always misses, put is a
    no-op) — one code path for cache-on and cache-off deployments.
    """

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._data: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self._lock = make_lock("serving.cache")
        self._hits = 0
        self._misses = 0

    def get(self, key: tuple) -> np.ndarray | None:
        with self._lock:
            row = self._data.get(key)
            if row is None:
                self._misses += 1
                return None
            self._data.move_to_end(key)
            self._hits += 1
            return row

    def put(self, key: tuple, value: np.ndarray) -> None:
        if self.capacity <= 0:
            return
        value = np.array(value, copy=True)
        value.setflags(write=False)
        with self._lock:
            self._data[key] = value
            self._data.move_to_end(key)
            while len(self._data) > self.capacity:
                self._data.popitem(last=False)

    def clear(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def stats(self) -> dict:
        with self._lock:
            hits, misses, size = self._hits, self._misses, len(self._data)
        total = hits + misses
        return {
            "hits": hits,
            "misses": misses,
            "size": size,
            "capacity": self.capacity,
            "hit_rate": (hits / total) if total else 0.0,
        }
