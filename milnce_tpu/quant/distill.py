"""Distilled student text tower for the edge tier.

The text path is an embedding lookup plus a two-layer MLP
(models/text.py), so distillation is cheap: keep the teacher's frozen
word table (the lookup is under ``stop_gradient`` in teacher AND
student — reference s3dg.py:199-200), shrink the fat 2048-d hidden
layer, and regress the student's sentence embeddings onto frozen
teacher embeddings over synthetic caption batches.  The student stays
in the teacher's embedding SPACE (same ``embd_dim``), so the shared
video tower, the retrieval index and every serving surface work
unchanged — a student export is just an ordinary ``milnce-export``
artifact with a thinner ``text_hidden_dim`` in its model metadata.

No new training machinery: optax Adam + ``jax.value_and_grad`` on a
jitted step, deterministic ``np.random.default_rng(seed)`` batches —
the same recipe train/state.py uses, minus the mesh (the student is
tiny; distillation is a host-side offline pass like quantization)."""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from milnce_tpu.models.text import SentenceEmbedding


def student_model_config(teacher_cfg, hidden_dim: int):
    """Teacher ModelConfig -> student ModelConfig: only the text hidden
    width changes, so ``build_model`` on the export metadata
    reconstructs the student's shapes exactly."""
    return dataclasses.replace(teacher_cfg, text_hidden_dim=hidden_dim)


def build_student_variables(teacher_variables, student_params) -> dict:
    """Graft trained student text params into the full-model tree: the
    video tower and batch_stats are the teacher's, ``text_module`` is
    the student's — the tree a student export ships."""
    params = dict(teacher_variables["params"])
    params["text_module"] = student_params
    return {"params": params,
            "batch_stats": teacher_variables["batch_stats"]}


def _sample_tokens(rng: np.random.Generator, batch: int, max_words: int,
                   vocab_size: int) -> np.ndarray:
    """Synthetic caption batch: uniform token ids with variable length,
    pad id 0 on the tail (the contract models/text.py documents — pad
    rows participate in the word-axis max, so the student must see
    them at train time too)."""
    ids = rng.integers(1, vocab_size, size=(batch, max_words),
                       dtype=np.int64)
    lengths = rng.integers(1, max_words + 1, size=batch)
    ids[np.arange(max_words)[None, :] >= lengths[:, None]] = 0
    return ids.astype(np.int32)


def distill_text_student(model, variables, *, max_words: int,
                         hidden_dim: int | None = None,
                         steps: int = 200, batch_size: int = 32,
                         learning_rate: float = 1e-2,
                         seed: int = 0) -> tuple[dict, dict]:
    """Distill -> (student ``text_module`` params, info dict).

    ``model``/``variables`` are the full f32 teacher.  The student
    copies the teacher's (frozen) word table and ``embd_dim``;
    ``hidden_dim`` defaults to a quarter of the teacher's hidden
    width.  Deterministic under fixed ``seed``."""
    import jax
    import jax.numpy as jnp
    import optax

    from milnce_tpu.models.text import word2vec_embedding_init

    teacher_text = variables["params"]["text_module"]
    word_table = np.asarray(teacher_text["word_embd"]["embedding"])
    vocab_size, word_dim = word_table.shape
    embd_dim = int(np.asarray(teacher_text["fc2"]["kernel"]).shape[-1])
    teacher_hidden = int(
        np.asarray(teacher_text["fc1"]["kernel"]).shape[-1])
    if hidden_dim is None:
        hidden_dim = max(8, teacher_hidden // 4)

    student = SentenceEmbedding(
        embd_dim=embd_dim, vocab_size=vocab_size,
        word_embedding_dim=word_dim, hidden_dim=hidden_dim,
        embedding_init=word2vec_embedding_init(word_table))

    rng = np.random.default_rng(seed)
    init_ids = np.zeros((1, max_words), np.int32)
    params = student.init(jax.random.PRNGKey(seed), init_ids)["params"]
    opt = optax.adam(learning_rate)
    opt_state = opt.init(params)

    teacher_fn = jax.jit(
        lambda ids: model.apply(variables, None, ids, mode="text"))

    def loss_fn(p, ids, target):
        pred = student.apply({"params": p}, ids)
        mse = jnp.mean((pred - target) ** 2)
        cos = jnp.sum(pred * target, axis=-1) / (
            jnp.linalg.norm(pred, axis=-1)
            * jnp.linalg.norm(target, axis=-1) + 1e-12)
        return mse + (1.0 - jnp.mean(cos)), jnp.mean(cos)

    # params + opt state are consumed each step — donate both so the
    # distill loop never holds two copies of the student (GL003)
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def train_step(p, o, ids, target):
        (loss, cos), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(p, ids, target)
        updates, o = opt.update(grads, o, p)
        return optax.apply_updates(p, updates), o, loss, cos

    loss = cos = float("nan")
    for _ in range(steps):
        ids = _sample_tokens(rng, batch_size, max_words, vocab_size)
        target = teacher_fn(ids)
        params, opt_state, loss, cos = train_step(params, opt_state,
                                                  ids, target)
    info = {
        "hidden_dim": int(hidden_dim),
        "teacher_hidden_dim": teacher_hidden,
        "word_embedding_dim": int(word_dim),
        "embd_dim": embd_dim,
        "steps": int(steps),
        "batch_size": int(batch_size),
        "seed": int(seed),
        "final_loss": float(loss),
        "final_cosine": float(cos),
    }
    return jax.device_get(params), info
