"""Edge-tier quantization subsystem (ROADMAP item 5).

Three pieces, layered on the serving export family:

- :mod:`milnce_tpu.quant.quantize` — weight-only symmetric int8
  post-training quantization (per-tensor or per-channel scales chosen
  by the NUMERICS.md readiness rule) plus the duck-typed
  ``QuantizedModel`` wrapper the serving engine runs unchanged.
- :mod:`milnce_tpu.quant.calibrate` — the calibration pass: activation
  ranges over held-out clips/captions, embedding-space quality stats
  vs the f32 teacher, and the NUMERICS.md verdict reader that seeds
  the per-channel key set.
- :mod:`milnce_tpu.quant.distill` — the distilled student text tower
  (frozen word table + thinner MLP trained against frozen teacher
  embeddings), grafted back into a full-model variables tree so it
  exports/serves through the exact same machinery.
"""

from milnce_tpu.quant.quantize import (  # noqa: F401
    OUTLIER_FRACTION, PER_CHANNEL_RATIO, QUANT_SCHEME, QuantizedModel,
    dequantize_array, dequantize_params, quantize_array,
    quantize_variables, weight_readiness_row)
