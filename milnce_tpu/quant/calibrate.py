"""Post-training calibration pass for the int8 edge tier.

``calibrate_and_quantize`` is the offline entry: it quantizes a
{'params', 'batch_stats'} f32 tree with per-channel scales seeded from
the COMMITTED NUMERICS.md readiness verdicts (the
scripts/precision_audit.py table — single rule source in
quant/quantize.py), runs held-out clips/captions through both towers
to record per-layer activation absmax ranges, and measures the
embedding-space damage (cosine to the f32 teacher, top-k rank
agreement) that the export's ``quant.calibration`` metadata block then
carries — so a serving host can audit what a quantized artifact cost
WITHOUT re-running calibration.

Activation ranges are collected with flax ``capture_intermediates``:
weight-only int8 doesn't need them to serve (no activation is ever
quantized), but they are exactly the data a future w8a8 step needs,
and recording them at calibration time costs two forward passes."""

from __future__ import annotations

import os
import re

import numpy as np

from milnce_tpu.quant.quantize import (QUANT_SCHEME, QuantizedModel,
                                       _path_key,
                                       per_channel_keys_from_weights,
                                       quantize_variables)

# NUMERICS.md readiness-table row: | `params/...` | ... | <verdict> |
_VERDICT_ROW = re.compile(r"^\|\s*`(params/[^`]+)`\s*\|.*\|\s*"
                          r"(\*{0,2}per-channel\*{0,2}|per-tensor ok)"
                          r"\s*\|\s*$")


def read_numerics_verdicts(report_path: str) -> dict[str, bool]:
    """Parse the committed NUMERICS.md quantization-readiness table ->
    {'params/<layer>': needs_per_channel}.  Empty dict when the file
    has no readiness section (pre-Pass-5 tree) — callers fall back to
    computing verdicts from the weights directly."""
    verdicts: dict[str, bool] = {}
    with open(report_path) as fh:
        for line in fh:
            m = _VERDICT_ROW.match(line.strip())
            if m:
                verdicts[m.group(1)] = "per-channel" in m.group(2)
    return verdicts


def collect_activation_ranges(model, variables, *, video_batches=(),
                              text_batches=()) -> dict[str, float]:
    """Per-submodule activation absmax over the calibration batches ->
    {'<tower>/<module path>': absmax}.  Ranges max-reduce across
    batches (calibration wants the envelope, not the mean)."""
    import jax

    ranges: dict[str, float] = {}

    def _absorb(tower: str, intermediates) -> None:
        flat, _ = jax.tree_util.tree_flatten_with_path(intermediates)
        for path, leaf in flat:
            key = f"{tower}/{_path_key(path)}"
            absmax = float(np.abs(np.asarray(leaf)).max())
            ranges[key] = max(ranges.get(key, 0.0), absmax)

    for video in video_batches:
        _, aux = model.apply(variables, np.asarray(video, np.float32),
                             None, mode="video",
                             capture_intermediates=True,
                             mutable=["intermediates"])
        _absorb("video", aux["intermediates"])
    for tokens in text_batches:
        _, aux = model.apply(variables, None,
                             np.asarray(tokens, np.int32), mode="text",
                             capture_intermediates=True,
                             mutable=["intermediates"])
        _absorb("text", aux["intermediates"])
    return ranges


def _rank_agreement(ref: np.ndarray, test: np.ndarray, k: int) -> float:
    """Mean top-k overlap between two (Q, N) similarity matrices —
    the retrieval-facing half of the quality report (cosine alone can
    look fine while rankings reshuffle)."""
    k = min(k, ref.shape[1])
    ref_top = np.argsort(-ref, axis=1)[:, :k]
    test_top = np.argsort(-test, axis=1)[:, :k]
    hits = [len(set(r) & set(t)) / k
            for r, t in zip(ref_top, test_top)]
    return float(np.mean(hits))


def quantization_quality(model, variables, qvariables, *,
                         video_batches=(), text_batches=(),
                         k: int = 10) -> dict:
    """Embedding-space damage report: per-row cosine between f32 and
    int8 embeddings for each tower, plus text->video top-k rank
    agreement when both modalities were supplied."""
    qmodel = QuantizedModel(model)

    def _cos(a: np.ndarray, b: np.ndarray) -> np.ndarray:
        num = (a * b).sum(axis=-1)
        den = (np.linalg.norm(a, axis=-1)
               * np.linalg.norm(b, axis=-1) + 1e-12)
        return num / den

    out: dict = {"scheme": QUANT_SCHEME}
    ref_v = ref_t = q_v = q_t = None
    if video_batches:
        video = np.concatenate([np.asarray(b, np.float32)
                                for b in video_batches])
        ref_v = np.asarray(model.apply(variables, video, None,
                                       mode="video"))
        q_v = np.asarray(qmodel.apply(qvariables, video, None,
                                      mode="video"))
        cos = _cos(ref_v, q_v)
        out["video_cosine_mean"] = float(cos.mean())
        out["video_cosine_min"] = float(cos.min())
    if text_batches:
        tokens = np.concatenate([np.asarray(b, np.int32)
                                 for b in text_batches])
        ref_t = np.asarray(model.apply(variables, None, tokens,
                                       mode="text"))
        q_t = np.asarray(qmodel.apply(qvariables, None, tokens,
                                      mode="text"))
        cos = _cos(ref_t, q_t)
        out["text_cosine_mean"] = float(cos.mean())
        out["text_cosine_min"] = float(cos.min())
    if ref_v is not None and ref_t is not None:
        out[f"rank_agreement_top{k}"] = _rank_agreement(
            ref_t @ ref_v.T, q_t @ q_v.T, k)
    return out


def calibrate_and_quantize(model, variables, *, video_batches=(),
                           text_batches=(), per_channel_keys=None,
                           numerics_report: str = "",
                           k: int = 10) -> tuple[dict, dict]:
    """The full offline pass -> (quantized variables tree, JSON-safe
    calibration metadata block for the quantized export).

    ``per_channel_keys=None`` (the default) reads the committed
    NUMERICS.md verdicts when ``numerics_report`` names one, else
    derives them from the weights with the same rule."""
    if per_channel_keys is None:
        verdicts = (read_numerics_verdicts(numerics_report)
                    if numerics_report and os.path.exists(numerics_report)
                    else {})
        if verdicts:
            # intersect with what this model can actually quantize: a
            # committed report may cover another preset's layers (or a
            # stale table may still carry non-quantizable 1-D rows),
            # and quantize_variables is LOUD about unknown keys by
            # design — the report is a default, not a command
            import jax

            from milnce_tpu.quant.quantize import _should_quantize

            flat, _ = jax.tree_util.tree_flatten_with_path(
                variables["params"])
            quantizable = {
                "params/" + _path_key(path) for path, leaf in flat
                if _should_quantize(leaf)}
            per_channel_keys = tuple(sorted(
                key for key, pc in verdicts.items()
                if pc and key in quantizable))
            verdict_source = numerics_report
        else:
            per_channel_keys = per_channel_keys_from_weights(
                variables["params"])
            verdict_source = "weights (readiness rule, no report)"
    else:
        per_channel_keys = tuple(sorted(per_channel_keys))
        verdict_source = "caller"

    qvariables = quantize_variables(variables,
                                    per_channel_keys=per_channel_keys)
    calibration = {
        "scheme": QUANT_SCHEME,
        "per_channel": list(per_channel_keys),
        "verdict_source": verdict_source,
        "n_video_batches": len(video_batches),
        "n_text_batches": len(text_batches),
    }
    if video_batches or text_batches:
        ranges = collect_activation_ranges(
            model, variables, video_batches=video_batches,
            text_batches=text_batches)
        # the envelope summary ships in metadata; the full per-module
        # dict is large and only the extremes steer a future w8a8 pass
        calibration["activation_absmax_max"] = (
            max(ranges.values()) if ranges else 0.0)
        calibration["activation_ranges"] = {
            key: round(val, 6) for key, val in sorted(
                ranges.items(), key=lambda kv: -kv[1])[:16]}
        calibration["quality"] = quantization_quality(
            model, variables, qvariables,
            video_batches=video_batches, text_batches=text_batches,
            k=k)
    return qvariables, calibration
