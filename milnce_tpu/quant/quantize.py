"""Weight-only symmetric int8 quantization for the embed towers.

The scheme is deliberately the simplest one that serves: every float
param with ndim >= 2 (conv/dense kernels and the word-embedding table)
is stored as int8 with a float32 scale — scalar for per-tensor, one
per output channel (the LAST axis, matching the NUMERICS.md readiness
table) when the readiness rule says a single scale would waste bits.
Biases, BatchNorm affine params and all ``batch_stats`` stay f32: they
are a rounding error of the artifact size and the f32-residency set
(analysis/numerics.py GL015) must hold regardless of the weight store.

At serve time :class:`QuantizedModel` dequantizes INSIDE the jitted
embed program (``q.astype(f32) * scale`` then the ordinary f32
``dot_general``): int8 weights are what lives in HBM, accumulation is
f32 by construction — no GL016 low-precision-accumulation site exists
anywhere on the path, which the ``serve_quant_*`` trace-invariant and
numerics census entries pin.

The readiness rule (PER_CHANNEL_RATIO / OUTLIER_FRACTION /
last-axis-channel) lives HERE as the single source;
``scripts/precision_audit.py`` imports it, so the committed NUMERICS.md
verdicts and the calibration defaults can never drift apart.
"""

from __future__ import annotations

import numpy as np

QUANT_SCHEME = "symmetric-int8"

# Readiness thresholds (shared with scripts/precision_audit.py, which
# renders them into NUMERICS.md).  A layer whose per-output-channel
# absmax spread exceeds the ratio needs per-channel scales (one
# per-tensor scale wastes log2(ratio) of int8's 8 bits on the quiet
# channels); a layer with heavy >6-sigma outliers wants per-channel
# treatment for the same reason — the outlier sets the scale.
PER_CHANNEL_RATIO = 4.0
OUTLIER_FRACTION = 1e-3

_QMAX = 127.0     # symmetric: int8 range [-127, 127], zero-point 0


def weight_readiness_row(key: str, arr: np.ndarray) -> dict:
    """One quantization-readiness row for a weight array: dynamic
    range, >6-sigma outlier ratio, per-channel absmax spread and the
    per-channel verdict.  Pure host numpy — the single source for both
    the NUMERICS.md table and the calibration defaults."""
    arr = np.asarray(arr)
    absmax = float(np.abs(arr).max()) if arr.size else 0.0
    std = float(arr.std()) if arr.size else 0.0
    outliers = (float((np.abs(arr) > 6 * std).mean())
                if std > 0 else 0.0)
    if arr.ndim >= 2:
        ch = np.abs(arr.reshape(-1, arr.shape[-1])).max(axis=0)
        med = float(np.median(ch))
        ratio = float(ch.max() / med) if med > 0 else float("inf")
    else:
        ratio = 1.0
    return dict(
        key=key, shape=list(arr.shape), absmax=absmax, std=std,
        outlier_ratio=outliers, channel_range_ratio=ratio,
        per_channel=(ratio > PER_CHANNEL_RATIO
                     or outliers > OUTLIER_FRACTION))


def quantize_array(arr: np.ndarray,
                   per_channel: bool = False) -> tuple[np.ndarray,
                                                       np.ndarray]:
    """f32 array -> (int8 array, f32 scale).  Per-channel scales are
    one per LAST-axis slice (the output channel of every kernel layout
    in this model), shape (C,), broadcastable against the weight."""
    arr = np.asarray(arr, dtype=np.float32)
    if per_channel:
        if arr.ndim < 2:
            raise ValueError("per-channel quantization needs ndim >= 2, "
                             f"got shape {arr.shape}")
        absmax = np.abs(arr.reshape(-1, arr.shape[-1])).max(axis=0)
    else:
        absmax = np.abs(arr).max()
    scale = np.asarray(absmax, np.float32) / _QMAX
    # all-zero tensors/channels: scale 1 keeps the round-trip exact
    # (0 * 1 = 0) instead of dividing by zero
    scale = np.where(scale == 0, np.float32(1.0), scale).astype(np.float32)
    q = np.clip(np.rint(arr / scale), -_QMAX, _QMAX).astype(np.int8)
    return q, scale


def dequantize_array(q, scale):
    """Inverse of :func:`quantize_array` (host or traced — works on
    numpy and jax arrays alike; per-channel (C,) scales broadcast over
    the (..., C) weight)."""
    return q.astype(np.float32) * scale if isinstance(q, np.ndarray) \
        else _jax_dequant(q, scale)


def _jax_dequant(q, scale):
    import jax.numpy as jnp

    return q.astype(jnp.float32) * scale


def _path_key(path) -> str:
    """jax key-path -> '/'-joined name (mirror of serving/export.py's
    ``_key_name`` — duplicated locally so quant/ never imports the
    export module it feeds)."""
    names = []
    for k in path:
        for attr in ("key", "name", "idx"):
            if hasattr(k, attr):
                names.append(str(getattr(k, attr)))
                break
        else:
            names.append(str(k))
    return "/".join(names)


def _should_quantize(leaf) -> bool:
    leaf = np.asarray(leaf)
    return (leaf.dtype.kind == "f" and leaf.ndim >= 2 and leaf.size > 0)


def per_channel_keys_from_weights(params) -> tuple[str, ...]:
    """Apply the readiness rule directly to a params tree -> the
    'params/...'-keyed set that needs per-channel scales.  The offline
    path reads the committed NUMERICS.md instead
    (calibrate.read_numerics_verdicts); this is the fallback when no
    report is on disk."""
    import jax

    keys = []
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    for path, leaf in flat:
        if not _should_quantize(leaf):
            continue
        key = "params/" + _path_key(path)
        if weight_readiness_row(key, np.asarray(leaf))["per_channel"]:
            keys.append(key)
    return tuple(sorted(keys))


def quantize_variables(variables, *,
                       per_channel_keys=()) -> dict:
    """{'params', 'batch_stats'} f32 tree -> quantized variables tree
    ``{'params': <int8 where quantized>, 'batch_stats': <f32>,
    'quant_scales': {'params/<path>': f32 scale}}``.

    ``per_channel_keys`` are 'params/...'-style keys (NUMERICS.md
    readiness-table spelling); every other quantized leaf gets one
    per-tensor scale."""
    import jax

    per_channel = frozenset(per_channel_keys)
    scales: dict[str, np.ndarray] = {}

    flat, treedef = jax.tree_util.tree_flatten_with_path(
        variables["params"])
    out = []
    for path, leaf in flat:
        if not _should_quantize(leaf):
            out.append(np.asarray(leaf))
            continue
        key = "params/" + _path_key(path)
        q, scale = quantize_array(np.asarray(leaf),
                                  per_channel=key in per_channel)
        scales[key] = scale
        out.append(q)
    unknown = per_channel - set(scales)
    if unknown:
        raise ValueError("per_channel_keys name layers that are not "
                         f"quantizable params: {sorted(unknown)}")
    return {
        "params": jax.tree_util.tree_unflatten(treedef, out),
        "batch_stats": jax.tree_util.tree_map(np.asarray,
                                              variables["batch_stats"]),
        "quant_scales": scales,
    }


def dequantize_params(params, scales):
    """Quantized params tree + flat scales dict -> f32 params tree.
    Traceable: inside a jitted program this lowers to int8 HBM reads +
    one convert_element_type per quantized leaf."""
    import jax

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    out = []
    for path, leaf in flat:
        key = "params/" + _path_key(path)
        scale = scales.get(key)
        out.append(leaf if scale is None
                   else dequantize_array(leaf, scale))
    return jax.tree_util.tree_unflatten(treedef, out)


class QuantizedModel:
    """Duck-typed model wrapper serving a quantized variables tree.

    The embed programs (train/step.py ``make_text_embed_fn`` /
    ``make_video_embed_fn``) touch exactly two attributes of a model:
    ``apply`` and ``dtype``.  This wrapper provides both, dequantizing
    ``variables['params']`` with ``variables['quant_scales']`` before
    delegating to the wrapped flax module — so the serving engine,
    bucket ladder, warmup sweep, recompile accounting and replica pool
    all run a quantized export with zero special cases."""

    def __init__(self, model):
        self.model = model

    @property
    def dtype(self):
        import jax.numpy as jnp

        return getattr(self.model, "dtype", jnp.float32)

    def apply(self, variables, *args, **kwargs):
        variables = dict(variables)
        scales = variables.pop("quant_scales", {})
        variables["params"] = dequantize_params(variables["params"],
                                                scales)
        return self.model.apply(variables, *args, **kwargs)
