"""Hard (exact) DTW with path backtracking — eval-only utility.

Re-design of the reference dtw.py:5-75 (python cell loops on GPU tensors)
as a skewed `lax.scan` DP + a `fori_loop` backtrack, fully jittable.

Loss semantics (dtw.py:73-75): with the optimal path P,
``logsumexp_j(sum_i cost*P) - logsumexp_j(sum_i cost)``; the min/backtrack
is detached so gradients flow through the cost only (dtw.py:52).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from milnce_tpu.ops.softdtw import BIG, skew_cost, _cosine_sim


def dtw_table(cost: jax.Array) -> jax.Array:
    """(B, N, M) cost -> (B, N, M) accumulated-cost table
    tc[i,j] = cost[i,j] + min(tc[i-1,j-1], tc[i-1,j], tc[i,j-1])."""
    bsz, n, m = cost.shape
    d_skew = skew_cost(cost)
    i_buf = jnp.arange(n + 1)
    init_mm = jnp.full((bsz, n + 1), BIG, cost.dtype).at[:, 0].set(0.0)
    init_m = jnp.full((bsz, n + 1), BIG, cost.dtype)

    def step(carry, inputs):
        r_mm, r_m = carry
        cost_row, p = inputs
        best = jnp.minimum(jnp.minimum(r_mm[:, :-1], r_m[:, :-1]), r_m[:, 1:])
        r_new = jnp.concatenate(
            [jnp.full((bsz, 1), BIG, cost.dtype), cost_row + best], axis=1)
        j_buf = p - i_buf
        valid = (i_buf >= 1) & (j_buf >= 1) & (j_buf <= m)
        r_new = jnp.where(valid[None], r_new, BIG)
        return (r_m, r_new), r_new

    diag_ids = jnp.arange(2, n + m + 1)
    _, diags = lax.scan(step, (init_mm, init_m),
                        (d_skew.transpose(1, 0, 2), diag_ids))
    # un-skew: tc[i, j] lives at diags[i + j, i + 1]
    i_idx = jnp.arange(n)[:, None]
    j_idx = jnp.arange(m)[None, :]
    return diags[i_idx + j_idx, :, i_idx + 1].transpose(2, 0, 1)


def dtw_path(cost: jax.Array) -> jax.Array:
    """Backtrack the optimal alignment path (reference dtw.py:56-72),
    stopping at the first border hit, always marking (0, 0).

    The reference picks the predecessor by exact float equality
    (tc[i,j] - cost[i,j] == tc[pred]) and prints 'error' when rounding
    makes none match (dtw.py:60-71); we pick argmin of the three
    predecessors (diag preferred on ties, like the reference's check
    order), which is the same path in exact arithmetic and robust in
    float32."""
    tc = dtw_table(cost)
    bsz, n, m = cost.shape

    def one(tc_b, cost_b):
        path = jnp.zeros((n, m), cost.dtype).at[n - 1, m - 1].set(1.0)

        def body(_, state):
            i, j, path, stopped = state
            stalled = (i == 0) | (j == 0)
            p_diag = tc_b[i - 1, j - 1]
            p_up = tc_b[i - 1, j]
            p_left = tc_b[i, j - 1]
            best = jnp.minimum(jnp.minimum(p_diag, p_up), p_left)
            take_diag = p_diag == best
            take_up = (~take_diag) & (p_up == best)
            ni = jnp.where(take_diag | take_up, i - 1, i)
            nj = jnp.where(take_diag, j - 1, jnp.where(take_up, j, j - 1))
            move = ~(stopped | stalled)
            ni = jnp.where(move, ni, i)
            nj = jnp.where(move, nj, j)
            path = jnp.where(move, path.at[ni, nj].set(1.0), path)
            return ni, nj, path, stopped | stalled

        _, _, path, _ = lax.fori_loop(0, n + m, body,
                                      (n - 1, m - 1, path, False))
        return path.at[0, 0].set(1.0)

    return jax.vmap(one)(tc, cost)


def dtw_loss(x: jax.Array, y: jax.Array) -> jax.Array:
    """Reference DTW.forward (dtw.py:22-75) on cosine distance:
    x, y: (B, N, D), (B, M, D) -> (B,)."""
    cost = 1.0 - _cosine_sim(x, y, 1e-8)
    path = lax.stop_gradient(dtw_path(cost))
    pos = jax.nn.logsumexp(jnp.sum(cost * path, axis=1), axis=1)
    neg = jax.nn.logsumexp(jnp.sum(cost, axis=1), axis=1)
    return pos - neg
