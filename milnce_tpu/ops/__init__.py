from milnce_tpu.ops.softdtw import SoftDTW, softdtw_scan  # noqa: F401
from milnce_tpu.ops.softdtw_pallas import softdtw_pallas  # noqa: F401
from milnce_tpu.ops.dtw import dtw_loss  # noqa: F401
