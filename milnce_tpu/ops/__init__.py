from milnce_tpu.ops.softdtw import SoftDTW, softdtw_scan  # noqa: F401
from milnce_tpu.ops import softdtw_pallas  # noqa: F401  (submodule; its
# main entry point is softdtw_pallas.softdtw_pallas — re-exporting the
# function here would shadow the submodule attribute)
from milnce_tpu.ops import milnce_pallas  # noqa: F401  (submodule; its
# entry point is milnce_pallas.milnce_stream_pallas — the chunked
# MIL-NCE stream's fused kernel)
from milnce_tpu.ops.dtw import dtw_loss  # noqa: F401
from milnce_tpu.ops.softdtw_sp import softdtw_seq_parallel  # noqa: F401
