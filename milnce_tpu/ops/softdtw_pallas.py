"""Soft-DTW as a Pallas TPU kernel (forward + analytic backward).

TPU-native redesign of the reference's numba-CUDA wavefront kernels
(soft_dtw_cuda.py:34-76 forward, :79-112 backward, :115-175 autograd
wiring):

- CUDA launches one block per pair with one thread per row and a
  ``syncthreads`` barrier per anti-diagonal.  On TPU the whole wavefront
  of one pair lives in VMEM: the kernel runs a ``fori_loop`` over the
  2N-1 anti-diagonals, each step a fully-vectorized VPU op over the
  diagonal (no barriers — the sequential loop IS the dependency chain).
- Tables are kept **diagonal-major (skewed) and diagonal-LEADING**:
  refs have shape (n_diagonals, batch_tile, N+1).  The per-step dynamic
  index (the diagonal counter) lands on the *leading, untiled* dimension
  — a cheap address offset in Mosaic — while the (batch_tile, N+1)
  slices the loop actually computes on are statically-shaped, fully
  tiled (8, 128) vector ops.  Putting the diagonal on a tiled dimension
  instead makes every loop step a read-modify-write of the whole block
  (measured ~300x slower than lax.scan on a v5e before this layout).
- Batch is tiled into the block (``bt`` multiple of 8 on the sublane
  dim): alignment lengths in the MIL-NCE regime are 8-32 frames, and
  SDTW_3 evaluates B^2 pairs — batch fills the VPU the short diagonal
  can't.
- The backward pass implements the Cuturi-Blondel E-matrix recurrence as
  a reverse wavefront over the saved R table, wired in via
  ``jax.custom_vjp`` (mirror of soft_dtw_cuda.py:148-175).
- No 1024-length cap (the CUDA block-size limit that forces the
  reference onto its CPU path, soft_dtw_cuda.py:318-320): when the
  per-pair tables outgrow VMEM the forward streams diagonals from HBM in
  chunks (two carry rows of scratch) and the backward falls back to the
  scan — the ceiling is HBM, not VMEM.
- Borders use the same large-finite sentinel as the scan reference
  (`BIG`), with invalid cells mapped to ``-BIG`` in the backward — the
  finite analog of the reference's ``inf -> -inf`` fixup
  (soft_dtw_cuda.py:101-102).

On non-TPU backends the kernel runs in Pallas interpret mode, so the
same code path is unit-testable on CPU.  All three variants (in-VMEM,
chunked, backward) lower through Mosaic and run compiled on real TPU
(verified on v5e; see BENCH_SOFTDTW.md for timings and the lowering
rules the layout was bought with).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from milnce_tpu.ops.softdtw import BIG, check_bandwidth, skew_cost


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------- forward
def _fwd_kernel(d_ref, r_ref, *, n: int, m: int, gamma: float,
                bandwidth: int, bt: int):
    """One batch tile of ``bt`` pairs, whole wavefront in VMEM.

    d_ref: (N+M-1, bt, N) skewed costs.  r_ref: (N+M+1, bt, N+1) skewed
    DP tables (padded coords).  Both diagonal-leading: ``ref[p]`` is the
    (bt, N+1) anti-diagonal p — a static-shaped slice at a dynamic
    leading offset."""
    n1 = n + 1
    i_buf = lax.broadcasted_iota(jnp.int32, (bt, n1), 1)

    # Diagonal 0: R[0,0] = 0, rest BIG.  Diagonal 1: all BIG (borders).
    r_ref[0] = jnp.where(i_buf == 0, 0.0, BIG)
    r_ref[1] = jnp.full((bt, n1), BIG, jnp.float32)

    inv_gamma = 1.0 / gamma

    def body(p, _):
        r_mm = r_ref[p - 2]                         # diag p-2: (bt, N+1)
        r_m = r_ref[p - 1]                          # diag p-1
        cost = d_ref[p - 2]                         # D[i-1, j-1] along diag p
        prev_diag = r_mm[:, :-1]                    # R[i-1, j-1]
        prev_up = r_m[:, :-1]                       # R[i-1, j]
        prev_left = r_m[:, 1:]                      # R[i, j-1]
        n0 = -prev_diag * inv_gamma
        n1_ = -prev_up * inv_gamma
        n2 = -prev_left * inv_gamma
        mx = jnp.maximum(jnp.maximum(n0, n1_), n2)
        softmin = -gamma * (jnp.log(jnp.exp(n0 - mx) + jnp.exp(n1_ - mx)
                                    + jnp.exp(n2 - mx)) + mx)
        interior = cost + softmin                   # i = 1..N
        row = jnp.concatenate(
            [jnp.full((bt, 1), BIG, jnp.float32), interior], axis=1)
        j_buf = p - i_buf
        valid = ((i_buf >= 1) & (j_buf >= 1) & (j_buf <= m))
        if bandwidth > 0:                           # soft_dtw_cuda.py:66
            valid &= jnp.abs(i_buf - j_buf) <= bandwidth
        r_ref[p] = jnp.where(valid, row, BIG)
        return 0

    lax.fori_loop(2, n + m + 1, body, 0)


def _fwd_kernel_chunked(d_ref, r_ref, carry, *, n: int, m: int,
                        gamma: float, bandwidth: int, chunk: int, bt: int):
    """Streaming forward: grid (B/bt, n_chunks), diagonals arrive in
    CHUNK-sized blocks from HBM; only two carry rows live across chunks
    (VMEM scratch).  Removes the all-diagonals-in-VMEM requirement, so the
    sequence-length ceiling is HBM, not VMEM (the reference's ceiling was
    1024 CUDA threads, soft_dtw_cuda.py:318-320).

    Block t of chunk c holds diagonal p = c*chunk + t + 2; r_ref stores
    diagonals >= 2 (diagonals 0/1 are constants, re-attached host-side).
    The chunk index is the fast grid axis, so for each batch tile the
    chunks arrive in order and the carry threads through.
    """
    n1 = n + 1
    c = pl.program_id(1)
    i_buf = lax.broadcasted_iota(jnp.int32, (bt, n1), 1)
    inv_gamma = 1.0 / gamma

    @pl.when(c == 0)
    def _init():
        carry[0] = jnp.where(i_buf == 0, 0.0, BIG)           # diag 0
        carry[1] = jnp.full((bt, n1), BIG, jnp.float32)      # diag 1

    def body(t, _):
        p = c * chunk + t + 2
        r_mm = carry[0]
        r_m = carry[1]
        cost = d_ref[t]                              # (bt, N)
        n0 = -r_mm[:, :-1] * inv_gamma
        n1_ = -r_m[:, :-1] * inv_gamma
        n2 = -r_m[:, 1:] * inv_gamma
        mx = jnp.maximum(jnp.maximum(n0, n1_), n2)
        softmin = -gamma * (jnp.log(jnp.exp(n0 - mx) + jnp.exp(n1_ - mx)
                                    + jnp.exp(n2 - mx)) + mx)
        row = jnp.concatenate(
            [jnp.full((bt, 1), BIG, jnp.float32), cost + softmin], axis=1)
        j_buf = p - i_buf
        valid = ((i_buf >= 1) & (j_buf >= 1) & (j_buf <= m))
        if bandwidth > 0:
            valid &= jnp.abs(i_buf - j_buf) <= bandwidth
        row = jnp.where(valid, row, BIG)
        r_ref[t] = row
        carry[0] = r_m
        carry[1] = row
        return 0

    lax.fori_loop(0, chunk, body, 0)


# Budget (in f32 elements) for the per-block VMEM resident set of the
# single-shot kernels.  The backward holds THREE (N+M+3)x(N+2) tables per
# pair and Pallas double-buffers HBM<->VMEM, so the worst case is
# ~6x table x bt x 4 bytes plus temporaries; 1.2M elements keeps that
# under ~11 MB of the ~16 MB/core (verified against a real v5e scoped-
# vmem OOM at 1.9M-element blocks).
_VMEM_TABLE_BUDGET = 1_200_000

_CHUNK_VMEM_ELEMS = 500_000  # chunked-path block budget (d+r, dbl-buffered)

# Empirical Mosaic vector-lowering cap on (leading-dim x sublane) block
# area, bisected on v5e libtpu 2026-07 (see _batch_tile docstring); both
# kernel layouts must respect it.
_MOSAIC_BLOCK_AREA_CAP = 5120


def _batch_tile(n: int, m: int) -> int:
    """Pairs per block, multiple of 8 (Mosaic sublane tiling), capped at
    128.  0 means even an 8-pair tile busts the VMEM budget — callers
    must take the streaming/scan long-sequence path.

    Extra cap (empirical, v5e libtpu 2026-07): grids whose
    (leading-dim x batch-tile) block area is too large crash Mosaic's
    vector lowering (`Check failed: limits[i] <= dim(i)` in
    vector_extract_strided_slice).  Bisected boundaries: the forward
    survives products up to ~8192 (65x128 dies, 65x120 ok); the backward
    dies earlier (67x88=5896 dies, 67x80=5360 and 131x40=5240 ok).  Cap
    both at 5120 — under every observed-good point with margin — using
    the larger (backward) leading dim N+M+3."""
    table = (n + m + 3) * (n + 2)
    bt = min(_VMEM_TABLE_BUDGET // (3 * table), 128) // 8 * 8
    return min(bt, _MOSAIC_BLOCK_AREA_CAP // (n + m + 3) // 8 * 8)


def _table_fits_vmem(n: int, m: int) -> bool:
    return _batch_tile(n, m) >= 8


def _pad_batch(x: jax.Array, bt: int) -> jax.Array:
    bsz = x.shape[0]
    pad = (-bsz) % bt
    return x if pad == 0 else jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))


def _tile_for_batch(bsz: int, n: int, m: int) -> int:
    """The batch tile the single-shot kernels use for an actual batch:
    VMEM/Mosaic-capped, never padding a tiny batch up to a full tile."""
    bt = _batch_tile(n, m)
    assert bt >= 8, (f"soft-DTW tables for N={n}, M={m} exceed the Pallas "
                     "VMEM budget; use the chunked/scan long-sequence path")
    return min(bt, -(-bsz // 8) * 8)


def fits_one_block(bsz: int, n: int, m: int) -> bool:
    """True when the whole padded batch runs as a SINGLE kernel block —
    the regime where the wavefront kernel beats the scan (~3x on v5e;
    BENCH_SOFTDTW.md).  Multi-block grids re-run the diagonal loop per
    tile and lose to one scan over the full batch."""
    bt = _batch_tile(n, m)
    return bt >= 8 and -(-bsz // 8) * 8 <= bt


def _run_forward(d_skew: jax.Array, n: int, m: int, gamma: float,
                 bandwidth: int):
    """d_skew: (B, N+M-1, N) -> (value (B,), r_skew (B, N+M+1, N+1))."""
    bsz = d_skew.shape[0]
    bt = _tile_for_batch(bsz, n, m)
    d3 = _pad_batch(d_skew, bt).transpose(1, 0, 2)   # diag-leading
    bp = d3.shape[1]
    kernel = functools.partial(_fwd_kernel, n=n, m=m, gamma=gamma,
                               bandwidth=bandwidth, bt=bt)
    r3 = pl.pallas_call(
        kernel,
        grid=(bp // bt,),
        in_specs=[pl.BlockSpec((n + m - 1, bt, n), lambda b: (0, b, 0))],
        out_specs=pl.BlockSpec((n + m + 1, bt, n + 1), lambda b: (0, b, 0)),
        out_shape=jax.ShapeDtypeStruct((n + m + 1, bp, n + 1), jnp.float32),
        interpret=_interpret(),
    )(d3)
    r_skew = r3.transpose(1, 0, 2)[:bsz]
    return r_skew[:, n + m, n], r_skew


# -------------------------------------------------- batch-on-lanes layout
# Alternative single-shot layout for LARGE batches of SHORT pairs (the
# SDTW_3 B^2 regime): refs are (diagonals, N+1, batch_lanes), i.e. the
# alignment index lives on SUBLANES and batch fills the 128-wide LANE
# dimension.  Per wavefront step this touches ceil((N+1)/8) vector tiles
# instead of ceil(bt/8) — for batch >> N+1 that is up to n1/128 of the
# sublane-batch layout's total tile traffic.  Measured compiled on v5e
# (BENCH_SOFTDTW.md): 25.8x over the scan at (128, 17, 15) fwd+bwd and
# 3.5x at (1024, 32, 32) — regimes where the sublane-batch layout LOSES
# to the scan — so it is the default wherever its shape conditions hold
# (escape hatch: MILNCE_SDTW_LANES=0).


def _lane_tile(bsz: int) -> int:
    """Lanes per block: one full-lane block (<=128, lane dim equal to
    the array's) or 128-lane blocks over a padded batch."""
    return bsz if bsz <= 128 else 128


def _use_lanes(bsz: int, n: int, m: int) -> bool:
    if os.environ.get("MILNCE_SDTW_LANES") == "0":
        return False
    area = (n + m + 3) * (n + 2)
    bl = _lane_tile(bsz)
    return (area <= _MOSAIC_BLOCK_AREA_CAP
            and 3 * area * bl <= _VMEM_TABLE_BUDGET
            and bsz > n + 1)


def prefers_pallas(bsz: int, n: int, m: int) -> bool:
    """Shape-dispatch rule for ``SoftDTW(backend='auto')``, from the v5e
    measurements in BENCH_SOFTDTW.md: the kernel wins wherever the
    batch-on-lanes layout applies (3.5-26x, any batch size) or the whole
    padded batch runs as a single sublane-batch block (~3x).  Elsewhere —
    multi-block sublane grids re-running the diagonal loop per tile —
    one scan over the full batch wins."""
    return _use_lanes(bsz, n, m) or fits_one_block(bsz, n, m)


def _lanes_pad(x: jax.Array):
    bl = _lane_tile(x.shape[0])
    return _pad_batch(x, bl), bl


def _fwd_kernel_lanes(d_ref, r_ref, *, n: int, m: int, gamma: float,
                      bandwidth: int, bl: int):
    """d_ref: (N+M-1, N, bl); r_ref: (N+M+1, N+1, bl).  Same recurrence
    as _fwd_kernel with i on sublanes and batch on lanes."""
    n1 = n + 1
    i_buf = lax.broadcasted_iota(jnp.int32, (n1, bl), 0)

    r_ref[0] = jnp.where(i_buf == 0, 0.0, BIG)
    r_ref[1] = jnp.full((n1, bl), BIG, jnp.float32)

    inv_gamma = 1.0 / gamma

    def body(p, _):
        r_mm = r_ref[p - 2]                         # (N+1, bl)
        r_m = r_ref[p - 1]
        cost = d_ref[p - 2]                         # (N, bl)
        prev_diag = r_mm[:-1, :]                    # R[i-1, j-1]
        prev_up = r_m[:-1, :]                       # R[i-1, j]
        prev_left = r_m[1:, :]                      # R[i, j-1]
        n0 = -prev_diag * inv_gamma
        n1_ = -prev_up * inv_gamma
        n2 = -prev_left * inv_gamma
        mx = jnp.maximum(jnp.maximum(n0, n1_), n2)
        softmin = -gamma * (jnp.log(jnp.exp(n0 - mx) + jnp.exp(n1_ - mx)
                                    + jnp.exp(n2 - mx)) + mx)
        row = jnp.concatenate(
            [jnp.full((1, bl), BIG, jnp.float32), cost + softmin], axis=0)
        j_buf = p - i_buf
        valid = ((i_buf >= 1) & (j_buf >= 1) & (j_buf <= m))
        if bandwidth > 0:
            valid &= jnp.abs(i_buf - j_buf) <= bandwidth
        r_ref[p] = jnp.where(valid, row, BIG)
        return 0

    lax.fori_loop(2, n + m + 1, body, 0)


def _run_forward_lanes(d_skew: jax.Array, n: int, m: int, gamma: float,
                       bandwidth: int):
    """d_skew: (B, N+M-1, N) -> (value (B,), r_skew (B, N+M+1, N+1))."""
    bsz = d_skew.shape[0]
    d_pad, bl = _lanes_pad(d_skew)
    d3 = d_pad.transpose(1, 2, 0)                    # (S, N, B_pad)
    bp = d3.shape[2]
    kernel = functools.partial(_fwd_kernel_lanes, n=n, m=m, gamma=gamma,
                               bandwidth=bandwidth, bl=bl)
    r3 = pl.pallas_call(
        kernel,
        grid=(bp // bl,),
        in_specs=[pl.BlockSpec((n + m - 1, n, bl), lambda b: (0, 0, b))],
        out_specs=pl.BlockSpec((n + m + 1, n + 1, bl), lambda b: (0, 0, b)),
        out_shape=jax.ShapeDtypeStruct((n + m + 1, n + 1, bp), jnp.float32),
        interpret=_interpret(),
    )(d3)
    r_skew = r3.transpose(2, 0, 1)[:bsz]
    return r_skew[:, n + m, n], r_skew


def _bwd_kernel_lanes(r_ref, d_ref, e_ref, *, n: int, m: int, gamma: float,
                      bandwidth: int, bl: int):
    """Reverse wavefront, lanes layout: refs (N+M+3, N+2, bl)."""
    n2 = n + 2
    i_buf = lax.broadcasted_iota(jnp.int32, (n2, bl), 0)
    inv_gamma = 1.0 / gamma

    e_ref[...] = jnp.zeros((n + m + 3, n2, bl), jnp.float32)
    e_ref[n + m + 2] = (i_buf == n + 1).astype(jnp.float32)

    def shift_up(row):                              # row[i] -> row[i+1]
        return jnp.concatenate(
            [row[1:, :], jnp.zeros((1, bl), row.dtype)], axis=0)

    def body(k, _):
        q = n + m + 2 - k
        r_q = r_ref[q]                              # (N+2, bl)
        r_q1 = r_ref[q + 1]
        r_q2 = r_ref[q + 2]
        d_q1 = d_ref[q + 1]
        d_q2 = d_ref[q + 2]
        e_q1 = e_ref[q + 1]
        e_q2 = e_ref[q + 2]

        a = jnp.exp((shift_up(r_q1) - r_q - shift_up(d_q1)) * inv_gamma)
        b_ = jnp.exp((r_q1 - r_q - d_q1) * inv_gamma)
        c = jnp.exp((shift_up(r_q2) - r_q - shift_up(d_q2)) * inv_gamma)
        e_row = shift_up(e_q1) * a + e_q1 * b_ + shift_up(e_q2) * c

        j_buf = q - i_buf
        valid = ((i_buf >= 1) & (i_buf <= n) & (j_buf >= 1) & (j_buf <= m)
                 & (r_q > -BIG / 2))
        if bandwidth > 0:
            valid &= jnp.abs(i_buf - j_buf) <= bandwidth
        e_ref[q] = jnp.where(valid, e_row, 0.0)
        return 0

    lax.fori_loop(2, n + m + 1, body, 0)


def _run_backward_lanes(r_ext_skew: jax.Array, d_ext_skew: jax.Array,
                        n: int, m: int, gamma: float,
                        bandwidth: int) -> jax.Array:
    bsz = r_ext_skew.shape[0]
    r_pad, bl = _lanes_pad(r_ext_skew)
    d_pad, _ = _lanes_pad(d_ext_skew)
    r3 = r_pad.transpose(1, 2, 0)
    d3 = d_pad.transpose(1, 2, 0)
    bp = r3.shape[2]
    kernel = functools.partial(_bwd_kernel_lanes, n=n, m=m, gamma=gamma,
                               bandwidth=bandwidth, bl=bl)
    spec = pl.BlockSpec((n + m + 3, n + 2, bl), lambda b: (0, 0, b))
    out = pl.pallas_call(
        kernel,
        grid=(bp // bl,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((n + m + 3, n + 2, bp), jnp.float32),
        interpret=_interpret(),
    )(r3, d3)
    return out.transpose(2, 0, 1)[:bsz]


def _run_forward_chunked(d_skew: jax.Array, n: int, m: int, gamma: float,
                         bandwidth: int, chunk: int | None = None):
    """d_skew: (B, N+M-1, N) -> (value (B,), r_skew (B, N+M+1, N+1))."""
    import math

    bsz = d_skew.shape[0]
    bt = 8
    if chunk is None:
        # chunk is the untiled leading dim, so a floor of 1 is legal; never
        # let the floor push the block past the VMEM budget at huge N
        chunk = max(1, min(512, _CHUNK_VMEM_ELEMS // (bt * (2 * n + 1))))
    n_diag = n + m - 1                    # diagonals 2..n+m
    n_chunks = math.ceil(n_diag / chunk)
    pad_p = n_chunks * chunk - n_diag
    d3 = jnp.pad(_pad_batch(d_skew, bt),
                 ((0, 0), (0, pad_p), (0, 0))).transpose(1, 0, 2)
    bp = d3.shape[1]
    kernel = functools.partial(_fwd_kernel_chunked, n=n, m=m, gamma=gamma,
                               bandwidth=bandwidth, chunk=chunk, bt=bt)
    r3 = pl.pallas_call(
        kernel,
        grid=(bp // bt, n_chunks),
        in_specs=[pl.BlockSpec((chunk, bt, n), lambda b, c: (c, b, 0))],
        out_specs=pl.BlockSpec((chunk, bt, n + 1), lambda b, c: (c, b, 0)),
        out_shape=jax.ShapeDtypeStruct((n_chunks * chunk, bp, n + 1),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((2, bt, n + 1), jnp.float32)],
        interpret=_interpret(),
    )(d3)
    r_body = r3.transpose(1, 0, 2)[:bsz, :n_diag]
    # re-attach the constant diagonals 0 and 1
    diag0 = jnp.where(jnp.arange(n + 1) == 0, 0.0, BIG)
    head = jnp.stack([diag0, jnp.full((n + 1,), BIG)], axis=0)
    head = jnp.broadcast_to(head[None], (bsz, 2, n + 1))
    r_skew = jnp.concatenate([head, r_body], axis=1)
    return r_skew[:, n + m, n], r_skew


def _softdtw_bwd_scan(r_ext: jax.Array, d_ext_skew: jax.Array, n: int,
                      m: int, gamma: float, bandwidth: int) -> jax.Array:
    """Any-length backward: the reverse-wavefront E recurrence as a
    lax.scan over diagonals (rows stream from HBM automatically).  Used
    when the whole table exceeds the Pallas kernel's VMEM budget."""
    bsz = r_ext.shape[0]
    n2 = n + 2
    i_buf = jnp.arange(n2)
    inv_gamma = 1.0 / gamma

    def shift_left(row):
        return jnp.concatenate(
            [row[:, 1:], jnp.zeros((bsz, 1), row.dtype)], axis=1)

    def step(carry, inputs):
        e_q1, e_q2 = carry                     # diagonals q+1, q+2
        r_q, r_q1, r_q2, d_q1, d_q2, q = inputs
        a = jnp.exp((shift_left(r_q1) - r_q - shift_left(d_q1)) * inv_gamma)
        b_ = jnp.exp((r_q1 - r_q - d_q1) * inv_gamma)
        c = jnp.exp((shift_left(r_q2) - r_q - shift_left(d_q2)) * inv_gamma)
        e_row = shift_left(e_q1) * a + e_q1 * b_ + shift_left(e_q2) * c
        j_buf = q - i_buf
        valid = ((i_buf >= 1) & (i_buf <= n) & (j_buf >= 1) & (j_buf <= m))
        valid = valid[None, :] & (r_q > -BIG / 2)
        if bandwidth > 0:
            valid &= (jnp.abs(i_buf - j_buf) <= bandwidth)[None, :]
        e_row = jnp.where(valid, e_row, 0.0)
        return (e_row, e_q1), e_row

    # iterate q = n+m down to 2; inputs pre-gathered per diagonal
    qs = jnp.arange(n + m, 1, -1)
    r_q = r_ext[:, qs, :]
    r_q1 = r_ext[:, qs + 1, :]
    r_q2 = r_ext[:, qs + 2, :]
    d_q1 = d_ext_skew[:, qs + 1, :]
    d_q2 = d_ext_skew[:, qs + 2, :]
    swap = lambda x: x.transpose(1, 0, 2)
    e_init_q2 = jnp.zeros((bsz, n2), jnp.float32).at[:, n + 1].set(1.0)
    e_init_q1 = jnp.zeros((bsz, n2), jnp.float32)
    (_, _), e_rows = lax.scan(
        step, (e_init_q1, e_init_q2),
        (swap(r_q), swap(r_q1), swap(r_q2), swap(d_q1), swap(d_q2), qs))
    # e_rows[k] = diagonal q = n+m-k; build skewed E table rows 0..n+m+2
    e_skew = jnp.zeros((bsz, n + m + 3, n2), jnp.float32)
    e_skew = e_skew.at[:, qs, :].set(swap(e_rows))
    return e_skew


def _bwd_kernel_chunked(r_ref, d_ref, e_ref, carry, *, n: int, m: int,
                        gamma: float, bandwidth: int, chunk: int, bt: int,
                        n_chunks: int):
    """Streaming backward: grid (B/bt, n_chunks), the E-recurrence's
    mirror of ``_fwd_kernel_chunked``.  The chunk axis index_map REVERSES
    block order (the wavefront runs high diagonal -> low), and six carry
    rows — E, R, D at diagonals q+1 and q+2 — thread across chunk
    boundaries in VMEM scratch, so no block ever reads a neighbor
    diagonal from another block.  The sequence-length ceiling is HBM,
    like the forward; the reference's backward simply stops at 1024
    (soft_dtw_cuda.py:79-112, 318-320).

    Diagonal q lives at array row q; rows above n+m+2 are zero padding
    whose E is masked to 0 (their q fails the j<=m validity test) and
    whose r/d values only ever neighbor the overridden/masked top rows.
    The q = n+m+2 corner seed (E=1 at i=N+1, soft_dtw_cuda.py:166-167)
    is applied as a where-override, which keeps the loop body uniform
    across real, seed, and padding rows."""
    n2 = n + 2
    c = pl.program_id(1)
    i_buf = lax.broadcasted_iota(jnp.int32, (bt, n2), 1)
    inv_gamma = 1.0 / gamma

    @pl.when(c == 0)
    def _init():
        carry[...] = jnp.zeros((6, bt, n2), jnp.float32)

    def shift_left(row):                            # row[i] -> row[i+1]
        return jnp.concatenate(
            [row[:, 1:], jnp.zeros((bt, 1), row.dtype)], axis=1)

    def body(s, _):
        t = chunk - 1 - s                           # top row of the block first
        q = (n_chunks - 1 - c) * chunk + t          # diagonal index
        e_q1, e_q2 = carry[0], carry[1]
        r_q1, r_q2 = carry[2], carry[3]
        d_q1, d_q2 = carry[4], carry[5]
        r_q = r_ref[t]
        d_q = d_ref[t]

        a = jnp.exp((shift_left(r_q1) - r_q - shift_left(d_q1)) * inv_gamma)
        b_ = jnp.exp((r_q1 - r_q - d_q1) * inv_gamma)
        c_ = jnp.exp((shift_left(r_q2) - r_q - shift_left(d_q2)) * inv_gamma)
        e_row = shift_left(e_q1) * a + e_q1 * b_ + shift_left(e_q2) * c_

        j_buf = q - i_buf
        valid = ((i_buf >= 1) & (i_buf <= n) & (j_buf >= 1) & (j_buf <= m)
                 & (r_q > -BIG / 2))                # unreached cells -> 0
        if bandwidth > 0:
            valid &= jnp.abs(i_buf - j_buf) <= bandwidth
        e_row = jnp.where(valid, e_row, 0.0)
        e_row = jnp.where(q == n + m + 2,           # corner seed E[N+1,M+1]=1
                          (i_buf == n + 1).astype(jnp.float32), e_row)
        e_ref[t] = e_row
        carry[1] = e_q1                             # next step's q+2
        carry[0] = e_row                            # next step's q+1
        carry[3] = r_q1
        carry[2] = r_q
        carry[5] = d_q1
        carry[4] = d_q
        return 0

    lax.fori_loop(0, chunk, body, 0)


def _run_backward_chunked(r_ext_skew: jax.Array, d_ext_skew: jax.Array,
                          n: int, m: int, gamma: float, bandwidth: int,
                          chunk: int | None = None) -> jax.Array:
    """(B, N+M+3, N+2) extended skewed R and D -> skewed E table, any
    length: diagonals stream from HBM in chunks, highest first."""
    import math

    bsz = r_ext_skew.shape[0]
    bt = 8
    n2 = n + 2
    if chunk is None:
        # three streams (r, d, e) share the block budget; floor 1 is legal
        chunk = max(1, min(512, _CHUNK_VMEM_ELEMS // (bt * 3 * n2)))
    n_rows = n + m + 3
    n_chunks = math.ceil(n_rows / chunk)
    pad_p = n_chunks * chunk - n_rows
    r3 = jnp.pad(_pad_batch(r_ext_skew, bt),
                 ((0, 0), (0, pad_p), (0, 0))).transpose(1, 0, 2)
    d3 = jnp.pad(_pad_batch(d_ext_skew, bt),
                 ((0, 0), (0, pad_p), (0, 0))).transpose(1, 0, 2)
    bp = r3.shape[1]
    kernel = functools.partial(_bwd_kernel_chunked, n=n, m=m, gamma=gamma,
                               bandwidth=bandwidth, chunk=chunk, bt=bt,
                               n_chunks=n_chunks)
    spec = pl.BlockSpec((chunk, bt, n2), lambda b, c: (n_chunks - 1 - c, b, 0))
    out = pl.pallas_call(
        kernel,
        grid=(bp // bt, n_chunks),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((n_chunks * chunk, bp, n2),
                                       jnp.float32),
        scratch_shapes=[pltpu.VMEM((6, bt, n2), jnp.float32)],
        interpret=_interpret(),
    )(r3, d3)
    return out.transpose(1, 0, 2)[:bsz, :n_rows]


# --------------------------------------------------------------- backward
def _bwd_kernel(r_ref, d_ref, e_ref, *, n: int, m: int, gamma: float,
                bandwidth: int, bt: int):
    """Reverse wavefront over padded-extended coords i in [0,N+1],
    j in [0,M+1] (diag q = i+j in [0, N+M+2]), skewed diagonal-leading
    layout, a tile of ``bt`` pairs per block (see _fwd_kernel on why).
    r_ref/d_ref/e_ref: (N+M+3, bt, N+2)."""
    n2 = n + 2
    i_buf = lax.broadcasted_iota(jnp.int32, (bt, n2), 1)
    inv_gamma = 1.0 / gamma

    e_ref[...] = jnp.zeros((n + m + 3, bt, n2), jnp.float32)
    # E[N+1, M+1] = 1 (corner seed, soft_dtw_cuda.py:166-167)
    corner = (i_buf == n + 1).astype(jnp.float32)
    e_ref[n + m + 2] = corner

    def shift_left(row):                            # row[i] -> row[i+1]
        return jnp.concatenate(
            [row[:, 1:], jnp.zeros((bt, 1), row.dtype)], axis=1)

    def body(k, _):
        q = n + m + 2 - k
        r_q = r_ref[q]                              # R[i, q-i]: (bt, N+2)
        r_q1 = r_ref[q + 1]                         # diag q+1
        r_q2 = r_ref[q + 2]                         # diag q+2
        d_q1 = d_ref[q + 1]
        d_q2 = d_ref[q + 2]
        e_q1 = e_ref[q + 1]
        e_q2 = e_ref[q + 2]

        r_up = shift_left(r_q1)                     # R[i+1, j]
        r_left = r_q1                               # R[i, j+1]
        r_diag = shift_left(r_q2)                   # R[i+1, j+1]
        d_up = shift_left(d_q1)                     # D_[i+1, j]
        d_left = d_q1                               # D_[i, j+1]
        d_diag = shift_left(d_q2)                   # D_[i+1, j+1]
        e_up = shift_left(e_q1)
        e_left = e_q1
        e_diag = shift_left(e_q2)

        a = jnp.exp((r_up - r_q - d_up) * inv_gamma)
        b_ = jnp.exp((r_left - r_q - d_left) * inv_gamma)
        c = jnp.exp((r_diag - r_q - d_diag) * inv_gamma)
        e_row = e_up * a + e_left * b_ + e_diag * c

        j_buf = q - i_buf
        valid = ((i_buf >= 1) & (i_buf <= n) & (j_buf >= 1) & (j_buf <= m)
                 & (r_q > -BIG / 2))                # unreached cells -> 0
        if bandwidth > 0:
            valid &= jnp.abs(i_buf - j_buf) <= bandwidth
        e_ref[q] = jnp.where(valid, e_row, 0.0)
        return 0

    # Start at q = n+m (k=2): diagonal n+m+1 holds no valid cell (j would
    # exceed M), and skipping it keeps every q+2 read in bounds.
    lax.fori_loop(2, n + m + 1, body, 0)


def _run_backward(r_ext_skew: jax.Array, d_ext_skew: jax.Array, n: int,
                  m: int, gamma: float, bandwidth: int) -> jax.Array:
    bsz = r_ext_skew.shape[0]
    bt = _tile_for_batch(bsz, n, m)
    r3 = _pad_batch(r_ext_skew, bt).transpose(1, 0, 2)
    d3 = _pad_batch(d_ext_skew, bt).transpose(1, 0, 2)
    bp = r3.shape[1]
    kernel = functools.partial(_bwd_kernel, n=n, m=m, gamma=gamma,
                               bandwidth=bandwidth, bt=bt)
    spec = pl.BlockSpec((n + m + 3, bt, n + 2), lambda b: (0, b, 0))
    out = pl.pallas_call(
        kernel,
        grid=(bp // bt,),
        in_specs=[spec, spec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((n + m + 3, bp, n + 2), jnp.float32),
        interpret=_interpret(),
    )(r3, d3)
    return out.transpose(1, 0, 2)[:bsz]


# ----------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def softdtw_pallas(D: jax.Array, gamma: float = 1.0,
                   bandwidth: int = 0) -> jax.Array:
    """Batched soft-DTW of cost matrices D (B, N, M) -> (B,)."""
    value, _ = _softdtw_pallas_fwd(D, gamma, bandwidth)
    return value


def _softdtw_pallas_fwd(D, gamma, bandwidth):
    bsz, n, m = D.shape
    check_bandwidth(n, m, int(bandwidth))
    d_skew = skew_cost(D.astype(jnp.float32))
    if _use_lanes(bsz, n, m):
        value, r_skew = _run_forward_lanes(d_skew, n, m, float(gamma),
                                           int(bandwidth))
    elif _table_fits_vmem(n, m):
        value, r_skew = _run_forward(d_skew, n, m, float(gamma),
                                     int(bandwidth))
    else:
        # long-sequence path: stream diagonals in chunks
        value, r_skew = _run_forward_chunked(d_skew, n, m, float(gamma),
                                             int(bandwidth))
    return value, (D, r_skew)


def _softdtw_pallas_bwd(gamma, bandwidth, residuals, grad_out):
    D, r_skew = residuals
    bsz, n, m = D.shape
    # Extended R in skewed layout: pad with BIG (-> treated as unreached),
    # then seed the (N+1, M+1) corner with R[N, M] (soft_dtw_cuda.py:162-164).
    r_ext = jnp.pad(r_skew, ((0, 0), (0, 2), (0, 1)), constant_values=BIG)
    r_ext = jnp.where(r_ext >= BIG / 2, -BIG, r_ext)
    r_ext = r_ext.at[:, n + m + 2, n + 1].set(r_skew[:, n + m, n])
    # Padded costs D_[i, j] (zeros border), skewed to match.
    d_ext = jnp.pad(D.astype(jnp.float32), ((0, 0), (1, 1), (1, 1)))
    d_ext_skew = skew_cost(d_ext)                   # (B, N+M+3, N+2)
    if _use_lanes(bsz, n, m):
        e_skew = _run_backward_lanes(r_ext, d_ext_skew, n, m, float(gamma),
                                     int(bandwidth))
    elif _table_fits_vmem(n, m):
        e_skew = _run_backward(r_ext, d_ext_skew, n, m, float(gamma),
                               int(bandwidth))
    elif os.environ.get("MILNCE_SDTW_BWD_SCAN") == "1":
        # debugging escape hatch / cross-implementation golden
        e_skew = _softdtw_bwd_scan(r_ext, d_ext_skew, n, m, float(gamma),
                                   int(bandwidth))
    else:
        # long-sequence path: stream diagonals from HBM, highest first
        e_skew = _run_backward_chunked(r_ext, d_ext_skew, n, m,
                                       float(gamma), int(bandwidth))
    # grad_D[i, j] = g * E[i+1, j+1]  (skewed: diag i+j+2, idx i+1)
    i_idx = jnp.arange(n)[:, None]
    j_idx = jnp.arange(m)[None, :]
    e_full = e_skew[:, i_idx + j_idx + 2, i_idx + 1]
    return (grad_out[:, None, None] * e_full.astype(D.dtype),)


softdtw_pallas.defvjp(_softdtw_pallas_fwd, _softdtw_pallas_bwd)
