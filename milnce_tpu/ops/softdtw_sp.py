"""Sequence-parallel soft-DTW: the DP wavefront sharded over the mesh.

The reference caps soft-DTW at sequence length 1024 (CUDA block limit,
soft_dtw_cuda.py:318-320) and runs one GPU per pair.  The single-chip
Pallas kernel (softdtw_pallas.py) already removes the cap; this module
removes the single-CHIP limit: the anti-diagonal wavefront itself is
distributed over the mesh, so one alignment's memory and per-diagonal
compute scale 1/P with the device count — soft-DTW as a first-class
long-context primitive (SURVEY §5 long-context note).

Decomposition (row-sharded wavefront):

- the (B, N, M) cost matrix is sharded over N (device p owns rows
  [p*K, (p+1)*K));
- the DP recurrence R[i, j] = D[i-1, j-1] + softmin(R[i-1, j-1],
  R[i-1, j], R[i, j-1]) walks anti-diagonals exactly like the scan
  golden (softdtw.py:52-91), but each diagonal is now a DISTRIBUTED
  vector sharded the same way;
- the only cross-device dependency is the ``i-1`` shift: each step,
  every device sends its LAST row's value to its right neighbor — one
  (B, 2) ``ppermute`` over ICI per diagonal (the halo exchange);
- the final R[N, M] lives on one device and is ``psum``-broadcast.

The backward pass is plain JAX AD: ``ppermute``/``scan``/``where`` all
have transpose rules, so ``jax.grad`` of a shard_map'ed call yields the
sharded E-matrix gradient with the reverse halo exchange inserted by
XLA — no hand-written VJP needed (the reference hand-codes its backward
kernel, soft_dtw_cuda.py:79-112).

Wall-clock per diagonal is O(N/P) vector work + one ICI hop, N+M-1
diagonals total.  For the alignment shapes this framework trains on,
the single-chip kernels are faster (no per-step collective); use this
when one sequence's DP table outgrows a chip — lengths of 10^5+ frames.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from milnce_tpu.parallel.compat import axis_size, shard_map
from milnce_tpu.ops.softdtw import (BIG, check_bandwidth, skew_cost,
                                    softmin3)


def _softdtw_sp_local(D_local: jax.Array, n: int, m: int, gamma,
                      axis_name: str, bandwidth: int = 0) -> jax.Array:
    """Shard-local body (call inside shard_map; D row-sharded on dim 1).

    Returns the (B,) soft-DTW values, identical on every shard."""
    bsz, k, _ = D_local.shape
    p_count = axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    row_offset = idx * k                       # global D-row of local row 0
    g_rows = row_offset + jnp.arange(k)        # global D-row ids (= i-1)
    gamma = jnp.asarray(gamma, D_local.dtype)

    n_diags = n + m - 1
    d_skew = skew_cost(D_local, n_diags, row_offset)       # (B, Q, K)

    fwd_perm = [(s, s + 1) for s in range(p_count - 1)]

    def shift_in(x, fill):
        """y[r] = x[r-1] with the left neighbor's last row crossing the
        shard boundary; device 0's row 0 gets scalar `fill` (the i=0
        border)."""
        recv = lax.ppermute(x[:, -1:], axis_name, fwd_perm)   # (B, 1)
        first = jnp.where(idx == 0, jnp.broadcast_to(fill, recv.shape), recv)
        return jnp.concatenate([first, x[:, :-1]], axis=1)

    # Buffers hold interior rows only (buffer row r <-> padded DP row
    # i = g_rows[r] + 1); the i=0 border row is synthesized by shift_in.
    init = jnp.full((bsz, k), BIG, D_local.dtype)

    def step(carry, inputs):
        r_mm, r_m = carry                      # diagonals p-2, p-1
        cost_row, p = inputs
        # R[0, j] on diag p-2 is R[0, p-2]: 0 iff p == 2, else BIG
        fill_mm = jnp.where(p == 2, 0.0, BIG).astype(D_local.dtype)
        prev_diag = shift_in(r_mm, fill_mm)
        prev_up = shift_in(r_m, jnp.asarray(BIG, D_local.dtype))
        prev_left = r_m
        interior = cost_row + softmin3(prev_diag, prev_up, prev_left, gamma)
        i_glob = g_rows[None, :] + 1
        j_glob = p - i_glob
        valid = (j_glob >= 1) & (j_glob <= m) & (i_glob <= n)
        if bandwidth > 0:                      # soft_dtw_cuda.py:66
            valid &= jnp.abs(i_glob - j_glob) <= bandwidth
        r_new = jnp.where(valid, interior, BIG)
        return (r_m, r_new), None

    diag_ids = jnp.arange(2, n + m + 1)
    (_, r_last), _ = lax.scan(step, (init, init),
                              (d_skew.transpose(1, 0, 2), diag_ids))

    # R[N, M] sits at buffer row with g_rows == N-1 on one device
    local_val = jnp.sum(jnp.where(g_rows[None, :] == n - 1, r_last, 0.0),
                        axis=1)
    return lax.psum(local_val, axis_name)


@functools.lru_cache(maxsize=32)
def _build_sp_fn(mesh: Mesh, axis_name: str, n: int, m: int,
                 bandwidth: int):
    """One jitted distributed-scan program per (mesh, shape, bandwidth);
    gamma stays a traced argument so sweeping it never recompiles."""

    def local(D_local, gamma):
        return _softdtw_sp_local(D_local, n=n, m=m, gamma=gamma,
                                 axis_name=axis_name, bandwidth=bandwidth)

    return jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(None, axis_name, None), P()),
        out_specs=P(), check_vma=False))


def softdtw_seq_parallel(D: jax.Array, gamma: float, mesh: Mesh,
                         axis_name: str = "data",
                         bandwidth: int = 0) -> jax.Array:
    """Distributed soft-DTW of (B, N, M) costs over ``mesh[axis_name]``.

    Rows are padded to a multiple of the axis size and sharded; returns
    (B,) replicated values.  Differentiable (plain JAX AD through the
    shard_map program).  Computes and returns float32 regardless of the
    input dtype: the BIG-sentinel border arithmetic needs f32 range
    (bfloat16 saturates), unlike the in-dtype scan golden."""
    bsz, n, m = D.shape
    check_bandwidth(n, m, int(bandwidth))
    p_count = mesh.shape[axis_name]
    k = -(-n // p_count)
    D_pad = jnp.pad(D.astype(jnp.float32), ((0, 0), (0, k * p_count - n),
                                            (0, 0)))
    fn = _build_sp_fn(mesh, axis_name, n, m, int(bandwidth))
    return fn(jax.device_put(
        D_pad, NamedSharding(mesh, P(None, axis_name, None))),
        jnp.float32(gamma))
