"""Chunked MIL-NCE logsumexp as a fused Pallas TPU kernel.

The pure-jax stream (losses/milnce_chunked.py ``_stream_lse_scan``)
already removes the O(B_local * Bg * K) similarity cubes, but each scan
step still round-trips its chunk logits block through XLA-managed HBM
temporaries.  This kernel fuses the whole step — chunk matmul (MXU) +
online max/rescale + accumulate (VPU) — in VMEM:

- grid ``(n_chunks,)``: Pallas streams the ``(chunk, D)`` /
  ``(chunk*K, D)`` negative blocks from HBM (double-buffered by the
  pipeline) while the local ``(B, D)`` / ``(B*K, D)`` blocks and the
  four accumulator blocks stay VMEM-resident across the grid via
  constant-index BlockSpecs (``@pl.when(c == 0)`` initializes them);
- accumulators are ``(rows, 128)`` blocks with all lanes equal — a
  per-row scalar broadcast over the lane dim, so every read/write is a
  full (8, 128)-tileable block (the softdtw_pallas lowering lesson:
  never make Mosaic slice a 1-wide lane);
- the backward is its OWN kernel behind ``jax.custom_vjp``
  (the soft-DTW wiring): it recomputes each chunk's logits, forms the
  softmax weights ``exp(x - lse) * g`` and emits the local grads as
  accumulated blocks plus the gathered-negative grads as per-chunk
  output blocks — nothing O(Bg * K) beyond the embeddings themselves;
- padding rows (batch to sublane multiples, Bg to whole chunks) are
  masked to ``-BIG`` logits / zero weights, the same finite-sentinel
  discipline as ops/softdtw.py.

On non-TPU backends the kernel runs in Pallas interpret mode, so the
same code path is unit-testable on CPU (tests/test_milnce_chunked.py
pins value+grad parity against the scan stream and the dense loss).
``prefers_pallas`` is the ``backend='auto'`` shape-dispatch rule — a
pure function of static shapes, pinned no-recompile by the
``milnce_chunked_dispatch`` trace-invariant entry.  TPU timings:
BENCH_MILNCE_LOSS.md (CPU numbers committed; the chip crossover is
predicted from the VMEM-residency rule, not yet measured — same status
the im2col stem had before its chip session).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from milnce_tpu.ops.softdtw import BIG

_LANES = 128

# f32 elements the per-step VMEM resident set may use: local blocks +
# double-buffered chunk blocks + logits temporaries + accumulators.
# Same budget scale the soft-DTW kernels verified against a real v5e
# scoped-vmem OOM (ops/softdtw_pallas.py _VMEM_TABLE_BUDGET).
_VMEM_F32_BUDGET = 1_200_000


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _pad8(n: int) -> int:
    return -(-n // 8) * 8


def _pad_rows(x: jax.Array, rows: int) -> jax.Array:
    pad = rows - x.shape[0]
    return x if pad == 0 else jnp.pad(x, ((0, pad), (0, 0)))


def prefers_pallas(b: int, b_global: int, k: int, d: int,
                   chunk: int) -> bool:
    """``backend='auto'`` rule: the fused kernel wherever its blocks are
    lane-aligned (D a multiple of 128 — the MXU contraction dim) and the
    per-step resident set fits the VMEM budget; the scan otherwise.
    Conservative by construction: CPU interpret-mode parity is pinned in
    tests, the TPU win is predicted from VMEM residency (one fused
    pipeline vs per-chunk HBM temporaries) pending a chip session —
    BENCH_MILNCE_LOSS.md records which."""
    if chunk % 8 and chunk != b_global:
        # a sublane-misaligned EXPLICIT chunk (the default rule always
        # aligns) would hand Mosaic (chunk, D) blocks off the (8, 128)
        # tile grid — legal in interpret mode only; route it to the scan
        # (single-chunk streams are exempt: the block equals the array)
        return False
    bp, bkp = _pad8(b), _pad8(b * k)
    ck = chunk * k
    # budget the BACKWARD kernel — the larger of the two resident sets
    # (it holds recomputed logits AND weight blocks, the gv/gt grad
    # accumulators, and the per-chunk gva/gta output blocks the forward
    # doesn't have); a rule that only modeled the forward would compile
    # the forward and VMEM-OOM mid-step in the backward on a real chip
    resident = (2 * (bp + bkp) * d          # v/t blocks + gv/gt accums
                + 2 * (bp + bkp) * _LANES   # lse + cotangent blocks
                + 4 * (chunk + ck) * d      # chunk in + grad out blocks,
                                            # double-buffered
                + 2 * (bp * ck + bkp * chunk))  # logits + weight temps
    return d % _LANES == 0 and resident <= _VMEM_F32_BUDGET


def _check_chunk_alignment(chunk: int, bg: int) -> None:
    """Compiled-TPU precondition, checked at trace time so an explicit
    ``backend='pallas'`` with a misaligned ``loss.milnce_chunk`` fails
    naming the knob instead of as an opaque Mosaic lowering error deep
    in the step compile.  Interpret mode (every non-TPU backend) has no
    tile grid and legitimately accepts any chunk — the parity tests'
    odd chunks stay runnable on CPU."""
    if _interpret():
        return
    if chunk % 8 and chunk != bg:
        raise ValueError(
            f"loss.milnce_chunk={chunk} is not sublane-aligned for the "
            "compiled Pallas kernel (chunk blocks need 8-row-aligned "
            "sublanes; trailing dims Mosaic pads itself): use a "
            f"multiple of 8, a chunk >= the gathered batch ({bg}), or "
            "backend='scan'")


def _row_scalar(ref):
    """Per-row scalar out of an all-lanes-equal (rows, 128) accumulator
    block: a full-block read + lane-max (max of equal values), never a
    1-wide lane slice."""
    return jnp.max(ref[...], axis=1, keepdims=True)


def _store_scalar(ref, col, rows):
    ref[...] = jnp.broadcast_to(col, (rows, _LANES))


# ---------------------------------------------------------------- forward
def _fwd_kernel(v_ref, t_ref, va_ref, ta_ref, rm_ref, rs_ref, cm_ref,
                cs_ref, *, bg, k, chunk, bp, bkp):
    """One negative chunk: fused matmul + online max/rescale/accumulate.
    rm/rs (rows) and cm/cs (cols) are the running (max, rescaled-sum)
    logsumexp accumulators, resident across the grid."""
    c = pl.program_id(0)

    @pl.when(c == 0)
    def _init():
        _store_scalar(rm_ref, jnp.full((bp, 1), -BIG, jnp.float32), bp)
        _store_scalar(rs_ref, jnp.zeros((bp, 1), jnp.float32), bp)
        _store_scalar(cm_ref, jnp.full((bkp, 1), -BIG, jnp.float32), bkp)
        _store_scalar(cs_ref, jnp.zeros((bkp, 1), jnp.float32), bkp)

    ck = chunk * k
    # chunk blocks arrive in the INPUT dtype (upcasting the gathered
    # arrays host-side would materialize O(Bg*D) f32 copies) and promote
    # to f32 here, in VMEM, one block at a time
    ta = ta_ref[...].astype(jnp.float32)
    va = va_ref[...].astype(jnp.float32)
    # rows: local videos vs this chunk's candidate texts -> (bp, ck)
    x = lax.dot_general(v_ref[...], ta, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    col = c * ck + lax.broadcasted_iota(jnp.int32, (bp, ck), 1)
    x = jnp.where(col < bg * k, x, -BIG)
    m_old, s_old = _row_scalar(rm_ref), _row_scalar(rs_ref)
    m_new = jnp.maximum(m_old, jnp.max(x, axis=1, keepdims=True))
    s_new = (s_old * jnp.exp(m_old - m_new)
             + jnp.sum(jnp.exp(x - m_new), axis=1, keepdims=True))
    _store_scalar(rm_ref, m_new, bp)
    _store_scalar(rs_ref, s_new, bp)

    # cols: local candidate texts vs this chunk's videos -> (bkp, chunk)
    y = lax.dot_general(t_ref[...], va, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    row = c * chunk + lax.broadcasted_iota(jnp.int32, (bkp, chunk), 1)
    y = jnp.where(row < bg, y, -BIG)
    m_old, s_old = _row_scalar(cm_ref), _row_scalar(cs_ref)
    m_new = jnp.maximum(m_old, jnp.max(y, axis=1, keepdims=True))
    s_new = (s_old * jnp.exp(m_old - m_new)
             + jnp.sum(jnp.exp(y - m_new), axis=1, keepdims=True))
    _store_scalar(cm_ref, m_new, bkp)
    _store_scalar(cs_ref, s_new, bkp)


def _run_forward(v, t, v_all, t_all, chunk, bg, k):
    b, d = v.shape
    bk = t.shape[0]
    bp, bkp = _pad8(b), _pad8(bk)
    _check_chunk_alignment(chunk, bg)
    nc = -(-bg // chunk)
    f32 = jnp.float32
    vp = _pad_rows(v.astype(f32), bp)
    tp = _pad_rows(t.astype(f32), bkp)
    vap = _pad_rows(v_all, nc * chunk)          # input dtype: the kernel
    tap = _pad_rows(t_all, nc * chunk * k)      # upcasts per block
    kernel = functools.partial(_fwd_kernel, bg=bg, k=k, chunk=chunk,
                               bp=bp, bkp=bkp)
    const = lambda shape: pl.BlockSpec(shape, lambda c: (0, 0))  # noqa: E731
    rm, rs, cm, cs = pl.pallas_call(
        kernel,
        grid=(nc,),
        in_specs=[const((bp, d)), const((bkp, d)),
                  pl.BlockSpec((chunk, d), lambda c: (c, 0)),
                  pl.BlockSpec((chunk * k, d), lambda c: (c, 0))],
        out_specs=[const((bp, _LANES)), const((bp, _LANES)),
                   const((bkp, _LANES)), const((bkp, _LANES))],
        out_shape=[jax.ShapeDtypeStruct((bp, _LANES), f32),
                   jax.ShapeDtypeStruct((bp, _LANES), f32),
                   jax.ShapeDtypeStruct((bkp, _LANES), f32),
                   jax.ShapeDtypeStruct((bkp, _LANES), f32)],
        interpret=_interpret(),
    )(vp, tp, vap, tap)
    row_lse = rm[:b, 0] + jnp.log(rs[:b, 0])
    col_lse = cm[:bk, 0] + jnp.log(cs[:bk, 0])
    return row_lse, col_lse


# --------------------------------------------------------------- backward
def _bwd_kernel(v_ref, t_ref, va_ref, ta_ref, rls_ref, grow_ref, cls_ref,
                gcol_ref, gv_ref, gt_ref, gva_ref, gta_ref, *, bg, k,
                chunk, bp, bkp):
    """Recompute this chunk's logits, weight by exp(x - lse) * g, and
    emit grads: gv/gt accumulate across the grid (constant-index
    blocks), gva/gta are this chunk's output blocks."""
    c = pl.program_id(0)

    @pl.when(c == 0)
    def _init():
        gv_ref[...] = jnp.zeros_like(gv_ref)
        gt_ref[...] = jnp.zeros_like(gt_ref)

    ck = chunk * k
    v, t = v_ref[...], t_ref[...]
    ta = ta_ref[...].astype(jnp.float32)
    va = va_ref[...].astype(jnp.float32)
    x = lax.dot_general(v, ta, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    col = c * ck + lax.broadcasted_iota(jnp.int32, (bp, ck), 1)
    w = (jnp.where(col < bg * k, jnp.exp(x - _row_scalar(rls_ref)), 0.0)
         * _row_scalar(grow_ref))                        # (bp, ck)
    gv_ref[...] += jnp.dot(w, ta, preferred_element_type=jnp.float32)
    gta_ref[...] = lax.dot_general(w, v, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32
                                   ).astype(gta_ref.dtype)

    y = lax.dot_general(t, va, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)
    row = c * chunk + lax.broadcasted_iota(jnp.int32, (bkp, chunk), 1)
    u = (jnp.where(row < bg, jnp.exp(y - _row_scalar(cls_ref)), 0.0)
         * _row_scalar(gcol_ref))                        # (bkp, chunk)
    gt_ref[...] += jnp.dot(u, va, preferred_element_type=jnp.float32)
    gva_ref[...] = lax.dot_general(u, t, (((0,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32
                                   ).astype(gva_ref.dtype)


def _bcast_rows(a, rows):
    """(n,) -> all-lanes-equal (rows, 128) f32 block, zero-padded: the
    pad rows pair a zero lse with a zero cotangent, so their weights are
    exactly 0 (exp(0) * 0) with no overflow risk."""
    col = jnp.zeros((rows,), jnp.float32).at[:a.shape[0]].set(
        a.astype(jnp.float32))
    return jnp.broadcast_to(col[:, None], (rows, _LANES))


def _run_backward(v, t, v_all, t_all, row_lse, col_lse, g_row, g_col,
                  chunk, bg, k):
    b, d = v.shape
    bk = t.shape[0]
    bp, bkp = _pad8(b), _pad8(bk)
    _check_chunk_alignment(chunk, bg)
    nc = -(-bg // chunk)
    f32 = jnp.float32
    vp = _pad_rows(v.astype(f32), bp)
    tp = _pad_rows(t.astype(f32), bkp)
    vap = _pad_rows(v_all, nc * chunk)          # input dtype: the kernel
    tap = _pad_rows(t_all, nc * chunk * k)      # upcasts per block
    kernel = functools.partial(_bwd_kernel, bg=bg, k=k, chunk=chunk,
                               bp=bp, bkp=bkp)
    const = lambda shape: pl.BlockSpec(shape, lambda c: (0, 0))  # noqa: E731
    g_v, g_t, g_va, g_ta = pl.pallas_call(
        kernel,
        grid=(nc,),
        in_specs=[const((bp, d)), const((bkp, d)),
                  pl.BlockSpec((chunk, d), lambda c: (c, 0)),
                  pl.BlockSpec((chunk * k, d), lambda c: (c, 0)),
                  const((bp, _LANES)), const((bp, _LANES)),
                  const((bkp, _LANES)), const((bkp, _LANES))],
        out_specs=[const((bp, d)), const((bkp, d)),
                   pl.BlockSpec((chunk, d), lambda c: (c, 0)),
                   pl.BlockSpec((chunk * k, d), lambda c: (c, 0))],
        out_shape=[jax.ShapeDtypeStruct((bp, d), f32),
                   jax.ShapeDtypeStruct((bkp, d), f32),
                   jax.ShapeDtypeStruct((nc * chunk, d), v_all.dtype),
                   jax.ShapeDtypeStruct((nc * chunk * k, d), t_all.dtype)],
        interpret=_interpret(),
    )(vp, tp, vap, tap,
      _bcast_rows(row_lse, bp), _bcast_rows(g_row, bp),
      _bcast_rows(col_lse, bkp), _bcast_rows(g_col, bkp))
    return (g_v[:b], g_t[:bk], g_va[:bg], g_ta[:bg * k])


# ----------------------------------------------------------- custom VJP
@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def milnce_stream_pallas(v, t, v_all, t_all, chunk):
    """(row_lse (B,), col_lse_flat (B*K,)) of the MIL-NCE similarity
    cube, fused in VMEM — the kernel twin of
    ``losses.milnce_chunked._stream_lse_scan`` (identical contract)."""
    out, _ = _milnce_stream_fwd(v, t, v_all, t_all, chunk)
    return out


def _milnce_stream_fwd(v, t, v_all, t_all, chunk):
    b = v.shape[0]
    k = t.shape[0] // b
    bg = v_all.shape[0]
    row_lse, col_lse = _run_forward(v, t, v_all, t_all, chunk, bg, k)
    return (row_lse, col_lse), (v, t, v_all, t_all, row_lse, col_lse)


def _milnce_stream_bwd(chunk, res, cots):
    v, t, v_all, t_all, row_lse, col_lse = res
    g_row, g_col = cots
    b = v.shape[0]
    k = t.shape[0] // b
    bg = v_all.shape[0]
    g_v, g_t, g_va, g_ta = _run_backward(v, t, v_all, t_all, row_lse,
                                         col_lse, g_row, g_col, chunk,
                                         bg, k)
    return (g_v.astype(v.dtype), g_t.astype(t.dtype),
            g_va.astype(v_all.dtype), g_ta.astype(t_all.dtype))


milnce_stream_pallas.defvjp(_milnce_stream_fwd, _milnce_stream_bwd)
